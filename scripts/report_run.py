#!/usr/bin/env python
"""Render one run's full observability story from its telemetry output.

Usage: python scripts/report_run.py <run.jsonl> [spans.jsonl]
       python scripts/report_run.py <A/run.jsonl> <B/run.jsonl>   # diff mode

When the second file is itself a run log (it has task/epoch records rather
than spans), the report becomes a side-by-side diff of the two runs:
per-task accuracy deltas, final forgetting/BWT deltas, per-task stall
accounting deltas, and recompile-count deltas — the "did my change help"
question answered from the committed logs alone (ROADMAP PR 2 follow-up).

Consumes the unified-sink JSONL a ``--telemetry_dir`` run produces (and the
span file next to it, auto-discovered when not given):

* config provenance + the per-task accuracy table,
* the task x task accuracy matrix with per-slice **forgetting** and **BWT**
  columns (math imported from ``telemetry.cil_metrics`` — the same module
  the engine logs from, so report and log can never disagree),
* per-epoch input-stall accounting (host_s vs device_s vs wall),
* every recompile event, with unexpected ones called out,
* per-device HBM samples when the backend reports them,
* span phase coverage: how much of the ``fit`` wall time the depth-1 task
  spans account for (the acceptance gate is >= 95%), and the phase-level
  time breakdown under them,
* fleet telemetry: per-process sibling streams (``run_p<i>.jsonl``) merged
  into one report, wall clocks aligned via the heartbeat ``ts``/``mono``
  anchors,
* crash timeline: the supervisor's ``crash_report.json`` (or raw
  ``flight_*.json`` dumps) rendered as each process's last-events tail,
  ending with the span that was still open when it died,
* serving: artifact exports, the hot-swap timeline (including failed
  swaps), latency percentile windows, and training/serving skew.
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from a_pytorch_tutorial_to_class_incremental_learning_tpu.telemetry.cil_metrics import (  # noqa: E501,E402
    average_incremental_accuracy,
    backward_transfer,
    per_task_forgetting,
)
from a_pytorch_tutorial_to_class_incremental_learning_tpu.telemetry.spans import (  # noqa: E402
    load_spans,
)


def load_records(path: str):
    by_type = defaultdict(list)
    with open(path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # truncated trailing line of a killed run
            by_type[rec.get("type", "?")].append(rec)
    return by_type


def render_tasks(tasks):
    print("| task | new classes | cum. top-1 (%) | WA γ | seconds |")
    print("|---|---|---|---|---|")
    for t in tasks:
        gamma = f"{t['gamma']:.4f}" if t.get("gamma") is not None else "—"
        print(
            f"| {t['task_id']} | {t.get('nb_new', '?')} | {t['acc1']:.2f} | "
            f"{gamma} | {t.get('seconds', '?')} |"
        )
    print()


def render_matrix(tasks):
    rows = {t["task_id"]: t.get("acc_per_task") for t in tasks}
    if not rows or any(r is None for r in rows.values()):
        return
    T = max(rows) + 1
    complete = sorted(rows) == list(range(T)) and all(
        len(rows[t]) == t + 1 for t in rows
    )
    matrix = [rows[t] for t in sorted(rows)] if complete else None
    forgetting = per_task_forgetting(matrix) if matrix else None
    bwt = backward_transfer(matrix) if matrix else None
    print("accuracy matrix (row = after task t, col = val slice of task j):\n")
    header = [f"j={j}" for j in range(T)]
    print("| after task | " + " | ".join(header) + " | forgetting j | BWT |")
    print("|---|" + "---|" * (T + 2))
    for tid in sorted(rows):
        r = rows[tid]
        cells = [f"{a:.2f}" for a in r] + ["—"] * (T - len(r))
        # Forgetting/BWT are properties of the *final* row's protocol
        # prefix; earlier rows carry them blank.
        fcell = bcell = "—"
        if tid == T - 1 and forgetting is not None:
            fcell = ", ".join(f"{f:+.2f}" for f in forgetting)
            bcell = f"{bwt:+.3f}"
        print(f"| {tid} | " + " | ".join(cells) + f" | {fcell} | {bcell} |")
    if not complete:
        print(
            "\n(partial matrix — log starts mid-protocol; forgetting/BWT "
            "need rows for every task)"
        )
    print()


def render_stalls(epochs):
    timed = [e for e in epochs if "host_s" in e and "device_s" in e]
    if not timed:
        print("(no stall accounting in this log — pre-telemetry run)\n")
        return
    print("input-pipeline stall accounting (per epoch):\n")
    print("| task | epoch | wall s | host s | device s | stall frac |")
    print("|---|---|---|---|---|---|")
    for e in timed:
        print(
            f"| {e.get('task_id', '?')} | {e.get('epoch', '?')} | "
            f"{e.get('epoch_s', 0):.2f} | {e['host_s']:.3f} | "
            f"{e['device_s']:.3f} | {e.get('stall_frac', 0):.3f} |"
        )
    worst = max(timed, key=lambda e: e.get("stall_frac", 0))
    print(
        f"\nworst stall: task {worst.get('task_id')} epoch "
        f"{worst.get('epoch')} at {worst.get('stall_frac', 0):.1%} "
        "host-bound\n"
    )


def render_recompiles(recompiles, warnings_):
    if not recompiles:
        print("recompiles: none recorded\n")
        return
    total = sum(r.get("new_programs", 0) for r in recompiles)
    print(
        f"recompiles: {total} new program(s) across "
        f"{len(recompiles)} event(s), {len(warnings_)} unexpected\n"
    )
    print("| where | group | new | total | expected |")
    print("|---|---|---|---|---|")
    for r in recompiles:
        print(
            f"| {r.get('where', '?')} | {r.get('group', '—')} | "
            f"{r.get('new_programs', '?')} | {r.get('total_programs', '?')} | "
            f"{'yes' if r.get('expected') else '**NO**'} |"
        )
    print()


def render_serve(by_type):
    """Serving panel: artifact exports, the swap timeline, latency windows,
    and training/serving skew — rendered from whichever of the serve_*
    record types this log carries (a training log has exports + skew, a
    server log has swaps + latency)."""
    exports = by_type["serve_export"]
    swaps = by_type["serve_swap"] + by_type["serve_swap_failed"]
    latency = by_type["serve_latency"]
    skew = by_type["serve_skew"]
    fleet = (by_type["serve_shed"] + by_type["replica_ejected"]
             + by_type["serve_rollback"] + by_type["frontend_retry"])
    if not (exports or swaps or latency or skew or fleet):
        return
    print("## serving\n")
    if exports:
        ok = [e for e in exports if not e.get("error")]
        failed = [e for e in exports if e.get("error")]
        print(f"artifact exports: {len(ok)} ok, {len(failed)} failed\n")
        print("| task | known | buckets | seconds | error |")
        print("|---|---|---|---|---|")
        for e in exports:
            print(
                f"| {e.get('task_id', '?')} | {e.get('known', '—')} | "
                f"{','.join(str(b) for b in e.get('buckets', [])) or '—'} | "
                f"{e.get('seconds', '—')} | {e.get('error', '—')} |"
            )
        print()
    if swaps:
        print("swap timeline:\n")
        print("| ts | event | task | load ms | compile ms |")
        print("|---|---|---|---|---|")
        for s in sorted(swaps, key=lambda r: r.get("ts", 0)):
            if s.get("type") == "serve_swap":
                frm = s.get("from_task")
                label = ("initial load" if frm is None
                         else f"swap {frm} -> {s.get('to_task')}")
                print(
                    f"| {s.get('ts', '?')} | {label} | {s.get('to_task')} | "
                    f"{s.get('load_ms', 0):.0f} | {s.get('compile_ms', 0):.0f} |"
                )
            else:
                print(
                    f"| {s.get('ts', '?')} | **swap FAILED** "
                    f"({s.get('error', '?')}) | {s.get('task_id')} | — | — |"
                )
        print()
    if latency:
        print("latency windows:\n")
        print("| task | n | p50 ms | p95 ms | p99 ms | req/s | occupancy |")
        print("|---|---|---|---|---|---|---|")
        for rec in latency:
            print(
                f"| {rec.get('task_id', '?')} | {rec.get('count', '?')} | "
                f"{rec.get('p50_ms', 0):.2f} | {rec.get('p95_ms', 0):.2f} | "
                f"{rec.get('p99_ms', 0):.2f} | "
                f"{rec.get('throughput_rps', 0):.1f} | "
                f"{rec.get('bucket_occupancy', 0):.3f} |"
            )
        print()
    if fleet:
        # The front end's availability story in one ts-ordered timeline:
        # sheds (admission policy), retries (failover), eject/readmit
        # cycles (breaker + relaunch), rollbacks (skew-gated swaps).
        ejections = by_type["replica_ejected"]
        rollbacks = by_type["serve_rollback"]
        retries = by_type["frontend_retry"]
        shed_total = sum(
            s.get("shed_total", 1) for s in by_type["serve_shed"][-1:]) or len(
            by_type["serve_shed"])
        print(
            f"fleet health: {len(ejections)} eject/readmit event(s), "
            f"{len(retries)} failover retry(ies), "
            f"{len(rollbacks)} rollback(s), ~{shed_total} shed(s)\n")
        print("| ts | event | detail |")
        print("|---|---|---|")
        for rec in sorted(fleet, key=lambda r: r.get("ts", 0)):
            kind = rec.get("type")
            if kind == "serve_shed":
                detail = (f"priority={rec.get('priority')} "
                          f"queued={rec.get('queued')}/"
                          f"{rec.get('capacity')} "
                          f"total={rec.get('shed_total', '—')}")
            elif kind == "replica_ejected":
                kind = f"replica {rec.get('replica')} {rec.get('event')}"
                detail = rec.get("reason", "—")
            elif kind == "serve_rollback":
                kind = "**ROLLBACK**"
                detail = (f"replica={rec.get('replica', '—')} "
                          f"task {rec.get('task_id')} -> "
                          f"{rec.get('rolled_back_to')} "
                          f"({rec.get('reason', '?')})")
            else:
                detail = (f"replica={rec.get('replica')} "
                          f"attempt={rec.get('attempt')} "
                          f"{rec.get('error', '')}")
            print(f"| {rec.get('ts', '?')} | {kind} | {detail} |")
        print()
    if skew:
        print("training/serving skew (served artifact vs training row):\n")
        print("| task | served acc1 | skew abs max | n |")
        print("|---|---|---|---|")
        for rec in skew:
            sk = rec.get("skew_abs_max")
            cell = f"{sk:.5f}" if sk is not None else "—"
            flag = " **NONZERO**" if sk else ""
            print(
                f"| {rec.get('task_id', '?')} | "
                f"{rec.get('served_acc1', 0):.2f} | {cell}{flag} | "
                f"{rec.get('n', '?')} |"
            )
        print()


def render_metrics(by_type):
    """Metrics-plane panel: the registry time series (``metrics_snapshot``
    records that the in-process pump and the fleet scraper flush) and any
    ``slo_burn`` burn-rate alerts, per source."""
    snaps = by_type["metrics_snapshot"]
    burns = by_type["slo_burn"]
    if not (snaps or burns):
        return
    print("## metrics plane\n")
    if snaps:
        by_source = {}
        for s in snaps:
            by_source.setdefault(s.get("source", "?"), []).append(s)
        print("| source | snapshots | span s | series | key totals |")
        print("|---|---|---|---|---|")
        for source, rows in sorted(by_source.items()):
            last = rows[-1]
            span = last.get("ts", 0) - rows[0].get("ts", 0)
            counters = last.get("counters", {})
            nseries = (len(counters) + len(last.get("gauges", {}))
                       + len(last.get("histograms", {})))
            totals = {}
            for k, v in counters.items():
                base = k.split("{", 1)[0]
                totals[base] = totals.get(base, 0.0) + v
            key_cell = " ".join(
                f"{n}={totals[n]:g}"
                for n in ("steps_total", "serve_requests_total",
                          "fe_requests_total", "fe_shed_total")
                if n in totals) or "—"
            print(f"| {source} | {len(rows)} | {span:.0f} | {nseries} | "
                  f"{key_cell} |")
        print()
        for source, rows in sorted(by_source.items()):
            # The throughput story over time: per-snapshot summed rate of
            # the progress series, most recent last.
            history = []
            for s in rows:
                rates = s.get("rates") or {}
                for name in ("steps_total", "serve_requests_total",
                             "fe_requests_total"):
                    total = sum(v for k, v in rates.items()
                                if k.split("{", 1)[0] == name)
                    if total or any(k.split("{", 1)[0] == name
                                    for k in rates):
                        history.append((name, total))
                        break
            if history:
                name = history[0][0]
                tail = ", ".join(f"{r:.1f}" for _, r in history[-10:])
                print(f"{source} {name}/s (last {min(len(history), 10)} "
                      f"snapshots): {tail}\n")
        fleet_rows = by_source.get("fleet")
        if fleet_rows:
            up = fleet_rows[-1].get("up") or {}
            if up:
                alive = sum(1 for v in up.values() if v)
                down = ", ".join(
                    k for k, v in sorted(up.items()) if not v)
                print(f"fleet scrape targets up: {alive}/{len(up)} "
                      f"({('down: ' + down) if down else 'all healthy'})\n")
    if burns:
        print("SLO burn-rate alerts:\n")
        print("| ts | slo | severity | burn long/short | threshold "
              "| window s |")
        print("|---|---|---|---|---|---|")
        for b in sorted(burns, key=lambda r: r.get("ts", 0)):
            short = b.get("short_burn_rate")
            burn_cell = (f"{b.get('burn_rate', 0):.2f}/"
                         + (f"{short:.2f}" if short is not None else "—"))
            print(f"| {b.get('ts', '?')} | **{b.get('slo', '?')}** | "
                  f"{b.get('severity', '—')} | {burn_cell} | "
                  f"{b.get('threshold', '—')} | {b.get('window_s', '—')} |")
        print()


def render_hbm(hbm):
    if not hbm:
        return
    print("per-device HBM at task boundaries (peak bytes in use):\n")
    print("| task | " + " | ".join(sorted(next(iter(hbm))["devices"])) + " |")
    print("|---|" + "---|" * len(next(iter(hbm))["devices"]))
    for rec in hbm:
        cells = [
            str(
                rec["devices"][d].get(
                    "peak_bytes_in_use", rec["devices"][d].get("bytes_in_use", "?")
                )
            )
            for d in sorted(rec["devices"])
        ]
        print(f"| {rec.get('task_id', '?')} | " + " | ".join(cells) + " |")
    print()


def render_spans(spans_path: str):
    spans = load_spans(spans_path)
    if not spans:
        print(f"(no spans at {spans_path})\n")
        return
    fit = next((s for s in spans if s["name"] == "fit"), None)
    if fit is None or fit["dur_s"] <= 0:
        print("(no completed `fit` root span — run killed mid-protocol?)\n")
        return
    children = [s for s in spans if s.get("parent") == fit["span_id"]]
    covered = sum(s["dur_s"] for s in children)
    frac = covered / fit["dur_s"]
    gate = "PASS" if frac >= 0.95 else "FAIL"
    print(
        f"span coverage: depth-1 spans account for {frac:.1%} of the "
        f"{fit['dur_s']:.1f}s `fit` wall time — {gate} (gate: >= 95%)\n"
    )
    task_ids = {s["span_id"] for s in children}
    phases = defaultdict(float)
    for s in spans:
        if s.get("parent") in task_ids:
            phases[s["name"]] += s["dur_s"]
    if phases:
        print("phase breakdown (summed over tasks):\n")
        print("| phase | seconds | share of covered |")
        print("|---|---|---|")
        for name, dur in sorted(phases.items(), key=lambda kv: -kv[1]):
            print(f"| {name} | {dur:.2f} | {dur / max(covered, 1e-9):.1%} |")
        print()


# --------------------------------------------------------------------------- #
# Fleet telemetry: multi-process stream merge + crash forensics
# --------------------------------------------------------------------------- #


def discover_process_streams(run_path: str) -> dict:
    """``{process_index: path}`` for a run log and its per-process siblings.

    Process 0 writes the legacy name (``run.jsonl``), process *i* writes
    ``run_p{i}.jsonl`` (``utils.logging.process_suffixed``) — the single-
    process case degrades to ``{0: run_path}`` with no sibling scan hits.
    """
    stem, ext = os.path.splitext(run_path)
    out = {0: run_path}
    for p in sorted(glob.glob(f"{glob.escape(stem)}_p[0-9]*{ext}")):
        m = re.search(r"_p(\d+)" + re.escape(ext) + r"$", p)
        if m:
            out[int(m.group(1))] = p
    return out


def read_fleet_heartbeats(run_dir: str) -> dict:
    """``{process_index: beat}`` from ``heartbeat.json`` + per-process
    siblings next to the run log (unreadable files are skipped)."""
    out = {}
    for p in sorted(glob.glob(os.path.join(glob.escape(run_dir) or ".",
                                           "heartbeat*.json"))):
        try:
            with open(p) as f:
                beat = json.load(f)
        except (OSError, ValueError):
            continue
        m = re.search(r"heartbeat_p(\d+)\.json$", p)
        out[int(m.group(1)) if m else beat.get("process_index", 0)] = beat
    return out


def clock_offsets(heartbeats: dict) -> dict:
    """Per-process wall-clock offset (seconds) relative to process 0.

    Each beat stamps the wall clock (``ts``) and the monotonic clock
    (``mono``) at the same instant, so ``ts - mono`` is a per-process clock
    anchor and the difference of anchors is the skew:
    ``aligned_ts = ts - offset[p]`` puts every stream on process 0's clock.
    Processes without a usable anchor (old logs, missing beats) get 0.0 —
    unaligned beats worse than dropped.  Note this trusts the monotonic
    clocks to tick at the same rate (same boot for a simulated fleet; NTP-
    disciplined hosts in a real pod), which is exactly the skew class
    heartbeats exhibit in practice.
    """
    base = None
    b0 = heartbeats.get(0)
    if b0 and "ts" in b0 and "mono" in b0:
        base = b0["ts"] - b0["mono"]
    out = {}
    for pi, beat in heartbeats.items():
        if base is not None and beat and "ts" in beat and "mono" in beat:
            out[pi] = round((beat["ts"] - beat["mono"]) - base, 3)
        else:
            out[pi] = 0.0
    return out


def render_fleet(run_path: str) -> dict:
    """Merge per-process streams into one fleet section; returns
    ``{process_index: by_type}`` so the caller can reuse the merged load.
    Prints nothing in the single-process case (legacy reports unchanged)."""
    streams = discover_process_streams(run_path)
    merged = {pi: load_records(p) for pi, p in streams.items()}
    if len(streams) <= 1:
        return merged
    heartbeats = read_fleet_heartbeats(os.path.dirname(run_path))
    offsets = clock_offsets(heartbeats)
    print(f"fleet telemetry: {len(streams)} process stream(s) merged "
          "(timestamps aligned to process 0's clock via heartbeat "
          "ts/mono anchors):\n")
    print("| proc | host | records | faults | last record | "
          "last ts (aligned) | clock skew s |")
    print("|---|---|---|---|---|---|---|")
    for pi in sorted(merged):
        recs = [r for rs in merged[pi].values() for r in rs]
        recs.sort(key=lambda r: r.get("ts", 0))
        last = recs[-1] if recs else None
        host = next((r["host_id"] for r in recs if "host_id" in r), "?")
        off = offsets.get(pi, 0.0)
        aligned = f"{last['ts'] - off:.3f}" if last else "—"
        print(f"| {pi} | {host} | {len(recs)} | "
              f"{len(merged[pi]['fault_injected'])} | "
              f"{last['type'] if last else '—'} | {aligned} | {off:+.3f} |")
    print()
    return merged


def _event_label(e: dict) -> str:
    """One-line description of a flight event for the crash timeline."""
    keys = ("name", "task", "task_id", "epoch", "step", "phase", "spec",
            "site", "where")
    detail = " ".join(f"{k}={e[k]}" for k in keys if e.get(k) is not None)
    return f"{e.get('type', '?')}" + (f" [{detail}]" if detail else "")


def render_crash_timeline(run_path: str) -> None:
    """Per-process crash timeline from the supervisor's ``crash_report.json``
    (or, lacking one, the raw ``flight_*.json`` dumps) next to the run log:
    the flight-recorder tail of each process and the span that was still
    open when it died."""
    run_dir = os.path.dirname(run_path)
    report = None
    crash_path = os.path.join(run_dir, "crash_report.json")
    if os.path.exists(crash_path):
        try:
            with open(crash_path) as f:
                report = json.load(f)
        except (OSError, ValueError):
            report = None
    if report is not None:
        dumps = report.get("flight_dumps", [])
        src = crash_path
    else:
        dumps = []
        for p in sorted(glob.glob(os.path.join(glob.escape(run_dir) or ".",
                                               "flight_*.json"))):
            try:
                with open(p) as f:
                    d = json.load(f)
            except (OSError, ValueError):
                continue
            # Clean-exit dumps are steady-state artifacts, not crashes.
            if d.get("reason") not in ("close", "atexit"):
                dumps.append(d)
        src = run_dir
    if not dumps:
        return
    print(f"crash timeline (from {src}):\n")
    if report is not None:
        print(f"child exit: returncode={report.get('returncode')} "
              f"hung={report.get('hung')} "
              f"uptime={report.get('uptime_s', '?')}s "
              f"attempt={report.get('attempt', '?')}")
        if report.get("fault_ledger"):
            specs = [rec.get("spec") for rec in report["fault_ledger"]]
            print(f"fault ledger: {specs}")
        print()
    for dump in dumps:
        pi = dump.get("process_index", 0)
        t_dump = dump.get("ts", 0)
        events = dump.get("events", [])
        print(f"process {pi} (host {dump.get('host_id', '?')}, "
              f"pid {dump.get('pid', '?')}): dump reason "
              f"{dump.get('reason', '?')!r}, {len(events)} event(s) "
              f"buffered, {dump.get('dropped', 0)} older dropped")
        for e in events[-12:]:
            rel = e.get("ts", t_dump) - t_dump
            print(f"  {rel:+9.3f}s  {_event_label(e)}")
        open_spans = dump.get("open_spans") or []
        if open_spans:
            chain = " > ".join(s.get("name", "?") for s in open_spans)
            print(f"  open spans at death: {chain}")
            print(f"  last open span at death: {dump.get('last_open_span')}")
        else:
            print("  open spans at death: none")
        print()


def _is_run_log(by_type) -> bool:
    return bool(by_type["task"] or by_type["epoch"] or by_type["run"]
                or by_type["final"])


def _final_matrix(tasks):
    """The complete accuracy matrix of a run, or None (partial log)."""
    rows = {t["task_id"]: t.get("acc_per_task") for t in tasks}
    if not rows or any(r is None for r in rows.values()):
        return None
    T = max(rows) + 1
    if sorted(rows) != list(range(T)) or any(len(rows[t]) != t + 1 for t in rows):
        return None
    return [rows[t] for t in sorted(rows)]


def _task_stalls(epochs):
    """task_id -> (host_s, device_s, wall_s) summed over its epochs."""
    out = defaultdict(lambda: [0.0, 0.0, 0.0])
    for e in epochs:
        if "host_s" not in e or "device_s" not in e:
            continue
        acc = out[e.get("task_id", "?")]
        acc[0] += e["host_s"]
        acc[1] += e["device_s"]
        acc[2] += e.get("epoch_s", e["host_s"] + e["device_s"])
    return out


def _fmt_delta(a, b, fmt="{:+.2f}"):
    if a is None or b is None:
        return "—"
    return fmt.format(b - a)


def diff_runs(path_a: str, path_b: str):
    """Side-by-side deltas of two run logs (B relative to A)."""
    a, b = load_records(path_a), load_records(path_b)
    print(f"# run diff — A: {path_a}  vs  B: {path_b}\n")

    runs_a, runs_b = a["run"], b["run"]
    if runs_a and runs_b:
        ca = {k: v for k, v in runs_a[-1].items() if k not in ("type", "ts")}
        cb = {k: v for k, v in runs_b[-1].items() if k not in ("type", "ts")}
        changed = {k for k in set(ca) | set(cb) if ca.get(k) != cb.get(k)}
        if changed:
            print("config differences:\n")
            print("| key | A | B |")
            print("|---|---|---|")
            for k in sorted(changed):
                print(f"| {k} | {ca.get(k, '—')} | {cb.get(k, '—')} |")
            print()
        else:
            print("config: identical\n")

    ta = {t["task_id"]: t for t in a["task"]}
    tb = {t["task_id"]: t for t in b["task"]}
    stalls_a, stalls_b = _task_stalls(a["epoch"]), _task_stalls(b["epoch"])
    if ta or tb:
        print("per-task cumulative top-1 and input stall (Δ = B − A):\n")
        print("| task | A acc1 | B acc1 | Δ acc1 | A stall | B stall | Δ stall |")
        print("|---|---|---|---|---|---|---|")
        for tid in sorted(set(ta) | set(tb)):
            ra, rb = ta.get(tid), tb.get(tid)
            acc_a = ra["acc1"] if ra else None
            acc_b = rb["acc1"] if rb else None
            sa = stalls_a.get(tid)
            sb = stalls_b.get(tid)
            fa = sa[0] / max(sa[2], 1e-9) if sa else None
            fb = sb[0] / max(sb[2], 1e-9) if sb else None
            cells = [
                str(tid),
                f"{acc_a:.2f}" if acc_a is not None else "—",
                f"{acc_b:.2f}" if acc_b is not None else "—",
                _fmt_delta(acc_a, acc_b),
                f"{fa:.3f}" if fa is not None else "—",
                f"{fb:.3f}" if fb is not None else "—",
                _fmt_delta(fa, fb, "{:+.3f}"),
            ]
            print("| " + " | ".join(cells) + " |")
        print()

    acc_a = [ta[t]["acc1"] for t in sorted(ta)]
    acc_b = [tb[t]["acc1"] for t in sorted(tb)]
    if acc_a and acc_b:
        avg_a = average_incremental_accuracy(acc_a)
        avg_b = average_incremental_accuracy(acc_b)
        print(
            f"avg incremental top-1: A {avg_a:.3f}%  B {avg_b:.3f}%  "
            f"(Δ {avg_b - avg_a:+.3f})\n"
        )

    ma, mb = _final_matrix(a["task"]), _final_matrix(b["task"])
    if ma and mb:
        fga, fgb = per_task_forgetting(ma), per_task_forgetting(mb)
        print("final forgetting per val slice (Δ = B − A, negative = less "
              "forgetting):\n")
        print("| slice | A | B | Δ |")
        print("|---|---|---|---|")
        for j in range(max(len(fga), len(fgb))):
            va = fga[j] if j < len(fga) else None
            vb = fgb[j] if j < len(fgb) else None
            ca = f"{va:+.2f}" if va is not None else "—"
            cb = f"{vb:+.2f}" if vb is not None else "—"
            print(f"| j={j} | {ca} | {cb} | {_fmt_delta(va, vb)} |")
        bwt_a, bwt_b = backward_transfer(ma), backward_transfer(mb)
        print(f"\nBWT: A {bwt_a:+.3f}  B {bwt_b:+.3f}  "
              f"(Δ {bwt_b - bwt_a:+.3f})\n")
    elif ma or mb:
        print("(forgetting/BWT diff skipped: one run has a partial matrix)\n")

    rc_a = sum(r.get("new_programs", 0) for r in a["recompile"])
    rc_b = sum(r.get("new_programs", 0) for r in b["recompile"])
    warn_a, warn_b = len(a["recompile_warning"]), len(b["recompile_warning"])
    print(
        f"recompiles: A {rc_a} program(s) ({warn_a} unexpected)  "
        f"B {rc_b} program(s) ({warn_b} unexpected)  (Δ {rc_b - rc_a:+d})"
    )


def render_jaxlint(report_path: str) -> None:
    """Static-analysis panel from ``jaxlint --format json`` output.

    The lint report is run evidence like any other artifact: a report that
    says "clean, N baselined" next to the accuracy table is the PR-review
    answer to "did this run's code pass its own discipline checks".  Raises
    ``ValueError`` on schema drift so CI notices a broken producer instead
    of silently rendering nothing.
    """
    with open(report_path) as f:
        rep = json.load(f)
    for key in ("version", "counts", "findings"):
        if key not in rep:
            raise ValueError(
                f"{report_path}: not a jaxlint --format json report "
                f"(missing {key!r})"
            )
    counts = rep["counts"]
    print(
        f"## static analysis — {counts['new']} new, "
        f"{counts['baselined']} baselined, "
        f"{counts['stale_baseline']} stale baseline entr(y/ies)\n"
    )
    by_rule = defaultdict(int)
    for f in rep["findings"]:
        missing = {"file", "line", "rule", "message", "suppressed"} - set(f)
        if missing:
            raise ValueError(
                f"{report_path}: finding missing field(s) {sorted(missing)}"
            )
        by_rule[f["rule"]] += 1
    if by_rule:
        print("| rule | findings | summary |")
        print("|------|----------|---------|")
        rules = rep.get("rules", {})
        for rule in sorted(by_rule):
            print(f"| {rule} | {by_rule[rule]} "
                  f"| {rules.get(rule, '?')} |")
        print()
    new = [f for f in rep["findings"] if not f["suppressed"]]
    for f in new:
        print(f"- **{f['rule']}** {f['file']}:{f['line']}: {f['message']}")
    if new:
        print()


def render_lockstep(by_type) -> None:
    """SPMD lockstep panel: fingerprinted dispatches and any divergence."""
    fps = by_type["lockstep_fingerprint"]
    violations = by_type["lockstep_violation"]
    if not fps and not violations:
        return
    units = defaultdict(int)
    for fp in fps:
        units[fp.get("unit", "?")] += 1
    unit_s = ", ".join(f"{u}={n}" for u, n in sorted(units.items()))
    print(f"## lockstep — {len(fps)} fingerprinted dispatch(es) "
          f"({unit_s}), {len(violations)} violation(s)\n")
    for v in violations:
        fields = ", ".join(v.get("fields", [])) or "-"
        where = (f"step {v['step']}" if v.get("step") is not None
                 else f"seq {v.get('seq')}")
        print(f"- **{v.get('kind', '?')}** at {where} "
              f"({v.get('unit', '?')}, peer {v.get('peer', '?')}): "
              f"divergent fields: {fields}")
        if "mine" in v:
            print(f"  mine: `{json.dumps(v['mine'], sort_keys=True)}` "
                  f"theirs: `{json.dumps(v['theirs'], sort_keys=True)}`")
    if violations:
        print()


def main(run_path: str, second_path: str | None = None,
         jaxlint_path: str | None = None):
    if second_path and _is_run_log(load_records(second_path)):
        # Two run logs -> side-by-side diff.  A spans file has only span
        # records, so the old `report_run.py run.jsonl spans.jsonl` form
        # still renders the single-run report below.
        diff_runs(run_path, second_path)
        return
    spans_path = second_path
    by_type = load_records(run_path)
    print(f"# run report — {run_path}\n")
    if by_type["run"]:
        cfg = {
            k: v
            for k, v in by_type["run"][-1].items()
            if k not in ("type", "ts")
        }
        print(f"config: `{json.dumps(cfg, sort_keys=True)}`\n")
    tasks = by_type["task"]
    if tasks:
        render_tasks(tasks)
        render_matrix(tasks)
        acc1s = [t["acc1"] for t in tasks]
        print(
            f"avg incremental top-1: "
            f"{average_incremental_accuracy(acc1s):.3f}% over "
            f"{len(acc1s)} task(s)\n"
        )
    else:
        print("(no completed tasks in this log)\n")
    render_stalls(by_type["epoch"])
    render_recompiles(by_type["recompile"], by_type["recompile_warning"])
    render_lockstep(by_type)
    render_serve(by_type)
    render_metrics(by_type)
    render_hbm(by_type["hbm"])
    render_fleet(run_path)
    if jaxlint_path:
        render_jaxlint(jaxlint_path)
    if spans_path is None:
        candidate = os.path.join(os.path.dirname(run_path), "spans.jsonl")
        spans_path = candidate if os.path.exists(candidate) else None
    if spans_path:
        render_spans(spans_path)
    render_crash_timeline(run_path)


if __name__ == "__main__":
    argv = sys.argv[1:]
    jaxlint_path = None
    if "--jaxlint" in argv:
        i = argv.index("--jaxlint")
        try:
            jaxlint_path = argv[i + 1]
        except IndexError:
            sys.exit("--jaxlint needs a path (jaxlint --format json output)")
        del argv[i:i + 2]
    if not argv:
        sys.exit(
            "usage: report_run.py <run.jsonl> [spans.jsonl | other_run.jsonl]"
            " [--jaxlint <jaxlint.json>]"
        )
    main(argv[0], argv[1] if len(argv) > 1 else None,
         jaxlint_path=jaxlint_path)
