#!/usr/bin/env bash
# Probe the tunneled TPU until it answers, then capture the round's real-chip
# evidence: bench.py JSON (with the profiler-trace MFU witness) and
# profile_mfu.py JSON into experiments/.  The axon tunnel wedges for hours at
# a time (a killed client can wedge the chip); every probe runs in a killable
# subprocess with a timeout so the watchdog itself never hangs.
#
# Before probing, the watchdog consults the trainer's heartbeat file
# (telemetry/heartbeat.py): a fresh beat means a live training process owns
# the chip — liveness is logged from the beat (step/task/epoch) and the
# blind probe is skipped entirely.
#
# Division of labour: this script probes and captures; *relaunching* a dead
# or hung trainer is scripts/supervise.py's job — the protocol runs below go
# through it, so a preemption mid-run costs at most the epochs since the
# last checkpoint instead of the whole run.
#
# Evidence-preservation: bench/profile output is written to a temp file and
# only moved into experiments/ on rc=0, so a timed-out or crashed capture
# never overwrites previously captured evidence with an empty/partial file.
# Every probe attempt is appended to experiments/tpu_watchdog.log (committed
# even if the chip never answers, as proof of the attempt).
#
#   nohup setsid ./scripts/tpu_watchdog.sh &   # survives the session
set -u
cd "$(dirname "$0")/.."
mkdir -p experiments
LOG=experiments/tpu_watchdog.log

log() { echo "$(date -u +%FT%TZ) $*" | tee -a "$LOG"; }

capture() {  # capture <timeout_s> <dest> <cmd...> — atomic move on success only
  local t=$1 dest=$2; shift 2
  local tmp
  # Temp file lives in experiments/ itself: /tmp is often a separate tmpfs,
  # where mv degrades to copy+unlink and a mid-copy kill could truncate
  # previously captured evidence — same-filesystem rename is atomic.
  # stderr goes to /tmp (diagnostic noise, not evidence; keeps the
  # committed experiments/ dir free of machine-local .err files).
  tmp=$(mktemp experiments/.tpu_capture.XXXXXX)
  if timeout "$t" "$@" > "$tmp" 2> "/tmp/$(basename "$dest").err"; then
    mv "$tmp" "$dest"
    log "captured $dest: $(tail -1 "$dest")"
    return 0
  else
    local rc=$?
    log "capture of $dest failed rc=$rc (prior evidence preserved)"
    rm -f "$tmp"
    return "$rc"
  fi
}

INTERVAL=${INTERVAL:-600}
# Liveness file written by a running trainer (telemetry.heartbeat; enable
# with --telemetry_dir or --heartbeat_path).  While it is fresh the chip is
# demonstrably busy training — log the trainer's position and DO NOT open a
# fresh device client to probe (round 5: a probing client can wedge the
# chip under the very training run we care about).
HEARTBEAT=${HEARTBEAT:-experiments/heartbeat.json}
HB_MAX_AGE=${HB_MAX_AGE:-120}

heartbeat_fresh() {  # prints the beat summary and returns 0 when fresh
  # Probes $HEARTBEAT plus every per-process sibling (heartbeat_p<i>.json —
  # each JAX process beats into its own file); a fleet is fresh only when
  # every process that has ever beaten is fresh.
  python - "$HEARTBEAT" "$HB_MAX_AGE" <<'PY'
import glob, os, sys
sys.path.insert(0, ".")
from a_pytorch_tutorial_to_class_incremental_learning_tpu.telemetry import (
    read_heartbeat,
)

primary, max_age = sys.argv[1], float(sys.argv[2])
stem, ext = os.path.splitext(primary)
paths = [primary] + sorted(glob.glob(f"{glob.escape(stem)}_p[0-9]*{ext}"))
beats = {p: read_heartbeat(p, max_age) for p in paths if os.path.exists(p)}
if beats and all(b.get("fresh") for b in beats.values()):
    beat = beats[primary] if primary in beats else next(iter(beats.values()))
    worst = max(b["age_s"] for b in beats.values())
    print(
        f"procs={len(beats)} worst_age={worst}s pid={beat.get('pid')} "
        f"step={beat.get('step')} task={beat.get('task')} "
        f"epoch={beat.get('epoch')} phase={beat.get('phase')}"
    )
    sys.exit(0)
sys.exit(1)
PY
}

log "watchdog started (pid $$, interval ${INTERVAL}s, heartbeat $HEARTBEAT)"
while true; do
  if BEAT=$(heartbeat_fresh); then
    log "trainer heartbeat fresh ($BEAT) — skipping chip probe"
    sleep "$INTERVAL"
    continue
  fi
  if timeout -k 10 90 python -c "
import jax, numpy as np
x = jax.numpy.ones((128, 128))
assert jax.default_backend() == 'tpu', jax.default_backend()
float(np.asarray((x @ x).sum()))
print('tpu alive')
" >/dev/null 2>&1; then
    log "TPU alive — capturing bench + profiler witness"
    capture 1800 experiments/bench_tpu.json python bench.py
    capture 900 experiments/profile_mfu_tpu.json python scripts/profile_mfu.py
    # Full-recipe protocol evidence on the real chip: 140 epochs (the
    # reference's code default) is minutes on TPU vs hours on CPU.
    # MEMORY=256 + synthetic_hard128 = the dynamics-valid regime (the
    # default 2000-exemplar budget nearly replays the 6400-image synthetic
    # stream, so no forgetting could show — see run_protocol.sh).
    #
    # Launched under scripts/supervise.py, which owns the relaunch half of
    # fault tolerance (this watchdog only probes/captures): a preempted or
    # hung trainer is killed on heartbeat staleness and relaunched with
    # --resume, continuing from the newest valid task/epoch checkpoint
    # (CKPT_DIR below; run_protocol.sh forwards the resume flag).
    log "starting 140-epoch TPU protocol runs (supervised)"
    EPOCHS=140 SUFFIX=_tpu140 DATASET=synthetic_hard128 MEMORY=256 \
      AA=rand-m9-mstd0.5-inc1 CKPT_DIR=experiments/ckpt_tpu140 \
      EXTRA_ARGS="--telemetry_dir experiments ${EXTRA_ARGS:-}" \
      timeout 10800 python scripts/supervise.py \
        --heartbeat "$HEARTBEAT" --max_age "$HB_MAX_AGE" --grace 300 \
        --log experiments/supervise_tpu140.log \
        -- bash scripts/run_protocol.sh \
      > /tmp/protocol_tpu.log 2>&1 || log "TPU protocol rc=$?"
    log "watchdog done"
    exit 0
  fi
  log "TPU unreachable; retry in ${INTERVAL}s"
  sleep "$INTERVAL"
done
