#!/usr/bin/env bash
# Probe the tunneled TPU until it answers, then capture the round's real-chip
# evidence: bench.py JSON (with the profiler-trace MFU witness) and
# profile_mfu.py JSON into experiments/.  The axon tunnel wedges for hours at
# a time (a killed client can wedge the chip); every probe runs in a killable
# subprocess with a timeout so the watchdog itself never hangs.
#
#   nohup setsid ./scripts/tpu_watchdog.sh &   # survives the session
set -u
cd "$(dirname "$0")/.."
mkdir -p experiments

INTERVAL=${INTERVAL:-600}
while true; do
  if timeout -k 10 90 python -c "
import jax, numpy as np
x = jax.numpy.ones((128, 128))
assert jax.default_backend() == 'tpu', jax.default_backend()
float(np.asarray((x @ x).sum()))
print('tpu alive')
" >/dev/null 2>&1; then
    echo "$(date -u +%FT%TZ) TPU alive — capturing bench + profiler witness"
    timeout 1800 python bench.py > experiments/bench_tpu.json 2> /tmp/bench_tpu.err
    timeout 900 python scripts/profile_mfu.py \
      > experiments/profile_mfu_tpu.json 2> /tmp/profile_mfu_tpu.err
    echo "$(date -u +%FT%TZ) captured:"
    tail -1 experiments/bench_tpu.json || true
    tail -1 experiments/profile_mfu_tpu.json || true
    # Full-recipe protocol evidence on the real chip: 140 epochs (the
    # reference's code default) is minutes on TPU vs hours on CPU.
    echo "$(date -u +%FT%TZ) starting 140-epoch TPU protocol runs"
    EPOCHS=140 SUFFIX=_tpu140 timeout 10800 bash scripts/run_protocol.sh \
      > /tmp/protocol_tpu.log 2>&1 || echo "TPU protocol rc=$?"
    echo "$(date -u +%FT%TZ) watchdog done"
    exit 0
  fi
  echo "$(date -u +%FT%TZ) TPU unreachable; retry in ${INTERVAL}s"
  sleep "$INTERVAL"
done
