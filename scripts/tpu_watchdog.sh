#!/usr/bin/env bash
# Probe the tunneled TPU until it answers, then capture the round's real-chip
# evidence: bench.py JSON (with the profiler-trace MFU witness) and
# profile_mfu.py JSON into experiments/.  The axon tunnel wedges for hours at
# a time (a killed client can wedge the chip); every probe runs in a killable
# subprocess with a timeout so the watchdog itself never hangs.
#
# Evidence-preservation: bench/profile output is written to a temp file and
# only moved into experiments/ on rc=0, so a timed-out or crashed capture
# never overwrites previously captured evidence with an empty/partial file.
# Every probe attempt is appended to experiments/tpu_watchdog.log (committed
# even if the chip never answers, as proof of the attempt).
#
#   nohup setsid ./scripts/tpu_watchdog.sh &   # survives the session
set -u
cd "$(dirname "$0")/.."
mkdir -p experiments
LOG=experiments/tpu_watchdog.log

log() { echo "$(date -u +%FT%TZ) $*" | tee -a "$LOG"; }

capture() {  # capture <timeout_s> <dest> <cmd...> — atomic move on success only
  local t=$1 dest=$2; shift 2
  local tmp
  # Temp file lives in experiments/ itself: /tmp is often a separate tmpfs,
  # where mv degrades to copy+unlink and a mid-copy kill could truncate
  # previously captured evidence — same-filesystem rename is atomic.
  # stderr goes to /tmp (diagnostic noise, not evidence; keeps the
  # committed experiments/ dir free of machine-local .err files).
  tmp=$(mktemp experiments/.tpu_capture.XXXXXX)
  if timeout "$t" "$@" > "$tmp" 2> "/tmp/$(basename "$dest").err"; then
    mv "$tmp" "$dest"
    log "captured $dest: $(tail -1 "$dest")"
    return 0
  else
    local rc=$?
    log "capture of $dest failed rc=$rc (prior evidence preserved)"
    rm -f "$tmp"
    return "$rc"
  fi
}

INTERVAL=${INTERVAL:-600}
log "watchdog started (pid $$, interval ${INTERVAL}s)"
while true; do
  if timeout -k 10 90 python -c "
import jax, numpy as np
x = jax.numpy.ones((128, 128))
assert jax.default_backend() == 'tpu', jax.default_backend()
float(np.asarray((x @ x).sum()))
print('tpu alive')
" >/dev/null 2>&1; then
    log "TPU alive — capturing bench + profiler witness"
    capture 1800 experiments/bench_tpu.json python bench.py
    capture 900 experiments/profile_mfu_tpu.json python scripts/profile_mfu.py
    # Full-recipe protocol evidence on the real chip: 140 epochs (the
    # reference's code default) is minutes on TPU vs hours on CPU.
    # MEMORY=256 + synthetic_hard128 = the dynamics-valid regime (the
    # default 2000-exemplar budget nearly replays the 6400-image synthetic
    # stream, so no forgetting could show — see run_protocol.sh).
    log "starting 140-epoch TPU protocol runs"
    EPOCHS=140 SUFFIX=_tpu140 DATASET=synthetic_hard128 MEMORY=256 \
      AA=rand-m9-mstd0.5-inc1 timeout 10800 bash scripts/run_protocol.sh \
      > /tmp/protocol_tpu.log 2>&1 || log "TPU protocol rc=$?"
    log "watchdog done"
    exit 0
  fi
  log "TPU unreachable; retry in ${INTERVAL}s"
  sleep "$INTERVAL"
done
