#!/usr/bin/env python
"""Race the ACTUAL reference implementation end-to-end on torch-CPU.

This is the integrated-trajectory parity baseline (r4 verdict Next #1): it
imports the reference backbone **directly from /root/reference/resnet.py**
(the code being raced — nothing is copied into this repo) and drives it with
a faithful torch restatement of the reference experiment loop
(``template.py:226-303``): per task — cumulative val split, rehearsal
injection, head growth (``template.py:241``), fresh SGD momentum + cosine
schedule (246-249), CE + λ·KD epochs (251-280), weight alignment (285-286),
teacher snapshot (290), herding feature pass → memory (292-302).

Pieces the reference outsources to libraries that are not installed here are
taken from this repo's golden-tested equivalents so both sides of the race
see *identical* task splits and exemplar semantics:

* scenario/task order:  ``data.build_scenario``  (continuum parity-tested)
* rehearsal memory:     ``data.RehearsalMemory`` (continuum parity-tested)

and the small reference classes/criteria whose libraries are absent are
restated here with line citations (CilClassifier/CilModel/weight_align ←
``template.py:87-166``; SoftTarget ← ``utils.py:121-133``; timm
``accuracy`` ← exact top-k counting).  The race recipe runs augmentation
both sides implement identically (RandomCrop(32, pad 4, zero fill) +
horizontal flip + normalize): ``--aa none --color_jitter 0``.

The JSONL log uses the same record schema as the JAX trainer (run/task/
final, with ``acc_per_task``), so ``scripts/summarize_results.py`` renders
both sides and ``scripts/compare_race.py`` diffs them.

Single-process by construction (world_size 1): DDP wrapping, the
distributed barrier and sampler padding are no-ops at world 1, so nothing
of the reference's algorithm is lost on one CPU.
"""

from __future__ import annotations

import argparse
import copy
import os
import sys
import time

import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, "/root/reference")  # the implementation being raced

# This process never runs JAX compute, but the repo's data package imports
# jax at module level; pin the platform so nothing can accidentally
# initialize the (possibly wedged) tunneled-TPU backend.  config.update,
# not the env var: the axon sitecustomize overrides JAX_PLATFORMS.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import resnet as reference_resnet  # noqa: E402  /root/reference/resnet.py

from a_pytorch_tutorial_to_class_incremental_learning_tpu.config import (  # noqa: E402
    CilConfig,
)
from a_pytorch_tutorial_to_class_incremental_learning_tpu.data import (  # noqa: E402
    RehearsalMemory,
    build_scenario,
)
from a_pytorch_tutorial_to_class_incremental_learning_tpu.utils.native import (  # noqa: E402
    native_available,
)


class PlainJsonl:
    """Same record format as ``utils.logging.JsonlLogger`` (type + ts +
    fields, one object per line) without touching jax.process_index() —
    this harness is single-process torch by design."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        open(path, "w").close()

    def log(self, record_type: str, **fields) -> None:
        import json

        record = {"type": record_type, "ts": round(time.time(), 3), **fields}
        with open(self.path, "a") as f:
            f.write(json.dumps(record) + "\n")


# --------------------------------------------------------------------------- #
# Reference model surface (template.py:87-166, restated for CPU)
# --------------------------------------------------------------------------- #


class CilClassifier(nn.Module):
    """Growing multi-head classifier (reference ``template.py:87-104``)."""

    def __init__(self, embed_dim: int):
        super().__init__()
        self.embed_dim = embed_dim
        self.heads = nn.ModuleList()

    def adaption(self, nb_classes: int) -> None:
        self.heads.append(nn.Linear(self.embed_dim, nb_classes))

    def forward(self, x):
        return torch.cat([head(x) for head in self.heads], dim=1)


class CilModel(nn.Module):
    """Backbone + growing head (reference ``template.py:107-166``), with the
    backbone instantiated from the reference's own ``resnet.py``."""

    def __init__(self, backbone: str):
        super().__init__()
        self.backbone = getattr(reference_resnet, backbone)()
        self.fc = CilClassifier(self.backbone.out_dim)

    def forward(self, x):
        feats = self.backbone(x)
        return self.fc(feats), feats

    @torch.no_grad()
    def weight_align(self, nb_new_classes: int) -> float:
        """Reference ``weight_align`` (``template.py:156-166``): scale the
        newest head by mean old-norm / mean new-norm."""
        w = torch.cat([head.weight.data for head in self.fc.heads], dim=0)
        norms = torch.norm(w, dim=1)
        gamma = norms[:-nb_new_classes].mean() / norms[-nb_new_classes:].mean()
        self.fc.heads[-1].weight.data.mul_(gamma)
        return float(gamma)


class SoftTarget(nn.Module):
    """KD criterion (reference ``utils.py:121-133``)."""

    def __init__(self, T: float = 2.0):
        super().__init__()
        self.T = T

    def forward(self, out_s, out_t):
        return (
            F.kl_div(
                F.log_softmax(out_s / self.T, dim=1),
                F.softmax(out_t / self.T, dim=1),
                reduction="batchmean",
            )
            * self.T
            * self.T
        )


# --------------------------------------------------------------------------- #
# Input pipeline (the race recipe: crop + flip + normalize; aa=none)
# --------------------------------------------------------------------------- #


def augment_batch(rs: np.random.RandomState, x_u8: np.ndarray) -> np.ndarray:
    """torchvision ``RandomCrop(32, padding=4)`` (zero fill) + horizontal
    flip on a uint8 NHWC batch — the reference's non-AA train transform
    (``utils.py:210-229`` with the 32px RandomCrop override)."""
    b, h, w, c = x_u8.shape
    out = np.empty_like(x_u8)
    padded = np.zeros((b, h + 8, w + 8, c), x_u8.dtype)
    padded[:, 4 : 4 + h, 4 : 4 + w] = x_u8
    offs = rs.randint(0, 9, size=(b, 2))
    flips = rs.rand(b) < 0.5
    for i in range(b):
        oy, ox = offs[i]
        img = padded[i, oy : oy + h, ox : ox + w]
        out[i] = img[:, ::-1] if flips[i] else img
    return out


def to_model_input(x_u8: np.ndarray, mean, std) -> torch.Tensor:
    """uint8 NHWC -> normalized float32 NCHW (ToTensor + Normalize)."""
    mean = np.asarray(mean, np.float32) * 255.0
    std = np.asarray(std, np.float32) * 255.0
    x = (x_u8.astype(np.float32) - mean) / std
    return torch.from_numpy(np.ascontiguousarray(x.transpose(0, 3, 1, 2)))


# --------------------------------------------------------------------------- #
# Eval (reference template.py:169-188; exact weighted counting at world 1)
# --------------------------------------------------------------------------- #


@torch.no_grad()
def eval_totals(model, task_val, batch_size, mean, std) -> np.ndarray:
    """``[loss_sum, correct1, correct5, n]`` over one val set (same totals
    contract as the JAX trainer's ``_eval_totals`` so slice sums reproduce
    the cumulative metrics exactly)."""
    model.eval()
    n = len(task_val)
    loss_sum = c1 = c5 = 0.0
    for lo in range(0, n, batch_size):
        xb = task_val.x[lo : lo + batch_size]
        yb = torch.from_numpy(task_val.y[lo : lo + batch_size])
        logits, _ = model(to_model_input(xb, mean, std))
        loss_sum += float(F.cross_entropy(logits, yb, reduction="sum"))
        k = min(5, logits.shape[1])
        topk = logits.topk(k, dim=1).indices
        hit = topk.eq(yb[:, None])
        c1 += float(hit[:, 0].sum())
        c5 += float(hit.any(dim=1).sum())
    return np.array([loss_sum, c1, c5, float(n)])


def acc_of(totals: np.ndarray) -> float:
    return float(100.0 * totals[1] / max(totals[3], 1.0))


# --------------------------------------------------------------------------- #
# The experiment (reference template.py:191-303)
# --------------------------------------------------------------------------- #


def main() -> None:
    p = argparse.ArgumentParser("torch-CPU reference race")
    p.add_argument("--data_set", default="synthetic_hard128")
    p.add_argument("--num_bases", default=50, type=int)
    p.add_argument("--increment", default=10, type=int)
    p.add_argument("--backbone", default="resnet32")
    p.add_argument("--batch_size", default=128, type=int)
    p.add_argument("--num_epochs", default=20, type=int)
    p.add_argument("--memory_size", default=256, type=int)
    p.add_argument("--lr", default=0.1, type=float)
    p.add_argument("--momentum", default=0.9, type=float)
    p.add_argument("--weight_decay", default=5e-4, type=float)
    p.add_argument("--lambda_kd", default=0.5, type=float)
    p.add_argument("--kd_temperature", default=2.0, type=float)
    p.add_argument("--seed", default=0, type=int)
    p.add_argument("--log_file", default="experiments/race_torch.jsonl")
    args = p.parse_args()

    # Scenario/class-order/normalization from the SAME config machinery the
    # JAX side uses: both sides see identical arrays and task splits.
    cfg = CilConfig(
        data_set=args.data_set,
        num_bases=args.num_bases,
        increment=args.increment,
        backbone=args.backbone,
        batch_size=args.batch_size,
        num_epochs=args.num_epochs,
        memory_size=args.memory_size,
        lr=args.lr,
        seed=args.seed,
        aa=None,
        color_jitter=0.0,
    )
    scenario_train, nb_classes = build_scenario(cfg, train=True)
    scenario_val, _ = build_scenario(cfg, train=False)
    mean, std = cfg.normalization_stats()

    # init_seed (template.py:52-58); cuda calls are no-ops here.
    np.random.seed(args.seed)
    torch.manual_seed(args.seed)

    model = CilModel(args.backbone)
    memory = RehearsalMemory(
        memory_size=args.memory_size,
        herding_method="barycenter",
        fixed_memory=False,
        prefer_native=native_available(),
    )
    teacher = None
    criterion = nn.CrossEntropyLoss()
    kd_criterion = SoftTarget(T=args.kd_temperature)
    increments = scenario_train.increments()

    jsonl = PlainJsonl(args.log_file)
    jsonl.log(
        "run",
        framework="torch-reference",
        reference_backbone=os.path.join("/root/reference", "resnet.py"),
        data_set=args.data_set,
        backbone=args.backbone,
        num_bases=args.num_bases,
        increment=args.increment,
        batch_size=args.batch_size,
        global_batch=args.batch_size,
        num_epochs=args.num_epochs,
        lr=args.lr,
        seed=args.seed,
        aa=None,
        memory_size=args.memory_size,
        compute_dtype="float32",
        backend="torch-cpu",
        mesh={"data": 1, "model": 1},
        processes=1,
        torch_version=torch.__version__,
    )

    known = 0
    acc1s = []
    for task_id, task_train in enumerate(scenario_train):
        nb_new = increments[task_id]
        if task_id > 0:
            task_train.add_samples(*memory.get())  # template.py:230-231
        model.fc.adaption(nb_new)  # template.py:241 (prev_model_adaption)

        optimizer = torch.optim.SGD(  # template.py:246-247 (fresh per task)
            model.parameters(),
            lr=args.lr,
            momentum=args.momentum,
            weight_decay=args.weight_decay,
        )
        scheduler = torch.optim.lr_scheduler.CosineAnnealingLR(
            optimizer, T_max=args.num_epochs  # template.py:248-249
        )

        n = len(task_train)
        t0 = time.time()
        for epoch in range(args.num_epochs):
            model.train()
            # DistributedSampler shuffle at world 1 (template.py:232-233,
            # 253): torch.randperm seeded seed+epoch via set_epoch.
            g = torch.Generator().manual_seed(args.seed + epoch)
            perm = torch.randperm(n, generator=g).numpy()
            rs = np.random.RandomState(
                hash((args.seed, task_id, epoch)) & 0x7FFFFFFF
            )
            ce_sum = kd_sum = acc_sum = 0.0
            nb_steps = 0
            for lo in range(0, n, args.batch_size):
                idx = perm[lo : lo + args.batch_size]
                xb = augment_batch(rs, task_train.x[idx])
                x = to_model_input(xb, mean, std)
                y = torch.from_numpy(task_train.y[idx])
                logits, _ = model(x)  # template.py:258
                loss_ce = criterion(logits, y)
                if teacher is not None:  # template.py:260-263
                    with torch.no_grad():
                        t_logits, _ = teacher(x)
                    loss_kd = args.lambda_kd * kd_criterion(
                        logits[:, :known], t_logits
                    )
                else:
                    loss_kd = torch.tensor(0.0)
                loss = loss_ce + loss_kd
                optimizer.zero_grad()
                loss.backward()
                optimizer.step()
                ce_sum += float(loss_ce)
                kd_sum += float(loss_kd)
                acc_sum += float(
                    (logits.argmax(1) == y).float().mean() * 100.0
                )
                nb_steps += 1
            scheduler.step()  # template.py:278 (per epoch)
            # The reference's per-epoch cadence eval print (template.py:
            # 282-283) is omitted as state-neutral: model.eval()/no_grad
            # touches no parameters, buffers, or RNG draws, so the final
            # trajectory is unchanged with or without it.
            print(
                f"train states: epoch :[{epoch + 1}/{args.num_epochs}] "
                f"ce: {ce_sum / nb_steps:.4f}  kd: {kd_sum / nb_steps:.4f}  "
                f"acc1: {acc_sum / nb_steps:.4f}",
                flush=True,
            )

        gamma = None
        if task_id > 0:  # template.py:285-286 (after_model_adaption)
            gamma = model.weight_align(nb_new)
            print(f"old norm / new norm ={gamma}")

        # Eval per val slice; cumulative = exact sum of slice totals (same
        # contract as the JAX trainer, so the two logs are comparable
        # row-for-row and cell-for-cell).
        slice_totals = [
            eval_totals(model, scenario_val[j], args.batch_size, mean, std)
            for j in range(task_id + 1)
        ]
        totals = np.sum(slice_totals, axis=0)
        acc1 = acc_of(totals)
        acc1s.append(acc1)
        task_s = time.time() - t0
        print(
            f"task id = {task_id}  @Acc1 = {acc1:.5f}, acc1s = {acc1s}"
            f"  ({task_s:.1f}s)",
            flush=True,
        )
        jsonl.log(
            "task",
            task_id=task_id,
            acc1=acc1,
            acc1s=list(acc1s),
            acc_per_task=[round(acc_of(t), 5) for t in slice_totals],
            gamma=gamma,
            nb_new=nb_new,
            known_after=known + nb_new,
            seconds=round(task_s, 1),
        )

        # Teacher snapshot (template.py:290).
        teacher = copy.deepcopy(model)
        teacher.eval()
        for param in teacher.parameters():
            param.requires_grad_(False)

        # Herding feature pass (template.py:292-302): unshuffled loader over
        # the *train-transformed* dataset, model in eval mode (the preceding
        # eval() left it there in the reference).
        model.eval()
        feats = []
        rs = np.random.RandomState(0xFEED + task_id)
        with torch.no_grad():
            for lo in range(0, n, args.batch_size):
                xb = augment_batch(rs, task_train.x[lo : lo + args.batch_size])
                feats.append(
                    model.backbone(to_model_input(xb, mean, std)).numpy()
                )
        memory.add(
            *task_train.get_raw_samples(), np.concatenate(feats)
        )
        known += nb_new

    avg_inc = float(np.mean(acc1s)) if acc1s else 0.0
    print(f"avg incremental top-1 = {avg_inc:.3f}")
    jsonl.log("final", acc1s=list(acc1s), avg_incremental_acc1=avg_inc)


if __name__ == "__main__":
    main()
