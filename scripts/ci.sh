#!/usr/bin/env bash
# One-shot CI: static analysis first (jaxlint, then ruff/mypy when they are
# installed), telemetry-schema lint over the committed evidence logs, a CPU
# prefetch determinism smoke, the chaos + serving smokes (single-server and replicated
# fleet), the perf-regression gates (train step, serving p99, and fleet p99
# under overload), then the tier-1 test suite (the exact
# ROADMAP.md command).  Run from anywhere:
#
#   bash scripts/ci.sh
#
# Exits non-zero on the first failing stage.
set -uo pipefail
cd "$(dirname "$0")/.."

echo "== stage 1/12: jaxlint (JAX-hazard + lock-discipline static analysis) =="
# Fails on any finding not in analysis/jaxlint_baseline.json, and
# (--check-baseline) on any baseline entry that no longer matches a live
# finding — suppressions must not rot.  After fixing or justifying
# findings, refresh with: python scripts/jaxlint.py --write-baseline
python scripts/jaxlint.py --check-baseline || exit 1

echo "== stage 2/12: ruff + mypy (skipped when not installed) =="
# Configured in pyproject.toml; the container does not bake these in, so the
# stage gates on availability instead of failing the whole run.
if command -v ruff >/dev/null 2>&1; then
  ruff check . || exit 1
else
  echo "ruff not installed; skipping"
fi
if command -v mypy >/dev/null 2>&1; then
  mypy || exit 1
else
  echo "mypy not installed; skipping"
fi

echo "== stage 3/12: telemetry schema lint =="
python scripts/check_telemetry_schema.py experiments/*.jsonl || exit 1

echo "== stage 4/12: CPU prefetch smoke (depth 2 ≡ depth 0) =="
# Two-task synthetic run on the per-batch step path at --prefetch_depth 2;
# its accuracy matrix must match a depth-0 run exactly (the asynchronous
# input pipeline's determinism guarantee, data/prefetch.py).
timeout -k 10 600 env JAX_PLATFORMS=cpu python scripts/prefetch_smoke.py || exit 1

echo "== stage 5/12: jaxlint self-test fixtures =="
# The linter must still *find* the hazards it exists for (incl. the PR 3
# restore-aliasing regression); covered by tests/test_jaxlint.py in tier-1,
# but a broken linter that silently passes everything would also pass stage 1,
# so assert non-zero exit on the known-bad fixture tree here too.
python - <<'PY' || exit 1
import pathlib, subprocess, sys, tempfile

BAD = '''
import pickle
import jax
import jax.numpy as jnp

step = jax.jit(lambda s, b: s, donate_argnums=(0,))

def resume(path, state, batch):
    with open(path, "rb") as f:
        payload = pickle.load(f)
    params = jax.device_put(payload["params"])
    state = state.replace(params=params)
    state = step(state, batch)
    return state
'''
with tempfile.TemporaryDirectory() as d:
    p = pathlib.Path(d, "bad.py")
    p.write_text(BAD)
    proc = subprocess.run(
        [sys.executable, "scripts/jaxlint.py", "--baseline", "none", str(p)],
        capture_output=True, text=True)
    if proc.returncode == 0 or "JL002" not in proc.stdout:
        print(proc.stdout + proc.stderr)
        print("jaxlint failed to flag the restore-aliasing fixture")
        sys.exit(1)
print("jaxlint flags the restore-aliasing fixture: OK")
PY

echo "== stage 6/12: CPU chaos smoke (SIGKILL + supervised resume ≡ twin) =="
# A tiny synthetic run SIGKILLs itself mid-task (--fault_spec kill@task1.epoch2),
# scripts/supervise.py relaunches it with --resume, and the completed run's
# accuracy matrix must be bit-identical to its fault-free twin — the
# acceptance proof for the fault-injection / epoch-checkpoint / supervisor
# stack (faults/injector.py, utils/checkpoint.py, scripts/supervise.py).
# The chaos run executes under --check_threads and must emit zero
# thread_violation records (analysis/threadcheck.py).
timeout -k 10 1200 env JAX_PLATFORMS=cpu python scripts/chaos_smoke.py || exit 1

echo "== stage 7/12: CPU serve smoke (export + hot-swap under fire) =="
# Train a tiny 2-task run with --export_dir, then serve the artifacts under
# live traffic while hot-swapping task 0 -> 1 with an injected swap_ioerror:
# the failed swap must degrade gracefully (keep serving task 0, emit
# serve_swap_failed), the retry must swap cleanly, no request may fail, the
# exported programs must be bit-identical to direct model calls, and the
# serving hot path must run zero traces (serving/, scripts/serve_smoke.py).
# Both the training child and the in-process server run under the
# ThreadCheck sentinel and must emit zero thread_violation records.
timeout -k 10 1200 env JAX_PLATFORMS=cpu python scripts/serve_smoke.py || exit 1

echo "== stage 8/12: perf regression gate (bench.py vs BASELINE.json) =="
# step_ms is hard-gated at +15% vs the committed bench_gate entry;
# fetch_overhead_ms loosely (see scripts/perf_gate.py).  After a deliberate
# perf change, refresh with: python scripts/perf_gate.py --update-baseline
timeout -k 10 600 env JAX_PLATFORMS=cpu python scripts/perf_gate.py || exit 1

echo "== stage 9/12: serving perf gate (bench.py --serve vs BASELINE.json) =="
# Closed-loop p99 latency of the micro-batching server, gated at +15% vs
# the serve_gate entry.  Refresh: python scripts/perf_gate.py --serve --update-baseline
timeout -k 10 600 env JAX_PLATFORMS=cpu python scripts/perf_gate.py --serve || exit 1

echo "== stage 10/12: fleet overload soak (replicas + SIGKILL + rolling swap) =="
# The resilience-tier chaos smoke: three supervised replica subprocesses
# behind the admission-controlled front end under live bursty two-priority
# traffic.  One replica is SIGKILL'd mid-traffic (breaker eject -> supervised
# relaunch -> warm-probe readmit) and a rolling swap hits one injected
# swap_ioerror (rollback on that replica only, wave halts, retry converges).
# Zero failed client requests; sheds/rollbacks/ejections must appear as
# schema-valid records; everything runs under --check_threads
# (serving/frontend.py, serving/replica.py, serving/health.py).
timeout -k 10 1200 env JAX_PLATFORMS=cpu python scripts/serve_smoke.py --fleet || exit 1

echo "== stage 11/12: overload perf gate (bench.py --serve bursty vs BASELINE.json) =="
# High-priority p99 under bursty overload through the replicated front end,
# gated at +15% vs the serve_overload_gate entry: shedding low-priority work
# exists precisely to keep this number flat.  Refresh:
# python scripts/perf_gate.py --serve-overload --update-baseline
timeout -k 10 600 env JAX_PLATFORMS=cpu python scripts/perf_gate.py --serve-overload || exit 1

echo "== stage 12/12: tier-1 tests =="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
  -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
  -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
exit "$rc"
