#!/usr/bin/env bash
# One-shot CI: static analysis first (jaxlint, then ruff/mypy when they are
# installed), telemetry-schema lint over the committed evidence logs, a CPU
# prefetch determinism smoke, contractlint (cross-artifact contract
# analysis, JL5xx), the chaos + warm-cache + lockstep + serving
# smokes (single-server and replicated fleet), the perf-regression gates
# (train step, warm-cache compile cost, serving p99, and fleet p99
# under overload), then the tier-1 test suite (the exact
# ROADMAP.md command).  Run from anywhere:
#
#   bash scripts/ci.sh
#
# Exits non-zero on the first failing stage.
set -uo pipefail
cd "$(dirname "$0")/.."

echo "== stage 1/17: jaxlint (JAX-hazard + lock-discipline static analysis) =="
# Fails on any finding not in analysis/jaxlint_baseline.json, and
# (--check-baseline) on any baseline entry that no longer matches a live
# finding — suppressions must not rot.  After fixing or justifying
# findings, refresh with: python scripts/jaxlint.py --write-baseline
python scripts/jaxlint.py --check-baseline || exit 1

echo "== stage 2/17: ruff + mypy (skipped when not installed) =="
# Configured in pyproject.toml; the container does not bake these in, so the
# stage gates on availability instead of failing the whole run.
if command -v ruff >/dev/null 2>&1; then
  ruff check . || exit 1
else
  echo "ruff not installed; skipping"
fi
if command -v mypy >/dev/null 2>&1; then
  mypy || exit 1
else
  echo "mypy not installed; skipping"
fi

echo "== stage 3/17: telemetry schema lint =="
python scripts/check_telemetry_schema.py experiments/*.jsonl || exit 1

echo "== stage 4/17: CPU prefetch smoke (depth 2 ≡ depth 0) =="
# Two-task synthetic run on the per-batch step path at --prefetch_depth 2;
# its accuracy matrix must match a depth-0 run exactly (the asynchronous
# input pipeline's determinism guarantee, data/prefetch.py).
timeout -k 10 600 env JAX_PLATFORMS=cpu python scripts/prefetch_smoke.py || exit 1

echo "== stage 5/17: jaxlint self-test fixtures =="
# The linter must still *find* the hazards it exists for (incl. the PR 3
# restore-aliasing regression); covered by tests/test_jaxlint.py in tier-1,
# but a broken linter that silently passes everything would also pass stage 1,
# so assert non-zero exit on the known-bad fixture tree here too.
python - <<'PY' || exit 1
import pathlib, subprocess, sys, tempfile

BAD = '''
import pickle
import jax
import jax.numpy as jnp

step = jax.jit(lambda s, b: s, donate_argnums=(0,))

def resume(path, state, batch):
    with open(path, "rb") as f:
        payload = pickle.load(f)
    params = jax.device_put(payload["params"])
    state = state.replace(params=params)
    state = step(state, batch)
    return state
'''
with tempfile.TemporaryDirectory() as d:
    p = pathlib.Path(d, "bad.py")
    p.write_text(BAD)
    proc = subprocess.run(
        [sys.executable, "scripts/jaxlint.py", "--baseline", "none", str(p)],
        capture_output=True, text=True)
    if proc.returncode == 0 or "JL002" not in proc.stdout:
        print(proc.stdout + proc.stderr)
        print("jaxlint failed to flag the restore-aliasing fixture")
        sys.exit(1)
print("jaxlint flags the restore-aliasing fixture: OK")

# fleetlint (JL401-405): one fixture per SPMD hazard with *exact* file:line:rule
# expectations, plus a fixed twin that must lint clean — a linter that drifts
# off the documented lines or starts flagging the corrected idioms fails here.
import re

FLEET_BAD = '''import os
import time
import jax
import jax.numpy as jnp
from parallel.dist import barrier, process_allgather

step = jax.jit(lambda s, b: s)

def helper_sync():
    barrier()

def train(state, local_batch, class_ids):
    if jax.process_index() == 0:
        barrier()                      # JL401 direct
    if os.environ.get("RANK") == "0":
        helper_sync()                  # JL401 transitive
    with open("status.json", "w") as f:   # JL402
        f.write("x")
    classes = set(class_ids)
    for c in classes:                  # JL403
        state = step(state, jnp.full((1,), c))
    seed = int(time.time())
    key = jax.random.PRNGKey(seed)     # JL404
    n = len(local_batch)
    state = step(state, local_batch[:n])
    out = step(state, n)               # JL405
    return state, key, out
'''
EXPECT = {(14, "JL401"), (16, "JL401"), (17, "JL402"), (20, "JL403"),
          (23, "JL404"), (25, "JL405"), (26, "JL405")}

FLEET_OK = '''import jax
import jax.numpy as jnp
from parallel.dist import barrier, is_main_process
from telemetry.process import process_suffixed

step = jax.jit(lambda s, b: s)

def train(state, local_batch, class_ids, config, out_dir):
    barrier()
    if is_main_process():
        with open(out_dir + "/status.json", "w") as f:
            f.write("x")
    with open(process_suffixed(out_dir, jax.process_index()), "w") as f:
        f.write("x")
    for c in sorted(set(class_ids)):
        state = step(state, jnp.full((1,), c))
    key = jax.random.PRNGKey(config.seed)
    global_n = len(local_batch) * jax.process_count()
    out = step(state, global_n)
    return state, key, out
'''
with tempfile.TemporaryDirectory() as d:
    p = pathlib.Path(d, "fleet_bad.py")
    p.write_text(FLEET_BAD)
    proc = subprocess.run(
        [sys.executable, "scripts/jaxlint.py", "--baseline", "none", str(p)],
        capture_output=True, text=True)
    got = {(int(m.group(1)), m.group(2))
           for m in re.finditer(r":(\d+):\d+: (JL4\d\d) ", proc.stdout)}
    if proc.returncode == 0 or got != EXPECT:
        print(proc.stdout + proc.stderr)
        print(f"fleetlint drifted: expected {sorted(EXPECT)}, got {sorted(got)}")
        sys.exit(1)
    ok = pathlib.Path(d, "fleet_ok.py")
    ok.write_text(FLEET_OK)
    proc = subprocess.run(
        [sys.executable, "scripts/jaxlint.py", "--baseline", "none", str(ok)],
        capture_output=True, text=True)
    if proc.returncode != 0:
        print(proc.stdout + proc.stderr)
        print("fleetlint flags the corrected SPMD idioms")
        sys.exit(1)
print("fleetlint flags all five SPMD hazards at the expected lines: OK")
PY

echo "== stage 6/17: contractlint (cross-artifact contract analysis, JL501-506) =="
# Self-test first: a fixture tree seeded with one violation per contract rule
# (both directions where the rule is bidirectional) must be flagged at *exact*
# file:line:rule, and its corrected twin must lint clean — a pass that drifts
# off the documented lines or starts flagging the consistent idioms fails
# here.  Then the real gate: the repo itself must lint clean against
# analysis/contractlint_baseline.json, and the committed contract registry
# (analysis/contract_registry.json — the runtime sentinel's vocabulary)
# must match a fresh extraction.  After intentional contract changes:
#   python scripts/contractlint.py --write-baseline --write-registry
python - <<'PY' || exit 1
import pathlib, re, subprocess, sys, tempfile

BAD = {
    "schema.py": '''NUM = (int, float)
SCHEMA = {
    "epoch": ({"epoch": int}, {"loss": NUM}, None),
    "ghost_record": ({"x": int}, {}, None),
}
ALWAYS_REQUIRED = {"ts": NUM}
''',
    "emit.py": '''def run(sink):
    sink.log("epoch", epoch=0, loss=0.1)
    sink.log("mystery_record", x=1)
''',
    "consume.py": '''def tail(recs):
    epochs = [r for r in recs if r.get("type") == "epoch"]
    for e in epochs:
        print(e["loss"])
        print(e["bogus"])
''',
    "config.py": '''class FixtureConfig:
    dead_flag: int = 0
    live_flag: int = 1


def build(cfg):
    return cfg.live_flag + cfg.ghost_flag
''',
    "injector.py": '''ACTIONS = {
    "engine.epoch": frozenset({"kill"}),
    "ckpt.unfired": frozenset({"kill"}),
}


def run(inj):
    inj.fire("engine.epoch", epoch=1)
    inj.fire("engine.unknown", epoch=2)
''',
    "metricsreg.py": '''def setup(m):
    m.counter("requests_total", route="a")
    m.counter("requests_total", zone="b")
''',
    "bench.py": '''def report(snap, sum_counters):
    good = sum_counters(snap, "requests_total")
    bad = sum_counters(snap, "ghost_total")
    return good + bad
''',
    "README.md": '''# fixture

Run with `--live-flag` and `--no_such_flag`.
Rules JL501 and JL999.
The `epoch` record and the `ghost_type` record.
''',
}
EXPECT = {
    ("schema.py", 4, "JL501"),     # stale schema entry, no emitter
    ("emit.py", 3, "JL501"),       # emitted type unknown to the schema
    ("consume.py", 5, "JL502"),    # read outside the type's vocabulary
    ("config.py", 2, "JL503"),     # dead config field
    ("config.py", 7, "JL503"),     # cfg attribute nothing defines
    ("injector.py", 3, "JL504"),   # documented site never fired
    ("injector.py", 9, "JL504"),   # fired site outside the grammar
    ("metricsreg.py", 3, "JL505"),  # label-set drift across sites
    ("bench.py", 3, "JL505"),      # consumed metric never registered
    ("README.md", 3, "JL506"),     # documented flag does not exist
    ("README.md", 4, "JL506"),     # documented rule id does not exist
    ("README.md", 5, "JL506"),     # documented record type not in schema
}
OK = {
    "schema.py": '''NUM = (int, float)
SCHEMA = {
    "epoch": ({"epoch": int}, {"loss": NUM}, None),
}
ALWAYS_REQUIRED = {"ts": NUM}
''',
    "emit.py": '''def run(sink):
    sink.log("epoch", epoch=0, loss=0.1)
''',
    "consume.py": '''def tail(recs):
    epochs = [r for r in recs if r.get("type") == "epoch"]
    return [e["loss"] for e in epochs]
''',
    "config.py": '''class FixtureConfig:
    live_flag: int = 1


def build(cfg):
    return cfg.live_flag
''',
    "injector.py": '''ACTIONS = {
    "engine.epoch": frozenset({"kill"}),
}


def run(inj):
    inj.fire("engine.epoch", epoch=1)
''',
    "metricsreg.py": '''def setup(m):
    m.counter("requests_total", route="a")
''',
    "bench.py": '''def report(snap, sum_counters):
    return sum_counters(snap, "requests_total")
''',
    "README.md": '''# fixture

Run with `--live-flag`. Rule JL501 guards the `epoch` record.
''',
}

def run_tree(tree):
    with tempfile.TemporaryDirectory() as d:
        for name, text in tree.items():
            pathlib.Path(d, name).write_text(text)
        py = sorted(n for n in tree if n.endswith(".py"))
        return subprocess.run(
            [sys.executable, "scripts/contractlint.py", "--root", d,
             "--baseline", "none", *py],
            capture_output=True, text=True)

proc = run_tree(BAD)
got = {(m.group(1), int(m.group(2)), m.group(3))
       for m in re.finditer(r"(?m)^([\w./]+):(\d+):\d+: (JL\d{3}) ",
                            proc.stdout)}
if proc.returncode == 0 or got != EXPECT:
    print(proc.stdout + proc.stderr)
    print(f"contractlint drifted:\n  expected {sorted(EXPECT)}\n"
          f"  got      {sorted(got)}")
    sys.exit(1)
proc = run_tree(OK)
if proc.returncode != 0:
    print(proc.stdout + proc.stderr)
    print("contractlint flags the corrected contract idioms")
    sys.exit(1)
print("contractlint flags all six contract rules at the expected lines: OK")
PY
# The real gate over the repo: zero findings outside the baseline, no rotted
# baseline entries, and the committed registry matches a fresh extraction.
python scripts/contractlint.py --check-baseline --check-registry || exit 1

echo "== stage 7/17: CPU chaos smoke (SIGKILL + supervised resume ≡ twin) =="
# A tiny synthetic run SIGKILLs itself mid-task (--fault_spec kill@task1.epoch2),
# scripts/supervise.py relaunches it with --resume, and the completed run's
# accuracy matrix must be bit-identical to its fault-free twin — the
# acceptance proof for the fault-injection / epoch-checkpoint / supervisor
# stack (faults/injector.py, utils/checkpoint.py, scripts/supervise.py).
# The chaos run executes under --check_threads and must emit zero
# thread_violation records (analysis/threadcheck.py).
timeout -k 10 1200 env JAX_PLATFORMS=cpu python scripts/chaos_smoke.py || exit 1

echo "== stage 8/17: CPU warm-cache smoke (trace-free supervised resume + serving AOT load) =="
# The --compile_cache acceptance proof: the chaos protocol re-run against a
# run-local persistent XLA cache that starts EMPTY.  The first child compiles
# cold (populating the cache through the supervisor's env passthrough), kills
# itself, and the relaunch must resume with compile_s ~= 0 (compile_event
# telemetry via jax.monitoring) while holding its --recompile_budget; the
# exported artifact is then AOT-loaded twice and the second load must be
# served from the cache with an identical trace count
# (scripts/warmcache_smoke.py, telemetry/compilewatch.py).
timeout -k 10 3200 env JAX_PLATFORMS=cpu python scripts/warmcache_smoke.py || exit 1

echo "== stage 9/17: CPU lockstep chaos (2-process seeded divergence) =="
# A real 2-process jax.distributed CPU cluster under --check_lockstep
# (analysis/lockstep.py): the clean run must fingerprint every dispatch on
# both processes with zero violations, and a seeded single-process batch
# perturbation must surface as a schema-valid lockstep_violation naming the
# divergent field on BOTH processes — with flight-recorder dumps written —
# *before* any collective hangs (tests/test_multihost.py).
timeout -k 10 3400 env JAX_PLATFORMS=cpu python -m pytest \
  "tests/test_multihost.py::test_two_process_cluster_trains_in_lockstep" \
  "tests/test_multihost.py::test_lockstep_sentinel_catches_seeded_divergence" \
  -q -p no:cacheprovider -p no:xdist -p no:randomly -m '' || exit 1

echo "== stage 10/17: CPU serve smoke (export + hot-swap under fire) =="
# Train a tiny 2-task run with --export_dir, then serve the artifacts under
# live traffic while hot-swapping task 0 -> 1 with an injected swap_ioerror:
# the failed swap must degrade gracefully (keep serving task 0, emit
# serve_swap_failed), the retry must swap cleanly, no request may fail, the
# exported programs must be bit-identical to direct model calls, and the
# serving hot path must run zero traces (serving/, scripts/serve_smoke.py).
# Both the training child and the in-process server run under the
# ThreadCheck sentinel and must emit zero thread_violation records.
timeout -k 10 1200 env JAX_PLATFORMS=cpu python scripts/serve_smoke.py || exit 1

echo "== stage 11/17: perf regression gate (bench.py vs BASELINE.json) =="
# step_ms is hard-gated at +15% vs the committed bench_gate entry;
# fetch_overhead_ms loosely (see scripts/perf_gate.py).  After a deliberate
# perf change, refresh with: python scripts/perf_gate.py --update-baseline
timeout -k 10 600 env JAX_PLATFORMS=cpu python scripts/perf_gate.py || exit 1

echo "== stage 12/17: compile gate (bench.py cold/warm vs BASELINE.json) =="
# Warm-cache net XLA compile time (backend compile minus persistent-cache
# retrieval, jax.monitoring) measured by running bench.py twice against one
# fresh cache dir; the warm run is hard-gated vs the compile_gate entry and
# self-relatively vs its own cold run.  Refresh:
# python scripts/perf_gate.py --compile --update-baseline
timeout -k 10 1800 env JAX_PLATFORMS=cpu python scripts/perf_gate.py --compile || exit 1

echo "== stage 13/17: serving perf gate (bench.py --serve vs BASELINE.json) =="
# Closed-loop p99 latency of the micro-batching server, gated at +15% vs
# the serve_gate entry.  Refresh: python scripts/perf_gate.py --serve --update-baseline
timeout -k 10 600 env JAX_PLATFORMS=cpu python scripts/perf_gate.py --serve || exit 1

echo "== stage 14/17: fleet overload soak (replicas + SIGKILL + rolling swap) =="
# The resilience-tier chaos smoke: three supervised replica subprocesses
# behind the admission-controlled front end under live bursty two-priority
# traffic.  One replica is SIGKILL'd mid-traffic (breaker eject -> supervised
# relaunch -> warm-probe readmit) and a rolling swap hits one injected
# swap_ioerror (rollback on that replica only, wave halts, retry converges).
# Zero failed client requests; sheds/rollbacks/ejections must appear as
# schema-valid records; everything runs under --check_threads
# (serving/frontend.py, serving/replica.py, serving/health.py).
timeout -k 10 1200 env JAX_PLATFORMS=cpu python scripts/serve_smoke.py --fleet || exit 1

echo "== stage 15/17: overload perf gate (bench.py --serve bursty vs BASELINE.json) =="
# High-priority p99 under bursty overload through the replicated front end,
# gated at +15% vs the serve_overload_gate entry: shedding low-priority work
# exists precisely to keep this number flat.  Refresh:
# python scripts/perf_gate.py --serve-overload --update-baseline
timeout -k 10 600 env JAX_PLATFORMS=cpu python scripts/perf_gate.py --serve-overload || exit 1

echo "== stage 16/17: metrics overhead gate (bench.py --metrics paired) =="
# Registry-on vs registry-off cost of the hot-path instruments, measured
# over the identical compiled step in one process (alternating passes,
# min-of-passes).  Hard-gated at 3%: the metrics plane must stay
# effectively free or it gets switched off in production runs.
timeout -k 10 600 env JAX_PLATFORMS=cpu python scripts/perf_gate.py --metrics-overhead || exit 1

echo "== stage 17/17: tier-1 tests =="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
  -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
  -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
exit "$rc"
