#!/usr/bin/env bash
# One-shot CI: telemetry-schema lint over the committed evidence logs, a CPU
# prefetch determinism smoke, then the tier-1 test suite (the exact
# ROADMAP.md command).  Run from anywhere:
#
#   bash scripts/ci.sh
#
# Exits non-zero on the first failing stage.
set -uo pipefail
cd "$(dirname "$0")/.."

echo "== stage 1/3: telemetry schema lint =="
python scripts/check_telemetry_schema.py experiments/*.jsonl || exit 1

echo "== stage 2/3: CPU prefetch smoke (depth 2 ≡ depth 0) =="
# Two-task synthetic run on the per-batch step path at --prefetch_depth 2;
# its accuracy matrix must match a depth-0 run exactly (the asynchronous
# input pipeline's determinism guarantee, data/prefetch.py).
timeout -k 10 600 env JAX_PLATFORMS=cpu python scripts/prefetch_smoke.py || exit 1

echo "== stage 3/3: tier-1 tests =="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
  -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
  -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
exit "$rc"
