#!/usr/bin/env python
"""Profiler cross-check for bench.py's slope-timed step measurement.

bench.py times the compiled KD train step by the slope of two fetch-fenced
loops (see bench.py's module docstring for why `block_until_ready` cannot be
trusted on the tunneled TPU).  This script validates that number against an
independent witness: a ``jax.profiler`` trace of the same executable, whose
XLA device events record on-chip execution time directly.  VERDICT r2 weak
#3: "claimed numbers implying >100% MFU are bugs, not wins" — the trace is
how we know which.

The measurement harness is bench.py's own ``bench_step`` + ``trace_crosscheck``
(one copy of the logic; bench.main embeds the same witness in the driver
artifact when the backend is a real accelerator).  This script is the manual,
verbose form of that check.

Prints ONE JSON line:
    {"slope_step_ms", "trace_step_ms", "agreement", "est_mfu_trace", ...}

``agreement`` = slope/trace; honest timing lands near 1.0 (the slope includes
per-step host dispatch that the device events exclude, so slightly >1 is
expected at this model size).

Usage: python scripts/profile_mfu.py [--batch_size 512] [--steps 20]
       (falls back to CPU when the accelerator is unreachable, like bench.py;
       XLA:CPU emits no device plane, so there the witness is empty)
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


from a_pytorch_tutorial_to_class_incremental_learning_tpu.utils.profiling import (  # noqa: E402
    device_step_ms_from_xspaces,  # noqa: F401  (re-export for tests)
    trace_device_step_ms,  # noqa: F401  (re-export for tests)
)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--batch_size", type=int, default=512)
    p.add_argument("--steps", type=int, default=20)
    args = p.parse_args()

    # bench.py owns backend probing/fallback and the measurement harness.
    import bench

    backend = bench.probe_backend()
    if backend == "cpu":
        bench.force_cpu()
        args.batch_size = min(args.batch_size, 64)
        args.steps = min(args.steps, 5)

    import jax

    from a_pytorch_tutorial_to_class_incremental_learning_tpu.config import CilConfig
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.engine import (
        CilTrainer,
        Teacher,
    )

    cfg = CilConfig(
        data_set="synthetic",
        num_bases=50,
        increment=10,
        backbone="resnet32",
        batch_size=args.batch_size,
        seed=0,
    )
    trainer = CilTrainer(cfg, init_dist=False)
    img_s, dt, compile_s, flops, _m, _ovh, compiled = bench.bench_step(
        trainer, Teacher, iters=args.steps
    )

    result = {
        "metric": "profiler_crosscheck",
        "backend": jax.default_backend(),
        "global_batch": trainer.global_batch_size,
        "slope_step_ms": round(dt * 1e3, 3),
        "slope_img_s": round(img_s, 1),
        "compile_s": round(compile_s, 1),
    }
    result.update(bench.trace_crosscheck(trainer, compiled, args.steps, flops, dt))
    print(json.dumps(result))


if __name__ == "__main__":
    main()
