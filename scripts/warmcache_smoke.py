#!/usr/bin/env python
"""CPU warm-cache smoke: kill, supervised resume, prove the restart was
trace-free where it counts — the relaunch fetched its executables from the
persistent XLA compilation cache instead of re-compiling them.

The acceptance proof for ``--compile_cache`` end to end, with real
processes and a run-local cache directory that starts EMPTY (so cold vs
warm is measured, not assumed):

1. Run the tiny 2-task synthetic protocol under ``scripts/supervise.py
   --compile_cache <fresh dir>`` with ``--fault_spec kill@task1.epoch2``:
   the first child compiles everything cold (populating the cache via the
   supervisor's ``JAX_COMPILATION_CACHE_DIR`` env passthrough), SIGKILLs
   itself, and the relaunch resumes from the epoch checkpoint.
2. Assert from the run's ``compile_event`` telemetry (CompileWatch:
   net XLA work = backend compile time − persistent-cache retrieval time)
   that the cold events measured real compilation and the resumed event's
   ``compile_s`` is ≈0 — relative (< ``WARM_FRAC`` of cold) when the cold
   side is nontrivial, absolute (< ``WARM_SLACK_S``) always.
3. Assert the run held its ``--recompile_budget``: every
   ``recompile_budget`` record has ``ok=true`` (the traces that did happen
   were within the task-growth/restore budget — re-*tracing* is expected
   on relaunch; re-*compiling* is what the cache eliminates).
4. Serving twin of the same proof: AOT-load the artifact the run exported
   twice against one fresh serving cache — the second load's net compile
   must collapse the same way, with an identical trace count.

Exit 0 on pass, 1 otherwise, one JSON line either way.
Used by ``scripts/ci.sh``; runnable standalone from anywhere.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

WARM_FRAC = 0.2    # resumed compile_s must be under this fraction of cold
WARM_SLACK_S = 5.0  # ... and under this absolutely (sub-threshold programs
#                     below the persistence cutoff legitimately recompile)
COLD_FLOOR_S = 2.0  # the relative check arms only when cold was nontrivial

# Same shapes as chaos_smoke (2 tasks x 3 epochs, resnet20, batch 16) but
# WITHOUT the shared tests/.jax_cache: this smoke's entire point is a cache
# whose cold/warm state it controls.
_PROTO = [
    "--platform", "cpu",
    "--data_set", "synthetic10",
    "--num_bases", "0",
    "--increment", "5",
    "--backbone", "resnet20",
    "--batch_size", "16",
    "--num_epochs", "3",
    "--eval_every_epoch", "100",
    "--memory_size", "40",
    "--lr", "0.05",
    "--aa", "none",
    "--color_jitter", "0.0",
    "--seed", "7",
    "--no_fused_epochs",
]

# Serving AOT loader, run as a subprocess twice against one cache dir.  The
# child prints one JSON line: net XLA compile work + trace count of the load.
_SERVE_LOADER = r"""
import json, os, sys
sys.path.insert(0, os.environ["SMOKE_REPO"])
from a_pytorch_tutorial_to_class_incremental_learning_tpu.utils.platform import (
    force_platform,
)
force_platform("cpu")
import jax
jax.config.update("jax_compilation_cache_dir", os.environ["SMOKE_SERVE_CACHE"])
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
try:
    jax.config.update("jax_persistent_cache_enable_xla_caches", "none")
except AttributeError:
    pass
import numpy as np
from a_pytorch_tutorial_to_class_incremental_learning_tpu.telemetry import (
    CompileWatch,
)
from serving.server import InferenceServer
watch = CompileWatch.install()
before = watch.snapshot()
srv = InferenceServer(os.environ["SMOKE_EXPORT_DIR"], auto_swap=False).start()
meta = srv._artifact.meta
x = np.zeros((meta["input_size"], meta["input_size"], meta["channels"]),
             np.uint8)
srv.submit(x).result(timeout=300.0)
delta = CompileWatch.delta(before, watch.snapshot())
traces = srv.trace_count()
srv.stop()
print(json.dumps({**delta, "traces": traces}))
"""


def _records(path):
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def _serve_load(export_dir: str, cache_dir: str, timeout: float):
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        SMOKE_REPO=_REPO,
        SMOKE_EXPORT_DIR=export_dir,
        SMOKE_SERVE_CACHE=cache_dir,
    )
    proc = subprocess.run(
        [sys.executable, "-c", _SERVE_LOADER],
        cwd=_REPO, env=env, capture_output=True, text=True, timeout=timeout,
    )
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except ValueError:
            continue
    return {"error": f"serve loader rc={proc.returncode}: "
                     f"{proc.stderr.strip()[-400:]}"}


def main() -> int:
    with tempfile.TemporaryDirectory(prefix="warmcache_smoke_") as tmp:
        cache = os.path.join(tmp, "xla_cache")
        serve_cache = os.path.join(tmp, "serve_cache")
        tdir = os.path.join(tmp, "tel")
        run_log = os.path.join(tdir, "run.jsonl")
        ckpt_dir = os.path.join(tmp, "ckpt")
        export_dir = os.path.join(tmp, "export")

        cmd = [
            sys.executable, os.path.join(_REPO, "scripts", "supervise.py"),
            "--backoff_base", "0.1", "--backoff_max", "1",
            "--max_failures", "3", "--failure_window", "300",
            "--telemetry_dir", tdir,
            "--fault_ledger", os.path.join(ckpt_dir, "fault_ledger.jsonl"),
            "--compile_cache", cache,
            "--",
            sys.executable, os.path.join(_REPO, "train.py"), *_PROTO,
            "--telemetry_dir", tdir,
            "--ckpt_dir", ckpt_dir,
            "--export_dir", export_dir,
            "--epoch_ckpt_every", "1",
            "--fault_spec", "kill@task1.epoch2",
            "--recompile_budget",
        ]
        run = subprocess.run(cmd, cwd=_REPO, timeout=3000)

        failures = []
        if run.returncode != 0:
            failures.append(f"supervisor exited rc={run.returncode}")
        recs = _records(run_log) if os.path.exists(run_log) else []

        if not any(r.get("type") == "fault_injected" for r in recs):
            failures.append("kill fault did not fire")
        if not any(r.get("type") == "resume" for r in recs):
            failures.append("relaunch did not resume from a checkpoint")
        if not any(r.get("type") == "final" for r in recs):
            failures.append("run produced no final record")

        events = [r for r in recs if r.get("type") == "compile_event"]
        cold = [e for e in events if not e.get("resumed")]
        warm = [e for e in events if e.get("resumed")]
        cold_s = round(sum(e.get("compile_s", 0.0) for e in cold), 3)
        warm_s = round(sum(e.get("compile_s", 0.0) for e in warm), 3)
        warm_hits = sum(e.get("cache_hits", 0) for e in warm)
        if not cold:
            failures.append("no cold compile_event records")
        if not warm:
            failures.append("no resumed compile_event record — the relaunch "
                            "never reached its first epoch window")
        if warm:
            if warm_s > WARM_SLACK_S:
                failures.append(
                    f"resumed compile_s {warm_s} > {WARM_SLACK_S}s — the "
                    "relaunch re-compiled instead of fetching from the cache")
            if cold_s >= COLD_FLOOR_S and warm_s > cold_s * WARM_FRAC:
                failures.append(
                    f"resumed compile_s {warm_s} > {WARM_FRAC:.0%} of cold "
                    f"{cold_s} — warm restart is not trace-free")
            if warm_hits == 0:
                failures.append("resumed window saw zero persistent-cache "
                                "hits — the cache was not consulted")

        budget = [r for r in recs if r.get("type") == "recompile_budget"]
        bad_budget = [r for r in budget if not r.get("ok")]
        if not budget:
            failures.append("no recompile_budget records under "
                            "--recompile_budget")
        if bad_budget:
            failures.append(f"{len(bad_budget)} recompile_budget violation(s):"
                            f" {bad_budget[:2]}")

        # Serving twin: cold AOT load populates the serve cache, the second
        # load must be served from it with the identical trace count.
        serve_cold = serve_warm = None
        if os.path.isdir(export_dir):
            serve_cold = _serve_load(export_dir, serve_cache, timeout=1200)
            serve_warm = _serve_load(export_dir, serve_cache, timeout=1200)
            for side, res in (("cold", serve_cold), ("warm", serve_warm)):
                if res.get("error"):
                    failures.append(f"serving {side} load failed: "
                                    f"{res['error']}")
            if not failures or (serve_cold.get("error") is None
                                and serve_warm.get("error") is None):
                sc = serve_cold.get("compile_s", 0.0)
                sw = serve_warm.get("compile_s", 0.0)
                if sw > WARM_SLACK_S:
                    failures.append(f"warm serving load compile_s {sw} > "
                                    f"{WARM_SLACK_S}s")
                if sc >= COLD_FLOOR_S and sw > sc * WARM_FRAC:
                    failures.append(
                        f"warm serving load compile_s {sw} > "
                        f"{WARM_FRAC:.0%} of cold {sc}")
                if serve_warm.get("cache_hits", 0) == 0:
                    failures.append("warm serving load saw zero "
                                    "persistent-cache hits")
                if serve_cold.get("traces") != serve_warm.get("traces"):
                    failures.append(
                        f"serving trace counts differ cold vs warm: "
                        f"{serve_cold.get('traces')} vs "
                        f"{serve_warm.get('traces')}")
        else:
            failures.append("training run exported no serving artifact")

        print(json.dumps({
            "metric": "warmcache_smoke",
            "ok": not failures,
            "failures": failures,
            "cold_compile_s": cold_s,
            "resumed_compile_s": warm_s,
            "resumed_cache_hits": warm_hits,
            "serve_cold": serve_cold,
            "serve_warm": serve_warm,
        }))
        return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
