#!/usr/bin/env python
"""contractlint CLI — the CI gate over the repo's cross-artifact contracts.

Usage:
    python scripts/contractlint.py                    # lint the default targets
    python scripts/contractlint.py path1 path2 ...    # lint specific files/dirs
    python scripts/contractlint.py --write-baseline   # accept current findings
    python scripts/contractlint.py --write-registry   # refresh the committed
                                                      #   contract registry
    python scripts/contractlint.py --check-registry   # fail if the committed
                                                      #   registry is stale
    python scripts/contractlint.py --list-rules       # print the rule catalog
    python scripts/contractlint.py --format json      # machine-readable report

Same conventions as ``scripts/jaxlint.py``: exit 0 = no findings outside the
baseline; 1 = new findings (printed as ``path:line:col: RULE message``) or,
under ``--check-baseline``/``--check-registry``, a stale baseline entry /
stale committed registry; 2 = usage error.

The registry (``analysis/contract_registry.json``) is the static half of the
``--check_contracts`` runtime sentinel: it must be regenerated (and is
byte-for-byte deterministic) whenever a record type, metric instrument,
config field, or fault site is added — ``--check-registry`` is the CI proof
it was.

Stdlib-only: this never imports jax, so the lint stage runs anywhere.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

from analysis import (  # noqa: E402 - needs the sys.path bootstrap above
    DEFAULT_TARGETS,
    Baseline,
)
from analysis.contracts import (  # noqa: E402
    CONTRACT_RULES,
    DEFAULT_BASELINE,
    DEFAULT_REGISTRY,
    lint_contracts,
    write_registry,
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="contractlint", description=__doc__)
    parser.add_argument("paths", nargs="*", help="files/dirs relative to the "
                        "repo root (default: the committed lint scope)")
    parser.add_argument("--root", default=_REPO_ROOT,
                        help="project root findings are reported relative to")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE,
                        help="baseline JSON path, or 'none' to disable")
    parser.add_argument("--write-baseline", action="store_true",
                        help="rewrite the baseline to the current findings "
                        "(keeps reasons of entries that still match)")
    parser.add_argument("--check-baseline", action="store_true",
                        help="fail (exit 1) when a baseline entry no longer "
                        "matches any live finding, instead of only warning")
    parser.add_argument("--registry", default=DEFAULT_REGISTRY,
                        help="contract registry JSON path (the runtime "
                        "sentinel's vocabulary)")
    parser.add_argument("--write-registry", action="store_true",
                        help="regenerate the committed contract registry "
                        "from the current lint scope")
    parser.add_argument("--check-registry", action="store_true",
                        help="fail (exit 1) when the committed registry "
                        "differs from a fresh regeneration")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("--format", choices=("text", "json"), default="text",
                        help="'json' emits a stable machine-readable report "
                        "(schema: version, counts, findings[{file, line, col, "
                        "rule, message, suppressed}]); the exit code still "
                        "reflects new findings")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule, summary in sorted(CONTRACT_RULES.items()):
            print(f"{rule}  {summary}")
        return 0

    root = os.path.abspath(args.root)
    targets = args.paths or list(DEFAULT_TARGETS)
    findings, registry = lint_contracts(targets, root=root)

    registry_path = (args.registry if os.path.isabs(args.registry)
                     else os.path.join(root, args.registry))
    if args.write_registry:
        write_registry(registry, registry_path)
        print(f"contractlint: registry written "
              f"({len(registry['records'])} record type(s), "
              f"{len(registry['metrics'])} metric(s)) "
              f"-> {os.path.relpath(registry_path, root)}")
        if not (args.check_baseline or args.check_registry or findings):
            return 0

    baseline_path = None if args.baseline.lower() == "none" else (
        args.baseline if os.path.isabs(args.baseline)
        else os.path.join(root, args.baseline))
    baseline = Baseline.load(baseline_path) if baseline_path else Baseline()

    if args.write_baseline:
        if not baseline_path:
            print("contractlint: --write-baseline needs a baseline path",
                  file=sys.stderr)
            return 2
        baseline.write(baseline_path, findings, tool="contractlint")
        print(f"contractlint: baseline rewritten with {len(findings)} "
              f"finding(s) -> {os.path.relpath(baseline_path, root)}")
        return 0

    registry_stale = False
    if args.check_registry:
        committed = None
        if os.path.exists(registry_path):
            try:
                with open(registry_path) as f:
                    committed = json.load(f)
            except ValueError:
                committed = None
        if committed != registry:
            registry_stale = True
            print("contractlint: committed contract registry is stale "
                  f"({os.path.relpath(registry_path, root)}); refresh with "
                  "--write-registry")

    new, known, stale = baseline.split(findings)

    if args.format == "json":
        known_keys = {f.key for f in known}
        report = {
            "version": 1,
            "root": root,
            "rules": dict(sorted(CONTRACT_RULES.items())),
            "counts": {"new": len(new), "baselined": len(known),
                       "stale_baseline": len(stale)},
            "findings": [
                {
                    "file": f.path,
                    "line": f.line,
                    "col": f.col,
                    "rule": f.rule,
                    "message": f.message,
                    "suppressed": f.key in known_keys,
                }
                for f in sorted(findings,
                                key=lambda f: (f.path, f.line, f.col, f.rule))
            ],
            "stale_baseline": list(stale),
            "registry_stale": registry_stale,
        }
        print(json.dumps(report, indent=2, sort_keys=True))
        if registry_stale or (stale and args.check_baseline):
            return 1
        return 1 if new else 0

    for f in new:
        print(f.render())
    if known:
        print(f"contractlint: {len(known)} baselined finding(s) suppressed "
              f"(see {os.path.relpath(baseline_path, root)})")
    for e in stale:
        print(f"contractlint: stale baseline entry (fixed? refresh with "
              f"--write-baseline): {e['path']}:{e['line']} {e['rule']}")
    if stale and args.check_baseline:
        print(f"contractlint: --check-baseline: {len(stale)} stale baseline "
              "entr(y/ies) no longer match any live finding; remove them or "
              "refresh with --write-baseline")
        return 1
    if registry_stale:
        return 1
    if new:
        print(f"contractlint: {len(new)} new finding(s) in "
              f"{len(set(f.path for f in new))} file(s); fix them, add "
              "'# jaxlint: disable=<rule>' with a reason, or baseline with "
              "--write-baseline")
        return 1
    print(f"contractlint: clean ({len(findings)} finding(s) total, "
          f"{len(known)} baselined)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
