#!/usr/bin/env python
"""CI perf regression gate: bench.py vs the committed BASELINE.json entry.

Usage: python scripts/perf_gate.py                  # gate (ci.sh stage)
       python scripts/perf_gate.py --update-baseline  # (re)record the entry
       python scripts/perf_gate.py --result '<json>'  # gate a canned result
       python scripts/perf_gate.py --serve             # serving-latency gate
       python scripts/perf_gate.py --compile           # warm-cache compile gate

Runs ``bench.py`` (the CPU reduced fallback under ``JAX_PLATFORMS=cpu``:
batch 64, 5 iters — ~30 s with a warm compile cache), parses its single JSON
line, and compares against the ``bench_gate`` entry in ``BASELINE.json``:

* ``step_ms`` is the hard gate: measured > baseline × (1 + tolerance)
  (default 15%) fails the stage — a perf regression is a CI failure, not a
  footnote in a round log.
* ``fetch_overhead_ms`` is gated loosely (3× + 5 ms), and only when the
  baseline recorded a meaningful (≥ 1 ms) overhead: the slope-intercept
  estimate is scheduler noise at smaller magnitudes, but an input pipeline
  that *collapsed* (prefetch disabled, decode gone synchronous) still trips.
* A baseline recorded on a different backend or global batch is
  incomparable: the gate SKIPs (exit 0) with a warning instead of judging
  TPU numbers against a CPU baseline.
* A bench error / zero value always fails — a broken bench must not read as
  "no regression".

``--serve`` gates the serving path instead: ``bench.py --serve`` (the
micro-batching inference server over an exported artifact) against the
``serve_gate`` baseline entry.  The hard gate is the closed-loop p99 —
tail latency is the serving SLO, and a batcher bug (lost wakeup, lock held
across dispatch) shows up there long before mean throughput moves.  When
both the baseline and the run carry ``hist_p99_ms`` (the p99 scraped from
the server's own ``serve_batch_latency_ms`` registry histograms — every
request the server observed, not one run's sample list), the gate compares
those, rung-based: the ladder quantizes values to powers of ``growth``, so
the limit is one rung of slack rather than a percentage.  A baseline from a
different backend, bucket set, or max-wait is incomparable and SKIPs, same
rule as the train gate.

``--metrics-overhead`` gates the metrics plane itself: ``bench.py
--metrics paired`` runs the identical compiled step with the live registry
and with the no-op ``NullRegistry`` and the gate fails if the instrumented
step is more than 3% slower.  Self-relative, so no baseline entry exists
for it.

``--serve-overload`` gates the fleet under overload: ``bench.py --serve
--serve_pattern bursty`` drives a replicated front end (admission control +
priority shedding) with bursty open-loop arrivals and gates the
*high-priority* p99 against the ``serve_overload_gate`` entry.  The point of
shedding low-priority work is that the high class's tail stays flat through
the burst — a regression here means the shed policy, breaker, or hedging
changed behaviour.  Any hard request error fails outright; sheds are the
mechanism under test, not a failure.

``--compile`` gates the trace-free-restart promise: bench.py runs twice
against one fresh persistent compilation cache dir, and the second (warm)
run's net XLA compile time (``xla_compile_s``, jax.monitoring backend time
minus cache-retrieval time) is gated against the ``compile_gate`` baseline
entry plus an absolute slack, and self-relatively against the cold run — a
cache that silently stopped serving fails even when the baseline is stale.

Exit 0 on pass/skip, 1 on fail, one JSON verdict line either way.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_BASELINE = os.path.join(_REPO, "BASELINE.json")

DEFAULT_TOLERANCE = 0.15
# The serve gate's p99 tolerance rides the same 15% headroom; run-to-run p99
# noise beyond it means the batcher, not the scheduler, changed behaviour.
SERVE_TOLERANCE = 0.15
# Registry-on vs registry-off step cost: observability must stay effectively
# free.  3% is far above the real per-step instrument cost (two lock-guarded
# float adds, ~us against a ms-scale step) but below any change that put the
# registry on the wrong side of a dispatch or took its lock inside another.
METRICS_OVERHEAD_MAX = 0.03
FETCH_FACTOR = 3.0   # loose multiplicative gate for fetch_overhead_ms
FETCH_SLACK_MS = 5.0  # absolute slack on top of the factor
FETCH_ARM_MS = 1.0   # the fetch gate arms only at a meaningful baseline
# Warm-cache compile gate (--compile): the warm run's net XLA compile time
# (bench.py xla_compile_s — backend compile minus persistent-cache
# retrieval) must stay near zero.  The tolerance is generous (compile
# timing is noisier than step timing) plus an absolute slack; the
# self-relative check (warm vs the cold run measured in the same
# invocation) catches a cache that silently stopped serving even when the
# baseline entry is missing or stale.
COMPILE_TOLERANCE = 0.5
COMPILE_SLACK_S = 2.0
COMPILE_WARM_FRAC = 0.2  # warm must be < this fraction of cold


def run_bench(timeout_s: float = 600.0, extra_args=(), env_extra=None) -> dict:
    """Run bench.py on CPU and parse the last JSON line of its stdout."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", **(env_extra or {}))
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py"), *extra_args],
        cwd=_REPO, env=env, capture_output=True, text=True,
        timeout=timeout_s,
    )
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except ValueError:
            continue
    return {"error": f"bench.py produced no JSON (rc={proc.returncode})"}


def gate(result: dict, baseline: dict) -> dict:
    """Pure comparison: {'status': 'pass'|'fail'|'skip', 'reasons': [...]}.

    Separated from the subprocess plumbing so tests can gate canned results.
    """
    reasons = []
    if result.get("error") or not result.get("value"):
        return {"status": "fail",
                "reasons": [f"bench did not produce a valid measurement: "
                            f"{result.get('error', 'value=0')}"]}
    for key in ("backend", "global_batch"):
        if baseline.get(key) is not None and result.get(key) != baseline[key]:
            return {"status": "skip",
                    "reasons": [f"incomparable {key}: baseline "
                                f"{baseline[key]!r} vs measured "
                                f"{result.get(key)!r} — refresh the baseline "
                                "on this machine (--update-baseline)"]}
    tol = baseline.get("tolerance", DEFAULT_TOLERANCE)
    base_step = baseline.get("step_ms")
    step = result.get("step_ms")
    if base_step is None or step is None:
        return {"status": "skip",
                "reasons": ["no step_ms to compare (baseline entry missing "
                            "— record one with --update-baseline)"]}
    limit = base_step * (1.0 + tol)
    if step > limit:
        reasons.append(
            f"step_ms regressed: {step:.1f} > {limit:.1f} "
            f"(baseline {base_step:.1f} + {tol:.0%})")
    base_fetch = baseline.get("fetch_overhead_ms")
    fetch = result.get("fetch_overhead_ms")
    if (base_fetch is not None and fetch is not None
            and base_fetch >= FETCH_ARM_MS):
        # Below FETCH_ARM_MS the slope-intercept overhead estimate is pure
        # scheduler noise (observed 0 <-> 250 ms run to run on CPU); the
        # gate arms only when the baseline recorded a real overhead.
        fetch_limit = base_fetch * FETCH_FACTOR + FETCH_SLACK_MS
        if fetch > fetch_limit:
            reasons.append(
                f"fetch_overhead_ms collapsed: {fetch:.1f} > "
                f"{fetch_limit:.1f} (baseline {base_fetch:.1f})")
    if not reasons and step < base_step * (1.0 - tol):
        # Not a failure — but a silently stale baseline hides the *next*
        # regression inside the improvement's slack.
        reasons.append(
            f"note: step_ms improved {base_step:.1f} -> {step:.1f}; "
            "refresh the baseline to tighten the gate")
        return {"status": "pass", "reasons": reasons}
    return {"status": "fail" if reasons else "pass", "reasons": reasons}


def run_compile_pair(timeout_s: float = 900.0) -> dict:
    """Cold/warm compile measurement: bench.py twice against one fresh
    persistent-cache dir (``CIL_BENCH_CACHE_DIR``).  The first run pays the
    real XLA backend compile and populates the cache; the second must be
    served from it — its ``xla_compile_s`` is the number the compile gate
    hard-gates (trace-free restarts are the whole point of
    ``--compile_cache``)."""
    import shutil
    import tempfile

    cache = tempfile.mkdtemp(prefix="cil_compile_gate_")
    extra = ("--iters", "2", "--fused_n", "0", "--no_bf16")
    env = {"CIL_BENCH_CACHE_DIR": cache}
    try:
        cold = run_bench(timeout_s=timeout_s, extra_args=extra, env_extra=env)
        warm = run_bench(timeout_s=timeout_s, extra_args=extra, env_extra=env)
    finally:
        shutil.rmtree(cache, ignore_errors=True)
    result = {
        "metric": "compile_gate",
        "value": warm.get("xla_compile_s", 0.0),
        "unit": "s",
        "cold_compile_s": cold.get("xla_compile_s"),
        "warm_compile_s": warm.get("xla_compile_s"),
        "warm_cache_hits": warm.get("xla_cache_hits"),
        "backend": warm.get("backend"),
        "global_batch": warm.get("global_batch"),
    }
    for side, r in (("cold", cold), ("warm", warm)):
        if r.get("error"):
            result["error"] = f"{side} bench failed: {r['error']}"
    return result


def gate_compile(result: dict, baseline: dict) -> dict:
    """Compile gate: warm-cache net XLA compile time vs ``compile_gate``.

    Two independent checks (either trips the gate):

    * absolute/baseline — ``warm_compile_s`` above baseline × (1 + tol)
      + ``COMPILE_SLACK_S``; with no baseline entry the limit is the slack
      alone, so a cache that stopped serving fails even pre-baseline.
    * self-relative — warm above ``COMPILE_WARM_FRAC`` × cold (when the
      cold side measured a nontrivial compile): the warm run re-compiled a
      meaningful share of what the cold run built.
    """
    if result.get("error"):
        return {"status": "fail",
                "reasons": [f"compile bench did not produce a valid "
                            f"measurement: {result['error']}"]}
    warm = result.get("warm_compile_s")
    cold = result.get("cold_compile_s")
    if warm is None or cold is None:
        return {"status": "fail",
                "reasons": ["no cold/warm xla_compile_s in the bench result "
                            "(bench.py too old?)"]}
    for key in ("backend", "global_batch"):
        if baseline.get(key) is not None and result.get(key) != baseline[key]:
            return {"status": "skip",
                    "reasons": [f"incomparable {key}: baseline "
                                f"{baseline[key]!r} vs measured "
                                f"{result.get(key)!r} — refresh the baseline "
                                "on this machine (--compile "
                                "--update-baseline)"]}
    reasons = []
    tol = baseline.get("tolerance", COMPILE_TOLERANCE)
    base_warm = baseline.get("warm_compile_s")
    limit = (base_warm * (1.0 + tol) if base_warm is not None else 0.0
             ) + COMPILE_SLACK_S
    if warm > limit:
        reasons.append(
            f"warm-cache compile_s regressed: {warm:.2f} > {limit:.2f} "
            f"(baseline {base_warm if base_warm is not None else 0:.2f} "
            f"+ {tol:.0%} + {COMPILE_SLACK_S:g}s slack)")
    if cold > COMPILE_SLACK_S and warm > cold * COMPILE_WARM_FRAC:
        reasons.append(
            f"persistent cache not serving: warm compile_s {warm:.2f} > "
            f"{COMPILE_WARM_FRAC:.0%} of cold {cold:.2f} — the second run "
            "re-compiled what the first just cached")
    return {"status": "fail" if reasons else "pass", "reasons": reasons}


def _pick_p99(result: dict, baseline: dict, exact_key: str, hist_key: str):
    """Choose the p99 pair a serve gate compares.

    Prefers the registry-scraped histogram p99 when BOTH sides recorded one:
    the scraped series aggregates every request the server itself observed
    (the same ``/metrics`` ladder the fleet scraper reads), where a single
    bench run's exact percentile is one noisy sample.  Histogram values are
    quantized to the exponential ladder, so the caller gates them rung-based
    (one ``growth`` factor of slack) instead of the percentage tolerance —
    and a mixed exact-vs-hist comparison is never made, because the ladder's
    upper-bound bias would read as a fake regression.

    Returns ``(measured, base, key, growth)``; ``growth`` is None in exact
    mode.
    """
    if (result.get(hist_key) is not None
            and baseline.get(hist_key) is not None):
        growth = (baseline.get("hist_growth")
                  or result.get("hist_growth") or 2.0)
        return result[hist_key], baseline[hist_key], hist_key, growth
    return result.get(exact_key), baseline.get(exact_key), exact_key, None


def _p99_verdict(p99, base_p99, key: str, growth, tol: float, what: str):
    """Shared limit logic for both serve gates: rung-based when scraped,
    percentage-based when exact.  Returns (reasons, improved)."""
    reasons = []
    if growth is not None:
        limit = base_p99 * growth * 1.01  # one ladder rung of slack
        slack = f"one {growth:g}x rung above baseline {base_p99:.1f}"
        improved = p99 < base_p99 / growth * 0.99
    else:
        limit = base_p99 * (1.0 + tol)
        slack = f"baseline {base_p99:.1f} + {tol:.0%}"
        improved = p99 < base_p99 * (1.0 - tol)
    if p99 > limit:
        reasons.append(f"{what} {key} regressed: {p99:.1f} > {limit:.1f} "
                       f"({slack})")
    return reasons, improved


def gate_metrics_overhead(result: dict) -> dict:
    """Metrics-plane overhead gate: registry-on step cost vs registry-off.

    Self-relative (the paired bench measures both modes over the identical
    compiled step in one process), so there is no baseline entry to drift —
    the gate is the constant ``METRICS_OVERHEAD_MAX``.
    """
    if result.get("error"):
        return {"status": "fail",
                "reasons": [f"metrics-overhead bench did not produce a "
                            f"valid measurement: {result['error']}"]}
    overhead = result.get("overhead_frac")
    if overhead is None:
        return {"status": "fail",
                "reasons": ["no overhead_frac in the bench result"]}
    if overhead > METRICS_OVERHEAD_MAX:
        return {"status": "fail",
                "reasons": [
                    f"metrics registry overhead {overhead:.1%} exceeds "
                    f"{METRICS_OVERHEAD_MAX:.0%} (step_ms on/off: "
                    f"{result.get('step_ms_on')}/"
                    f"{result.get('step_ms_off')})"]}
    return {"status": "pass", "reasons": []}


def gate_serve(result: dict, baseline: dict) -> dict:
    """Serving gate: closed-loop p99 vs the ``serve_gate`` entry — the
    scraped ``hist_p99_ms`` when both sides have it, exact ``p99_ms``
    otherwise (see ``_pick_p99``)."""
    if result.get("error") or not result.get("value"):
        return {"status": "fail",
                "reasons": [f"serve bench did not produce a valid "
                            f"measurement: {result.get('error', 'value=0')}"]}
    if result.get("failed"):
        # Failed requests are a correctness bug, not a perf data point.
        return {"status": "fail",
                "reasons": [f"{result['failed']} request(s) failed during "
                            "the serve bench"]}
    for key in ("backend", "buckets", "max_wait_ms"):
        if baseline.get(key) is not None and result.get(key) != baseline[key]:
            return {"status": "skip",
                    "reasons": [f"incomparable {key}: baseline "
                                f"{baseline[key]!r} vs measured "
                                f"{result.get(key)!r} — refresh the baseline "
                                "on this machine (--serve --update-baseline)"]}
    tol = baseline.get("tolerance", SERVE_TOLERANCE)
    p99, base_p99, key, growth = _pick_p99(
        result, baseline, "p99_ms", "hist_p99_ms")
    if base_p99 is None or p99 is None:
        return {"status": "skip",
                "reasons": ["no p99_ms to compare (baseline entry missing — "
                            "record one with --serve --update-baseline)"]}
    reasons, improved = _p99_verdict(p99, base_p99, key, growth, tol, "serve")
    if not reasons and improved:
        reasons.append(
            f"note: serve {key} improved {base_p99:.1f} -> {p99:.1f}; "
            "refresh the baseline to tighten the gate")
        return {"status": "pass", "reasons": reasons}
    return {"status": "fail" if reasons else "pass", "reasons": reasons}


def gate_serve_overload(result: dict, baseline: dict) -> dict:
    """Overload gate: high-priority p99 vs the ``serve_overload_gate`` entry."""
    if result.get("error") or not result.get("value"):
        return {"status": "fail",
                "reasons": [f"overload bench did not produce a valid "
                            f"measurement: {result.get('error', 'value=0')}"]}
    if result.get("errors"):
        # Hard errors under overload are a resilience bug (shedding exists
        # precisely so overload degrades to 503s, never to failures).
        return {"status": "fail",
                "reasons": [f"{result['errors']} request(s) hard-failed "
                            "during the overload bench"]}
    for key in ("backend", "replicas", "pattern", "rps"):
        if baseline.get(key) is not None and result.get(key) != baseline[key]:
            return {"status": "skip",
                    "reasons": [f"incomparable {key}: baseline "
                                f"{baseline[key]!r} vs measured "
                                f"{result.get(key)!r} — refresh the baseline "
                                "on this machine (--serve-overload "
                                "--update-baseline)"]}
    tol = baseline.get("tolerance", SERVE_TOLERANCE)
    p99, base_p99, key, growth = _pick_p99(
        result, baseline, "p99_high_ms", "hist_p99_high_ms")
    if base_p99 is None or p99 is None:
        return {"status": "skip",
                "reasons": ["no p99_high_ms to compare (baseline entry "
                            "missing — record one with --serve-overload "
                            "--update-baseline)"]}
    reasons, improved = _p99_verdict(
        p99, base_p99, key, growth, tol, "overload")
    if not reasons and improved:
        reasons.append(
            f"note: overload {key} improved {base_p99:.1f} -> "
            f"{p99:.1f}; refresh the baseline to tighten the gate")
        return {"status": "pass", "reasons": reasons}
    return {"status": "fail" if reasons else "pass", "reasons": reasons}


def load_baseline(path: str = _BASELINE) -> dict:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def update_baseline(result: dict, path: str = _BASELINE,
                    serve: bool = False, overload: bool = False,
                    compile_: bool = False) -> dict:
    doc = load_baseline(path)
    if compile_:
        entry = {
            "warm_compile_s": result.get("warm_compile_s"),
            "cold_compile_s": result.get("cold_compile_s"),
            "backend": result.get("backend"),
            "global_batch": result.get("global_batch"),
            "tolerance": COMPILE_TOLERANCE,
            "recorded_ts": round(time.time(), 3),
        }
        doc["compile_gate"] = entry
    elif overload:
        entry = {
            "p99_high_ms": result.get("p99_high_ms"),
            "hist_p99_high_ms": result.get("hist_p99_high_ms"),
            "hist_growth": result.get("hist_growth"),
            "backend": result.get("backend"),
            "replicas": result.get("replicas"),
            "pattern": result.get("pattern"),
            "rps": result.get("rps"),
            "capacity": result.get("capacity"),
            "tolerance": SERVE_TOLERANCE,
            "recorded_ts": round(time.time(), 3),
        }
        doc["serve_overload_gate"] = entry
    elif serve:
        entry = {
            "p99_ms": result.get("p99_ms"),
            "hist_p99_ms": result.get("hist_p99_ms"),
            "hist_growth": result.get("hist_growth"),
            "p50_ms": result.get("p50_ms"),
            "req_s": result.get("value"),
            "backend": result.get("backend"),
            "buckets": result.get("buckets"),
            "max_wait_ms": result.get("max_wait_ms"),
            "tolerance": SERVE_TOLERANCE,
            "recorded_ts": round(time.time(), 3),
        }
        doc["serve_gate"] = entry
    else:
        entry = {
            "step_ms": result.get("step_ms"),
            "fetch_overhead_ms": result.get("fetch_overhead_ms"),
            "backend": result.get("backend"),
            "global_batch": result.get("global_batch"),
            "img_s": result.get("value"),
            "tolerance": DEFAULT_TOLERANCE,
            "recorded_ts": round(time.time(), 3),
        }
        doc["bench_gate"] = entry
    with open(path, "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    return entry


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--update-baseline", action="store_true",
                   help="run bench.py and write its numbers as the new "
                   "bench_gate entry instead of gating")
    p.add_argument("--serve", action="store_true",
                   help="gate the serving bench (bench.py --serve) against "
                   "the serve_gate entry instead of the train step")
    p.add_argument("--serve-overload", action="store_true",
                   help="gate the fleet overload bench (bench.py --serve "
                   "--serve_pattern bursty) against serve_overload_gate")
    p.add_argument("--compile", action="store_true", dest="compile_",
                   help="gate the warm-cache compile cost (bench.py twice "
                   "against one fresh persistent cache dir) against the "
                   "compile_gate entry — trace-free restarts must stay "
                   "trace-free")
    p.add_argument("--metrics-overhead", action="store_true",
                   help="gate the metrics-plane cost (bench.py --metrics "
                   "paired) against the fixed 3%% registry-on vs "
                   "registry-off budget — no baseline entry involved")
    p.add_argument("--result", default=None,
                   help="gate this JSON result instead of running bench.py "
                   "(tests / canned measurements)")
    p.add_argument("--baseline", default=_BASELINE,
                   help="path to BASELINE.json")
    args = p.parse_args(argv)

    if args.compile_:
        extra = ()
        entry_key = "compile_gate"
    elif args.metrics_overhead:
        extra = ("--metrics", "paired",
                 "--step_path_epochs", "1", "--step_path_steps", "4")
        entry_key = "metrics_overhead_gate"
    elif args.serve_overload:
        # Fixed args so the recorded baseline stays comparable run to run.
        extra = ("--serve", "--serve_pattern", "bursty", "--serve_rps", "40",
                 "--serve_duration_s", "3", "--serve_buckets", "1,8")
        entry_key = "serve_overload_gate"
    elif args.serve:
        extra = ("--serve",)
        entry_key = "serve_gate"
    else:
        extra = ()
        entry_key = "bench_gate"
    result = (json.loads(args.result) if args.result
              else run_compile_pair() if args.compile_
              else run_bench(extra_args=extra))
    if args.metrics_overhead:
        # Self-relative gate: no baseline entry, no --update-baseline.
        verdict = gate_metrics_overhead(result)
        print(json.dumps({
            "metric": "perf_gate",
            "gate": entry_key,
            "status": verdict["status"],
            "reasons": verdict["reasons"],
            "measured": {k: result.get(k) for k in
                         ("overhead_frac", "step_ms_on", "step_ms_off",
                          "passes", "backend")},
            "budget": METRICS_OVERHEAD_MAX,
        }))
        return 1 if verdict["status"] == "fail" else 0
    if args.update_baseline:
        entry = update_baseline(result, args.baseline, serve=args.serve,
                                overload=args.serve_overload,
                                compile_=args.compile_)
        print(json.dumps({"metric": "perf_gate", "status": "updated",
                          entry_key: entry}))
        return 0 if not result.get("error") else 1
    baseline = load_baseline(args.baseline).get(entry_key, {})
    if args.compile_:
        verdict = gate_compile(result, baseline)
        measured_keys = ("warm_compile_s", "cold_compile_s",
                         "warm_cache_hits", "backend", "global_batch")
    elif args.serve_overload:
        verdict = gate_serve_overload(result, baseline)
        measured_keys = ("p99_high_ms", "hist_p99_high_ms", "value",
                         "errors", "backend", "replicas", "pattern", "rps",
                         "capacity")
    elif args.serve:
        verdict = gate_serve(result, baseline)
        measured_keys = ("p99_ms", "hist_p99_ms", "p50_ms", "value",
                         "failed", "backend", "buckets", "max_wait_ms")
    else:
        verdict = gate(result, baseline)
        measured_keys = ("step_ms", "fetch_overhead_ms", "value", "backend",
                         "global_batch")
    print(json.dumps({
        "metric": "perf_gate",
        "gate": entry_key,
        "status": verdict["status"],
        "reasons": verdict["reasons"],
        "measured": {k: result.get(k) for k in measured_keys},
        "baseline": baseline or None,
    }))
    return 1 if verdict["status"] == "fail" else 0


if __name__ == "__main__":
    sys.exit(main())
