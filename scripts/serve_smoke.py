#!/usr/bin/env python
"""CPU serving smoke: train → export → serve → hot-swap under fire.

The acceptance proof for the serving subsystem, end to end:

1. Run a tiny 2-task synthetic protocol with ``--export_dir``: the trainer
   freezes + AOT-exports an artifact after each task's weight alignment
   (plus a ``serve_skew`` self-check through the reloaded artifact).
2. Stage a serving directory containing only task 0 and start an
   ``InferenceServer`` over it with ``swap_ioerror@task1`` armed, driving
   continuous traffic from a client thread.
3. Publish task 1 into the serving directory mid-traffic.  The first swap
   attempt hits the injected IOError: the server must emit
   ``serve_swap_failed`` and KEEP serving task 0 — graceful degradation,
   zero dropped requests.  The clause is one-shot, so the next manifest
   poll swaps cleanly and responses flip to task 1.
4. Assert the bit-identity contract both ways: every bucket's exported
   program reproduces a freshly rebuilt flax model's logits exactly (pre-
   and post-swap artifacts), and a quiet-server response matches the direct
   call for the same image.
5. Assert zero traces on the serving hot path (``trace_count() == 0`` —
   queries only ever run AOT-compiled executables) and that every telemetry
   file the run produced passes the schema lint.

Exit 0 when all of it holds, 1 otherwise, one JSON line either way.
Used by ``scripts/ci.sh``; runnable standalone from anywhere.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

_PROTO = [
    "--platform", "cpu",
    "--data_set", "synthetic10",
    "--num_bases", "0",
    "--increment", "5",
    "--backbone", "resnet20",
    "--batch_size", "16",
    "--num_epochs", "1",
    "--eval_every_epoch", "100",
    "--memory_size", "40",
    "--lr", "0.05",
    "--aa", "none",
    "--color_jitter", "0.0",
    "--seed", "7",
    "--no_fused_epochs",
    "--serve_buckets", "1,8",
    "--serve_skew_check",
    "--compile_cache", os.path.join(_REPO, "tests", ".jax_cache"),
]


def _records(path):
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def main() -> int:  # noqa: C901 — one linear scenario, asserted densely
    failures = []
    with tempfile.TemporaryDirectory(prefix="serve_smoke_") as tmp:
        export_dir = os.path.join(tmp, "export")
        train_log = os.path.join(tmp, "train.jsonl")
        train_cmd = [sys.executable, os.path.join(_REPO, "train.py"),
                     *_PROTO, "--export_dir", export_dir,
                     "--log_file", train_log, "--check_threads"]
        train = subprocess.run(train_cmd, cwd=_REPO, timeout=900)
        if train.returncode != 0:
            print(json.dumps({"metric": "serve_smoke", "ok": False,
                              "failures":
                              [f"train run failed rc={train.returncode}"]}))
            return 1

        # The trainer must have exported both tasks and self-checked skew.
        train_recs = _records(train_log)
        exports = [r for r in train_recs if r.get("type") == "serve_export"
                   and not r.get("error")]
        if len(exports) != 2:
            failures.append(f"expected 2 serve_export records, got {exports}")
        skews = [r for r in train_recs if r.get("type") == "serve_skew"]
        if len(skews) != 2 or any(s.get("skew_abs_max") not in (0, 0.0)
                                  for s in skews):
            failures.append(
                f"serve_skew must report exactly-zero skew per task: {skews}")

        # Late imports: force_platform must happen via train.py's children
        # only; this process configures JAX itself.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from a_pytorch_tutorial_to_class_incremental_learning_tpu.utils.logging import (  # noqa: E501
            JsonlLogger,
        )
        from a_pytorch_tutorial_to_class_incremental_learning_tpu.utils.platform import (  # noqa: E501
            force_platform,
        )
        from faults.injector import FaultInjector, parse_fault_spec
        from serving import (
            InferenceServer,
            direct_predict,
            load_artifact,
            read_manifest,
            register_artifact,
        )

        force_platform(
            "cpu",
            compile_cache_dir=os.path.join(_REPO, "tests", ".jax_cache"),
        )
        import numpy as np

        man = read_manifest(export_dir)
        if sorted(man.get("artifacts", {})) != ["0", "1"]:
            failures.append(f"manifest lacks both artifacts: {man}")
            print(json.dumps({"metric": "serve_smoke", "ok": False,
                              "failures": failures}))
            return 1

        # Bit-identity per artifact x bucket: the exported program vs a
        # freshly rebuilt (tracing) flax model over the same weights.
        rng = np.random.RandomState(0)
        for t in ("0", "1"):
            apath = os.path.join(export_dir, man["artifacts"][t]["path"])
            art = load_artifact(apath)
            for bucket in art.buckets:
                x = rng.randint(0, 256, (bucket, 32, 32, 3)).astype(np.uint8)
                served = art.predict_padded(x, bucket)
                direct = direct_predict(apath, x)
                if not np.array_equal(served, direct):
                    failures.append(
                        f"task {t} bucket {bucket}: exported logits "
                        "differ from the direct model call")

        # Stage a serving dir holding only task 0, then serve under fire.
        serve_dir = os.path.join(tmp, "serve")
        os.makedirs(serve_dir)
        shutil.copytree(os.path.join(export_dir, "task_000"),
                        os.path.join(serve_dir, "task_000"))
        register_artifact(serve_dir, 0, {"path": "task_000"})

        # The whole serve-under-fire scenario runs under the ThreadCheck
        # sentinel: the server's lock (created below, post-install) is
        # instrumented, and any lock-order inversion or lock-held blocking
        # on the batcher/watcher/client threads emits thread_violation.
        from analysis import threadcheck

        check = threadcheck.install()

        serve_log = os.path.join(tmp, "serve.jsonl")
        sink = JsonlLogger(serve_log)
        check.bind_sink(sink)
        inj = FaultInjector(
            parse_fault_spec("swap_ioerror@task1"),
            ledger_path=os.path.join(tmp, "fault_ledger.jsonl"),
            sink=sink,
        )
        server = InferenceServer(
            serve_dir, max_wait_ms=2.0, poll_s=0.1, sink=sink, faults=inj,
        ).start()

        results, errors = [], []
        stop_traffic = threading.Event()

        def traffic() -> None:
            img = rng.randint(0, 256, (32, 32, 3)).astype(np.uint8)
            while not stop_traffic.is_set():
                try:
                    results.append(server.submit(img).result(timeout=60))
                except Exception as e:  # noqa: BLE001 — recorded, asserted ==0
                    errors.append(repr(e))

        client = threading.Thread(target=traffic)
        client.start()
        try:
            time.sleep(0.5)  # traffic against task 0 first
            # Publish task 1 mid-traffic.  First poll trips swap_ioerror,
            # second swaps cleanly.
            shutil.copytree(os.path.join(export_dir, "task_001"),
                            os.path.join(serve_dir, "task_001"))
            register_artifact(serve_dir, 1, {"path": "task_001"})
            deadline = time.time() + 30
            while time.time() < deadline and server.task_id != 1:
                time.sleep(0.1)
            time.sleep(0.5)  # traffic against task 1 after the swap
        finally:
            stop_traffic.set()
            client.join()
            server.stop()

        stats = server.stats()
        if errors or stats["failed"]:
            failures.append(
                f"dropped/failed requests: errors={errors[:3]} "
                f"failed={stats['failed']}")
        task_ids = [r["task_id"] for r in results]
        if not (task_ids and task_ids[0] == 0 and task_ids[-1] == 1
                and sorted(set(task_ids)) == [0, 1]):
            failures.append(
                f"responses did not transition 0 -> 1: {sorted(set(task_ids))}")
        if stats["swap_failures"] != 1:
            failures.append(
                f"expected exactly 1 failed swap, got {stats['swap_failures']}")
        if server.trace_count() != 0:
            failures.append(
                f"serving hot path traced {server.trace_count()} program(s); "
                "queries must only run AOT executables")

        serve_recs = _records(serve_log)
        kinds = [r.get("type") for r in serve_recs]
        if "serve_swap_failed" not in kinds:
            failures.append(f"no serve_swap_failed record: {kinds}")
        swaps = [r for r in serve_recs if r.get("type") == "serve_swap"]
        if [s.get("to_task") for s in swaps] != [0, 1]:
            failures.append(f"serve_swap sequence wrong: {swaps}")

        # Through-the-server bit-identity: a quiet server batches a lone
        # request at bucket 1, so the response must equal the direct call.
        server2 = InferenceServer(serve_dir, max_wait_ms=0.0, sink=sink).start()
        try:
            probe = rng.randint(0, 256, (32, 32, 3)).astype(np.uint8)
            res = server2.submit(probe).result(timeout=60)
            direct = direct_predict(
                os.path.join(serve_dir, "task_001"), probe[None]
            )
            if not (res["task_id"] == 1
                    and np.array_equal(res["logits"], direct[0])):
                failures.append(
                    "server response logits differ from the direct model call")
        finally:
            server2.stop()

        # Hot-swap under fire must have been lock-discipline clean: zero
        # thread_violation records (and none in the training child's log —
        # it ran under --check_threads too).
        threadcheck.uninstall()
        tviol = [r for r in _records(serve_log) + train_recs
                 if r.get("type") == "thread_violation"]
        if check.violations or tviol:
            failures.append(
                f"ThreadCheck violations under traffic: "
                f"{(check.violations + tviol)[:3]}")

        # Every telemetry stream the scenario produced must pass the lint.
        lint = subprocess.run(
            [sys.executable,
             os.path.join(_REPO, "scripts", "check_telemetry_schema.py"),
             train_log, serve_log],
            cwd=_REPO, timeout=120, capture_output=True, text=True)
        if lint.returncode != 0:
            failures.append(
                f"schema lint failed on smoke telemetry: {lint.stdout.strip()} "
                f"{lint.stderr.strip()}")

        print(json.dumps({
            "metric": "serve_smoke",
            "ok": not failures,
            "failures": failures,
            "served": stats["served"],
            "swaps": stats["swaps"],
            "swap_failures": stats["swap_failures"],
            "task_transition": sorted(set(task_ids)),
            "trace_count": server.trace_count(),
        }))
        return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
