#!/usr/bin/env python
"""CPU serving smoke: train → export → serve → hot-swap under fire.

The acceptance proof for the serving subsystem, end to end:

1. Run a tiny 2-task synthetic protocol with ``--export_dir``: the trainer
   freezes + AOT-exports an artifact after each task's weight alignment
   (plus a ``serve_skew`` self-check through the reloaded artifact).
2. Stage a serving directory containing only task 0 and start an
   ``InferenceServer`` over it with ``swap_ioerror@task1`` armed, driving
   continuous traffic from a client thread.
3. Publish task 1 into the serving directory mid-traffic.  The first swap
   attempt hits the injected IOError: the server must emit
   ``serve_swap_failed`` and KEEP serving task 0 — graceful degradation,
   zero dropped requests.  The clause is one-shot, so the next manifest
   poll swaps cleanly and responses flip to task 1.
4. Assert the bit-identity contract both ways: every bucket's exported
   program reproduces a freshly rebuilt flax model's logits exactly (pre-
   and post-swap artifacts), and a quiet-server response matches the direct
   call for the same image.
5. Assert zero traces on the serving hot path (``trace_count() == 0`` —
   queries only ever run AOT-compiled executables) and that every telemetry
   file the run produced passes the schema lint.

``--fleet`` runs the resilience-tier chaos smoke instead: three supervised
replica subprocesses (``scripts/supervise.py`` relaunch machinery) behind an
in-process ``serving.Frontend`` under live bursty two-priority traffic.
Mid-traffic, one replica is SIGKILL'd (ejected by the breaker, relaunched by
its supervisor, re-admitted after the warm-up probe) and a new task is
published with ``swap_ioerror@task1`` armed on one replica: the rolling swap
must roll back on that replica only (``serve_rollback``), halt the wave, and
converge on the retry.  The acceptance bar: ZERO failed client requests
(503 sheds are the admission policy working, not failures), at least one
``serve_shed`` and one ``serve_rollback`` record, an eject/readmit cycle for
the killed replica, every replica finishing on the new task with
``trace_count() == 0``, zero ThreadCheck violations, and schema-clean
telemetry throughout.

Exit 0 when all of it holds, 1 otherwise, one JSON line either way.
Used by ``scripts/ci.sh``; runnable standalone from anywhere.
"""

from __future__ import annotations

import http.client
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

_PROTO = [
    "--platform", "cpu",
    "--data_set", "synthetic10",
    "--num_bases", "0",
    "--increment", "5",
    "--backbone", "resnet20",
    "--batch_size", "16",
    "--num_epochs", "1",
    "--eval_every_epoch", "100",
    "--memory_size", "40",
    "--lr", "0.05",
    "--aa", "none",
    "--color_jitter", "0.0",
    "--seed", "7",
    "--no_fused_epochs",
    "--serve_buckets", "1,8",
    "--serve_skew_check",
    "--compile_cache", os.path.join(_REPO, "tests", ".jax_cache"),
]


def _records(path):
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def main() -> int:  # noqa: C901 — one linear scenario, asserted densely
    failures = []
    with tempfile.TemporaryDirectory(prefix="serve_smoke_") as tmp:
        export_dir = os.path.join(tmp, "export")
        train_log = os.path.join(tmp, "train.jsonl")
        train_cmd = [sys.executable, os.path.join(_REPO, "train.py"),
                     *_PROTO, "--export_dir", export_dir,
                     "--log_file", train_log, "--check_threads",
                     "--check_contracts"]
        train = subprocess.run(train_cmd, cwd=_REPO, timeout=900)
        if train.returncode != 0:
            print(json.dumps({"metric": "serve_smoke", "ok": False,
                              "failures":
                              [f"train run failed rc={train.returncode}"]}))
            return 1

        # The trainer must have exported both tasks and self-checked skew.
        train_recs = _records(train_log)
        exports = [r for r in train_recs if r.get("type") == "serve_export"
                   and not r.get("error")]
        if len(exports) != 2:
            failures.append(f"expected 2 serve_export records, got {exports}")
        skews = [r for r in train_recs if r.get("type") == "serve_skew"]
        if len(skews) != 2 or any(s.get("skew_abs_max") not in (0, 0.0)
                                  for s in skews):
            failures.append(
                f"serve_skew must report exactly-zero skew per task: {skews}")

        # Late imports: force_platform must happen via train.py's children
        # only; this process configures JAX itself.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from a_pytorch_tutorial_to_class_incremental_learning_tpu.utils.logging import (  # noqa: E501
            JsonlLogger,
        )
        from a_pytorch_tutorial_to_class_incremental_learning_tpu.utils.platform import (  # noqa: E501
            force_platform,
        )
        from faults.injector import FaultInjector, parse_fault_spec
        from serving import (
            InferenceServer,
            direct_predict,
            load_artifact,
            read_manifest,
            register_artifact,
        )

        force_platform(
            "cpu",
            compile_cache_dir=os.path.join(_REPO, "tests", ".jax_cache"),
        )
        import numpy as np

        man = read_manifest(export_dir)
        if sorted(man.get("artifacts", {})) != ["0", "1"]:
            failures.append(f"manifest lacks both artifacts: {man}")
            print(json.dumps({"metric": "serve_smoke", "ok": False,
                              "failures": failures}))
            return 1

        # Bit-identity per artifact x bucket: the exported program vs a
        # freshly rebuilt (tracing) flax model over the same weights.
        rng = np.random.RandomState(0)
        for t in ("0", "1"):
            apath = os.path.join(export_dir, man["artifacts"][t]["path"])
            art = load_artifact(apath)
            for bucket in art.buckets:
                x = rng.randint(0, 256, (bucket, 32, 32, 3)).astype(np.uint8)
                served = art.predict_padded(x, bucket)
                direct = direct_predict(apath, x)
                if not np.array_equal(served, direct):
                    failures.append(
                        f"task {t} bucket {bucket}: exported logits "
                        "differ from the direct model call")

        # Stage a serving dir holding only task 0, then serve under fire.
        serve_dir = os.path.join(tmp, "serve")
        os.makedirs(serve_dir)
        shutil.copytree(os.path.join(export_dir, "task_000"),
                        os.path.join(serve_dir, "task_000"))
        register_artifact(serve_dir, 0, {"path": "task_000"})

        # The whole serve-under-fire scenario runs under the ThreadCheck
        # sentinel: the server's lock (created below, post-install) is
        # instrumented, and any lock-order inversion or lock-held blocking
        # on the batcher/watcher/client threads emits thread_violation.
        # The ContractCheck sentinel rides along: every record the server
        # emits is validated against the committed contract registry.
        from analysis import contractcheck, threadcheck

        check = threadcheck.install()
        contracts = contractcheck.install()

        serve_log = os.path.join(tmp, "serve.jsonl")
        sink = contractcheck.wrap_sink(JsonlLogger(serve_log))
        check.bind_sink(sink)
        contracts.bind_sink(sink)
        inj = FaultInjector(
            parse_fault_spec("swap_ioerror@task1"),
            ledger_path=os.path.join(tmp, "fault_ledger.jsonl"),
            sink=sink,
        )
        server = InferenceServer(
            serve_dir, max_wait_ms=2.0, poll_s=0.1, sink=sink, faults=inj,
        ).start()

        results, errors = [], []
        stop_traffic = threading.Event()

        def traffic() -> None:
            img = rng.randint(0, 256, (32, 32, 3)).astype(np.uint8)
            while not stop_traffic.is_set():
                try:
                    results.append(server.submit(img).result(timeout=60))
                except Exception as e:  # noqa: BLE001 — recorded, asserted ==0
                    errors.append(repr(e))

        client = threading.Thread(target=traffic)
        client.start()
        try:
            time.sleep(0.5)  # traffic against task 0 first
            # Publish task 1 mid-traffic.  First poll trips swap_ioerror,
            # second swaps cleanly.
            shutil.copytree(os.path.join(export_dir, "task_001"),
                            os.path.join(serve_dir, "task_001"))
            register_artifact(serve_dir, 1, {"path": "task_001"})
            deadline = time.time() + 30
            while time.time() < deadline and server.task_id != 1:
                time.sleep(0.1)
            time.sleep(0.5)  # traffic against task 1 after the swap
        finally:
            stop_traffic.set()
            client.join()
            server.stop()

        stats = server.stats()
        if errors or stats["failed"]:
            failures.append(
                f"dropped/failed requests: errors={errors[:3]} "
                f"failed={stats['failed']}")
        task_ids = [r["task_id"] for r in results]
        if not (task_ids and task_ids[0] == 0 and task_ids[-1] == 1
                and sorted(set(task_ids)) == [0, 1]):
            failures.append(
                f"responses did not transition 0 -> 1: {sorted(set(task_ids))}")
        if stats["swap_failures"] != 1:
            failures.append(
                f"expected exactly 1 failed swap, got {stats['swap_failures']}")
        if server.trace_count() != 0:
            failures.append(
                f"serving hot path traced {server.trace_count()} program(s); "
                "queries must only run AOT executables")

        serve_recs = _records(serve_log)
        kinds = [r.get("type") for r in serve_recs]
        if "serve_swap_failed" not in kinds:
            failures.append(f"no serve_swap_failed record: {kinds}")
        swaps = [r for r in serve_recs if r.get("type") == "serve_swap"]
        if [s.get("to_task") for s in swaps] != [0, 1]:
            failures.append(f"serve_swap sequence wrong: {swaps}")

        # Through-the-server bit-identity: a quiet server batches a lone
        # request at bucket 1, so the response must equal the direct call.
        server2 = InferenceServer(serve_dir, max_wait_ms=0.0, sink=sink).start()
        try:
            probe = rng.randint(0, 256, (32, 32, 3)).astype(np.uint8)
            res = server2.submit(probe).result(timeout=60)
            direct = direct_predict(
                os.path.join(serve_dir, "task_001"), probe[None]
            )
            if not (res["task_id"] == 1
                    and np.array_equal(res["logits"], direct[0])):
                failures.append(
                    "server response logits differ from the direct model call")
        finally:
            server2.stop()

        # Hot-swap under fire must have been lock-discipline clean: zero
        # thread_violation records (and none in the training child's log —
        # it ran under --check_threads too).
        threadcheck.uninstall()
        contractcheck.uninstall()
        serve_recs = _records(serve_log)
        tviol = [r for r in serve_recs + train_recs
                 if r.get("type") == "thread_violation"]
        if check.violations or tviol:
            failures.append(
                f"ThreadCheck violations under traffic: "
                f"{(check.violations + tviol)[:3]}")

        # ... and contract-discipline clean: every record both processes
        # emitted matched the committed registry vocabulary.
        cviol = [r for r in serve_recs + train_recs
                 if r.get("type") == "contract_violation"]
        if contracts.violations or cviol:
            failures.append(
                f"ContractCheck violations under traffic: "
                f"{(contracts.violations + cviol)[:3]}")

        # Every telemetry stream the scenario produced must pass the lint.
        lint = subprocess.run(
            [sys.executable,
             os.path.join(_REPO, "scripts", "check_telemetry_schema.py"),
             train_log, serve_log],
            cwd=_REPO, timeout=120, capture_output=True, text=True)
        if lint.returncode != 0:
            failures.append(
                f"schema lint failed on smoke telemetry: {lint.stdout.strip()} "
                f"{lint.stderr.strip()}")

        print(json.dumps({
            "metric": "serve_smoke",
            "ok": not failures,
            "failures": failures,
            "served": stats["served"],
            "swaps": stats["swaps"],
            "swap_failures": stats["swap_failures"],
            "task_transition": sorted(set(task_ids)),
            "trace_count": server.trace_count(),
        }))
        return 0 if not failures else 1


# --------------------------------------------------------------------- #
# Fleet chaos smoke (--fleet)
# --------------------------------------------------------------------- #


def _free_ports(n):
    """Pick n distinct free ports (bind-then-close; replicas rebind them)."""
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _get_json(port, path, timeout=3.0):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())
    finally:
        conn.close()


def fleet_main() -> int:  # noqa: C901 — one linear chaos scenario
    failures = []
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.utils.platform import (  # noqa: E501
        force_platform,
    )

    cache_dir = os.path.join(_REPO, "tests", ".jax_cache")
    force_platform("cpu", compile_cache_dir=cache_dir)
    import jax
    import numpy as np

    from a_pytorch_tutorial_to_class_incremental_learning_tpu.data.augment import (  # noqa: E501
        AugmentConfig,
    )
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.models import (
        create_model,
        grow,
    )
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.telemetry.metrics import (  # noqa: E501
        MetricsPump,
        MetricsRegistry,
    )
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.utils.logging import (  # noqa: E501
        JsonlLogger,
    )
    from serving import Frontend, register_artifact
    from serving.artifact import export_artifact
    from serving.replica import decode_logits, encode_image, supervised_replica_cmd

    N = 3
    FAULT_REPLICA = 0  # refuses its first swap to task 1 (swap_ioerror)
    KILL_REPLICA = 2   # SIGKILL'd mid-traffic; supervisor relaunches it

    with tempfile.TemporaryDirectory(prefix="serve_fleet_") as tmp:
        # Two artifacts exported in-process (the train->export path is the
        # single-server smoke's job; this one is about the fleet).
        export_dir = os.path.join(tmp, "export")
        os.makedirs(export_dir)

        def _export(task_id, known, seed):
            model, variables = create_model("resnet20", 10)
            variables = grow(variables, jax.random.PRNGKey(seed), 0, known)
            export_artifact(
                export_dir, task_id, model, AugmentConfig(),
                variables["params"], variables["batch_stats"],
                known=known, class_order=list(range(10)),
                input_size=32, channels=3, buckets=(1, 8),
                model_meta={"backbone": "resnet20", "width": 10,
                            "compute_dtype": "float32", "bn_group_size": 0},
            )

        _export(0, 5, 0)
        _export(1, 10, 1)

        # The shared serving store starts with task 0 only; task 1 is
        # published mid-traffic to trigger the rolling swap.
        serve_dir = os.path.join(tmp, "serve")
        os.makedirs(serve_dir)
        shutil.copytree(os.path.join(export_dir, "task_000"),
                        os.path.join(serve_dir, "task_000"))
        register_artifact(serve_dir, 0, {"path": "task_000"})

        tdir = os.path.join(tmp, "telemetry")
        ports = _free_ports(N)
        procs, consoles = [], []
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   JAX_COMPILATION_CACHE_DIR=cache_dir)
        try:
            for i in range(N):
                rdir = os.path.join(tdir, f"replica_{i}")
                os.makedirs(rdir, exist_ok=True)
                cmd = supervised_replica_cmd(
                    _REPO, serve_dir, i, ports[i], tdir,
                    fault_spec=("swap_ioerror@task1" if i == FAULT_REPLICA
                                else None),
                    check_threads=True,
                    check_contracts=True,
                )
                console = open(os.path.join(rdir, "console.log"), "wb")
                consoles.append(console)
                procs.append(subprocess.Popen(
                    cmd, cwd=_REPO, env=env, start_new_session=True,
                    stdout=console, stderr=subprocess.STDOUT,
                ))

            # Fleet warm-up: every replica must answer /healthz warm before
            # traffic starts (cold replicas would read as chaos, not serve it).
            warm = set()
            deadline = time.time() + 300
            while time.time() < deadline and len(warm) < N:
                for i in range(N):
                    if i in warm:
                        continue
                    try:
                        st, info = _get_json(ports[i], "/healthz")
                        if st == 200 and info.get("warm"):
                            warm.add(i)
                    except (OSError, ValueError):
                        pass
                time.sleep(0.5)
            if len(warm) < N:
                print(json.dumps({
                    "metric": "serve_fleet_smoke", "ok": False,
                    "failures": [f"replicas never warmed: {sorted(warm)}"]}))
                return 1
            st, info = _get_json(ports[KILL_REPLICA], "/healthz")
            victim_pid = info["pid"]

            # Everything from here runs under the ThreadCheck sentinel: the
            # front end's locks are created post-install, so any lock held
            # across a socket read / future wait in the routing, breaker,
            # hedging or rollout paths emits thread_violation.  The
            # ContractCheck sentinel rides along and validates every record
            # and metric registration against the committed registry.
            from analysis import contractcheck, threadcheck

            check = threadcheck.install()
            contracts = contractcheck.install()
            fe_log = os.path.join(tmp, "frontend.jsonl")
            sink = contractcheck.wrap_sink(JsonlLogger(fe_log))
            check.bind_sink(sink)
            contracts.bind_sink(sink)
            # The front end's registry pumps metrics_snapshot records into
            # fe_log — the snapshot-file path of the fleet scraper, merged
            # with the replicas' live /metrics expositions below.
            fe_metrics = contractcheck.wrap_registry(MetricsRegistry())
            fe_pump = MetricsPump(fe_metrics, sink, interval_s=1.0,
                                  source="frontend")
            fe_pump.start()
            fe = Frontend(
                [("127.0.0.1", p) for p in ports],
                capacity=6, low_watermark=2,       # tight: bursts must shed
                default_deadline_ms=15000.0,
                max_attempts=5, retry_backoff_s=0.02,
                hedge_ms=250.0,
                error_threshold=3,
                heartbeat_max_age_s=8.0,
                heartbeat_paths=[
                    os.path.join(tdir, f"replica_{i}", "heartbeat.json")
                    for i in range(N)],
                probe_s=0.5,
                export_dir=serve_dir, rollout_poll_s=1.0,
                sink=sink,
                metrics=fe_metrics,
            ).start()

            # Fleet scraper sidecar: polls every replica's /metrics plus the
            # front end's snapshot stream, merges them, and evaluates one
            # shed-rate SLO.  Overload shedding is continuous in this smoke
            # (capacity 6 against 10 hammering clients), so the edge-
            # triggered monitor must fire exactly once and stay active.
            agent_out = os.path.join(tmp, "fleet_metrics.jsonl")
            shed_slo = {
                "name": "fleet-shed", "bad": "fe_shed_total",
                "total": "fe_requests_total", "objective": 0.999,
                "window_s": 30.0, "short_window_s": 5.0,
                "threshold": 0.05, "severity": "ticket",
            }
            agent_cmd = [
                sys.executable,
                os.path.join(_REPO, "scripts", "metrics_agent.py"),
                "--out", agent_out, "--interval_s", "1.0",
                "--train-log", fe_log, "--slo", json.dumps(shed_slo),
            ]
            for port in ports:
                agent_cmd += ["--replica", f"127.0.0.1:{port}"]
            agent_console = open(os.path.join(tmp, "agent_console.log"), "wb")
            agent_proc = subprocess.Popen(
                agent_cmd, cwd=_REPO, stdout=agent_console,
                stderr=subprocess.STDOUT)

            results = {"high": [], "low": []}
            sheds = {"high": 0, "low": 0}
            hard_failures = []
            first_payload = []
            res_lock = threading.Lock()
            stop_traffic = threading.Event()
            body = encode_image(np.random.RandomState(0).randint(
                0, 256, (32, 32, 3)).astype(np.uint8))

            def client(priority):
                while not stop_traffic.is_set():
                    conn = http.client.HTTPConnection(
                        "127.0.0.1", fe.port, timeout=30.0)
                    try:
                        conn.request("POST", "/predict", body=body, headers={
                            "Content-Type": "application/octet-stream",
                            "X-Priority": priority,
                            "X-Deadline-Ms": "15000",
                        })
                        resp = conn.getresponse()
                        payload = resp.read()
                        with res_lock:
                            if resp.status == 200:
                                results[priority].append(
                                    int(resp.getheader("X-Task-Id")))
                                if not first_payload:
                                    first_payload.append(payload)
                            elif resp.status == 503:
                                # A shed is the admission policy doing its
                                # job under overload — never a failure.
                                sheds[priority] += 1
                            else:
                                hard_failures.append(
                                    (priority, resp.status,
                                     payload[:120].decode("ascii", "replace")))
                    except Exception as e:  # noqa: BLE001 — asserted == 0
                        with res_lock:
                            hard_failures.append((priority, "exc", repr(e)))
                    finally:
                        conn.close()
                    if priority == "high":
                        time.sleep(0.01)

            clients = ([threading.Thread(target=client, args=("high",))
                        for _ in range(2)]
                       + [threading.Thread(target=client, args=("low",))
                          for _ in range(8)])
            for t in clients:
                t.start()
            converged_tasks = {}
            try:
                time.sleep(2.0)  # steady traffic against task 0

                # Chaos, act 1: SIGKILL one replica under live traffic.  The
                # breaker must eject it, the supervisor must relaunch it on
                # the same port, and the warm probe must re-admit it.
                os.kill(victim_pid, signal.SIGKILL)
                deadline = time.time() + 60
                while (time.time() < deadline
                       and KILL_REPLICA not in fe.health.ejected()):
                    time.sleep(0.2)
                if KILL_REPLICA not in fe.health.ejected():
                    failures.append("killed replica was never ejected")
                deadline = time.time() + 240
                while (time.time() < deadline
                       and not fe.health.is_healthy(KILL_REPLICA)):
                    time.sleep(0.5)
                if not fe.health.is_healthy(KILL_REPLICA):
                    failures.append("killed replica was never re-admitted")

                # Chaos, act 2: publish task 1.  The rollout wave must roll
                # back on FAULT_REPLICA (injected swap_ioerror), halt, then
                # converge on the retry once the one-shot clause is spent.
                shutil.copytree(os.path.join(export_dir, "task_001"),
                                os.path.join(serve_dir, "task_001"))
                register_artifact(serve_dir, 1, {"path": "task_001"})
                deadline = time.time() + 180
                while time.time() < deadline:
                    for i in range(N):
                        try:
                            st, info = _get_json(ports[i], "/healthz")
                            converged_tasks[i] = info.get("task_id")
                        except (OSError, ValueError):
                            converged_tasks[i] = None
                    if all(t == 1 for t in converged_tasks.values()):
                        break
                    time.sleep(0.5)
                if not all(t == 1 for t in converged_tasks.values()):
                    failures.append(
                        f"fleet never converged on task 1: {converged_tasks}")
                time.sleep(1.0)  # post-rollout traffic against task 1
            finally:
                stop_traffic.set()
                for t in clients:
                    t.join()
                fe_stats = fe.stats()
                fe_pump.stop()
                fe.stop()
                agent_proc.terminate()
                try:
                    agent_proc.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    agent_proc.kill()
                    agent_proc.wait()
                agent_console.close()
            threadcheck.uninstall()
            contractcheck.uninstall()

            # ---------------- assertions ---------------- #
            if hard_failures:
                failures.append(
                    f"{len(hard_failures)} failed client request(s) "
                    f"(first: {hard_failures[:3]})")
            if not results["high"] or not results["low"]:
                failures.append(f"no traffic served: { {p: len(v) for p, v in results.items()} }")  # noqa: E501
            if first_payload and decode_logits(first_payload[0]).ndim != 1:
                failures.append("response payload is not a logits row")
            if sheds["high"] + sheds["low"] == 0:
                failures.append("overload never shed a request")
            tasks_seen = sorted(set(results["high"]) | set(results["low"]))
            if tasks_seen != [0, 1] or (results["high"]
                                        and results["high"][-1] != 1):
                failures.append(
                    f"responses did not transition 0 -> 1: {tasks_seen}")

            st, relaunched = _get_json(ports[KILL_REPLICA], "/healthz")
            if relaunched.get("pid") == victim_pid:
                failures.append("killed replica was never relaunched")
            for i in range(N):
                if i == KILL_REPLICA:
                    continue  # survivors: their process lived the whole run
                st, stats_i = _get_json(ports[i], "/stats")
                if stats_i.get("trace_count") != 0:
                    failures.append(
                        f"survivor replica {i} traced "
                        f"{stats_i.get('trace_count')} program(s)")

            fe_recs = _records(fe_log)
            kinds = [r.get("type") for r in fe_recs]
            if "serve_shed" not in kinds:
                failures.append(f"no serve_shed record: {sorted(set(kinds))}")
            rollbacks = [r for r in fe_recs if r.get("type") == "serve_rollback"]
            if not rollbacks:
                failures.append("no serve_rollback record")
            if {r.get("replica") for r in rollbacks} - {FAULT_REPLICA}:
                failures.append(
                    f"rollback on an unfaulted replica: {rollbacks}")
            ejected = [r for r in fe_recs if r.get("type") == "replica_ejected"
                       and r.get("replica") == KILL_REPLICA]
            events = [r.get("event") for r in ejected]
            if "eject" not in events or "readmit" not in events:
                failures.append(
                    f"no eject/readmit cycle for replica {KILL_REPLICA}: "
                    f"{events}")
            if "frontend_retry" not in kinds:
                failures.append("SIGKILL under traffic produced no "
                                "frontend_retry record")

            # ---- metrics plane: the scraped fleet aggregate must survive
            # the SIGKILL chaos — the dead replica's series goes stale
            # (up=0) and comes back, the aggregate never loses the serve
            # counters the survivors keep feeding, and the edge-triggered
            # shed SLO fires exactly once for the whole overloaded run.
            def _series_sum(counters, name):
                return sum(v for k, v in counters.items()
                           if k.split("{", 1)[0] == name)

            agent_recs = _records(agent_out)
            fleet_snaps = [r for r in agent_recs
                           if r.get("type") == "metrics_snapshot"]
            burns = [r for r in agent_recs if r.get("type") == "slo_burn"]
            if len(fleet_snaps) < 5:
                failures.append(
                    f"fleet scraper produced only {len(fleet_snaps)} "
                    "snapshot(s)")
            else:
                ups = [s.get("up", {}).get(f"replica_{KILL_REPLICA}")
                       for s in fleet_snaps]
                if 0 not in ups:
                    failures.append(
                        "killed replica's scrape never went stale (up=0)")
                elif 1 not in ups[ups.index(0):]:
                    failures.append(
                        "killed replica's scrape never recovered after "
                        "relaunch")
                ts_seq = [s.get("ts", 0) for s in fleet_snaps]
                max_gap = max(b - a for a, b in zip(ts_seq, ts_seq[1:]))
                if max_gap > 15.0:
                    failures.append(
                        f"fleet scrape cadence broke: {max_gap:.1f}s gap "
                        "between snapshots")
                served_polls = [
                    i for i, s in enumerate(fleet_snaps)
                    if _series_sum(s.get("counters", {}),
                                   "serve_requests_total") > 0]
                if not served_polls:
                    failures.append(
                        "fleet aggregate never saw serve_requests_total")
                else:
                    dropped = [
                        fleet_snaps[i].get("seq")
                        for i in range(served_polls[0], len(fleet_snaps))
                        if i not in served_polls]
                    if dropped:
                        failures.append(
                            "fleet aggregate qps went dark during the kill "
                            f"window (polls {dropped[:5]})")
                last_snap = fleet_snaps[-1]
                if not any(k.split("{", 1)[0] == "serve_batch_latency_ms"
                           for k in last_snap.get("histograms", {})):
                    failures.append(
                        "no serve_batch_latency_ms histograms in the "
                        "merged fleet aggregate")
                if _series_sum(last_snap.get("counters", {}),
                               "fe_requests_total") <= 0:
                    failures.append(
                        "front-end snapshot stream never merged into the "
                        "fleet aggregate")
            if (len(burns) != 1 or burns[0].get("slo") != "fleet-shed"):
                failures.append(
                    "expected exactly one fleet-shed slo_burn, got "
                    f"{[(b.get('slo'), b.get('ts')) for b in burns]}")

            # Lock discipline: zero violations in this process AND in every
            # replica subprocess (they all ran --check_threads).
            replica_logs = [
                os.path.join(tdir, f"replica_{i}", "run.jsonl")
                for i in range(N)
                if os.path.exists(os.path.join(tdir, f"replica_{i}",
                                               "run.jsonl"))
            ]
            tviol = [r for r in fe_recs if r.get("type") == "thread_violation"]
            for path in replica_logs:
                tviol += [r for r in _records(path)
                          if r.get("type") == "thread_violation"]
            if check.violations or tviol:
                failures.append(
                    f"ThreadCheck violations under chaos: "
                    f"{(check.violations + tviol)[:3]}")

            # Contract discipline: zero violations in this process AND in
            # every replica subprocess (they all ran --check_contracts).
            cviol = [r for r in fe_recs
                     if r.get("type") == "contract_violation"]
            for path in replica_logs:
                cviol += [r for r in _records(path)
                          if r.get("type") == "contract_violation"]
            if contracts.violations or cviol:
                failures.append(
                    f"ContractCheck violations under chaos: "
                    f"{(contracts.violations + cviol)[:3]}")

            lint = subprocess.run(
                [sys.executable,
                 os.path.join(_REPO, "scripts", "check_telemetry_schema.py"),
                 fe_log, agent_out, *replica_logs],
                cwd=_REPO, timeout=120, capture_output=True, text=True)
            if lint.returncode != 0:
                failures.append(
                    f"schema lint failed on fleet telemetry: "
                    f"{lint.stdout.strip()} {lint.stderr.strip()}")

            print(json.dumps({
                "metric": "serve_fleet_smoke",
                "ok": not failures,
                "failures": failures,
                "served": fe_stats["served"],
                "shed": fe_stats["shed"],
                "client_sheds": sheds,
                "retries": fe_stats["retries"],
                "hedges": fe_stats["hedges"],
                "rollout_swaps": fe_stats["rollout_swaps"],
                "rollout_rollbacks": fe_stats["rollout_rollbacks"],
                "converged_tasks": converged_tasks,
                "fleet_snapshots": len(fleet_snaps),
                "slo_burns": len(burns),
            }))
            return 0 if not failures else 1
        finally:
            for p in procs:
                try:
                    os.killpg(p.pid, signal.SIGTERM)
                except (ProcessLookupError, PermissionError):
                    pass
            for p in procs:
                try:
                    p.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    try:
                        os.killpg(p.pid, signal.SIGKILL)
                    except (ProcessLookupError, PermissionError):
                        pass
                    p.wait()
            for console in consoles:
                console.close()


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--fleet", action="store_true",
                    help="run the replicated-fleet chaos smoke instead of "
                    "the single-server train->export->serve smoke")
    sys.exit(fleet_main() if ap.parse_args().fleet else main())
