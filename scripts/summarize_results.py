#!/usr/bin/env python
"""Render RESULTS.md from experiment JSONL logs (utils/logging.JsonlLogger).

Usage: python scripts/summarize_results.py experiments/*.jsonl > RESULTS.md

Per run: the per-task cumulative top-1 trajectory (``acc1s``), the weight-
alignment γ per task, seconds per task, and the avg incremental top-1 —
the reference's headline artifact (template.py:225,288-289).
"""

from __future__ import annotations

import json
import sys
from pathlib import Path


def load(path: str):
    tasks, final, meta = [], None, {}
    epochs: dict = {}
    with open(path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                # A killed run can leave a truncated trailing line; render
                # what completed instead of aborting the whole report.
                continue
            if rec.get("type") == "task":
                tasks.append(rec)
            elif rec.get("type") == "epoch" and "epoch_s" in rec:
                epochs.setdefault(rec.get("task_id", 0), []).append(
                    rec["epoch_s"]
                )
            elif rec.get("type") == "final":
                final = rec
            elif rec.get("type") == "run":
                meta = rec
            elif rec.get("type") == "resume":
                # Segment marker contract (engine/loop.py): a crash between a
                # task's records and its checkpoint replays that task — drop
                # pre-resume records the resumed run re-emits.  A marker
                # without start_task keeps everything (fail open, not empty).
                start = rec.get("start_task")
                if start is not None:
                    tasks = [t for t in tasks if t.get("task_id", 0) < start]
                    epochs = {t: v for t, v in epochs.items() if t < start}
                    final = None
    return tasks, final, meta, epochs


def compile_overhead_s(epoch_times):
    """First-epoch wall time minus steady-state median: the visible XLA
    (re)compile cost a fresh task pays (r3 Weak #7).  None when a task has
    fewer than 2 timed epochs."""
    if not epoch_times or len(epoch_times) < 2:
        return None
    rest = sorted(epoch_times[1:])
    median = rest[len(rest) // 2]
    return max(0.0, epoch_times[0] - median)


def render_matrix(tasks):
    """Accuracy-matrix table: row t = after training task t, column j = top-1
    on task j's own val slice (``acc_per_task`` in the task records).  Renders
    per-task forgetting (best prior accuracy on j minus final accuracy on j)
    and BWT (mean over j<T-1 of final minus just-after-training accuracy) —
    the standard continual-learning decomposition the cumulative trajectory
    can't show."""
    rows = {
        t.get("task_id", i): t.get("acc_per_task") for i, t in enumerate(tasks)
    }
    if not rows or any(r is None for r in rows.values()):
        return  # older logs predate the matrix
    # Rows are keyed by task_id, NOT list position: a --resume relaunch into
    # a fresh log file starts mid-protocol, and positional indexing would
    # silently publish wrong forgetting/BWT numbers for it.
    T = max(len(r) for r in rows.values())
    print("accuracy matrix (row = after task t, col = val slice of task j):\n")
    print("| after task | " + " | ".join(f"j={j}" for j in range(T)) + " |")
    print("|---|" + "---|" * T)
    for tid in sorted(rows):
        r = rows[tid]
        cells = [f"{a:.2f}" for a in r] + ["—"] * (T - len(r))
        print(f"| {tid} | " + " | ".join(cells) + " |")
    complete = sorted(rows) == list(range(T)) and all(
        len(rows[t]) == t + 1 for t in rows
    )
    if T > 1 and complete:
        final_row = rows[T - 1]
        forgetting = [
            max(rows[t][j] for t in range(j, T - 1)) - final_row[j]
            for j in range(T - 1)
        ]
        bwt = sum(final_row[j] - rows[j][j] for j in range(T - 1)) / (T - 1)
        fstr = ", ".join(f"j={j}: {f:+.2f}" for j, f in enumerate(forgetting))
        print(f"\nforgetting (best−final per slice): {fstr}")
        print(f"\nBWT (mean final−diagonal): {bwt:+.3f}\n")
    elif T > 1:
        print(
            "\n(partial matrix — log starts mid-protocol; forgetting/BWT "
            "need rows for every task)\n"
        )


def main(paths):
    print("# RESULTS — committed protocol-scale runs\n")
    print(
        "Synthetic-100 (class-separable low-frequency templates + heavy "
        "pixel noise, `data/datasets.load_synthetic` via `synthetic_hard*`) "
        "runs in two regimes, both reproducible with "
        "`scripts/run_protocol.sh`:\n\n"
        "- **Mechanism-proof** (`*_synthetic_hard`, memory 2000): every WA "
        "stage — head growth, KD, weight alignment, herding, shrinking "
        "quotas — executes over every task. With 2000 exemplars against a "
        "6400-image stream, rehearsal nearly replays the data, so "
        "accuracies saturate and no forgetting can show (by design).\n"
        "- **Dynamics-proof** (`*_mem256`, memory 256 = the reference's "
        "2000/50000 ≈ 4% rehearsal pressure, RandAugment on, σ=128 noise): "
        "the trajectory shows real forgetting and the WA γ correction "
        "(γ<1 pulls the over-normed new head down each task).\n\n"
        "Round-5 additions: `*_mesh8` is the same dynamics protocol run "
        "on an **8-device mesh** (`--host_devices 8`, global batch 128 = "
        "8 × 16 per device) — its trajectory must track the 1-device twin "
        "up to float reduction order, proving the distributed task loop "
        "(sharded loader, global-batch BN, replicated herding) at protocol "
        "scale (measured: within 1.7 points of the twin at every task, avg "
        "96.63 vs 97.59). `*_bf16` is the twin with `--compute_dtype "
        "bfloat16` (the TPU recipe's candidate dtype — activations/compute "
        "bf16, parameters f32); measured avg incremental 90.58 vs the f32 "
        "twin's 97.59 — a ~7-point cost for naive all-bf16 compute on this "
        "35-epoch recipe under XLA:CPU emulation (the TPU MXU accumulates "
        "in f32, so the chip figure should be better, but the committed "
        "evidence says don't flip the default blindly). "
        "`race_jax`/`race_torch*` are "
        "the two sides of the end-to-end reference race (see `RACE.md`).\n\n"
        "Runs suffixed `_resume` were SIGKILLed mid-task and relaunched "
        "with `--resume` from their orbax checkpoints (the `resume` marker "
        "in the JSONL records the restart point); task-boundary resume is "
        "exact, so their accuracy and γ columns must match the "
        "uninterrupted twin run bit-for-bit (the wall-clock/compile "
        "columns legitimately differ) — live preemption-recovery "
        "evidence, not a separate configuration. The checkpoint tree "
        "behind the resume is recorded as a sha256 manifest + twin "
        "equality check (`experiments/ckpt_b50_resume_manifest.json`, "
        "`scripts/make_resume_manifest.py`) instead of committed "
        "binary blobs.\n"
    )
    print(
        "Context for reading the tables: (1) No real CIFAR-100/ImageNet "
        "exists on this zero-egress machine (probed each round; only "
        "library loader stubs found), so the north-star CIFAR parity run "
        "remains blocked on data, not on framework capability — "
        "`--data_set cifar` is fully wired for the standard pickle "
        "distribution. (2) Each run's provenance header (`config:` line "
        "below) records backend/mesh/batch; when the tunneled TPU chip is "
        "unreachable the runs fall back to CPU. (3) At reduced epochs the "
        "640-image first task of B0-Inc10 is undertrained (tens of SGD "
        "steps); cumulative accuracy recovers over later tasks as "
        "rehearsal replays those classes — visible below as a rising-then-"
        "declining trajectory. More epochs shrink (not fully remove) the "
        "artifact: synthetic-100 has 64 images/class vs CIFAR's 500.\n"
    )
    for path in paths:
        tasks, final, meta, epochs = load(path)
        name = Path(path).stem
        print(f"## {name}\n")
        if meta:
            cfg = {k: v for k, v in meta.items() if k not in ("type", "ts")}
            print(f"config: `{json.dumps(cfg, sort_keys=True)}`\n")
        print(
            "| task | new classes | cum. top-1 (%) | WA γ | seconds "
            "| compile s |"
        )
        print("|---|---|---|---|---|---|")
        for t in tasks:
            gamma = f"{t['gamma']:.4f}" if t.get("gamma") is not None else "—"
            comp = compile_overhead_s(epochs.get(t.get("task_id", 0)))
            comp_s = f"{comp:.1f}" if comp is not None else "—"
            print(
                f"| {t['task_id']} | {t.get('nb_new', '?')} | "
                f"{t['acc1']:.2f} | {gamma} | {t.get('seconds', '?')} | "
                f"{comp_s} |"
            )
        print()
        render_matrix(tasks)
        if final:
            print(
                f"\n**avg incremental top-1: "
                f"{final['avg_incremental_acc1']:.3f}%** over "
                f"{len(final['acc1s'])} tasks\n"
            )
        else:
            print("\n(run did not complete — no `final` record)\n")


if __name__ == "__main__":
    if len(sys.argv) < 2:
        sys.exit("usage: summarize_results.py <jsonl...>")
    main(sys.argv[1:])
