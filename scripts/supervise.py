#!/usr/bin/env python3
"""Supervised auto-restart driver for long training runs.

On TPU pods the trainer *will* die — preemption, OOM, a flaky host — and the
recovery loop (relaunch with ``--resume``, which picks the newest valid task
or epoch checkpoint) should not depend on a human watching the terminal.
This supervisor owns that loop, subsuming the relaunch half of
``scripts/tpu_watchdog.sh`` (whose probing half already reads the heartbeat
file this supervisor also watches):

* Launches the trainer command (everything after ``--``) in its own process
  group and waits.
* Exit 0 ⇒ done, supervisor exits 0.
* Crash (non-zero exit, or a signal like the SIGKILL a preemption or an
  injected ``kill@...`` fault delivers) ⇒ relaunch under exponential backoff,
  appending ``--resume`` (once) so the child continues from its newest valid
  checkpoint.
* Hang (any per-process heartbeat file stale beyond ``--max_age`` while the
  child still lives) ⇒ kill the whole process group, then treat it as a
  crash.  ``--heartbeat`` names process 0's file; per-process siblings
  (``heartbeat_p<i>.json``) are probed automatically.
* Crash-loop breaker: more than ``--max_failures`` failures inside a sliding
  ``--failure_window`` ⇒ stop relaunching, report, exit 2.  An uptime longer
  than the window resets the count — a run that trains for an hour between
  two unrelated preemptions is not a crash loop.
* Crash forensics: on every failure, before relaunching, the supervisor
  harvests the flight-recorder dumps (``flight_*.json``), the last
  per-process heartbeats, and the fault ledger into one atomic
  ``<telemetry_dir>/crash_report.json`` — a self-contained artifact that
  survives the relaunch overwriting the live telemetry files.

Stdlib-only (like ``analysis/`` and ``faults/``): the supervisor must never
import jax — it outlives trainer processes whose jax runtime is wedged.

Example::

    python scripts/supervise.py --heartbeat /tmp/run/heartbeat.json \
        --max_age 120 -- \
        python train.py --ckpt_dir /tmp/run/ckpt --epoch_ckpt_every 5 \
            --telemetry_dir /tmp/run ...

Every supervisor decision is emitted as a JSON line on stdout (and to
``--log`` when given) so a fleet controller can tail it.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import random
import signal
import subprocess
import sys
import time
from typing import List, Optional


def backoff_delay(rng: random.Random, base: float, cap: float,
                  prev: float) -> float:
    """Decorrelated-jitter restart delay (the AWS exponential-backoff
    variant): ``min(cap, uniform(base, prev * 3))``.

    Plain doubling relaunches every replica of a fleet killed together at
    the same instant — a thundering herd against the artifact store and the
    accelerator allocator.  Jitter decorrelates them while keeping the
    envelope exponential: the delay never falls below ``base``, never
    exceeds ``cap``, and grows at most 3x per consecutive failure.
    """
    return min(cap, rng.uniform(base, max(base, prev * 3.0)))


def _parse_args(argv: List[str]):
    p = argparse.ArgumentParser(
        description="launch, watch and auto-restart a training command",
    )
    p.add_argument("--heartbeat", default=None,
                   help="heartbeat JSON the child maintains "
                   "(--heartbeat_path / <telemetry_dir>/heartbeat.json)")
    p.add_argument("--max_age", type=float, default=0.0,
                   help="seconds of heartbeat staleness that counts as a "
                   "hang (0 = liveness watching off; exit codes only)")
    p.add_argument("--poll", type=float, default=2.0,
                   help="child poll / heartbeat check cadence in seconds")
    p.add_argument("--grace", type=float, default=30.0,
                   help="seconds after launch before staleness checks start "
                   "(process startup + first heartbeat write)")
    p.add_argument("--backoff_base", type=float, default=1.0,
                   help="minimum relaunch delay; the decorrelated-jitter "
                   "envelope grows from here up to --backoff_max")
    p.add_argument("--backoff_max", type=float, default=300.0,
                   help="hard cap on any relaunch delay")
    p.add_argument("--backoff_seed", type=int, default=None,
                   help="seed for the jitter RNG (deterministic tests); "
                   "default derives from pid+time so replicas killed "
                   "together do not relaunch in lockstep")
    p.add_argument("--max_failures", type=int, default=5,
                   help="failures within --failure_window that trip the "
                   "crash-loop breaker (exit 2)")
    p.add_argument("--failure_window", type=float, default=3600.0,
                   help="sliding window for the breaker; uptime beyond it "
                   "also resets the consecutive-failure backoff")
    p.add_argument("--resume_flag", default="--resume",
                   help="flag appended (once) to the command after the "
                   "first crash so relaunches continue from the newest "
                   "checkpoint; '' disables")
    p.add_argument("--telemetry_dir", default=None,
                   help="the child's --telemetry_dir; flight dumps and "
                   "heartbeats are harvested from here into "
                   "crash_report.json on every failure (defaults to the "
                   "--heartbeat file's directory)")
    p.add_argument("--fault_ledger", default=None,
                   help="the child's fault fire-ledger "
                   "(<ckpt_dir>/fault_ledger.jsonl); included in "
                   "crash_report.json when given")
    p.add_argument("--stall_age", type=float, default=0.0,
                   help="seconds without progress (the heartbeat's "
                   "metrics digest fields frozen while the beat stays "
                   "fresh) that counts as a stall and triggers a kill + "
                   "relaunch (0 = progress watching off)")
    p.add_argument("--stall_fields", default="steps_total,serve_requests_total",
                   help="comma-separated heartbeat digest fields watched "
                   "by --stall_age; a process whose beat carries none of "
                   "them is never stall-killed")
    p.add_argument("--compile_cache", default=None,
                   help="persistent XLA compile-cache directory exported to "
                   "every (re)launch as JAX_COMPILATION_CACHE_DIR, so a "
                   "resumed child re-fetches its executables instead of "
                   "re-tracing+re-compiling them (trace-free restarts); "
                   "the supervisor itself never imports jax — env is the "
                   "only mechanism that survives the process boundary")
    p.add_argument("--metrics_agent", default=None,
                   help="argument string for scripts/metrics_agent.py, run "
                   "as a sidecar for the supervised run's lifetime "
                   "(e.g. '--replica 127.0.0.1:9101 --out fleet.jsonl')")
    p.add_argument("--log", default=None,
                   help="also append the JSON event lines here")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="-- then the training command")
    args = p.parse_args(argv)
    cmd = list(args.command)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        p.error("no training command given (everything after --)")
    args.command = cmd
    return args


class Supervisor:
    def __init__(self, args):
        self.args = args
        self.failures: List[float] = []  # monotonic timestamps, sliding window
        seed = (args.backoff_seed if args.backoff_seed is not None
                else os.getpid() ^ int(time.time() * 1000))
        self._rng = random.Random(seed)
        self._prev_delay = 0.0  # decorrelated-jitter state
        self._stall_fields = [
            f for f in (s.strip() for s in args.stall_fields.split(","))
            if f
        ]
        # Per-heartbeat progress memory: path -> (digest tuple, last time
        # the tuple changed).  Reset at every (re)launch — a fresh child
        # starts its counters over.
        self._progress: dict = {}

    # ------------------------------------------------------------------ #

    def _event(self, kind: str, **fields) -> None:
        line = json.dumps({"event": kind, "ts": round(time.time(), 3), **fields})
        print(line, flush=True)
        if self.args.log:
            with open(self.args.log, "a") as f:
                f.write(line + "\n")

    def _heartbeat_paths(self) -> List[str]:
        """The configured heartbeat plus its per-process siblings
        (``heartbeat_p<i>.json``) — in a multi-process run every process
        beats into its own file, and any one going stale is a hang."""
        hb = self.args.heartbeat
        if not hb:
            return []
        stem, ext = os.path.splitext(hb)
        return [hb] + sorted(glob.glob(f"{stem}_p[0-9]*{ext}"))

    def _heartbeat_stale(self) -> Optional[float]:
        """Worst stale age in seconds across per-process heartbeats, else
        None.  A file not written yet is not stale (grace covers startup),
        but one process's dead heartbeat hangs the fleet."""
        max_age = self.args.max_age
        if max_age <= 0:
            return None
        worst = None
        for path in self._heartbeat_paths():
            try:
                age = time.time() - os.stat(path).st_mtime
            except OSError:
                continue  # not written yet; the grace period covers startup
            if age > max_age and (worst is None or age > worst):
                worst = age
        return worst

    def _progress_stalled(self) -> Optional[dict]:
        """'Alive but stalled' probe: the heartbeat file keeps getting
        rewritten (fresh mtime — the liveness probe stays quiet) while the
        metrics digest fields the pump embeds (``steps_total`` /
        ``serve_requests_total``) have not moved for ``--stall_age``.  A
        beat carrying none of the watched fields is never stall-killed —
        absence of the digest means the metrics plane is off, not that the
        process stopped progressing."""
        stall_age = self.args.stall_age
        if stall_age <= 0:
            return None
        now = time.monotonic()
        worst: Optional[tuple] = None
        for path in self._heartbeat_paths():
            beat = self._read_json(path)
            if beat is None:
                continue
            vals = tuple(beat.get(f) for f in self._stall_fields)
            if all(v is None for v in vals):
                continue
            prev = self._progress.get(path)
            if prev is None or prev[0] != vals:
                self._progress[path] = (vals, now)
                continue
            age = now - prev[1]
            if age > stall_age and (worst is None or age > worst[1]):
                worst = (path, age)
        if worst is None:
            return None
        return {"heartbeat": worst[0], "stalled_s": round(worst[1], 1),
                "fields": list(self._stall_fields)}

    def _kill_group(self, proc: subprocess.Popen) -> None:
        """SIGTERM then SIGKILL the child's whole process group (the trainer
        may have its own children: compile workers, profilers)."""
        for sig in (signal.SIGTERM, signal.SIGKILL):
            try:
                os.killpg(proc.pid, sig)
            except (ProcessLookupError, PermissionError):
                return
            try:
                proc.wait(timeout=10.0)
                return
            except subprocess.TimeoutExpired:
                continue

    def _child_env(self) -> Optional[dict]:
        """Child environment.  ``--compile_cache`` rides the env (jax config
        options read their uppercase env names at import), so *every*
        relaunch — not just ones whose command line carries a flag — lands
        on the same persistent XLA cache and resumes trace-free.  The
        thresholds are zeroed so even the small CIL-sized programs persist;
        explicit settings already in the environment win."""
        if not self.args.compile_cache:
            return None  # inherit untouched
        env = dict(os.environ)
        cache_dir = os.path.abspath(self.args.compile_cache)
        env.setdefault("JAX_COMPILATION_CACHE_DIR", cache_dir)
        env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
        env.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
        return env

    def _run_once(self, cmd: List[str]):
        """Launch and babysit one child; returns (returncode, uptime_s,
        hung)."""
        start = time.monotonic()
        self._progress.clear()  # a fresh child restarts its counters
        proc = subprocess.Popen(cmd, start_new_session=True,
                                env=self._child_env())
        self._event("launch", pid=proc.pid, cmd=cmd)
        hung = False
        while True:
            try:
                rc = proc.wait(timeout=self.args.poll)
                break
            except subprocess.TimeoutExpired:
                pass
            if time.monotonic() - start < self.args.grace:
                continue
            age = self._heartbeat_stale()
            if age is not None:
                self._event("hang", pid=proc.pid,
                            heartbeat_age_s=round(age, 1))
                self._kill_group(proc)
                hung = True
                rc = proc.returncode if proc.returncode is not None else -9
                break
            stall = self._progress_stalled()
            if stall is not None:
                self._event("stall", pid=proc.pid, **stall)
                self._kill_group(proc)
                hung = True
                rc = proc.returncode if proc.returncode is not None else -9
                break
        uptime = time.monotonic() - start
        self._event("exit", pid=proc.pid, returncode=rc, hung=hung,
                    uptime_s=round(uptime, 1))
        return rc, uptime, hung

    # ------------------------------------------------------------------ #
    # Crash forensics
    # ------------------------------------------------------------------ #

    def _telemetry_dir(self) -> Optional[str]:
        if self.args.telemetry_dir:
            return self.args.telemetry_dir
        if self.args.heartbeat:
            return os.path.dirname(os.path.abspath(self.args.heartbeat))
        return None

    @staticmethod
    def _read_json(path: str) -> Optional[dict]:
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None  # missing / torn file: absence is its own evidence

    @staticmethod
    def _read_jsonl(path: str) -> List[dict]:
        out: List[dict] = []
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        continue  # torn trailing line of a killed process
        except OSError:
            return out
        return out

    def _write_crash_report(self, rc: int, hung: bool, uptime: float,
                            attempt: int) -> None:
        """Harvest flight dumps + heartbeats + fault ledger into one atomic
        ``crash_report.json`` before the relaunch overwrites the live files.
        Best-effort by design: forensics must never block recovery."""
        tdir = self._telemetry_dir()
        if not tdir or not os.path.isdir(tdir):
            return
        flight_dumps = [
            d for d in (
                self._read_json(p)
                for p in sorted(glob.glob(os.path.join(tdir, "flight_*.json")))
            ) if d is not None
        ]
        heartbeats = [
            b for b in (self._read_json(p) for p in self._heartbeat_paths()
                        or sorted(glob.glob(os.path.join(
                            tdir, "heartbeat*.json"))))
            if b is not None
        ]
        report = {
            "type": "crash_report",
            "ts": round(time.time(), 3),
            "returncode": rc,
            "hung": hung,
            "uptime_s": round(uptime, 1),
            "attempt": attempt,
            "telemetry_dir": tdir,
            "flight_dumps": flight_dumps,
            "heartbeats": heartbeats,
            "fault_ledger": (self._read_jsonl(self.args.fault_ledger)
                             if self.args.fault_ledger else []),
        }
        path = os.path.join(tdir, "crash_report.json")
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(report, f)
            os.replace(tmp, path)
        except OSError:
            return  # a full disk must not stop the relaunch loop
        self._event("crash_report", path=path,
                    flight_dumps=len(flight_dumps),
                    heartbeats=len(heartbeats))

    # ------------------------------------------------------------------ #

    def run(self) -> int:
        sidecar = self._start_metrics_agent()
        try:
            return self._run_loop()
        finally:
            self._stop_metrics_agent(sidecar)

    def _start_metrics_agent(self) -> Optional[subprocess.Popen]:
        """Optional scraper sidecar: one metrics_agent.py lives for the
        whole supervised run (it spans relaunches — the fleet aggregate
        must not restart when a child does)."""
        if not self.args.metrics_agent:
            return None
        import shlex

        agent = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "metrics_agent.py")
        cmd = [sys.executable, agent] + shlex.split(self.args.metrics_agent)
        proc = subprocess.Popen(cmd, start_new_session=True)
        self._event("metrics_agent", pid=proc.pid, cmd=cmd)
        return proc

    def _stop_metrics_agent(self, proc: Optional[subprocess.Popen]) -> None:
        if proc is None or proc.poll() is not None:
            return
        proc.terminate()
        try:
            proc.wait(timeout=10.0)
        except subprocess.TimeoutExpired:
            proc.kill()

    def _run_loop(self) -> int:
        args = self.args
        cmd = list(args.command)
        attempt = 0
        while True:
            attempt += 1
            rc, uptime, hung = self._run_once(cmd)
            if rc == 0:
                self._event("done", attempts=attempt)
                return 0
            self._write_crash_report(rc, hung, uptime, attempt)
            now = time.monotonic()
            if uptime > args.failure_window:
                # A long-lived child that eventually died is a fresh
                # incident, not part of a crash loop.
                self.failures.clear()
                self._prev_delay = 0.0
            self.failures.append(now)
            self.failures = [t for t in self.failures
                             if now - t <= args.failure_window]
            if len(self.failures) > args.max_failures:
                self._event(
                    "breaker", failures=len(self.failures),
                    window_s=args.failure_window,
                    message="crash loop: relaunching stopped; inspect the "
                    "run log / last checkpoint before restarting",
                )
                return 2
            if args.resume_flag and args.resume_flag not in cmd:
                cmd = cmd + [args.resume_flag]
            delay = backoff_delay(self._rng, args.backoff_base,
                                  args.backoff_max, self._prev_delay)
            self._prev_delay = delay
            self._event("relaunch", attempt=attempt + 1,
                        backoff_s=round(delay, 2),
                        failures_in_window=len(self.failures))
            time.sleep(delay)


def main(argv: Optional[List[str]] = None) -> int:
    args = _parse_args(sys.argv[1:] if argv is None else argv)
    return Supervisor(args).run()


if __name__ == "__main__":
    sys.exit(main())
