#!/usr/bin/env bash
# Protocol-scale evidence runs: the full 10-task B0-inc10 and 6-task
# B50-inc10 class-incremental protocols (reference template.py:226-303) on
# synthetic-100, JSONL-logged into experiments/.  Reduced epochs by default —
# the point is the WA mechanism working over every task (head growth, KD,
# weight alignment, herding, shrinking quotas), not peak accuracy.
#
#   EPOCHS=8 ./scripts/run_protocol.sh                       # real chip
#   PLATFORM_ARGS="--platform cpu --host_devices 8" ...      # virtual mesh
set -euo pipefail
cd "$(dirname "$0")/.."
mkdir -p experiments

EPOCHS=${EPOCHS:-25}
SEED=${SEED:-0}
BATCH=${BATCH:-128}  # per device: 128 on 1 real chip = the reference's per-GPU
PLATFORM_ARGS=${PLATFORM_ARGS:-}
AA=${AA:-None}  # RandAugment off by default: compile cost, see tests/test_augment.py
# Exemplar budget. Default 2000 = the reference's flag default (CLI parity).
# NOTE for synthetic runs: 2000 is 4% of CIFAR-100's 50k train images, but
# synthetic-100 has only 6400 — 2000 nearly replays the whole stream and no
# forgetting can show.  Pass MEMORY=256 to reproduce the reference's 4%
# rehearsal pressure on synthetic data (r3 verdict Next #5); the committed
# *_mem256 evidence runs and the watchdog's TPU run do exactly that.
MEMORY=${MEMORY:-2000}
# synthetic_hard: heavy-noise variant — accuracies stay off the 100% ceiling
# so forgetting and WA recovery are visible in the trajectory.
DATASET=${DATASET:-synthetic_hard}
SUFFIX=${SUFFIX:-}  # e.g. SUFFIX=_tpu140 to keep runs side by side
ONLY=${ONLY:-}      # b0 | b50 | empty = both (single-protocol runs: the
                    # machine has ONE cpu core, so a full B0+B50 pair costs
                    # ~4h wall; B50 alone is the flagship 6-task protocol)
case "$ONLY" in
  ""|b0|b50) ;;
  *) echo "ONLY must be 'b0', 'b50' or empty, got '$ONLY'" >&2; exit 2 ;;
esac
EXTRA_ARGS=${EXTRA_ARGS:-}  # e.g. "--compute_dtype bfloat16"
# Fault tolerance (supervised runs, see scripts/supervise.py): CKPT_DIR
# gives each protocol its own checkpoint root (they must not share one —
# the b50 run would otherwise resume from the b0 run's checkpoints), and a
# trailing --resume argument (what the supervisor appends on relaunch) is
# forwarded to both train.py invocations so a relaunch continues from the
# newest valid task/epoch checkpoint.  A protocol that already finished
# resumes past its last task and just re-renders its summary.
CKPT_DIR=${CKPT_DIR:-}
CKPT_EVERY=${CKPT_EVERY:-10}
RESUME_ARG=""
if [ "${1:-}" = "--resume" ]; then RESUME_ARG="--resume"; fi

if [ "$ONLY" != "b50" ]; then
python train.py --data_set "$DATASET" --num_bases 0 --increment 10 \
  --backbone resnet32 --batch_size "$BATCH" --num_epochs "$EPOCHS" --aa "$AA" \
  --memory_size "$MEMORY" --seed "$SEED" $PLATFORM_ARGS $EXTRA_ARGS \
  ${CKPT_DIR:+--ckpt_dir "$CKPT_DIR/b0" --epoch_ckpt_every "$CKPT_EVERY"} \
  $RESUME_ARG \
  --log_file "experiments/b0_inc10_${DATASET}${SUFFIX}.jsonl"
fi

if [ "$ONLY" != "b0" ]; then
python train.py --data_set "$DATASET" --num_bases 50 --increment 10 \
  --backbone resnet32 --batch_size "$BATCH" --num_epochs "$EPOCHS" --aa "$AA" \
  --memory_size "$MEMORY" --seed "$SEED" $PLATFORM_ARGS $EXTRA_ARGS \
  ${CKPT_DIR:+--ckpt_dir "$CKPT_DIR/b50" --epoch_ckpt_every "$CKPT_EVERY"} \
  $RESUME_ARG \
  --log_file "experiments/b50_inc10_${DATASET}${SUFFIX}.jsonl"
fi

# Render every committed-evidence log present, not just this invocation's.
python scripts/summarize_results.py experiments/*.jsonl > RESULTS.md
echo "wrote RESULTS.md"
