#!/usr/bin/env python
"""CPU chaos smoke: SIGKILL mid-task, supervise back, match the twin exactly.

The acceptance proof for the fault-injection + epoch-resume + supervisor
stack, end to end with *real processes* and a *real* SIGKILL (not an
exception a test harness can intercept):

1. Run a tiny 2-task synthetic protocol to completion — the fault-free twin.
2. Run the same protocol with ``--fault_spec kill@task1.epoch2`` and
   ``--epoch_ckpt_every 1`` under ``scripts/supervise.py``: the trainer
   SIGKILLs itself right after task 1's second epoch lands its checkpoint;
   the supervisor relaunches it with ``--resume``; the fault ledger keeps the
   relaunch from re-firing; the relaunch restores the *epoch* checkpoint and
   finishes the protocol.
3. Assert from the chaos run's JSONL evidence that the kill actually fired
   (``fault_injected``), that the resume was epoch-granular
   (``resume.kind == "epoch"`` at task 1, epoch 2 — not a task-boundary
   restart), and that the final accuracy matrix, acc1 trajectory and
   alignment γ are **bit-identical** to the twin's.
4. Assert the crash left a forensic trail: the supervisor harvested a
   ``crash_report.json`` whose flight-recorder tail contains the killed
   process's ``fault_injected`` event with the ``task`` span still open
   (the kill fires at the engine.epoch site, after the epoch span closed),
   and ``report_run.py`` renders a crash timeline naming that span.

Exit 0 on exact match, 1 otherwise, one JSON line either way.
Used by ``scripts/ci.sh``; runnable standalone from anywhere.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# Shapes chosen to reuse the compiled programs the tier-1 suite and
# prefetch_smoke already put in tests/.jax_cache (same model, batch, path).
_PROTO = [
    "--platform", "cpu",
    "--data_set", "synthetic10",
    "--num_bases", "0",
    "--increment", "5",
    "--backbone", "resnet20",
    "--batch_size", "16",
    "--num_epochs", "3",
    "--eval_every_epoch", "100",
    "--memory_size", "40",
    "--lr", "0.05",
    "--aa", "none",
    "--color_jitter", "0.0",
    "--seed", "7",
    "--no_fused_epochs",
    "--compile_cache", os.path.join(_REPO, "tests", ".jax_cache"),
]


def _records(path):
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def _last(records, kind):
    hits = [r for r in records if r.get("type") == kind]
    return hits[-1] if hits else None


def _task_gammas(records):
    gam = {}
    for r in records:
        if r.get("type") == "task":
            gam[r["task_id"]] = r.get("gamma")  # last record per task wins
    return gam


def main() -> int:
    with tempfile.TemporaryDirectory(prefix="chaos_smoke_") as tmp:
        twin_log = os.path.join(tmp, "twin.jsonl")
        tdir = os.path.join(tmp, "chaos_tel")
        chaos_log = os.path.join(tdir, "run.jsonl")  # --telemetry_dir default
        ckpt_dir = os.path.join(tmp, "ckpt")
        ledger = os.path.join(ckpt_dir, "fault_ledger.jsonl")

        twin_cmd = [sys.executable, os.path.join(_REPO, "train.py"),
                    *_PROTO, "--log_file", twin_log]
        twin = subprocess.run(twin_cmd, cwd=_REPO, timeout=900)
        if twin.returncode != 0:
            print(json.dumps({"metric": "chaos_smoke", "ok": False,
                              "reason": f"twin run failed rc={twin.returncode}"}))
            return 1

        chaos_cmd = [
            sys.executable, os.path.join(_REPO, "scripts", "supervise.py"),
            "--backoff_base", "0.1", "--backoff_max", "1",
            "--max_failures", "3", "--failure_window", "120",
            "--telemetry_dir", tdir,
            "--fault_ledger", ledger,
            "--",
            sys.executable, os.path.join(_REPO, "train.py"), *_PROTO,
            "--telemetry_dir", tdir,
            "--ckpt_dir", ckpt_dir,
            "--epoch_ckpt_every", "1",
            "--fault_spec", "kill@task1.epoch2",
            # The chaos run doubles as the ThreadCheck acceptance run: the
            # heartbeat/flight/prefetch locks are instrumented and any
            # inversion or lock-held blocking would emit thread_violation.
            "--check_threads",
            # ... and as the ContractCheck acceptance run: every record the
            # kill/resume cycle emits is validated against the committed
            # contract registry at emit time.
            "--check_contracts",
        ]
        chaos = subprocess.run(chaos_cmd, cwd=_REPO, timeout=900)

        failures = []
        if chaos.returncode != 0:
            failures.append(f"supervisor exited rc={chaos.returncode}")
        twin_recs = _records(twin_log)
        chaos_recs = _records(chaos_log) if os.path.exists(chaos_log) else []

        fault = _last(chaos_recs, "fault_injected")
        if not (fault and fault.get("action") == "kill"
                and fault.get("task") == 1 and fault.get("epoch") == 2):
            failures.append(f"kill fault did not fire as specified: {fault}")
        resume = _last(chaos_recs, "resume")
        if not (resume and resume.get("kind") == "epoch"
                and resume.get("start_task") == 1
                and resume.get("start_epoch") == 2):
            failures.append(
                f"resume was not epoch-granular at task1/epoch2: {resume}")

        tviol = [r for r in chaos_recs if r.get("type") == "thread_violation"]
        if tviol:
            failures.append(
                f"{len(tviol)} thread_violation record(s) under "
                f"--check_threads: {tviol[:3]}")

        cviol = [r for r in chaos_recs
                 if r.get("type") == "contract_violation"]
        if cviol:
            failures.append(
                f"{len(cviol)} contract_violation record(s) under "
                f"--check_contracts: {cviol[:3]}")

        twin_final = _last(twin_recs, "final")
        chaos_final = _last(chaos_recs, "final")
        if twin_final is None or chaos_final is None:
            failures.append("a run produced no final record")
        else:
            for key in ("acc1s", "avg_incremental_acc1"):
                if twin_final.get(key) != chaos_final.get(key):
                    failures.append(
                        f"{key} differs: twin={twin_final.get(key)} "
                        f"chaos={chaos_final.get(key)}")
        twin_task = _last(twin_recs, "task")
        chaos_task = _last(chaos_recs, "task")
        twin_gam = _task_gammas(twin_recs)
        chaos_gam = _task_gammas(chaos_recs)
        if twin_gam != chaos_gam:
            failures.append(f"gamma differs: twin={twin_gam} chaos={chaos_gam}")
        if (twin_task and chaos_task
                and twin_task.get("acc_per_task") != chaos_task.get("acc_per_task")):
            failures.append(
                f"final matrix row differs: twin={twin_task.get('acc_per_task')} "
                f"chaos={chaos_task.get('acc_per_task')}")

        # Crash forensics: the supervisor must have harvested the killed
        # process's flight-recorder tail into crash_report.json ...
        crash_path = os.path.join(tdir, "crash_report.json")
        last_open = None
        if not os.path.exists(crash_path):
            failures.append("supervisor harvested no crash_report.json")
        else:
            with open(crash_path) as f:
                crash = json.load(f)
            dumps = crash.get("flight_dumps", [])
            fatal = [d for d in dumps
                     if any(e.get("type") == "fault_injected"
                            for e in d.get("events", []))]
            if not fatal:
                failures.append(
                    "crash_report flight dumps lack the fault_injected "
                    f"event (reasons={[d.get('reason') for d in dumps]})")
            else:
                last_open = fatal[-1].get("last_open_span")
                # The kill fires at the engine.epoch site, after the epoch
                # span closed: the task span is what death interrupted.
                if last_open != "task":
                    failures.append(
                        f"flight dump last_open_span={last_open!r}, "
                        "want 'task'")
            if not crash.get("fault_ledger"):
                failures.append("crash_report carries no fault-ledger entries")
        # ... and report_run.py must render it as a crash timeline naming
        # the span that was open at death.
        report = subprocess.run(
            [sys.executable, os.path.join(_REPO, "scripts", "report_run.py"),
             chaos_log],
            cwd=_REPO, timeout=120, capture_output=True, text=True)
        if "last open span at death: task" not in report.stdout:
            failures.append(
                "report_run.py crash timeline does not name the open span "
                f"(rc={report.returncode})")

        print(json.dumps({
            "metric": "chaos_smoke",
            "ok": not failures,
            "failures": failures,
            "twin_acc1s": (twin_final or {}).get("acc1s"),
            "chaos_acc1s": (chaos_final or {}).get("acc1s"),
            "resume": resume,
            "fault": fault,
        }))
        return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
