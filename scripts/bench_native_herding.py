#!/usr/bin/env python
"""Measure the C++ herding kernel against the numpy fallback and write the
artifact behind README's speedup claim (r4 verdict Weak #5: perf claims
carry measurements or "projected" labels).

The shape is the CIFAR-100 protocol's real herding workload: 500 images per
class, 64-d features (reference resnet32 ``out_dim``), quota
2000/100 = 20 exemplars — run per class, so the per-call time is what the
task loop actually pays 100 times per task.

Usage: python scripts/bench_native_herding.py > experiments/native_herding_bench.json
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from a_pytorch_tutorial_to_class_incremental_learning_tpu.data.memory import (  # noqa: E402
    herd_barycenter,
)
from a_pytorch_tutorial_to_class_incremental_learning_tpu.utils.native import (  # noqa: E402
    native_available,
)


def time_call(fn, repeats: int) -> float:
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def main() -> None:
    if not native_available():
        json.dump({"error": "native library not built"}, sys.stdout)
        return
    n, d, quota = 500, 64, 20
    feats = np.random.RandomState(0).randn(n, d).astype(np.float32)

    # Parity first: a speedup over a kernel computing something else is
    # meaningless.
    sel_native = herd_barycenter(feats, quota, allow_native=True)
    sel_numpy = herd_barycenter(feats, quota, allow_native=False)
    parity = bool(np.array_equal(sel_native, sel_numpy))

    t_native = time_call(lambda: herd_barycenter(feats, quota, allow_native=True), 20)
    t_numpy = time_call(lambda: herd_barycenter(feats, quota, allow_native=False), 20)

    json.dump(
        {
            "workload": {"n": n, "d": d, "quota": quota,
                         "note": "per-class CIFAR-100 herding call"},
            "selections_identical": parity,
            "native_s": round(t_native, 6),
            "numpy_s": round(t_numpy, 6),
            "speedup": round(t_numpy / t_native, 2),
        },
        sys.stdout,
    )
    print()


if __name__ == "__main__":
    main()
