#!/usr/bin/env python
"""Lint telemetry/experiment JSONL files against the sink record vocabulary.

Usage: python scripts/check_telemetry_schema.py <files...>
       python scripts/check_telemetry_schema.py experiments/*.jsonl

The schema table itself lives in ``telemetry/schema.py`` (one source of
truth shared with contractlint's JL501/JL502 pass and the
``--check_contracts`` runtime sentinel); this script is the CLI over it.
A ``.json`` argument is treated as a single record (the heartbeat);
everything else as JSONL.

The point is drift detection: a producer that renames a field, drops a
required one, or invents an undeclared record type fails CI here — before a
consumer (``report_run.py``, ``summarize_results.py``, the watchdog) silently
renders nothing.

Exit 0 when every record of every file validates; 1 otherwise, with one line
per violation.
"""

from __future__ import annotations

import importlib.util
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SCHEMA_PATH = os.path.join(
    _REPO_ROOT,
    "a_pytorch_tutorial_to_class_incremental_learning_tpu",
    "telemetry",
    "schema.py",
)


def _load_schema_module():
    """Load telemetry/schema.py by file path, bypassing the package
    ``__init__`` (which imports jax — this script must stay stdlib-only)."""
    spec = importlib.util.spec_from_file_location("_telemetry_schema", _SCHEMA_PATH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_schema = _load_schema_module()

# Re-exported so existing importers (tests, tools) keep working.
NUM = _schema.NUM
SCHEMA = _schema.SCHEMA
ALWAYS_REQUIRED = _schema.ALWAYS_REQUIRED
ALWAYS_OPTIONAL = _schema.ALWAYS_OPTIONAL
check_record = _schema.check_record


def check_file(path: str) -> list:
    errs = []
    if path.endswith(".json"):
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError) as e:
            return [f"{path}: unreadable ({e})"]
        rec.setdefault("type", "heartbeat")
        return check_record(rec, path)
    with open(path) as f:
        for n, line in enumerate(f, 1):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                if n == sum(1 for _ in open(path)):
                    continue  # torn trailing line of a killed run is legal
                errs.append(f"{path}:{n}: unparsable line")
                continue
            errs.extend(check_record(rec, f"{path}:{n}"))
    return errs


def main(paths) -> int:
    errs = []
    total = 0
    for path in paths:
        errs.extend(check_file(path))
        total += 1
    for e in errs:
        print(e)
    print(
        f"checked {total} file(s): "
        + ("OK" if not errs else f"{len(errs)} violation(s)")
    )
    return 1 if errs else 0


if __name__ == "__main__":
    if len(sys.argv) < 2:
        sys.exit("usage: check_telemetry_schema.py <jsonl/json files...>")
    sys.exit(main(sys.argv[1:]))
