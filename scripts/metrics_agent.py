#!/usr/bin/env python
"""Fleet metrics scraper + SLO burn-rate alerter (stdlib-only).

The fleet's time-series plane has two producers: serving processes expose a
Prometheus-text ``/metrics`` endpoint (``serving/replica.py`` and
``serving/frontend.py``), and train processes pump ``metrics_snapshot``
records into their JSONL streams (``telemetry/metrics.py``).  This agent is
the consumer that makes them ONE fleet: every poll it

1. scrapes each ``--replica host:port`` endpoint (a failed or stale scrape
   marks that replica ``up=0`` and its series simply go stale — they stop
   contributing, they are never zeroed, so a SIGKILL'd replica cannot drag
   the aggregate down with phantom zeros),
2. tails the newest ``metrics_snapshot`` out of each ``--train-log`` JSONL,
3. merges everything — counters sum, gauges last-wins, histograms fold
   element-wise over identical bucket ladders (associative, so order never
   matters), and
4. appends one fleet-aggregate ``metrics_snapshot`` record (source
   ``"fleet"``, plus the per-replica ``up`` map) to ``--out``.

On top sit SLO objects (``--slo`` JSON, repeatable) with multi-window
burn-rate alerting in the Google-SRE style: the burn rate is the error
ratio over a window divided by the SLO's error budget ``1 - objective``;
an alert fires only when BOTH the long and the short window exceed the
threshold (the long window gives significance, the short one proves the
burn is still happening), emitting an edge-triggered ``slo_burn`` record —
one per activation, not one per poll.

Stdlib-only on purpose, like ``scripts/supervise.py``: the scraper must
keep observing a fleet whose accelerator runtime is wedged, so it imports
neither jax nor the repo packages.  It carries its own small exposition
parser; the merge semantics mirror ``telemetry/metrics.py``.

Usage:
  python scripts/metrics_agent.py --replica 127.0.0.1:9101 \\
      --replica 127.0.0.1:9102 --train-log exp/run.jsonl \\
      --out exp/fleet_metrics.jsonl --interval_s 2 \\
      --slo '{"name":"availability","bad":"fe_failed_total",
              "total":"fe_requests_total","objective":0.999,
              "window_s":30,"short_window_s":5,"threshold":2.0}'
"""

from __future__ import annotations

import argparse
import json
import math
import os
import re
import sys
import time
import urllib.error
import urllib.request

_SAMPLE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>\S+)$'
)
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"')


# --------------------------------------------------------------------------- #
# Exposition parsing (Prometheus text format v0.0.4)
# --------------------------------------------------------------------------- #


def _series_key(name: str, labels: dict) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


def parse_exposition(text: str) -> dict:
    """Parse exposition text into ``{"counters", "gauges", "histograms"}``.

    Histograms come back in *ladder* form — ``{"le": [bounds...],
    "cum": [cumulative counts...], "sum": s, "count": n}`` with the final
    ``+Inf`` bound as ``math.inf`` — the canonical fleet-merge shape (two
    cumulative ladders over identical bounds merge by element-wise
    addition, which is associative and commutative).
    """
    types: dict = {}
    counters: dict = {}
    gauges: dict = {}
    hist: dict = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        m = _SAMPLE.match(line)
        if not m:
            continue
        name = m.group("name")
        labels = dict(_LABEL.findall(m.group("labels") or ""))
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        if types.get(base) == "histogram" and name != base:
            le = labels.pop("le", None)
            key = _series_key(base, labels)
            h = hist.setdefault(
                key, {"le": [], "cum": [], "sum": 0.0, "count": 0})
            if name.endswith("_bucket") and le is not None:
                bound = math.inf if le in ("+Inf", "inf") else float(le)
                h["le"].append(bound)
                h["cum"].append(value)
            elif name.endswith("_sum"):
                h["sum"] = value
            elif name.endswith("_count"):
                h["count"] = int(value)
        elif types.get(name) == "counter":
            counters[_series_key(name, labels)] = value
        else:
            gauges[_series_key(name, labels)] = value
    for h in hist.values():
        order = sorted(range(len(h["le"])), key=lambda i: h["le"][i])
        h["le"] = [h["le"][i] for i in order]
        h["cum"] = [h["cum"][i] for i in order]
    return {"counters": counters, "gauges": gauges, "histograms": hist}


def snapshot_to_ladder(snap: dict) -> dict:
    """Convert a ``metrics_snapshot`` record's histogram form (``lowest`` /
    ``growth`` / per-bucket counts) into the same ladder form the exposition
    parser produces, so train and serve histograms merge identically."""
    out = {"counters": dict(snap.get("counters", {})),
           "gauges": dict(snap.get("gauges", {})),
           "histograms": {}}
    for key, h in snap.get("histograms", {}).items():
        n = len(h["buckets"]) - 1
        le = [h["lowest"] * h["growth"] ** i for i in range(n)] + [math.inf]
        cum, running = [], 0.0
        for c in h["buckets"]:
            running += c
            cum.append(running)
        out["histograms"][key] = {
            "le": le, "cum": cum, "sum": h["sum"], "count": h["count"]}
    return out


def merge_ladders(a: dict, b: dict) -> dict:
    """Element-wise merge of two ladder histograms over identical bounds."""
    if a["le"] != b["le"]:
        raise ValueError("cannot merge histograms with different le ladders")
    return {
        "le": list(a["le"]),
        "cum": [x + y for x, y in zip(a["cum"], b["cum"])],
        "sum": round(a["sum"] + b["sum"], 6),
        "count": a["count"] + b["count"],
    }


def merge_parsed(parts: list) -> dict:
    """Fold N parsed/converted metric sets into one fleet aggregate."""
    out = {"counters": {}, "gauges": {}, "histograms": {}}
    for part in parts:
        for k, v in part.get("counters", {}).items():
            out["counters"][k] = out["counters"].get(k, 0.0) + v
        for k, v in part.get("gauges", {}).items():
            out["gauges"][k] = v
        for k, h in part.get("histograms", {}).items():
            prev = out["histograms"].get(k)
            out["histograms"][k] = (
                dict(h, le=list(h["le"]), cum=list(h["cum"]))
                if prev is None else merge_ladders(prev, h))
    return out


def ladder_quantile(h: dict, q: float) -> float:
    """Quantile from a cumulative ladder, saturating at the largest finite
    bound (the +Inf bucket must not invent an unbounded estimate)."""
    total = h["count"]
    if total <= 0:
        return 0.0
    finite = [b for b in h["le"] if b != math.inf]
    if not finite:
        return 0.0
    target = q * total
    for bound, cum in zip(h["le"], h["cum"]):
        if cum >= target:
            return bound if bound != math.inf else finite[-1]
    return finite[-1]


def sum_counters(counters: dict, name: str) -> float:
    """Sum every series of a base name across its label sets."""
    total = 0.0
    for key, v in counters.items():
        base = key.split("{", 1)[0]
        if base == name:
            total += v
    return total


# --------------------------------------------------------------------------- #
# SLO burn-rate evaluation (multi-window, edge-triggered)
# --------------------------------------------------------------------------- #


class SloMonitor:
    """One SLO object over fleet counter series.

    ``spec`` fields: ``name``, ``bad`` (counter series of SLO-violating
    events), ``total`` (counter series of all events), ``objective``
    (e.g. 0.999), ``window_s`` (long window), ``short_window_s``,
    ``threshold`` (burn-rate multiple that pages).  Burn rate over a
    window = (Δbad / Δtotal) / (1 - objective); 1.0 means the error
    budget is being spent exactly at the sustainable rate.
    """

    def __init__(self, spec: dict):
        self.name = str(spec["name"])
        self.bad = str(spec["bad"])
        self.total = str(spec["total"])
        self.objective = float(spec.get("objective", 0.999))
        self.window_s = float(spec.get("window_s", 60.0))
        self.short_window_s = float(
            spec.get("short_window_s", max(self.window_s / 12.0, 1.0)))
        self.threshold = float(spec.get("threshold", 2.0))
        self.severity = str(spec.get("severity", "page"))
        self._history: list = []  # (mono, bad_total, total_total)
        self._active = False

    def _burn(self, now: float, window_s: float) -> float:
        cutoff = now - window_s
        base = None
        for sample in self._history:
            if sample[0] <= cutoff:
                base = sample
            else:
                break
        if base is None:
            base = self._history[0]
        head = self._history[-1]
        d_bad = head[1] - base[1]
        d_total = head[2] - base[2]
        if d_total <= 0:
            return 0.0
        ratio = d_bad / d_total
        budget = max(1.0 - self.objective, 1e-9)
        return ratio / budget

    def observe(self, now: float, counters: dict) -> dict:
        """Feed one poll's fleet counters; returns the evaluation, with
        ``fire=True`` exactly once per threshold crossing (edge trigger —
        the alert de-activates only when the LONG window recovers)."""
        bad = sum_counters(counters, self.bad)
        total = sum_counters(counters, self.total)
        self._history.append((now, bad, total))
        cutoff = now - 2 * self.window_s
        while len(self._history) > 2 and self._history[1][0] <= cutoff:
            self._history.pop(0)
        long_burn = self._burn(now, self.window_s)
        short_burn = self._burn(now, self.short_window_s)
        over = long_burn > self.threshold and short_burn > self.threshold
        fire = over and not self._active
        if over:
            self._active = True
        elif long_burn <= self.threshold:
            self._active = False
        return {
            "slo": self.name,
            "burn_rate": round(long_burn, 4),
            "short_burn_rate": round(short_burn, 4),
            "threshold": self.threshold,
            "window_s": self.window_s,
            "short_window_s": self.short_window_s,
            "objective": self.objective,
            "bad": bad,
            "total": total,
            "severity": self.severity,
            "fire": fire,
        }


# --------------------------------------------------------------------------- #
# Scraping
# --------------------------------------------------------------------------- #


def scrape_replica(endpoint: str, timeout_s: float = 2.0) -> dict:
    """GET ``http://<endpoint>/metrics`` and parse; raises OSError-family
    on any transport failure (the caller turns that into ``up=0``)."""
    url = f"http://{endpoint}/metrics"
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return parse_exposition(resp.read().decode())


def tail_snapshot(path: str, stale_s: float) -> dict:
    """Newest fresh ``metrics_snapshot`` record in a JSONL stream, in
    ladder form; ``{}`` when the file is missing, torn, has no snapshot,
    or the newest one is older than ``stale_s``."""
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError:
        return {}
    for line in reversed(lines):
        try:
            rec = json.loads(line)
        except ValueError:
            continue  # torn trailing line mid-write: legal, skip
        if rec.get("type") != "metrics_snapshot":
            continue
        if stale_s > 0 and time.time() - float(rec.get("ts", 0)) > stale_s:
            return {}
        return snapshot_to_ladder(rec)
    return {}


def poll_once(replicas: list, train_logs: list, stale_s: float,
              timeout_s: float = 2.0) -> dict:
    """One fleet poll: scrape + tail + merge.  Returns the aggregate plus
    the per-source ``up`` map (replica index / train log path -> 0 or 1)."""
    parts = []
    up: dict = {}
    for i, endpoint in enumerate(replicas):
        try:
            parts.append(scrape_replica(endpoint, timeout_s))
            up[f"replica_{i}"] = 1
        except (OSError, urllib.error.URLError, ValueError):
            up[f"replica_{i}"] = 0
    for path in train_logs:
        snap = tail_snapshot(path, stale_s)
        key = f"train_{os.path.basename(path)}"
        if snap:
            parts.append(snap)
            up[key] = 1
        else:
            up[key] = 0
    agg = merge_parsed(parts)
    for key, alive in up.items():
        agg["gauges"][f'up{{source="{key}"}}'] = float(alive)
    return {"aggregate": agg, "up": up}


def _emit(out_path: str, record: dict) -> None:
    """Append one JSONL record (same append-mode discipline as
    ``utils.logging.JsonlLogger`` — no tmp file needed for appends)."""
    with open(out_path, "a") as f:
        f.write(json.dumps(record) + "\n")


def _json_histograms(hist: dict) -> dict:
    """Ladder histograms with JSON-safe bounds (inf -> null)."""
    out = {}
    for key, h in hist.items():
        out[key] = {
            "le": [None if b == math.inf else b for b in h["le"]],
            "cum": h["cum"],
            "sum": h["sum"],
            "count": h["count"],
        }
    return out


def run_agent(args) -> int:
    slos = [SloMonitor(json.loads(s)) for s in args.slo]
    deadline = (time.monotonic() + args.duration_s
                if args.duration_s > 0 else None)
    seq = 0
    fired = 0
    while True:
        t_poll = time.monotonic()
        polled = poll_once(args.replica, args.train_log, args.stale_s,
                           timeout_s=args.scrape_timeout_s)
        agg = polled["aggregate"]
        seq += 1
        _emit(args.out, {
            "type": "metrics_snapshot",
            "ts": time.time(),
            "source": "fleet",
            "seq": seq,
            "interval_s": args.interval_s,
            "counters": agg["counters"],
            "gauges": agg["gauges"],
            "histograms": _json_histograms(agg["histograms"]),
            "up": polled["up"],
        })
        for slo in slos:
            verdict = slo.observe(t_poll, agg["counters"])
            if verdict.pop("fire"):
                fired += 1
                verdict["type"] = "slo_burn"
                verdict["ts"] = time.time()
                _emit(args.out, verdict)
                print(f"| metrics_agent: SLO burn: {verdict['slo']} "
                      f"burn_rate={verdict['burn_rate']} "
                      f"(threshold {verdict['threshold']})", flush=True)
        if args.once:
            break
        if deadline is not None and time.monotonic() >= deadline:
            break
        time.sleep(max(args.interval_s - (time.monotonic() - t_poll), 0.05))
    print(f"| metrics_agent: {seq} poll(s), {fired} slo_burn record(s) "
          f"-> {args.out}", flush=True)
    return 0


def _parse_args(argv=None):
    p = argparse.ArgumentParser("cil-tpu fleet metrics agent")
    p.add_argument("--replica", action="append", default=[],
                   help="replica or front-end /metrics endpoint host:port "
                   "(repeatable)")
    p.add_argument("--train-log", action="append", default=[],
                   help="train-process JSONL stream to tail for "
                   "metrics_snapshot records (repeatable)")
    p.add_argument("--out", required=True,
                   help="fleet-aggregate JSONL output (appended)")
    p.add_argument("--interval_s", type=float, default=2.0)
    p.add_argument("--duration_s", type=float, default=0.0,
                   help="stop after this long (0 = run until killed)")
    p.add_argument("--once", action="store_true",
                   help="one poll, one record, exit (tests)")
    p.add_argument("--stale_s", type=float, default=30.0,
                   help="a train snapshot older than this is stale (up=0)")
    p.add_argument("--scrape_timeout_s", type=float, default=2.0)
    p.add_argument("--slo", action="append", default=[],
                   help="SLO spec JSON: {name, bad, total, objective, "
                   "window_s, short_window_s, threshold} (repeatable)")
    return p.parse_args(argv)


def main(argv=None) -> int:
    return run_agent(_parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
