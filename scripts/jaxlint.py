#!/usr/bin/env python
"""jaxlint CLI — the CI gate over the repo's JAX-hazard rules.

Usage:
    python scripts/jaxlint.py                     # lint the default targets
    python scripts/jaxlint.py path1 path2 ...     # lint specific files/dirs
    python scripts/jaxlint.py --write-baseline    # accept current findings
    python scripts/jaxlint.py --baseline none     # ignore the baseline
    python scripts/jaxlint.py --list-rules        # print the rule catalog
    python scripts/jaxlint.py --format json       # machine-readable findings

Exit codes: 0 = no findings outside the baseline; 1 = new findings (printed
as ``path:line:col: RULE message``); 2 = usage error.  Stale baseline
entries (fixed findings still listed) are warned about but do not fail —
refresh with ``--write-baseline`` — unless ``--check-baseline`` is given
(the CI mode: a rotted suppression fails the run so the baseline always
matches reality).

Stdlib-only: this never imports jax, so the lint stage runs anywhere.
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

from analysis import (  # noqa: E402 - needs the sys.path bootstrap above
    DEFAULT_TARGETS,
    Baseline,
    RULES,
    lint_paths,
)
from analysis.contracts import CONTRACT_RULES  # noqa: E402
from analysis.linter import DEFAULT_BASELINE  # noqa: E402


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="jaxlint", description=__doc__)
    parser.add_argument("paths", nargs="*", help="files/dirs relative to the "
                        "repo root (default: the committed lint scope)")
    parser.add_argument("--root", default=_REPO_ROOT,
                        help="project root findings are reported relative to")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE,
                        help="baseline JSON path, or 'none' to disable")
    parser.add_argument("--write-baseline", action="store_true",
                        help="rewrite the baseline to the current findings "
                        "(keeps reasons of entries that still match)")
    parser.add_argument("--check-baseline", action="store_true",
                        help="fail (exit 1) when a baseline entry no longer "
                        "matches any live finding, instead of only warning")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("--format", choices=("text", "json"), default="text",
                        help="'json' emits a stable machine-readable report "
                        "(schema: version, counts, findings[{file, line, col, "
                        "rule, message, suppressed}]) for report_run.py; the "
                        "exit code still reflects new findings")
    args = parser.parse_args(argv)

    if args.list_rules:
        # The full JL catalog across every pass: the jaxlint AST rules
        # (JL0xx-JL4xx) plus the contractlint cross-artifact rules (JL5xx,
        # enforced by scripts/contractlint.py).  One namespace, one listing.
        for rule, summary in sorted({**RULES, **CONTRACT_RULES}.items()):
            print(f"{rule}  {summary}")
        return 0

    root = os.path.abspath(args.root)
    targets = args.paths or list(DEFAULT_TARGETS)
    findings = lint_paths(targets, root=root)

    baseline_path = None if args.baseline.lower() == "none" else (
        args.baseline if os.path.isabs(args.baseline)
        else os.path.join(root, args.baseline))
    baseline = Baseline.load(baseline_path) if baseline_path else Baseline()

    if args.write_baseline:
        if not baseline_path:
            print("jaxlint: --write-baseline needs a baseline path", file=sys.stderr)
            return 2
        baseline.write(baseline_path, findings)
        print(f"jaxlint: baseline rewritten with {len(findings)} finding(s) "
              f"-> {os.path.relpath(baseline_path, root)}")
        return 0

    new, known, stale = baseline.split(findings)

    if args.format == "json":
        import json

        known_keys = {f.key for f in known}
        report = {
            "version": 1,
            "root": root,
            "rules": dict(sorted(RULES.items())),
            "counts": {"new": len(new), "baselined": len(known),
                       "stale_baseline": len(stale)},
            "findings": [
                {
                    "file": f.path,
                    "line": f.line,
                    "col": f.col,
                    "rule": f.rule,
                    "message": f.message,
                    "suppressed": f.key in known_keys,
                }
                for f in sorted(findings,
                                key=lambda f: (f.path, f.line, f.col, f.rule))
            ],
            "stale_baseline": list(stale),
        }
        print(json.dumps(report, indent=2, sort_keys=True))
        if stale and args.check_baseline:
            return 1
        return 1 if new else 0

    for f in new:
        print(f.render())
    if known:
        print(f"jaxlint: {len(known)} baselined finding(s) suppressed "
              f"(see {os.path.relpath(baseline_path, root)})")
    for e in stale:
        print(f"jaxlint: stale baseline entry (fixed? refresh with "
              f"--write-baseline): {e['path']}:{e['line']} {e['rule']}")
    if stale and args.check_baseline:
        print(f"jaxlint: --check-baseline: {len(stale)} stale baseline "
              "entr(y/ies) no longer match any live finding; remove them or "
              "refresh with --write-baseline")
        return 1
    if new:
        print(f"jaxlint: {len(new)} new finding(s) in {len(set(f.path for f in new))} "
              "file(s); fix them, add '# jaxlint: disable=<rule>' with a reason, "
              "or baseline with --write-baseline")
        return 1
    print(f"jaxlint: clean ({len(findings)} finding(s) total, "
          f"{len(known)} baselined)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
