#!/usr/bin/env python
"""Replace the committed orbax checkpoint blob tree with reviewable evidence
(r4 verdict Weak #6 / Next #8): a sha256 manifest of every checkpoint file
plus the JSONL twin-equality check — the resumed run's post-resume task
records must match the uninterrupted twin bit-for-bit on every accuracy and
γ (wall-clock/compile columns legitimately differ).

Usage:
    python scripts/make_resume_manifest.py experiments/ckpt_b50_resume \
        experiments/b50_inc10_synthetic_hard128_aa35_mem256.jsonl \
        experiments/b50_inc10_synthetic_hard128_aa35_mem256_resume.jsonl \
        > experiments/ckpt_b50_resume_manifest.json
"""

from __future__ import annotations

import hashlib
import json
import os
import sys


def file_manifest(root: str):
    entries = []
    for dirpath, _dirnames, filenames in os.walk(root):
        for name in sorted(filenames):
            path = os.path.join(dirpath, name)
            h = hashlib.sha256()
            with open(path, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
            entries.append(
                {
                    "path": os.path.relpath(path, root),
                    "bytes": os.path.getsize(path),
                    "sha256": h.hexdigest(),
                }
            )
    return sorted(entries, key=lambda e: e["path"])


def task_records(path: str):
    records, start = {}, 0
    with open(path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("type") == "resume":
                start = max(start, rec.get("start_task") or 0)
            elif rec.get("type") == "task":
                records[rec["task_id"]] = rec
    return records, start


def main(ckpt_dir: str, twin_path: str, resume_path: str) -> None:
    twin, _ = task_records(twin_path)
    resumed, start = task_records(resume_path)
    comparisons = []
    equal = True
    for tid in sorted(resumed):
        if tid < start:
            continue  # pre-crash segment; the twin check covers post-resume
        a, b = twin.get(tid), resumed[tid]
        same = (
            a is not None
            and a["acc1"] == b["acc1"]
            and a.get("gamma") == b.get("gamma")
            and a.get("acc1s") == b.get("acc1s")
        )
        equal &= same
        comparisons.append(
            {
                "task_id": tid,
                "twin_acc1": None if a is None else a["acc1"],
                "resumed_acc1": b["acc1"],
                "twin_gamma": None if a is None else a.get("gamma"),
                "resumed_gamma": b.get("gamma"),
                "bitwise_equal": same,
            }
        )

    files = file_manifest(ckpt_dir)
    json.dump(
        {
            "what": (
                "sha256 manifest of the orbax checkpoint tree used for the "
                "live SIGKILL-and-resume evidence, plus the JSONL twin "
                "equality check; replaces the previously committed binary "
                "tree (r4 verdict Weak #6)"
            ),
            "ckpt_dir": ckpt_dir,
            "nb_files": len(files),
            "total_bytes": sum(e["bytes"] for e in files),
            "files": files,
            "twin_log": twin_path,
            "resume_log": resume_path,
            "resume_start_task": start,
            "post_resume_comparison": comparisons,
            "post_resume_bitwise_equal": equal,
        },
        sys.stdout,
        indent=1,
    )
    print()


if __name__ == "__main__":
    if len(sys.argv) != 4:
        sys.exit("usage: make_resume_manifest.py <ckpt_dir> <twin.jsonl> <resume.jsonl>")
    main(*sys.argv[1:])
