#!/usr/bin/env python
"""CPU prefetch smoke: depth-2 must reproduce depth-0 exactly.

Runs the same tiny 2-task synthetic protocol twice — synchronously
(``prefetch_depth=0``) and double-buffered (``prefetch_depth=2``) — on the
per-batch step path (``fused_epochs=False``), so all three prefetching
consumers (train step loop, eval, herding feature pass) execute for real.
The accuracy matrices must be **identical**: the prefetcher's determinism
guarantee (byte-identical batch streams) is a testable property, not a
comment.  Exit 0 on exact match, 1 otherwise, one JSON line either way.

Used by ``scripts/ci.sh``; runnable standalone from anywhere.
"""

from __future__ import annotations

import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def main() -> int:
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.utils.platform import (
        force_platform,
    )

    # Same persistent compile cache as the test suite: the smoke must not
    # repay the XLA:CPU compile of programs the tier-1 run already built.
    force_platform(
        "cpu", compile_cache_dir=os.path.join(_REPO, "tests", ".jax_cache")
    )
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.config import (
        CilConfig,
    )
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.engine import (
        CilTrainer,
    )

    base = dict(
        data_set="synthetic10",
        num_bases=0,
        increment=5,
        backbone="resnet20",
        batch_size=16,
        num_epochs=2,
        eval_every_epoch=100,
        memory_size=40,
        lr=0.05,
        aa=None,
        color_jitter=0.0,
        seed=7,
        fused_epochs=False,  # the per-batch path is what prefetching covers
    )
    matrices = {}
    for depth in (0, 2):
        trainer = CilTrainer(
            CilConfig(**base, prefetch_depth=depth), init_dist=False
        )
        matrices[depth] = trainer.fit()["acc_matrix"]
    identical = matrices[0] == matrices[2]
    print(
        json.dumps(
            {
                "metric": "prefetch_smoke",
                "identical": identical,
                "acc_matrix_depth0": matrices[0],
                "acc_matrix_depth2": matrices[2],
            }
        )
    )
    return 0 if identical else 1


if __name__ == "__main__":
    sys.exit(main())
