#!/usr/bin/env python
"""Render the reference-race report: JAX framework vs the actual reference
implementation (torch-CPU, ``scripts/torch_reference_race.py``) on the
identical protocol — per-task cumulative top-1, weight-alignment γ, the
per-slice accuracy matrix, and avg incremental top-1, with deltas and a
stated tolerance verdict (r4 verdict Next #1).

Usage:
    python scripts/compare_race.py experiments/race_jax.jsonl \
        experiments/race_torch.jsonl > RACE.md

Tolerances (stated up front, not fitted to the result): the two sides share
data, task splits, class order, batch math, herding semantics and
hyperparameters but draw independent RNG streams (init, shuffles,
augmentation), so agreement is trajectory-level, not bitwise.  We call the
race a PASS when cumulative per-task top-1 agrees within 3.0 points at
every task, γ within 0.10 at every alignment, and avg incremental top-1
within 2.0 points — tighter than the gap that would indicate an algorithmic
divergence (a missing KD term, a wrong alignment, broken rehearsal shift
trajectories by tens of points on this recipe; see the calibration pilots
in experiments/calib/).
"""

from __future__ import annotations

import json
import sys

TOL_TASK = 3.0
TOL_GAMMA = 0.10
TOL_AVG = 2.0


def load(path):
    tasks, final, meta = [], None, {}
    with open(path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("type") == "task":
                tasks.append(rec)
            elif rec.get("type") == "final":
                final = rec
            elif rec.get("type") == "run":
                meta = rec
    return tasks, final, meta


def main(jax_path, torch_path):
    jt, jf, jm = load(jax_path)
    tt, tf, tm = load(torch_path)
    if len(jt) != len(tt):
        sys.exit(
            f"task count mismatch: {jax_path} has {len(jt)}, "
            f"{torch_path} has {len(tt)}"
        )

    print("# RACE — this framework vs the reference implementation\n")
    print(
        "End-to-end behavioral race (r4 verdict Next #1): the **actual "
        "reference algorithm** — its own `resnet.py` backbone driven by a "
        "faithful torch-CPU restatement of `template.py:226-303` "
        "(`scripts/torch_reference_race.py`) — against this framework's "
        "`train.py`, on identical data, task splits, class order, "
        "hyperparameters, and herding semantics.  The sides share no "
        "compute code: torch autograd/BN/SGD vs JAX/XLA, PIL-style numpy "
        "augmentation vs on-device vmapped augmentation.  RNG streams are "
        "independent, so the comparison is trajectory-level.\n"
    )
    print(f"- JAX side:   `{jax_path}` — config `{json.dumps(jm, sort_keys=True)}`")
    print(f"- torch side: `{torch_path}` — config `{json.dumps(tm, sort_keys=True)}`\n")
    print(
        f"Stated tolerances: per-task cumulative top-1 within {TOL_TASK} "
        f"points, γ within {TOL_GAMMA}, avg incremental within {TOL_AVG} "
        "points (see script docstring for why).\n"
    )

    print("| task | jax top-1 | torch top-1 | Δ | jax γ | torch γ | Δγ |")
    print("|---|---|---|---|---|---|---|")
    ok = True
    for j, t in zip(jt, tt):
        d = j["acc1"] - t["acc1"]
        ok &= abs(d) <= TOL_TASK
        if j.get("gamma") is not None and t.get("gamma") is not None:
            dg = j["gamma"] - t["gamma"]
            ok &= abs(dg) <= TOL_GAMMA
            gcells = f"{j['gamma']:.4f} | {t['gamma']:.4f} | {dg:+.4f}"
        else:
            gcells = "— | — | —"
        print(
            f"| {j['task_id']} | {j['acc1']:.2f} | {t['acc1']:.2f} | "
            f"{d:+.2f} | {gcells} |"
        )

    if jf and tf:
        da = jf["avg_incremental_acc1"] - tf["avg_incremental_acc1"]
        ok &= abs(da) <= TOL_AVG
        print(
            f"\n**avg incremental top-1: jax "
            f"{jf['avg_incremental_acc1']:.3f} vs torch "
            f"{tf['avg_incremental_acc1']:.3f} (Δ {da:+.3f})**\n"
        )
    else:
        ok = False
        print("\n(one side did not complete — no `final` record)\n")

    # Per-slice accuracy matrix deltas: where forgetting happens must match,
    # not just the cumulative number.
    if all("acc_per_task" in r for r in jt + tt):
        T = len(jt)
        print("per-slice Δ(top-1) (jax − torch), row = after task t:\n")
        print("| after task | " + " | ".join(f"j={j}" for j in range(T)) + " |")
        print("|---|" + "---|" * T)
        worst = 0.0
        for j, t in zip(jt, tt):
            ds = [a - b for a, b in zip(j["acc_per_task"], t["acc_per_task"])]
            worst = max(worst, max(abs(x) for x in ds))
            cells = [f"{x:+.2f}" for x in ds] + ["—"] * (T - len(ds))
            print(f"| {j['task_id']} | " + " | ".join(cells) + " |")
        print(
            f"\nworst per-slice disagreement: {worst:.2f} points (slices "
            "are 10-class groups — noisier than the cumulative number; "
            "reported, not gated)\n"
        )

    print(
        f"**VERDICT: {'PASS' if ok else 'FAIL'}** — "
        + (
            "the integrated trajectories agree within the stated "
            "tolerances; every component-level parity claim survives "
            "end-to-end composition."
            if ok
            else "at least one metric exceeds its stated tolerance; see "
            "the deltas above."
        )
    )


if __name__ == "__main__":
    if len(sys.argv) != 3:
        sys.exit("usage: compare_race.py <jax.jsonl> <torch.jsonl>")
    main(sys.argv[1], sys.argv[2])
