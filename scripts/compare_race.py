#!/usr/bin/env python
"""Render the reference-race report: JAX framework vs the actual reference
implementation (torch-CPU, ``scripts/torch_reference_race.py``) on the
identical protocol — per-task cumulative top-1, weight-alignment γ, the
per-slice accuracy matrix, and avg incremental top-1, with deltas and a
stated tolerance verdict (r4 verdict Next #1).

Usage:
    python scripts/compare_race.py experiments/race_jax.jsonl \
        experiments/race_torch.jsonl [experiments/race_torch_seed1.jsonl \
        [experiments/race_jax_seed1.jsonl]] > RACE.md

The optional third log is a SECOND SEED of the torch side: it measures the
same-implementation seed-to-seed spread of this protocol, the only honest
yardstick for whether a cross-implementation delta means anything.  The
optional fourth log is a second seed of the JAX side, completing the 2x2:
both implementations' seed bands are rendered and checked for overlap.
The strict gates below stay a-priori; the noise sections are reported
separately and never edit the verdict.

Tolerances (stated up front, not fitted to the result): the two sides share
data, task splits, class order, batch math, herding semantics and
hyperparameters but draw independent RNG streams (init, shuffles,
augmentation), so agreement is trajectory-level, not bitwise.  We call the
race a PASS when cumulative per-task top-1 agrees within 3.0 points at
every task, γ within 0.10 at every alignment, and avg incremental top-1
within 2.0 points — tighter than the gap that would indicate an algorithmic
divergence (a missing KD term, a wrong alignment, broken rehearsal shift
trajectories by tens of points on this recipe; see the calibration pilots
in experiments/calib/).
"""

from __future__ import annotations

import json
import sys

TOL_TASK = 3.0
TOL_GAMMA = 0.10
TOL_AVG = 2.0


def load(path):
    tasks, final, meta = [], None, {}
    with open(path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("type") == "task":
                tasks.append(rec)
            elif rec.get("type") == "final":
                final = rec
            elif rec.get("type") == "run":
                meta = rec
    return tasks, final, meta


def load_checked(path, expected_len, other_path):
    """Load a second-seed log and refuse a task-count mismatch — a
    truncated log would make the spread and the cross deltas cover
    different task ranges, a miscalibrated yardstick."""
    tasks, final, meta = load(path)
    if len(tasks) != expected_len:
        sys.exit(
            f"task count mismatch: {path} has {len(tasks)}, "
            f"{other_path} has {expected_len}"
        )
    return tasks, final, meta


def main(jax_path, torch_path, noise_path=None, jax_noise_path=None):
    jt, jf, jm = load(jax_path)
    tt, tf, tm = load(torch_path)
    if len(jt) != len(tt):
        sys.exit(
            f"task count mismatch: {jax_path} has {len(jt)}, "
            f"{torch_path} has {len(tt)}"
        )

    print("# RACE — this framework vs the reference implementation\n")
    print(
        "End-to-end behavioral race (r4 verdict Next #1): the **actual "
        "reference algorithm** — its own `resnet.py` backbone driven by a "
        "faithful torch-CPU restatement of `template.py:226-303` "
        "(`scripts/torch_reference_race.py`) — against this framework's "
        "`train.py`, on identical data, task splits, class order, "
        "hyperparameters, and herding semantics.  The sides share no "
        "compute code: torch autograd/BN/SGD vs JAX/XLA, PIL-style numpy "
        "augmentation vs on-device vmapped augmentation.  RNG streams are "
        "independent, so the comparison is trajectory-level.\n"
    )
    print(f"- JAX side:   `{jax_path}` — config `{json.dumps(jm, sort_keys=True)}`")
    print(f"- torch side: `{torch_path}` — config `{json.dumps(tm, sort_keys=True)}`\n")
    print(
        f"Stated tolerances: per-task cumulative top-1 within {TOL_TASK} "
        f"points, γ within {TOL_GAMMA}, avg incremental within {TOL_AVG} "
        "points (see script docstring for why).\n"
    )

    print("| task | jax top-1 | torch top-1 | Δ | jax γ | torch γ | Δγ |")
    print("|---|---|---|---|---|---|---|")
    ok = True
    for j, t in zip(jt, tt):
        d = j["acc1"] - t["acc1"]
        ok &= abs(d) <= TOL_TASK
        if j.get("gamma") is not None and t.get("gamma") is not None:
            dg = j["gamma"] - t["gamma"]
            ok &= abs(dg) <= TOL_GAMMA
            gcells = f"{j['gamma']:.4f} | {t['gamma']:.4f} | {dg:+.4f}"
        elif j.get("task_id", 0) > 0:
            # Alignment runs on every task > 0, so a missing γ here means
            # one side skipped (or failed to log) a protocol stage — that
            # fails the γ gate rather than silently rendering a dash.
            ok = False
            gj = f"{j['gamma']:.4f}" if j.get("gamma") is not None else "MISSING"
            gt = f"{t['gamma']:.4f}" if t.get("gamma") is not None else "MISSING"
            gcells = f"{gj} | {gt} | —"
            print(
                f"WARNING: task {j['task_id']} is missing a gamma on "
                f"{'the jax side' if j.get('gamma') is None else 'the torch side'}"
                " — alignment did not run or did not log; γ gate FAILED",
                file=sys.stderr,
            )
        else:
            gcells = "— | — | —"  # task 0: no alignment by protocol
        print(
            f"| {j['task_id']} | {j['acc1']:.2f} | {t['acc1']:.2f} | "
            f"{d:+.2f} | {gcells} |"
        )

    if jf and tf:
        da = jf["avg_incremental_acc1"] - tf["avg_incremental_acc1"]
        ok &= abs(da) <= TOL_AVG
        print(
            f"\n**avg incremental top-1: jax "
            f"{jf['avg_incremental_acc1']:.3f} vs torch "
            f"{tf['avg_incremental_acc1']:.3f} (Δ {da:+.3f})**\n"
        )
    else:
        ok = False
        print("\n(one side did not complete — no `final` record)\n")

    # Per-slice accuracy matrix deltas: where forgetting happens must match,
    # not just the cumulative number.
    if all("acc_per_task" in r for r in jt + tt):
        T = len(jt)
        print("per-slice Δ(top-1) (jax − torch), row = after task t:\n")
        print("| after task | " + " | ".join(f"j={j}" for j in range(T)) + " |")
        print("|---|" + "---|" * T)
        worst = 0.0
        for j, t in zip(jt, tt):
            ds = [a - b for a, b in zip(j["acc_per_task"], t["acc_per_task"])]
            worst = max(worst, max(abs(x) for x in ds))
            cells = [f"{x:+.2f}" for x in ds] + ["—"] * (T - len(ds))
            print(f"| {j['task_id']} | " + " | ".join(cells) + " |")
        print(
            f"\nworst per-slice disagreement: {worst:.2f} points (slices "
            "are 10-class groups — noisier than the cumulative number; "
            "reported, not gated)\n"
        )

    print(
        f"**VERDICT: {'PASS' if ok else 'FAIL'}** — "
        + (
            "the integrated trajectories agree within the stated "
            "tolerances; no evidence of algorithmic divergence."
            if ok
            else "at least one metric exceeds its stated tolerance; see "
            "the deltas above."
        )
    )

    if noise_path:
        nt, nf, nm = load_checked(noise_path, len(tt), torch_path)
        print(
            "\n## Seed-noise yardstick (same implementation, second seed)\n"
        )
        print(
            f"`{noise_path}` re-runs the **torch reference side itself** "
            f"with seed {nm.get('seed')} — every delta below is two runs "
            "of the *same* code differing only in RNG, i.e. the protocol's "
            "intrinsic run-to-run spread:\n"
        )
        print("| task | torch seed0 | torch seed1 | same-impl Δ | cross-impl Δ (jax−torch) |")
        print("|---|---|---|---|---|")
        spread = 0.0
        for t, n_rec, j in zip(tt, nt, jt):
            ds = t["acc1"] - n_rec["acc1"]
            spread = max(spread, abs(ds))
            print(
                f"| {t['task_id']} | {t['acc1']:.2f} | {n_rec['acc1']:.2f} "
                f"| {ds:+.2f} | {j['acc1'] - t['acc1']:+.2f} |"
            )
        worst_cross = max(abs(j["acc1"] - t["acc1"]) for j, t in zip(jt, tt))
        if jf and tf and nf:
            avgs = sorted(
                [tf["avg_incremental_acc1"], nf["avg_incremental_acc1"]]
            )
            jx = jf["avg_incremental_acc1"]
            inside = avgs[0] <= jx <= avgs[1]
            print(
                f"\navg incremental top-1: torch seeds span "
                f"[{avgs[0]:.3f}, {avgs[1]:.3f}]; the jax run lands at "
                f"{jx:.3f} — {'INSIDE' if inside else 'outside'} the "
                "reference's own seed band.\n"
            )
        cross = [j["acc1"] - t["acc1"] for j, t in zip(jt, tt)]
        oscillates = any(d > 0 for d in cross) and any(d < 0 for d in cross)
        sign_clause = (
            ", and the deltas oscillate in sign (no side consistently "
            "ahead) — what seed noise looks like, not what an algorithmic "
            "divergence (missing KD/alignment/rehearsal) looks like: those "
            "shift trajectories by tens of points, always in one direction"
            if oscillates
            else "; note the deltas share one sign across tasks, so a "
            "small systematic offset cannot be ruled out at single-run "
            "resolution"
        )
        print(
            f"\nmax same-implementation spread: {spread:.2f} points; "
            f"max cross-implementation delta: {worst_cross:.2f} points. "
            + (
                "The cross-implementation deltas are within ~the "
                "same-implementation seed spread — the strict per-task "
                "gate above is tighter than this protocol's intrinsic "
                "noise at single-run resolution" + sign_clause + "."
                if worst_cross <= spread * 1.5
                else "The cross-implementation deltas EXCEED the measured "
                "seed spread — evidence of a systematic behavioral "
                "difference worth diagnosing."
            )
        )

    if jax_noise_path:
        if not noise_path:
            # Without the torch second seed there is no torch band; a
            # degenerate single-run "band" would misrepresent the data.
            sys.exit("jax_seed2 requires torch_seed2 (the 2x2 needs both)")
        jnt, jnf, jnm = load_checked(jax_noise_path, len(jt), jax_path)
        print("\n## Both seed bands (2×2)\n")
        print(
            f"`{jax_noise_path}` is the JAX side at seed "
            f"{jnm.get('seed')} — with two seeds per implementation, the "
            "per-task bands can be compared directly:\n"
        )
        print(
            "| task | jax band | torch band | bands overlap |"
        )
        print("|---|---|---|---|")
        overlaps = 0
        for j, jn, t, n_rec in zip(jt, jnt, tt, nt):
            jlo, jhi = sorted([j["acc1"], jn["acc1"]])
            tlo, thi = sorted([t["acc1"], n_rec["acc1"]])
            ov = jlo <= thi and tlo <= jhi
            overlaps += ov
            print(
                f"| {j['task_id']} | [{jlo:.2f}, {jhi:.2f}] | "
                f"[{tlo:.2f}, {thi:.2f}] | {'yes' if ov else 'no'} |"
            )
        if jf and jnf and tf and nf:
            jb = sorted([jf["avg_incremental_acc1"], jnf["avg_incremental_acc1"]])
            tb = sorted([tf["avg_incremental_acc1"], nf["avg_incremental_acc1"]])
            ov = jb[0] <= tb[1] and tb[0] <= jb[1]
            print(
                f"\navg incremental: jax band [{jb[0]:.3f}, {jb[1]:.3f}] vs "
                f"torch band [{tb[0]:.3f}, {tb[1]:.3f}] — "
                f"{'overlapping' if ov else 'disjoint'}. "
            )
        print(
            f"\n{overlaps}/{len(jt)} per-task bands overlap (two-seed "
            "bands understate the true spread, so non-overlap at a task "
            "is weak evidence by itself; the avg-incremental bands and "
            "the oscillation analysis above carry the conclusion)."
        )


if __name__ == "__main__":
    if len(sys.argv) not in (3, 4, 5):
        sys.exit(
            "usage: compare_race.py <jax.jsonl> <torch.jsonl> "
            "[torch_seed2.jsonl [jax_seed2.jsonl]]"
        )
    main(*sys.argv[1:])
