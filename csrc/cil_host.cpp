// Native host-side kernels for the TPU CIL framework.
//
// The reference inherits its native runtime from torch: the DataLoader's C++
// worker pool moves/collates batches (reference template.py:236-239) and
// continuum's herding runs in numpy.  Here the two host-side hot paths are
// C++ with a ctypes ABI (no pybind11 in this toolchain):
//
//   * herd_barycenter: the iCaRL greedy exemplar ranking
//     (reference README.md:134-136 derivation).  O(nb * n * d) with no
//     temporary allocations — the numpy version materializes an [n, d]
//     candidate-mean matrix per selection step.
//   * gather_u8: multithreaded fancy-index gather of uint8 rows, the batch
//     assembly step of the input pipeline (replaces DataLoader collate).
//
// Build: make -C csrc   (produces libcilhost.so; loaded via ctypes with a
// numpy fallback, utils/native.py).

#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <limits>
#include <thread>
#include <vector>

extern "C" {

// Rank `nb` of the `n` feature rows (row-major [n, d] float32) by iCaRL
// barycenter greedy; writes selected indices in selection order to out[nb].
// Returns 0 on success.
int herd_barycenter(const float* feats, int64_t n, int64_t d, int64_t nb,
                    int64_t* out) {
  if (n <= 0 || d <= 0 || nb <= 0) return 1;
  if (nb > n) nb = n;

  std::vector<double> mu(d, 0.0);
  for (int64_t i = 0; i < n; ++i) {
    const float* row = feats + i * d;
    for (int64_t j = 0; j < d; ++j) mu[j] += row[j];
  }
  for (int64_t j = 0; j < d; ++j) mu[j] /= static_cast<double>(n);

  std::vector<double> running(d, 0.0);
  std::vector<uint8_t> taken(n, 0);
  for (int64_t k = 0; k < nb; ++k) {
    const double denom = static_cast<double>(k + 1);
    double best = std::numeric_limits<double>::infinity();
    int64_t best_i = -1;
    for (int64_t i = 0; i < n; ++i) {
      if (taken[i]) continue;
      const float* row = feats + i * d;
      double dist = 0.0;
      for (int64_t j = 0; j < d; ++j) {
        // Same arithmetic as the numpy fallback (divide, squared distance)
        // so the two paths only differ by summation order (sub-ulp).
        const double diff = mu[j] - (running[j] + row[j]) / denom;
        dist += diff * diff;
      }
      if (dist < best) {
        best = dist;
        best_i = i;
      }
    }
    if (best_i < 0) return 2;
    out[k] = best_i;
    taken[best_i] = 1;
    const float* row = feats + best_i * d;
    for (int64_t j = 0; j < d; ++j) running[j] += row[j];
  }
  return 0;
}

// dst[i] = src[idx[i]] for rows of `item_bytes` bytes, fanned out over
// `threads` workers (0 = hardware concurrency).
int gather_u8(const uint8_t* src, int64_t n_src, const int64_t* idx,
              int64_t n_out, int64_t item_bytes, uint8_t* dst,
              int64_t threads) {
  for (int64_t i = 0; i < n_out; ++i)
    if (idx[i] < 0 || idx[i] >= n_src) return 1;
  int64_t nt = threads > 0
                   ? threads
                   : static_cast<int64_t>(std::thread::hardware_concurrency());
  if (nt < 1) nt = 1;
  if (nt > n_out) nt = n_out;
  // Below ~4 MB the thread spawn costs more than the copy.
  if (n_out * item_bytes < (4 << 20)) nt = 1;

  auto worker = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i)
      std::memcpy(dst + i * item_bytes, src + idx[i] * item_bytes,
                  static_cast<size_t>(item_bytes));
  };
  if (nt == 1) {
    worker(0, n_out);
    return 0;
  }
  std::vector<std::thread> pool;
  const int64_t chunk = (n_out + nt - 1) / nt;
  for (int64_t t = 0; t < nt; ++t) {
    const int64_t lo = t * chunk;
    const int64_t hi = std::min(n_out, lo + chunk);
    if (lo >= hi) break;
    pool.emplace_back(worker, lo, hi);
  }
  for (auto& th : pool) th.join();
  return 0;
}

}  // extern "C"
