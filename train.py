#!/usr/bin/env python
"""Repo-root launcher: ``python train.py [flags]`` — the TPU-native
equivalent of the reference's ``torchrun ... template.py`` command line."""

from a_pytorch_tutorial_to_class_incremental_learning_tpu.main import main

if __name__ == "__main__":
    main()
