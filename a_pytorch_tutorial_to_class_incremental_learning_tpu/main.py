"""CLI experiment driver.

Counterpart of the reference's ``__main__`` entry
(``template.py:191-303``; launched via torchrun, ``README.md:352-354``).
Here there is no launcher wrapper — a single process drives every local
device through the mesh, and multi-host pods launch the same command per host
(``jax.distributed`` auto-initializes).

Run as ``python -m a_pytorch_tutorial_to_class_incremental_learning_tpu``
or ``python train.py`` at the repo root, with the reference's flags::

    python train.py --data_set cifar --num_bases 50 --increment 10 \\
        --batch_size 128 --num_epochs 140
"""

from __future__ import annotations

import argparse
from typing import Optional, Sequence

from .config import config_from_args, get_args_parser
from .engine import CilTrainer
from .utils.platform import enable_compile_cache, force_platform


def main(argv: Optional[Sequence[str]] = None) -> dict:
    parser = argparse.ArgumentParser(
        "Class-Incremental Learning training and evaluation script (TPU)",
        parents=[get_args_parser()],
    )
    args = parser.parse_args(argv)
    if args.platform != "default":
        # Must happen before config_from_args, which may touch jax.devices()
        # to resolve the mesh shape.
        force_platform(args.platform, args.host_devices)
    elif args.host_devices:
        parser.error("--host_devices requires --platform cpu")
    if args.compile_cache:
        import jax

        # Respect a cache the embedding process already configured (e.g. the
        # test suite's tests/.jax_cache via conftest) — the CLI default only
        # fills the gap when none is set.
        if jax.config.jax_compilation_cache_dir is None:
            enable_compile_cache(args.compile_cache)
    config = config_from_args(args)
    trainer = CilTrainer(config)
    return trainer.fit()


if __name__ == "__main__":
    main()
