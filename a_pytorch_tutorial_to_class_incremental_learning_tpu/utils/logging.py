"""Metric smoothing and logging.

TPU-native counterpart of the reference's observability layer
(``SmoothedValue``/``MetricLogger``, reference utils.py:22-118).  Differences
by design:

* Values arriving from jitted steps are already *global* — the train/eval
  steps run on the full logical batch under ``jax.jit`` over the mesh, so the
  per-rank ``all_reduce`` of ``[count, total]`` (reference utils.py:36-43)
  is unnecessary inside a single process.  ``synchronize_between_processes``
  remains for the multi-host case, where it sums ``[count, total]`` over
  processes with a host-level allreduce.
* No CUDA tensors: everything is plain Python floats / numpy.
"""

from __future__ import annotations

import statistics
from collections import defaultdict, deque
from typing import Dict

import numpy as np


def _to_float(v) -> float:
    """Accept python numbers, 0-d numpy arrays and jax arrays."""
    if hasattr(v, "item"):
        return float(v.item())
    return float(v)


class SmoothedValue:
    """Sliding-window smoothed metric with global totals.

    Same surface as reference utils.py:22-73: ``update(value, n)``, window
    ``median``/``avg``, ``global_avg``, ``max``, ``value`` and a format
    string defaulting to ``"{median:.4f} ({global_avg:.4f})"``.
    """

    def __init__(self, window_size: int = 20, fmt: str | None = None):
        self.window: deque = deque(maxlen=window_size)
        self.total = 0.0
        self.count = 0
        self.fmt = fmt or "{median:.4f} ({global_avg:.4f})"

    def update(self, value, n: int = 1) -> None:
        value = _to_float(value)
        self.window.append(value)
        self.count += n
        self.total += value * n

    def synchronize_between_processes(self) -> None:
        """Sum ``[count, total]`` across JAX processes (multi-host only).

        Counterpart of the float64 NCCL all-reduce at reference
        utils.py:36-43.  Single-process (including single-process
        multi-device) is a no-op because step metrics are already global.
        """
        import jax

        if jax.process_count() == 1:
            return
        from jax.experimental import multihost_utils

        t = multihost_utils.process_allgather(
            np.asarray([self.count, self.total], dtype=np.float64)
        )
        t = np.sum(t, axis=0)
        self.count = int(t[0])
        self.total = float(t[1])

    @property
    def median(self) -> float:
        return statistics.median(self.window) if self.window else 0.0

    @property
    def avg(self) -> float:
        return sum(self.window) / len(self.window) if self.window else 0.0

    @property
    def global_avg(self) -> float:
        return self.total / max(self.count, 1)

    @property
    def max(self) -> float:
        return max(self.window) if self.window else 0.0

    @property
    def value(self) -> float:
        return self.window[-1] if self.window else 0.0

    def __str__(self) -> str:
        return self.fmt.format(
            median=self.median,
            avg=self.avg,
            global_avg=self.global_avg,
            max=self.max,
            value=self.value,
        )


class Sink:
    """The one telemetry sink interface: ``log(record_type, **fields)``.

    Everything structured this framework emits — experiment records
    (run/epoch/task/final), telemetry counters (recompile/hbm), spans,
    CIL metrics — goes through this surface, so consumers
    (``scripts/report_run.py``, ``scripts/check_telemetry_schema.py``)
    see one record vocabulary regardless of which subsystem produced it.
    """

    def log(self, record_type: str, **fields) -> None:  # pragma: no cover
        raise NotImplementedError


class NullSink(Sink):
    """Telemetry disabled: swallow every record (keeps call sites branch-free)."""

    def log(self, record_type: str, **fields) -> None:
        pass


def process_suffixed(path: str | None, process_index: int | None) -> str | None:
    """Per-process sibling of ``path``: process 0 keeps the legacy name
    (``run.jsonl``), process *i* > 0 writes ``run_p{i}.jsonl`` — the naming
    contract ``scripts/report_run.py`` uses to merge a fleet's streams and
    ``scripts/supervise.py``/``tpu_watchdog.sh`` use to probe every host."""
    if not path or not process_index:
        return path
    import os

    root, ext = os.path.splitext(path)
    return f"{root}_p{process_index}{ext}"


class JsonlLogger(Sink):
    """Structured experiment log: one JSON object per line.

    The reference's only output channel is rank-0 stdout (SURVEY.md §5
    "stdout only — no files, no structured logs"); this adds a
    machine-readable record (epoch metrics, per-task accuracies, gamma,
    timings).  Every process writes — each to its *own* per-process file
    (see :func:`process_suffixed`) — and every record is tagged with
    ``process_index``/``process_count``/``host_id`` so a merged multi-host
    report can attribute each line.  Disabled when ``path`` is falsy.
    ``process_index``/``process_count`` default from ``jax.process_index()``
    when distributed (0/1 otherwise); tests fake them to simulate a fleet.
    """

    def __init__(
        self,
        path: str | None,
        append: bool = False,
        process_index: int | None = None,
        process_count: int | None = None,
    ):
        if path and process_index is None:
            import jax

            process_index = jax.process_index()
            process_count = jax.process_count()
        self.process_index = int(process_index or 0)
        self.process_count = int(process_count or 1)
        self.path = process_suffixed(path, self.process_index)
        self._meta = {}
        if self.path:
            import os
            import socket

            self._meta = {
                "process_index": self.process_index,
                "process_count": self.process_count,
                "host_id": socket.gethostname(),
            }
            os.makedirs(
                os.path.dirname(os.path.abspath(self.path)), exist_ok=True
            )
            if not append:
                open(self.path, "w").close()  # one file per fresh run

    def log(self, record_type: str, **fields) -> None:
        if not self.path:
            return
        import json
        import time as _time

        record = {
            "type": record_type,
            "ts": round(_time.time(), 3),
            **self._meta,
            **fields,
        }
        with open(self.path, "a") as f:
            f.write(json.dumps(record) + "\n")


class MetricLogger:
    """Named collection of :class:`SmoothedValue` meters.

    Same surface as reference utils.py:76-118 (``update(**kw)``, attribute
    access to meters, ``synchronize_between_processes``, joined ``__str__``).
    """

    def __init__(self, delimiter: str = "\t"):
        self.meters: Dict[str, SmoothedValue] = defaultdict(SmoothedValue)
        self.delimiter = delimiter

    def update(self, **kwargs) -> None:
        for k, v in kwargs.items():
            if v is None:
                continue
            self.meters[k].update(_to_float(v))

    def update_dict(self, d) -> None:
        self.update(**d)

    def __getattr__(self, attr: str):
        meters = self.__dict__.get("meters")
        if meters is not None and attr in meters:
            return meters[attr]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{attr}'"
        )

    def __str__(self) -> str:
        return self.delimiter.join(
            f"{name}: {meter}" for name, meter in self.meters.items()
        )

    def synchronize_between_processes(self) -> None:
        for meter in self.meters.values():
            meter.synchronize_between_processes()

    def add_meter(self, name: str, meter: SmoothedValue) -> None:
        self.meters[name] = meter
