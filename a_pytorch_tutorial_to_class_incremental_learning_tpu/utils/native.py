"""ctypes bindings for the native host kernels (``csrc/cil_host.cpp``).

The library is optional: every entry point has a numpy fallback, and
:func:`load_native` attempts a one-shot ``make`` build when the shared object
is missing but a compiler is available.  Use ``CIL_TPU_NO_NATIVE=1`` to force
the numpy paths (the tests exercise both).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_CSRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "csrc")
_LIB_PATH = os.path.join(_CSRC, "libcilhost.so")

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def load_native() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None on any failure.

    The build is serialized across processes with an ``flock`` on the csrc
    directory so concurrent first-uses never read a half-written .so.  Call
    this once at startup (``CilTrainer.__init__`` does) — the first call may
    compile; later calls are a cached pointer read.
    """
    global _lib, _load_attempted
    if _load_attempted:
        return _lib
    _load_attempted = True
    if os.environ.get("CIL_TPU_NO_NATIVE"):
        return None
    try:
        if not os.path.exists(_LIB_PATH) and os.path.isdir(_CSRC):
            import fcntl

            with open(os.path.join(_CSRC, ".build.lock"), "w") as lockf:
                fcntl.flock(lockf, fcntl.LOCK_EX)
                try:
                    if not os.path.exists(_LIB_PATH):  # lost the race: built
                        subprocess.run(
                            ["make", "-C", _CSRC],
                            check=True,
                            capture_output=True,
                            timeout=120,
                        )
                finally:
                    fcntl.flock(lockf, fcntl.LOCK_UN)
        lib = ctypes.CDLL(_LIB_PATH)
        lib.herd_barycenter.restype = ctypes.c_int
        lib.herd_barycenter.argtypes = [
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.gather_u8.restype = ctypes.c_int
        lib.gather_u8.argtypes = [
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int64,
        ]
        _lib = lib
    except (OSError, subprocess.SubprocessError):
        _lib = None
    return _lib


def native_available() -> bool:
    return load_native() is not None


def herd_barycenter_native(features: np.ndarray, nb: int) -> Optional[np.ndarray]:
    """C++ iCaRL greedy ranking; None when the library is unavailable."""
    lib = load_native()
    if lib is None:
        return None
    feats = np.ascontiguousarray(features, dtype=np.float32)
    n, d = feats.shape
    nb = min(nb, n)
    out = np.empty(nb, np.int64)
    rc = lib.herd_barycenter(
        feats.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        n,
        d,
        nb,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    return out if rc == 0 else None


def gather_u8_native(src: np.ndarray, idx: np.ndarray) -> Optional[np.ndarray]:
    """Multithreaded ``src[idx]`` for uint8 row-major arrays; None = fallback."""
    lib = load_native()
    if lib is None or src.dtype != np.uint8 or not src.flags.c_contiguous:
        return None
    idx = np.ascontiguousarray(idx, dtype=np.int64)
    item_bytes = int(np.prod(src.shape[1:], dtype=np.int64))
    out = np.empty((len(idx),) + src.shape[1:], np.uint8)
    rc = lib.gather_u8(
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        len(src),
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(idx),
        item_bytes,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        0,
    )
    return out if rc == 0 else None


def gather_rows(src: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """Batch-assembly gather: native when possible, numpy otherwise."""
    out = gather_u8_native(src, idx)
    return src[idx] if out is None else out
