"""Profiler hooks (SURVEY.md §5: absent in the reference, near-free in JAX).

``task_trace`` wraps a region in a ``jax.profiler`` trace written to
``profile_dir`` (viewable in TensorBoard / xprof / Perfetto); no-op when
profiling is disabled.  ``annotate`` adds named sub-spans inside a trace.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def task_trace(profile_dir: Optional[str], name: str) -> Iterator[None]:
    if not profile_dir:
        yield
        return
    with jax.profiler.trace(profile_dir):
        with jax.profiler.TraceAnnotation(name):
            yield


def annotate(name: str):
    """Named span inside an active trace (decorator/context manager)."""
    return jax.profiler.TraceAnnotation(name)
