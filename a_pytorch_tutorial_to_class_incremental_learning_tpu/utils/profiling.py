"""Profiler hooks (SURVEY.md §5: absent in the reference, near-free in JAX).

``task_trace`` wraps a region in a ``jax.profiler`` trace written to
``profile_dir`` (viewable in TensorBoard / xprof / Perfetto); no-op when
profiling is disabled.  ``annotate`` adds named sub-spans inside a trace.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def task_trace(profile_dir: Optional[str], name: str) -> Iterator[Optional[str]]:
    """Capture a ``jax.profiler`` trace of the wrapped region.

    Yields the capture directory (``None`` when profiling is off) so the
    caller can record the trace's location in the run log.  Uses explicit
    ``start_trace``/``stop_trace`` rather than the ``trace`` context manager
    so a mid-region exception still stops the profiler (the capture up to
    the failure survives on disk — often exactly the evidence wanted).
    """
    if not profile_dir:
        yield None
        return
    jax.profiler.start_trace(profile_dir)
    try:
        with jax.profiler.TraceAnnotation(name):
            yield profile_dir
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named span inside an active trace (decorator/context manager)."""
    return jax.profiler.TraceAnnotation(name)


def device_step_ms_from_xspaces(xspaces, n_steps: int) -> dict:
    """Per-step device time from parsed XSpace protos.

    Walks the ``/device:*`` planes (the TPU plane's module lines record
    on-chip execution spans; XLA:CPU emits no device plane, in which case
    this returns {} — "no witness", not agreement) and averages the longest
    ``n_steps`` top-level jitted-module events (metadata names ``jit_*``),
    so fence/metrics mini-programs don't dilute the number.  The independent
    witness for slope-timed benchmarks (bench.py, scripts/profile_mfu.py).
    """
    import numpy as np

    durs_ps = []
    for xs in xspaces:
        for plane in xs.planes:
            if not plane.name.startswith("/device:"):
                continue
            md = {m.id: m.name for m in plane.event_metadata.values()}
            for line in plane.lines:
                for ev in line.events:
                    if md.get(ev.metadata_id, "").startswith("jit_"):
                        durs_ps.append(ev.duration_ps)
    if not durs_ps:
        return {}
    short = len(durs_ps) < n_steps
    durs_ps = sorted(durs_ps, reverse=True)[:n_steps]
    out = {
        "trace_step_ms": round(float(np.sum(durs_ps)) / 1e9 / len(durs_ps), 3),
        "trace_events_used": len(durs_ps),
    }
    if short:
        # Fewer jit_* device events than requested steps: the top-N now
        # includes *every* jitted program in the trace (fence/metrics
        # mini-programs included), which drags the mean down and inflates
        # est_mfu_trace.  Flag it so the witness is never silently diluted.
        out["trace_underpopulated"] = True
    return out


def trace_device_step_ms(trace_dir: str, n_steps: int) -> dict:
    """Load every ``*.xplane.pb`` under ``trace_dir`` and derive per-step
    device time.  Direct proto parsing — the tensorboard-plugin-profile
    tool-data pipeline in this image predates the installed protobuf and
    cannot import."""
    import glob
    import os

    paths = sorted(
        glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True)
    )
    if not paths:
        return {}
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except Exception:  # pragma: no cover - tf absent in some images
        return {}
    xspaces = []
    for p in paths:
        xs = xplane_pb2.XSpace()
        try:
            with open(p, "rb") as f:
                xs.ParseFromString(f.read())
        except Exception as e:  # noqa: BLE001
            return {"trace_parse_error": f"{type(e).__name__}: {e}"}
        xspaces.append(xs)
    return device_step_ms_from_xspaces(xspaces, n_steps)
