"""JAX platform pinning, done before any backend initialization.

The reference binds devices with ``torch.cuda.set_device`` per rank
(reference ``utils.py:146``); in JAX the platform is a process-level choice
made before the first backend-touching call.  This environment additionally
pins ``JAX_PLATFORMS`` to an accelerator plugin via sitecustomize, so the
env var alone cannot switch a process to CPU — ``jax.config`` must be
updated too, early enough.

This is the single shared implementation for repo code (``main.py``,
``bench.py``); ``tests/conftest.py`` and ``__graft_entry__.py`` keep
deliberately self-contained copies because they must run before the package
is importable.
"""

from __future__ import annotations

import os
import re
from typing import Optional


def enable_compile_cache(cache_dir: str) -> None:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    The protocol runs recompile the fused-epoch program once per distinct
    task-dataset length (engine/train.make_epoch_fn); on TPU that is most of
    a short run's wall-clock.  The cache makes every re-run (and every
    repeated task shape) skip XLA entirely.  XLA's extra AOT kernel caches
    stay off — their machine-feature check is brittle across hosts (see
    tests/conftest.py).
    """
    import jax

    jax.config.update("jax_compilation_cache_dir", os.path.expanduser(cache_dir))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    try:
        jax.config.update("jax_persistent_cache_enable_xla_caches", "none")
    except AttributeError:  # older jax without the sub-knob
        pass


def force_platform(
    platform: str,
    host_devices: int = 0,
    compile_cache_dir: Optional[str] = None,
) -> None:
    """Pin the JAX platform; optionally fake CPU devices and set the cache.

    ``host_devices > 0`` (CPU only) sets
    ``xla_force_host_platform_device_count``, replacing any stale value —
    the standard way to exercise a multi-device mesh without hardware.
    Raises ``RuntimeError`` with a clear diagnostic when a different backend
    was already initialized in this process (the pin cannot take effect).
    """
    if host_devices > 0:
        if platform != "cpu":
            raise ValueError(
                "host_devices only applies to platform='cpu' "
                f"(got platform={platform!r})"
            )
        flags = os.environ.get("XLA_FLAGS", "")
        want = f"--xla_force_host_platform_device_count={host_devices}"
        if "xla_force_host_platform_device_count" in flags:
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", want, flags
            )
        else:
            flags = f"{flags} {want}"
        os.environ["XLA_FLAGS"] = flags.strip()
    os.environ["JAX_PLATFORMS"] = platform

    import jax

    try:
        jax.config.update("jax_platforms", platform)
    except RuntimeError:
        pass  # too late — diagnosed by the post-check below

    if compile_cache_dir is not None:
        enable_compile_cache(compile_cache_dir)

    devs = jax.devices()
    actual = devs[0].platform if devs else "none"
    if actual != platform:
        raise RuntimeError(
            f"requested platform {platform!r} but a {actual!r} backend was "
            "already initialized in this process — the platform must be "
            "forced before any backend-touching call (run in a fresh process)"
        )
    if host_devices > 0 and len(devs) < host_devices:
        raise RuntimeError(
            f"requested {host_devices} virtual CPU devices but the backend "
            f"initialized with {len(devs)} — the CPU backend was created "
            "before the device-count flag could apply (fresh process needed)"
        )
