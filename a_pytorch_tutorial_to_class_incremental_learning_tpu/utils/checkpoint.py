"""Per-task checkpoint/resume.

The reference never persists anything — a crash in task 7 of 10 loses the run
(SURVEY.md §5 "checkpoint/resume: absent"); on TPU pods preemption makes this
mandatory.  Granularity is the task boundary: after task t finishes (post
weight-align, post herding) we persist everything ``fit()`` needs to continue
at task t+1 — params, batch stats, rehearsal memory, accuracy history, class
bookkeeping.  Momentum is *not* saved because the reference re-initializes the
optimizer every task anyway (``template.py:246``), so task-boundary resume is
exact: a killed-and-resumed run reproduces the uninterrupted run bit-for-bit
(same PRNG folds, same shuffles, same memory).

Two on-disk formats (``--ckpt_backend``):

* ``pickle`` (default): one pickle per task of host numpy pytrees (atomic
  rename), written by process 0 only.  Fine while parameters are replicated.
* ``orbax``: the *device array* state (params + batch stats) goes through
  orbax/tensorstore — every process writes its own shards and restore places
  arrays directly onto the mesh sharding, so no device array gathers to one
  host.  Host-side metadata (rehearsal memory, accuracy history,
  bookkeeping) still funnels through a process-0 sidecar pickle — and the
  rehearsal memory_store in it is the largest host-side state (up to
  ``memory_size`` raw images), so the no-gather property applies to device
  state only.  A checkpoint counts as complete only when both the sidecar
  and orbax's atomically-finalized directory exist.
"""

from __future__ import annotations

import os
import pickle
import re
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.dist import barrier, is_main_process


def _task_path(ckpt_dir: str, task_id: int, backend: str = "pickle") -> str:
    ext = "orbax" if backend == "orbax" else "ckpt"
    return os.path.join(ckpt_dir, f"task_{task_id:03d}.{ext}")


def _to_host(tree):
    return jax.tree_util.tree_map(lambda a: np.asarray(jax.device_get(a)), tree)


def _metadata(trainer, task_id: int) -> dict:
    return {
        "task_id": task_id,
        "known": trainer.known,  # already includes this task's classes
        "acc1s": list(trainer.acc1s),
        "acc_matrix": [list(r) if r is not None else None
                       for r in trainer.acc_matrix],
        "memory_store": trainer.memory._store,
        "config_seed": trainer.config.seed,
    }


def save_task_checkpoint(trainer, task_id: int) -> str:
    """Persist post-task state (called by ``CilTrainer.fit`` when
    ``ckpt_dir`` is set)."""
    ckpt_dir = trainer.config.ckpt_dir
    backend = trainer.config.ckpt_backend
    path = _task_path(ckpt_dir, task_id, backend)
    if backend == "orbax":
        import orbax.checkpoint as ocp

        if is_main_process():
            os.makedirs(ckpt_dir, exist_ok=True)
            # Sidecar first: resume requires sidecar AND the orbax dir, and
            # orbax finalizes its directory atomically — so a crash between
            # the two writes never yields a half-checkpoint that loads.
            tmp = path + ".meta.tmp"
            with open(tmp, "wb") as f:
                pickle.dump(
                    _metadata(trainer, task_id), f, protocol=pickle.HIGHEST_PROTOCOL
                )
            os.replace(tmp, path + ".meta")
        barrier()
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(
            os.path.abspath(path),
            {
                "params": trainer.state.params,
                "batch_stats": trainer.state.batch_stats,
            },
            force=True,
        )
        ckptr.wait_until_finished()
        ckptr.close()
    elif is_main_process():
        os.makedirs(ckpt_dir, exist_ok=True)
        payload = _metadata(trainer, task_id)
        payload["params"] = _to_host(trainer.state.params)
        payload["batch_stats"] = _to_host(trainer.state.batch_stats)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(payload, f, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, path)
    barrier()
    return path


def latest_task_checkpoint(ckpt_dir: str) -> Optional[str]:
    if not os.path.isdir(ckpt_dir):
        return None
    best = None
    for name in os.listdir(ckpt_dir):
        m = re.fullmatch(r"task_(\d+)\.(ckpt|orbax)", name)
        if not m:
            continue
        path = os.path.join(ckpt_dir, name)
        if m.group(2) == "orbax" and not os.path.exists(path + ".meta"):
            continue  # incomplete: sidecar missing
        if best is None or int(m.group(1)) > best[0]:
            best = (int(m.group(1)), path)
    return best[1] if best else None


def load_task_checkpoint(trainer, path: Optional[str] = None) -> bool:
    """Restore a trainer to the state right after the checkpointed task.

    Returns True when a checkpoint was found and loaded; ``trainer.fit()``
    then skips tasks ``<= task_id`` via ``start_task``.
    """
    from ..engine.train import Teacher, sgd_init
    from ..parallel.mesh import replicated_scalar, shard_params

    path = path or latest_task_checkpoint(trainer.config.ckpt_dir or "")
    found_task = -1
    if path and os.path.exists(path):
        m = re.search(r"task_(\d+)\.(ckpt|orbax)$", path)
        found_task = int(m.group(1)) if m else -1
    # Multi-host: every process must agree on the resume point, or they would
    # run different programs and deadlock.  Fail loudly on disagreement
    # (e.g. ckpt_dir on non-shared storage).
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        seen = multihost_utils.process_allgather(
            np.asarray(found_task, dtype=np.int64)
        )
        if len(np.unique(seen)) != 1:
            raise RuntimeError(
                f"processes disagree on the latest checkpoint ({seen.tolist()}); "
                "is ckpt_dir on storage shared by all hosts?"
            )
    if found_task < 0:
        return False
    if path.endswith(".orbax"):
        import orbax.checkpoint as ocp

        with open(path + ".meta", "rb") as f:
            payload = pickle.load(f)  # noqa: S301 - trusted local checkpoint
    else:
        with open(path, "rb") as f:
            payload = pickle.load(f)  # noqa: S301 - trusted local checkpoint
    if payload["config_seed"] != trainer.config.seed:
        raise ValueError(
            f"checkpoint seed {payload['config_seed']} != config seed "
            f"{trainer.config.seed}; refusing silent mix of experiments"
        )
    if path.endswith(".orbax"):
        # Restore straight onto the mesh sharding: the static full-width head
        # keeps every array's shape constant across tasks, so the live state
        # is its own restore template — no host-side gather at any point.
        template = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=a.sharding),
            {
                "params": trainer.state.params,
                "batch_stats": trainer.state.batch_stats,
            },
        )
        ckptr = ocp.StandardCheckpointer()
        restored = ckptr.restore(os.path.abspath(path), template)
        ckptr.close()
        # Same re-homing copy as the pickle branch below: restored arrays can
        # alias checkpoint-reader host buffers, which the donating train
        # programs must never be handed.
        params = jax.tree_util.tree_map(jnp.copy, restored["params"])
        batch_stats = jax.tree_util.tree_map(jnp.copy, restored["batch_stats"])
        if getattr(trainer.config, "check_donation", False):
            # Same contract as the pickle branch below: the state the trainer
            # keeps must not share buffers with the checkpoint reader's own
            # arrays, which the donating programs would otherwise free.
            from analysis.runtime import assert_unaliased

            assert_unaliased(
                restored,
                {"params": params, "batch_stats": batch_stats},
                where=path,
            )
    else:
        # jnp.copy after placement is load-bearing: on CPU, device_put of an
        # aligned host array is zero-copy, so the jax.Array would alias the
        # unpickled numpy buffer.  The fused epoch / train step *donate* the
        # TrainState, and XLA freeing a donated buffer it doesn't own
        # corrupts the heap (observed: NaN metrics on the resumed task, then
        # SIGBUS/abort in the epoch after restore).  The copy re-homes every
        # leaf into an XLA-owned buffer with the same sharding — the same
        # rule the teacher snapshot follows (engine/loop.py "Copied, not
        # aliased").
        params = jax.tree_util.tree_map(
            jnp.copy, shard_params(trainer.mesh, payload["params"])
        )
        batch_stats = jax.tree_util.tree_map(
            jnp.copy, shard_params(trainer.mesh, payload["batch_stats"])
        )
    if getattr(trainer.config, "check_donation", False):
        # Opt-in contract: prove the copies above actually re-homed every
        # leaf (no device array aliases the unpickled host buffers), then
        # poison the dead host payload — a surviving alias then fails as
        # NaN metrics at the restore point instead of SIGBUS epochs later.
        from analysis.runtime import assert_unaliased, poison_host_tree

        host_state = {k: payload[k] for k in ("params", "batch_stats")
                      if k in payload}
        assert_unaliased(
            host_state,
            {"params": params, "batch_stats": batch_stats},
            where=path,
        )
        poison_host_tree(host_state)
    known = int(payload["known"])
    trainer.state = trainer.state.replace(
        params=params,
        batch_stats=batch_stats,
        momentum=sgd_init(params),
        # Committed scalars: see replicated_scalar — a bare jnp.int32 here
        # would cost one silent recompile on the resumed task's second epoch.
        num_active=replicated_scalar(trainer.mesh, known),
        known=replicated_scalar(trainer.mesh, known),
    )
    # The post-task model *is* the teacher for the next task
    # (reference template.py:290).
    trainer.teacher = Teacher(
        params=jax.tree_util.tree_map(jnp.copy, params),
        batch_stats=jax.tree_util.tree_map(jnp.copy, batch_stats),
        known=replicated_scalar(trainer.mesh, known),
    )
    trainer.known = known
    trainer.acc1s = list(payload["acc1s"])
    # .get: pre-matrix checkpoints (r4 and earlier) lack the key.  Pad to
    # len(acc1s) with None rows so row index stays == task_id for the tasks
    # appended after resume (consumers see None where the matrix predates
    # the checkpoint, never a silently shifted row).
    matrix = [list(r) if r is not None else None
              for r in payload.get("acc_matrix", [])]
    matrix += [None] * (len(payload["acc1s"]) - len(matrix))
    trainer.acc_matrix = matrix
    trainer.memory._store = payload["memory_store"]
    trainer.start_task = payload["task_id"] + 1
    sentinel = getattr(trainer, "recompile_sentinel", None)
    if sentinel is not None:
        # A restore legitimately (re)compiles the resumed task's programs.
        sentinel.note_event("restore", task_id=payload["task_id"])
    print(f"| resumed from {path}: next task {trainer.start_task}, known={known}")
    return True
