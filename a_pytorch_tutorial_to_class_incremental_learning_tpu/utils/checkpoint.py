"""Task- and epoch-granular checkpoint/resume.

The reference never persists anything — a crash in task 7 of 10 loses the run
(SURVEY.md §5 "checkpoint/resume: absent"); on TPU pods preemption makes this
mandatory.  Two granularities:

* **Task boundary** (always on with ``--ckpt_dir``): after task t finishes
  (post weight-align, post herding) we persist everything ``fit()`` needs to
  continue at task t+1 — params, batch stats, rehearsal memory, accuracy
  history, class bookkeeping.  Momentum is *not* saved because the reference
  re-initializes the optimizer every task anyway (``template.py:246``).
* **Epoch boundary** (``--epoch_ckpt_every E``): mid-task
  ``task_{t}_epoch_{e}.ckpt`` files additionally capture the optimizer
  momentum, the teacher snapshot and the mid-task rehearsal/accuracy state,
  so a kill mid-task resumes at the last epoch boundary instead of replaying
  the whole task.  Resume is exact either way: every epoch's RNG is a pure
  fold of ``(seed, task, epoch)`` and its shuffle permutation a pure hash of
  the same triple (engine/loop.py), and the rehearsal memory only mutates at
  task boundaries — so the permutation cursor at an epoch boundary is always
  0 and a killed-and-resumed run reproduces the uninterrupted twin
  bit-for-bit.  Epoch checkpoints are deleted once their task's boundary
  checkpoint lands.

Integrity: every pickle payload gets a ``.sha256`` sidecar (for orbax, over
the ``.meta`` sidecar — orbax finalizes its own directory atomically).
Restore verifies the checksum and test-unpickles each candidate, falling back
to the newest *valid* checkpoint (logging a ``ckpt_fallback`` record per
skipped file) instead of crashing on a truncated or bit-flipped file.  Stale
``*.tmp`` leftovers from a crashed save are deleted on scan, never resumed
from.  Write order makes every crash window safe: payload tmp → checksum
sidecar → atomic rename (an orphan sidecar without its payload is ignored).

Two on-disk formats (``--ckpt_backend``):

* ``pickle`` (default): one pickle per task of host numpy pytrees (atomic
  rename), written by process 0 only.  Fine while parameters are replicated.
* ``orbax``: the *device array* state (params + batch stats, plus momentum
  and teacher trees at epoch granularity) goes through orbax/tensorstore —
  every process writes its own shards and restore places arrays directly
  onto the mesh sharding, so no device array gathers to one host.  Host-side
  metadata (rehearsal memory, accuracy history, bookkeeping) still funnels
  through a process-0 sidecar pickle.  A checkpoint counts as complete only
  when both the sidecar and orbax's atomically-finalized directory exist.
  Epoch checkpoints honour the backend too: ``task_{t}_epoch_{e}.orbax``
  directories with the same ``.meta`` sidecar-first write order.

Fault injection (``--fault_spec``): the saves call the trainer's injector at
site ``ckpt.save`` and apply the cooperative actions — ``save_ioerror``
raises before any byte is written, ``truncate_ckpt``/``corrupt_ckpt`` damage
the finished payload *without* refreshing its checksum, exactly the torn-write
and bit-rot failures the fallback scan exists to survive.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import re
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.dist import barrier, is_main_process

_TASK_RE = re.compile(r"task_(\d+)\.(ckpt|orbax)")
_EPOCH_RE = re.compile(r"task_(\d+)_epoch_(\d+)\.(ckpt|orbax)")


def _task_path(ckpt_dir: str, task_id: int, backend: str = "pickle") -> str:
    ext = "orbax" if backend == "orbax" else "ckpt"
    return os.path.join(ckpt_dir, f"task_{task_id:03d}.{ext}")


def _epoch_path(ckpt_dir: str, task_id: int, epoch: int,
                backend: str = "pickle") -> str:
    ext = "orbax" if backend == "orbax" else "ckpt"
    return os.path.join(
        ckpt_dir, f"task_{task_id:03d}_epoch_{epoch:03d}.{ext}"
    )


def _to_host(tree):
    return jax.tree_util.tree_map(lambda a: np.asarray(jax.device_get(a)), tree)


def _metadata(trainer, task_id: int) -> dict:
    return {
        "task_id": task_id,
        "known": trainer.known,  # already includes this task's classes
        "acc1s": list(trainer.acc1s),
        "acc_matrix": [list(r) if r is not None else None
                       for r in trainer.acc_matrix],
        "memory_store": trainer.memory._store,
        "config_seed": trainer.config.seed,
    }


# --------------------------------------------------------------------- #
# Integrity: sha256 sidecars + validated reads
# --------------------------------------------------------------------- #


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _write_sidecar(payload_path: str, payload_tmp: str) -> None:
    """Checksum of the (still-tmp) payload, landed atomically at
    ``<payload>.sha256`` *before* the payload's own rename — a crash between
    the two leaves an orphan sidecar, which readers ignore."""
    digest = _sha256_file(payload_tmp)
    tmp = payload_path + ".sha256.tmp"
    with open(tmp, "w") as f:
        f.write(digest + "\n")
    os.replace(tmp, payload_path + ".sha256")


def _payload_file(path: str) -> str:
    """The pickle that integrity checks cover (orbax keeps its metadata in a
    ``.meta`` sidecar; the orbax directory finalizes atomically on its own)."""
    return path + ".meta" if path.endswith(".orbax") else path


def _read_payload(path: str) -> Tuple[Optional[dict], Optional[str]]:
    """Checksum-verify and unpickle; ``(payload, None)`` or ``(None, why)``.

    A payload without a sidecar (pre-checksum checkpoints) is accepted iff it
    unpickles — truncation still fails the unpickle; only a bit-flip that
    keeps the pickle well-formed needs the sidecar to be caught.
    """
    target = _payload_file(path)
    if not os.path.exists(target):
        return None, "missing payload"
    sidecar = target + ".sha256"
    if os.path.exists(sidecar):
        with open(sidecar) as f:
            want = f.read().strip()
        got = _sha256_file(target)
        if got != want:
            return None, f"checksum mismatch (want {want[:12]}, got {got[:12]})"
    try:
        with open(target, "rb") as f:
            return pickle.load(f), None  # noqa: S301 - trusted local checkpoint
    except Exception as e:  # pickle raises half the exception zoo on torn files
        return None, f"unreadable payload: {e!r}"


# --------------------------------------------------------------------- #
# Candidate scan
# --------------------------------------------------------------------- #


def checkpoint_candidates(ckpt_dir: str) -> List[Tuple[int, Optional[int], str]]:
    """Resume candidates newest-progress-first as ``(task, epoch, path)``.

    ``epoch is None`` marks a task-boundary checkpoint, which outranks every
    epoch checkpoint of the same task (the task is fully done, align+herd
    included) and every checkpoint of earlier tasks.  Stale ``*.tmp`` /
    ``*.meta.tmp`` leftovers from a crashed save are deleted here — a torn
    temp file must never be picked (or even seen) as a resume point.
    """
    if not os.path.isdir(ckpt_dir):
        return []
    ranked = []
    for name in sorted(os.listdir(ckpt_dir)):
        path = os.path.join(ckpt_dir, name)
        if name.endswith(".tmp"):
            try:
                os.remove(path)
                print(f"| removed stale checkpoint temp file {path}")
            except OSError:
                pass  # multi-process scan race: the loser's delete is done
            continue
        m = _TASK_RE.fullmatch(name)
        if m:
            if m.group(2) == "orbax" and not os.path.exists(path + ".meta"):
                continue  # incomplete: sidecar missing
            ranked.append((int(m.group(1)), float("inf"), path))
            continue
        m = _EPOCH_RE.fullmatch(name)
        if m:
            if m.group(3) == "orbax" and not os.path.exists(path + ".meta"):
                continue  # incomplete: sidecar missing
            ranked.append((int(m.group(1)), float(m.group(2)), path))
    ranked.sort(key=lambda it: (it[0], it[1]), reverse=True)
    return [(t, None if e == float("inf") else int(e), p) for t, e, p in ranked]


def latest_task_checkpoint(ckpt_dir: str) -> Optional[str]:
    """Newest checkpoint that actually verifies (checksum + unpickle)."""
    for _task, _epoch, path in checkpoint_candidates(ckpt_dir):
        payload, _why = _read_payload(path)
        if payload is not None:
            return path
    return None


# --------------------------------------------------------------------- #
# Saves
# --------------------------------------------------------------------- #


def _fire_save_faults(trainer, task_id: int, epoch: Optional[int] = None):
    faults = getattr(trainer, "faults", None)
    if faults is None:
        return ()
    coords = {"task": task_id}
    if epoch is not None:
        coords["epoch"] = epoch
    actions = faults.fire("ckpt.save", **coords)
    if "save_ioerror" in actions:
        raise OSError(
            f"fault-injected transient checkpoint save failure "
            f"(task {task_id}, epoch {epoch})"
        )
    return actions


def _apply_payload_faults(actions, path: str) -> None:
    """Damage the *finished* payload the way real storage does — after the
    rename, without touching the checksum sidecar."""
    if not actions or not is_main_process():
        return
    target = _payload_file(path)
    size = os.path.getsize(target)
    if "truncate_ckpt" in actions:
        with open(target, "r+b") as f:
            f.truncate(max(size // 2, 1))
        print(f"| fault: truncated {target} to {max(size // 2, 1)} bytes")
    if "corrupt_ckpt" in actions:
        with open(target, "r+b") as f:
            f.seek(size // 2)
            byte = f.read(1)
            f.seek(size // 2)
            f.write(bytes([(byte[0] if byte else 0) ^ 0xFF]))
        print(f"| fault: flipped a byte at offset {size // 2} of {target}")


def _write_pickle_atomic(path: str, payload: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(payload, f, protocol=pickle.HIGHEST_PROTOCOL)
    _write_sidecar(path, tmp)
    os.replace(tmp, path)


def save_task_checkpoint(trainer, task_id: int) -> str:
    """Persist post-task state (called by ``CilTrainer.fit`` when
    ``ckpt_dir`` is set)."""
    ckpt_dir = trainer.config.ckpt_dir
    backend = trainer.config.ckpt_backend
    path = _task_path(ckpt_dir, task_id, backend)
    actions = _fire_save_faults(trainer, task_id)
    if backend == "orbax":
        import orbax.checkpoint as ocp

        if is_main_process():
            os.makedirs(ckpt_dir, exist_ok=True)
            # Sidecar first: resume requires sidecar AND the orbax dir, and
            # orbax finalizes its directory atomically — so a crash between
            # the two writes never yields a half-checkpoint that loads.
            _write_pickle_atomic(path + ".meta", _metadata(trainer, task_id))
        barrier()
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(
            os.path.abspath(path),
            {
                "params": trainer.state.params,
                "batch_stats": trainer.state.batch_stats,
            },
            force=True,
        )
        ckptr.wait_until_finished()
        ckptr.close()
    elif is_main_process():
        os.makedirs(ckpt_dir, exist_ok=True)
        payload = _metadata(trainer, task_id)
        payload["params"] = _to_host(trainer.state.params)
        payload["batch_stats"] = _to_host(trainer.state.batch_stats)
        _write_pickle_atomic(path, payload)
    _apply_payload_faults(actions, path)
    if is_main_process():
        _drop_epoch_checkpoints(ckpt_dir, task_id)
    barrier()
    return path


def _epoch_metadata(trainer, task_id: int, epoch: int, nb_new: int) -> dict:
    """The host-side (non-array) half of an epoch checkpoint — shared by the
    pickle payload and the orbax ``.meta`` sidecar."""
    return {
        "task_id": task_id,
        "epoch": epoch,               # completed epochs, 1-based
        "known": trainer.known,       # pre-task (the task is mid-flight)
        "nb_new": nb_new,
        "acc1s": list(trainer.acc1s),
        "acc_matrix": [list(r) if r is not None else None
                       for r in trainer.acc_matrix],
        "memory_store": trainer.memory._store,
        "config_seed": trainer.config.seed,
        "global_step": trainer._global_step,
        # Provenance, not state: epoch e+1's key is a pure fold of
        # (seed, task, epoch) and its permutation a pure hash of the same
        # triple, so the resume cursor at an epoch boundary is always 0.
        "rng": {"root_seed": trainer.config.seed, "task_fold": task_id,
                "next_epoch": epoch},
        "perm_cursor": 0,
    }


def save_epoch_checkpoint(trainer, task_id: int, epoch: int, nb_new: int) -> str:
    """Persist mid-task state after ``epoch`` completed epochs (1-based).

    Beyond the task-boundary payload this carries the optimizer momentum (a
    task boundary discards it, an epoch boundary must not), the teacher
    snapshot, the *pre-task* ``known``/``nb_new`` split, and the RNG
    provenance — everything ``load_task_checkpoint`` needs to drop the
    resumed process into ``_fit_task`` at ``start_epoch == epoch`` with
    device state bit-identical to the uninterrupted twin's.

    Backends mirror the task-boundary split: ``pickle`` gathers host copies
    through process 0; ``orbax`` writes the device trees (params, batch
    stats, momentum, teacher) through tensorstore — every process its own
    shards — with the host metadata in a checksummed ``.meta`` sidecar,
    landed *before* orbax's atomically-finalized directory so no crash
    window yields a half-checkpoint that loads.
    """
    ckpt_dir = trainer.config.ckpt_dir
    backend = trainer.config.ckpt_backend
    path = _epoch_path(ckpt_dir, task_id, epoch, backend)
    actions = _fire_save_faults(trainer, task_id, epoch=epoch)
    if backend == "orbax":
        import orbax.checkpoint as ocp

        if is_main_process():
            os.makedirs(ckpt_dir, exist_ok=True)
            meta = _epoch_metadata(trainer, task_id, epoch, nb_new)
            meta["has_teacher"] = trainer.teacher is not None
            _write_pickle_atomic(path + ".meta", meta)
        barrier()
        tree = {
            "params": trainer.state.params,
            "batch_stats": trainer.state.batch_stats,
            "momentum": trainer.state.momentum,
        }
        if trainer.teacher is not None:
            tree["teacher_params"] = trainer.teacher.params
            tree["teacher_batch_stats"] = trainer.teacher.batch_stats
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(os.path.abspath(path), tree, force=True)
        ckptr.wait_until_finished()
        ckptr.close()
    elif is_main_process():
        os.makedirs(ckpt_dir, exist_ok=True)
        teacher = None
        if trainer.teacher is not None:
            teacher = {
                "params": _to_host(trainer.teacher.params),
                "batch_stats": _to_host(trainer.teacher.batch_stats),
            }
        payload = _epoch_metadata(trainer, task_id, epoch, nb_new)
        payload.update(
            params=_to_host(trainer.state.params),
            batch_stats=_to_host(trainer.state.batch_stats),
            momentum=_to_host(trainer.state.momentum),
            teacher=teacher,
        )
        _write_pickle_atomic(path, payload)
    _apply_payload_faults(actions, path)
    barrier()
    return path


def _drop_epoch_checkpoints(ckpt_dir: str, task_id: int) -> None:
    """The task-boundary checkpoint supersedes its task's epoch scratch.

    Pickle epochs are a payload + ``.sha256``; orbax epochs are a directory
    + ``.meta`` pickle + ``.meta.sha256``."""
    import shutil

    if not os.path.isdir(ckpt_dir):
        return
    for name in os.listdir(ckpt_dir):
        m = _EPOCH_RE.fullmatch(name)
        if m and int(m.group(1)) == task_id:
            target = os.path.join(ckpt_dir, name)
            if os.path.isdir(target):
                shutil.rmtree(target, ignore_errors=True)
            for victim in (name, name + ".sha256",
                           name + ".meta", name + ".meta.sha256"):
                try:
                    os.remove(os.path.join(ckpt_dir, victim))
                except OSError:
                    pass  # the sidecar may legitimately not exist


# --------------------------------------------------------------------- #
# Restore
# --------------------------------------------------------------------- #


def _parse_ckpt_name(path: str) -> Tuple[int, Optional[int]]:
    name = os.path.basename(path)
    m = _EPOCH_RE.fullmatch(name)
    if m:
        return int(m.group(1)), int(m.group(2))
    m = _TASK_RE.fullmatch(name)
    if m:
        return int(m.group(1)), None
    return -1, None


def load_task_checkpoint(trainer, path: Optional[str] = None) -> bool:
    """Restore a trainer from the newest *valid* checkpoint.

    Task-boundary payloads restore to "right after task t" (``fit()`` skips
    tasks ``<= t`` via ``start_task``); epoch payloads restore to "task t,
    ``start_epoch`` epochs done" mid-task.  Candidates that fail the checksum
    or unpickle are skipped with a ``ckpt_fallback`` record, falling back to
    the next-newest valid one.  Returns True when something was loaded.
    """
    from ..engine.train import Teacher, sgd_init
    from ..parallel.mesh import replicated_scalar, shard_params

    sink = getattr(trainer, "jsonl", None)
    if path is not None:
        task_id, epoch = _parse_ckpt_name(path)
        candidates = [(task_id, epoch, path)] if os.path.exists(
            _payload_file(path)
        ) else []
    else:
        candidates = checkpoint_candidates(trainer.config.ckpt_dir or "")
    chosen = None
    for task_id, epoch, cand in candidates:
        payload, why = _read_payload(cand)
        if payload is None:
            print(f"| skipping invalid checkpoint {cand}: {why}")
            if sink is not None:
                sink.log("ckpt_fallback", skipped=cand, reason=why)
            continue
        chosen = (task_id, epoch, cand, payload)
        break
    # Multi-host: every process must agree on the resume point, or they would
    # run different programs and deadlock.  Fail loudly on disagreement
    # (e.g. ckpt_dir on non-shared storage).  The encoding orders resume
    # points exactly like checkpoint_candidates: task major, epoch minor,
    # task-boundary (epoch None) above any epoch of the same task.
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        found = -1
        if chosen is not None:
            t, e, _, _ = chosen
            found = t * 1_000_000 + (999_999 if e is None else e)
        seen = multihost_utils.process_allgather(
            np.asarray(found, dtype=np.int64)
        )
        if len(np.unique(seen)) != 1:
            raise RuntimeError(
                f"processes disagree on the latest checkpoint ({seen.tolist()}); "
                "is ckpt_dir on storage shared by all hosts?"
            )
    if chosen is None:
        return False
    task_id, epoch, path, payload = chosen
    if payload["config_seed"] != trainer.config.seed:
        raise ValueError(
            f"checkpoint seed {payload['config_seed']} != config seed "
            f"{trainer.config.seed}; refusing silent mix of experiments"
        )
    if epoch is not None:
        return _restore_epoch(trainer, path, payload)
    if path.endswith(".orbax"):
        import orbax.checkpoint as ocp

        # Restore straight onto the mesh sharding: the static full-width head
        # keeps every array's shape constant across tasks, so the live state
        # is its own restore template — no host-side gather at any point.
        template = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=a.sharding),
            {
                "params": trainer.state.params,
                "batch_stats": trainer.state.batch_stats,
            },
        )
        ckptr = ocp.StandardCheckpointer()
        restored = ckptr.restore(os.path.abspath(path), template)
        ckptr.close()
        # Same re-homing copy as the pickle branch below: restored arrays can
        # alias checkpoint-reader host buffers, which the donating train
        # programs must never be handed.
        params = jax.tree_util.tree_map(jnp.copy, restored["params"])
        batch_stats = jax.tree_util.tree_map(jnp.copy, restored["batch_stats"])
        if getattr(trainer.config, "check_donation", False):
            # Same contract as the pickle branch below: the state the trainer
            # keeps must not share buffers with the checkpoint reader's own
            # arrays, which the donating programs would otherwise free.
            from analysis.runtime import assert_unaliased

            assert_unaliased(
                restored,
                {"params": params, "batch_stats": batch_stats},
                where=path,
            )
    else:
        # jnp.copy after placement is load-bearing: on CPU, device_put of an
        # aligned host array is zero-copy, so the jax.Array would alias the
        # unpickled numpy buffer.  The fused epoch / train step *donate* the
        # TrainState, and XLA freeing a donated buffer it doesn't own
        # corrupts the heap (observed: NaN metrics on the resumed task, then
        # SIGBUS/abort in the epoch after restore).  The copy re-homes every
        # leaf into an XLA-owned buffer with the same sharding — the same
        # rule the teacher snapshot follows (engine/loop.py "Copied, not
        # aliased").
        params = jax.tree_util.tree_map(
            jnp.copy, shard_params(trainer.mesh, payload["params"])
        )
        batch_stats = jax.tree_util.tree_map(
            jnp.copy, shard_params(trainer.mesh, payload["batch_stats"])
        )
    if getattr(trainer.config, "check_donation", False):
        # Opt-in contract: prove the copies above actually re-homed every
        # leaf (no device array aliases the unpickled host buffers), then
        # poison the dead host payload — a surviving alias then fails as
        # NaN metrics at the restore point instead of SIGBUS epochs later.
        from analysis.runtime import assert_unaliased, poison_host_tree

        host_state = {k: payload[k] for k in ("params", "batch_stats")
                      if k in payload}
        assert_unaliased(
            host_state,
            {"params": params, "batch_stats": batch_stats},
            where=path,
        )
        poison_host_tree(host_state)
    known = int(payload["known"])
    trainer.state = trainer.state.replace(
        params=params,
        batch_stats=batch_stats,
        momentum=sgd_init(params),
        # Committed scalars: see replicated_scalar — a bare jnp.int32 here
        # would cost one silent recompile on the resumed task's second epoch.
        num_active=replicated_scalar(trainer.mesh, known),
        known=replicated_scalar(trainer.mesh, known),
    )
    # The post-task model *is* the teacher for the next task
    # (reference template.py:290).
    trainer.teacher = Teacher(
        params=jax.tree_util.tree_map(jnp.copy, params),
        batch_stats=jax.tree_util.tree_map(jnp.copy, batch_stats),
        known=replicated_scalar(trainer.mesh, known),
    )
    trainer.known = known
    trainer.acc1s = list(payload["acc1s"])
    # .get: pre-matrix checkpoints (r4 and earlier) lack the key.  Pad to
    # len(acc1s) with None rows so row index stays == task_id for the tasks
    # appended after resume (consumers see None where the matrix predates
    # the checkpoint, never a silently shifted row).
    matrix = [list(r) if r is not None else None
              for r in payload.get("acc_matrix", [])]
    matrix += [None] * (len(payload["acc1s"]) - len(matrix))
    trainer.acc_matrix = matrix
    trainer.memory._store = payload["memory_store"]
    trainer.start_task = payload["task_id"] + 1
    trainer.start_epoch = 0
    trainer.resumed_from = {"path": path, "kind": "task"}
    sentinel = getattr(trainer, "recompile_sentinel", None)
    if sentinel is not None:
        # A restore legitimately (re)compiles the resumed task's programs.
        sentinel.note_event("restore", task_id=payload["task_id"])
    print(f"| resumed from {path}: next task {trainer.start_task}, known={known}")
    return True


def _restore_epoch(trainer, path: str, payload: dict) -> bool:
    """Drop the trainer mid-task: task ``task_id`` already grew its head and
    ran ``epoch`` epochs; ``fit()`` continues that task at ``start_epoch``
    (skipping ``_grow_state`` — the restored params are post-growth)."""
    from ..engine.train import Teacher
    from ..parallel.mesh import replicated_scalar, shard_params

    copy_in = lambda tree: jax.tree_util.tree_map(  # noqa: E731
        jnp.copy, shard_params(trainer.mesh, tree)
    )
    known = int(payload["known"])
    nb_new = int(payload["nb_new"])
    if path.endswith(".orbax"):
        import orbax.checkpoint as ocp

        # Restore straight onto the mesh sharding — the static full-width
        # head keeps every array shape constant across tasks (and mid-task),
        # so the freshly-initialized live state is its own restore template.
        spec = lambda a: jax.ShapeDtypeStruct(  # noqa: E731
            a.shape, a.dtype, sharding=a.sharding
        )
        as_spec = lambda tree: jax.tree_util.tree_map(spec, tree)  # noqa: E731
        template = {
            "params": as_spec(trainer.state.params),
            "batch_stats": as_spec(trainer.state.batch_stats),
            "momentum": as_spec(trainer.state.momentum),
        }
        if payload["has_teacher"]:
            template["teacher_params"] = as_spec(trainer.state.params)
            template["teacher_batch_stats"] = as_spec(trainer.state.batch_stats)
        ckptr = ocp.StandardCheckpointer()
        restored = ckptr.restore(os.path.abspath(path), template)
        ckptr.close()
        # Same re-homing copy as every other restore path: restored arrays
        # can alias checkpoint-reader buffers the donating programs must
        # never be handed.
        rehome = lambda tree: jax.tree_util.tree_map(jnp.copy, tree)  # noqa: E731
        params = rehome(restored["params"])
        batch_stats = rehome(restored["batch_stats"])
        momentum = rehome(restored["momentum"])
        teacher_trees = None
        if payload["has_teacher"]:
            teacher_trees = (
                rehome(restored["teacher_params"]),
                rehome(restored["teacher_batch_stats"]),
            )
        if getattr(trainer.config, "check_donation", False):
            from analysis.runtime import assert_unaliased

            assert_unaliased(
                restored,
                {"params": params, "batch_stats": batch_stats,
                 "momentum": momentum},
                where=path,
            )
    else:
        # Same re-homing rule as the task branch: unpickled host buffers must
        # never reach the donating train programs (zero-copy device_put
        # aliasing).
        params = copy_in(payload["params"])
        batch_stats = copy_in(payload["batch_stats"])
        momentum = copy_in(payload["momentum"])
        if getattr(trainer.config, "check_donation", False):
            from analysis.runtime import assert_unaliased, poison_host_tree

            host_state = {
                k: payload[k] for k in ("params", "batch_stats", "momentum")
            }
            assert_unaliased(
                host_state,
                {"params": params, "batch_stats": batch_stats,
                 "momentum": momentum},
                where=path,
            )
            poison_host_tree(host_state)
        teacher_trees = None
        if payload["teacher"] is not None:
            teacher_trees = (
                copy_in(payload["teacher"]["params"]),
                copy_in(payload["teacher"]["batch_stats"]),
            )
    trainer.state = trainer.state.replace(
        params=params,
        batch_stats=batch_stats,
        momentum=momentum,  # mid-task: the optimizer is live, not reset
        num_active=replicated_scalar(trainer.mesh, known + nb_new),
        known=replicated_scalar(trainer.mesh, known),
    )
    if teacher_trees is not None:
        trainer.teacher = Teacher(
            params=teacher_trees[0],
            batch_stats=teacher_trees[1],
            known=replicated_scalar(trainer.mesh, known),
        )
    else:
        trainer.teacher = None
    trainer.known = known
    trainer.acc1s = list(payload["acc1s"])
    matrix = [list(r) if r is not None else None
              for r in payload.get("acc_matrix", [])]
    matrix += [None] * (len(payload["acc1s"]) - len(matrix))
    trainer.acc_matrix = matrix
    trainer.memory._store = payload["memory_store"]
    trainer.start_task = payload["task_id"]
    trainer.start_epoch = int(payload["epoch"])
    trainer._global_step = int(payload.get("global_step", 0))
    trainer.resumed_from = {"path": path, "kind": "epoch"}
    sentinel = getattr(trainer, "recompile_sentinel", None)
    if sentinel is not None:
        sentinel.note_event("restore", task_id=payload["task_id"])
    print(
        f"| resumed from {path}: task {trainer.start_task} at epoch "
        f"{trainer.start_epoch + 1}, known={known}+{nb_new}"
    )
    return True
