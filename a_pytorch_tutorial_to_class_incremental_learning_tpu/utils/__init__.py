from .logging import MetricLogger, SmoothedValue  # noqa: F401
from .platform import force_platform  # noqa: F401
