from .logging import JsonlLogger, MetricLogger, NullSink, Sink, SmoothedValue  # noqa: F401
from .platform import force_platform  # noqa: F401
