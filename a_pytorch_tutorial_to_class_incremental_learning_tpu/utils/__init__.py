from .logging import MetricLogger, SmoothedValue  # noqa: F401
