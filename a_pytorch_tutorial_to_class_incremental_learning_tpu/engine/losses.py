"""Losses and accuracy over masked full-width logits.

The reference computes CE + λ·KD over logits whose width physically grows each
task and slices ``logits[:, :known]`` for distillation
(reference ``template.py:259-266``, ``utils.py:121-132``).  With the static
masked head (models/classifier.py), slices become masks driven by the traced
scalars ``num_active`` / ``known`` — same math, one compilation.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _active_mask(width: int, num_active: jax.Array) -> jax.Array:
    return jnp.arange(width) < num_active


def cross_entropy(
    logits: jax.Array,
    labels: jax.Array,
    num_active: jax.Array,
    label_smoothing: float = 0.0,
    weights: Optional[jax.Array] = None,
) -> jax.Array:
    """Mean CE with label smoothing over the **active** classes.

    torch ``CrossEntropyLoss(label_smoothing=s)`` semantics (reference
    ``template.py:219,259``): target = (1-s)·one-hot + s/K uniform, K = number
    of (active) classes.  Masked columns hold NEG_INF, so ``log_softmax`` over
    the full width already matches a softmax over the active slice; the
    smoothing term is summed over active columns only.

    The accumulation runs in f32 regardless of the model's precision policy
    (ops/precision.LOSS_DTYPE): logits are upcast at entry, so a bf16 caller
    cannot silently shift the loss numerics.
    """
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    if label_smoothing:
        mask = _active_mask(logits.shape[-1], num_active)
        smooth = -jnp.where(mask, logp, 0.0).sum(-1) / num_active.astype(logp.dtype)
        per = (1.0 - label_smoothing) * nll + label_smoothing * smooth
    else:
        per = nll
    if weights is None:
        return per.mean()
    return (per * weights).sum() / jnp.maximum(weights.sum(), 1.0)


def soft_target_kd(
    student_logits: jax.Array,
    teacher_logits: jax.Array,
    known: jax.Array,
    temperature: float = 2.0,
) -> jax.Array:
    """SoftTarget distillation (reference ``utils.py:121-132``):
    ``KL(log_softmax(s/T) || softmax(t/T)) * T^2``, batchmean reduction, over
    the first ``known`` classes (the ``logits[:, :known]`` slice,
    ``template.py:263``).  Teacher logits are already masked to ``known``.

    KD is the numerically fragile half of WA's loss (temperature-scaled
    softmax over near-ties); both operand sets are upcast to f32 at entry
    (ops/precision.LOSS_DTYPE) so the divergence accumulates in f32 under
    every precision policy.
    """
    student_logits = student_logits.astype(jnp.float32)
    teacher_logits = teacher_logits.astype(jnp.float32)
    width = student_logits.shape[-1]
    mask = _active_mask(width, known)
    neg = jnp.float32(-1e9)
    s = jnp.where(mask, student_logits, neg) / temperature
    t = jnp.where(mask, teacher_logits, neg) / temperature
    logp_s = jax.nn.log_softmax(s, axis=-1)
    logp_t = jax.nn.log_softmax(t, axis=-1)
    p_t = jnp.exp(logp_t)
    kl_per = jnp.where(mask, p_t * (logp_t - logp_s), 0.0).sum(-1)
    return kl_per.mean() * temperature * temperature


def topk_correct(
    logits: jax.Array,
    labels: jax.Array,
    k: int,
    weights: Optional[jax.Array] = None,
) -> jax.Array:
    """Weighted count of samples whose label is in the top-k masked logits.

    ``k`` is clamped to the (static) logits width — the reference's
    ``topk=(1, min(5, logits.shape[1]))`` guard (``template.py:179-180``).
    """
    _, idx = jax.lax.top_k(logits, min(k, logits.shape[-1]))
    hit = (idx == labels[:, None]).any(axis=-1).astype(jnp.float32)
    if weights is None:
        return hit.sum()
    return (hit * weights).sum()


def accuracy(
    logits: jax.Array, labels: jax.Array, topk: Tuple[int, ...] = (1, 5)
) -> Tuple[jax.Array, ...]:
    """Batch top-k accuracies **in percent** (timm ``utils.accuracy``
    semantics, SURVEY.md #22; used at reference ``template.py:267-268``).
    Masked columns are NEG_INF so top-k never selects an inactive class;
    when fewer than ``k`` classes are active this reduces to top-active,
    matching the reference's ``min(5, nb_logits)`` guard.
    """
    b = logits.shape[0]
    return tuple(topk_correct(logits, labels, k) * (100.0 / b) for k in topk)
