"""Compiled train/eval/feature steps and the torch-parity SGD.

The reference's hot loop (``template.py:251-280``) is: augmented batch ->
forward -> CE + λ·KD -> backward -> SGD step -> explicit NCCL barrier.
TPU-native, the whole thing — *including augmentation* — is one jitted SPMD
program over the device mesh: XLA overlaps the gradient all-reduce with
backward compute, and there are no barriers (SURVEY.md §5 "distributed
communication backend").  The KD teacher forward runs inside the same
program, so the two forwards the reference pays serially get scheduled
together.

Step functions are built once per task-phase (with/without teacher) and cached
by shape-stable closure — ``num_active``/``known`` are traced scalars, so the
same executable serves every task (SURVEY.md §7 hard-part 1, option b).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import struct
from flax.core import unfreeze

from ..data.augment import AugmentConfig, eval_preprocess, train_augment
from ..parallel.mesh import batch_sharding
from .losses import accuracy, cross_entropy, soft_target_kd, topk_correct


@struct.dataclass
class TrainState:
    """All mutable training state as one pytree (donated through the step)."""

    params: Any
    batch_stats: Any
    momentum: Any  # SGD velocity, reset per task (reference template.py:246)
    num_active: jax.Array  # classes live in the head (traced -> no recompile)
    known: jax.Array  # classes seen before the current task


@struct.dataclass
class Teacher:
    """Frozen previous-task model (the reference's ``copy().freeze()``,
    ``template.py:290``); runs in eval mode inside the student's step."""

    params: Any
    batch_stats: Any
    known: jax.Array


# --------------------------------------------------------------------------- #
# SGD with exact torch semantics (reference template.py:246-247)
# --------------------------------------------------------------------------- #


def sgd_init(params: Any) -> Any:
    return jax.tree_util.tree_map(jnp.zeros_like, params)


def sgd_update(
    params: Any,
    grads: Any,
    momentum_buf: Any,
    lr: jax.Array,
    momentum: float,
    weight_decay: float,
    frozen: Any = None,
) -> Tuple[Any, Any]:
    """torch.optim.SGD: g += wd·p;  buf = m·buf + g;  p -= lr·buf.

    Weight decay hits every parameter (the reference passes all of
    ``model.parameters()``), dampening 0, no Nesterov.  ``frozen`` is an
    optional boolean pytree (``models.freeze_mask``) — the JAX equivalent of
    ``requires_grad=False``: frozen leaves receive no update and accumulate
    no momentum.
    """

    new_buf = jax.tree_util.tree_map(
        lambda p, g, b: momentum * b + g + weight_decay * p,
        params,
        grads,
        momentum_buf,
    )
    if frozen is not None:
        new_buf = jax.tree_util.tree_map(
            lambda f, b: jnp.zeros_like(b) if f else b, frozen, new_buf
        )
    new_params = jax.tree_util.tree_map(lambda p, b: p - lr * b, params, new_buf)
    return new_params, new_buf


def cosine_lr(base_lr: float, epoch: int, num_epochs: int) -> float:
    """torch ``CosineAnnealingLR(T_max=num_epochs)`` stepped per epoch
    (reference ``template.py:248-249,278``)."""
    import math

    return base_lr * 0.5 * (1.0 + math.cos(math.pi * epoch / num_epochs))


# --------------------------------------------------------------------------- #
# Step builders
# --------------------------------------------------------------------------- #


def _make_step_core(
    model,
    aug_cfg: AugmentConfig,
    label_smoothing: float,
    kd_temperature: float,
    momentum: float,
    weight_decay: float,
    has_teacher: bool,
    use_pallas_loss: bool = False,
    mesh=None,
    policy=None,
):
    """The un-jitted train-step body shared by the per-step and fused-epoch
    paths: augment -> student forward (+ teacher forward) -> CE+λKD ->
    backward -> SGD."""

    # The Pallas kernel compiles through Mosaic on TPU; on the CPU test mesh
    # it runs interpreted; on any other backend (GPU) fall back to the XLA
    # loss rather than silently emulating the kernel in the hot loop.  On a
    # multi-device mesh the kernel runs under shard_map (Mosaic kernels are
    # not auto-partitionable) — one fused pass per batch stripe.
    backend = jax.default_backend()
    pallas_loss = use_pallas_loss and backend in ("tpu", "cpu")
    if pallas_loss and policy is not None:
        # Custom kernels must opt into the run's precision policy
        # (ops/precision registry); an unregistered combination falls back
        # to the XLA loss instead of silently running unvalidated numerics.
        from ..ops.precision import kernel_policy_compatible

        pallas_loss = kernel_policy_compatible(
            "fused_masked_cross_entropy", policy
        )
    pallas_sharded = pallas_loss and mesh is not None and mesh.size > 1

    # jax.named_scope threads the phase names into XLA metadata, so device
    # profiler traces and the host-side span tracer (telemetry/spans.py)
    # speak the same phase vocabulary.
    def step(
        state: TrainState,
        teacher: Optional[Teacher],
        x_u8: jax.Array,
        labels: jax.Array,
        key: jax.Array,
        lr: jax.Array,
        lambda_kd: jax.Array,
    ):
        with jax.named_scope("augment"):
            x = train_augment(key, x_u8, aug_cfg)

        def loss_fn(params):
            with jax.named_scope("student_forward"):
                (logits, _feats), mutated = model.apply(
                    {"params": params, "batch_stats": state.batch_stats},
                    x,
                    num_active=state.num_active,
                    train=True,
                    mutable=["batch_stats"],
                )
            if pallas_sharded:
                from ..ops import sharded_fused_masked_cross_entropy

                ce = sharded_fused_masked_cross_entropy(
                    mesh,
                    logits,
                    labels,
                    state.num_active,
                    label_smoothing,
                    backend == "cpu",
                )
            elif pallas_loss:
                from ..ops import fused_masked_cross_entropy

                ce = fused_masked_cross_entropy(
                    logits,
                    labels,
                    state.num_active,
                    label_smoothing,
                    backend == "cpu",
                )
            else:
                ce = cross_entropy(logits, labels, state.num_active, label_smoothing)
            if has_teacher:
                with jax.named_scope("teacher_kd"):
                    t_logits, _ = model.apply(
                        {"params": teacher.params,
                         "batch_stats": teacher.batch_stats},
                        x,
                        num_active=teacher.known,
                        train=False,
                    )
                    kd = lambda_kd * soft_target_kd(
                        logits, t_logits, state.known, kd_temperature
                    )
            else:
                kd = jnp.float32(0.0)
            return ce + kd, (mutated["batch_stats"], logits, ce, kd)

        grads, (new_stats, logits, ce, kd) = jax.grad(loss_fn, has_aux=True)(
            state.params
        )
        # Mutable apply may hand back a FrozenDict; the scan carry (and the
        # donated TrainState) must keep one stable pytree type.
        new_stats = unfreeze(new_stats)
        with jax.named_scope("sgd_update"):
            new_params, new_buf = sgd_update(
                state.params, grads, state.momentum, lr, momentum, weight_decay
            )
        acc1, acc5 = accuracy(logits, labels, topk=(1, 5))
        new_state = state.replace(
            params=new_params, batch_stats=new_stats, momentum=new_buf
        )
        metrics = {"ce": ce, "kd": kd, "loss": ce + kd, "acc1": acc1, "acc5": acc5}
        return new_state, metrics

    return step


def make_train_step(
    model,
    aug_cfg: AugmentConfig,
    label_smoothing: float,
    kd_temperature: float,
    momentum: float,
    weight_decay: float,
    has_teacher: bool,
    use_pallas_loss: bool = False,
    mesh=None,
    policy=None,
):
    """Build the jitted per-batch train step.

    Two variants exist per run (task 0 has no teacher); each compiles once.
    Returns ``step(state, teacher, x_u8, labels, key, lr, lambda_kd) ->
    (state, metrics dict)`` with metrics as device scalars (no host sync in
    the loop — the reference barriers every step, ``template.py:272``; here
    synchronization happens implicitly at epoch-boundary logging).
    ``lr`` and ``lambda_kd`` are traced scalars: the cosine schedule and the
    (optionally dynamic) KD weight change without recompilation.
    """
    step = _make_step_core(
        model,
        aug_cfg,
        label_smoothing,
        kd_temperature,
        momentum,
        weight_decay,
        has_teacher,
        use_pallas_loss,
        mesh,
        policy,
    )
    return jax.jit(step, donate_argnums=(0,))


def make_epoch_fn(
    model,
    aug_cfg: AugmentConfig,
    label_smoothing: float,
    kd_temperature: float,
    momentum: float,
    weight_decay: float,
    has_teacher: bool,
    mesh,
    use_pallas_loss: bool = False,
    policy=None,
):
    """Build the fused-epoch program: shuffle + gather + every train step of
    an epoch as ONE compiled ``lax.scan``.

    The reference's epoch is a Python loop dispatching one CUDA step per
    batch with a DataLoader feeding it from worker processes
    (``template.py:251-276``).  TPU-first, the task's uint8 dataset lives in
    HBM for the whole task (CIFAR-100 is 150 MB — nothing), the epoch
    permutation is drawn **on device** from the epoch key, and a ``lax.scan``
    runs all steps back-to-back with zero host round-trips.  One dispatch per
    epoch instead of one per step; per-step host overhead (which rivals the
    1.4 ms step itself at this model size) disappears.

    Returns ``epoch(state, teacher, data_x, data_y, key, lr, lambda_kd) ->
    (state, metrics dict of [steps] arrays)``.  ``data_x`` is the full task
    dataset ``uint8 [N, H, W, C]`` (replicated over the mesh), ``data_y`` its
    labels.  Steps per epoch = ceil(N / global_batch) with wrap-around
    padding, the sampler's equalization rule.  Compiles once per distinct
    dataset length (task 0, then tasks 1+ share a shape when the rehearsal
    quota keeps N constant — the common CIFAR configuration).
    """
    step = _make_step_core(
        model,
        aug_cfg,
        label_smoothing,
        kd_temperature,
        momentum,
        weight_decay,
        has_teacher,
        use_pallas_loss,
        mesh,
        policy,
    )

    def epoch(
        state: TrainState,
        teacher: Optional[Teacher],
        data_x: jax.Array,
        data_y: jax.Array,
        key: jax.Array,
        lr: jax.Array,
        lambda_kd: jax.Array,
        global_batch: int,
    ):
        n = data_x.shape[0]
        nb_steps = max(1, -(-n // global_batch))
        perm = jax.random.permutation(jax.random.fold_in(key, 0xC0FFEE), n)
        idx = jnp.resize(perm, (nb_steps, global_batch))

        data_sharding = batch_sharding(mesh)

        def body(carry, step_i):
            st = carry
            take = idx[step_i]
            xb = jnp.take(data_x, take, axis=0)
            yb = jnp.take(data_y, take, axis=0)
            # The dataset is replicated; constrain the gathered batch onto
            # the data axis so each device materializes only its stripe and
            # the step runs sharded exactly like the per-batch path.
            xb = jax.lax.with_sharding_constraint(xb, data_sharding)
            yb = jax.lax.with_sharding_constraint(yb, data_sharding)
            step_key = jax.random.fold_in(key, step_i)
            st, metrics = step(st, teacher, xb, yb, step_key, lr, lambda_kd)
            return st, metrics

        state, metrics = jax.lax.scan(body, state, jnp.arange(nb_steps))
        return state, metrics

    return jax.jit(epoch, static_argnums=(7,), donate_argnums=(0,))


def make_eval_step(model, aug_cfg: AugmentConfig):
    """Weighted eval statistics for one batch (padding rows weigh 0).

    Returns device sums ``(loss_sum, correct1, correct5, weight_sum)`` —
    exact-count accounting instead of the reference's padded-sample double
    counting (SURVEY.md §7).
    """

    def step(params, batch_stats, x_u8, labels, weights, num_active):
        x = eval_preprocess(x_u8, aug_cfg)
        logits, _ = model.apply(
            {"params": params, "batch_stats": batch_stats},
            x,
            num_active=num_active,
            train=False,
        )
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
        loss_sum = (nll * weights).sum()
        c1 = topk_correct(logits, labels, 1, weights)
        c5 = topk_correct(logits, labels, 5, weights)
        return loss_sum, c1, c5, weights.sum()

    return jax.jit(step)


def make_feature_step(model, aug_cfg: AugmentConfig, augmented: bool):
    """Herding feature extraction (reference ``template.py:292-299``).

    ``augmented=True`` reproduces the reference exactly: its herding loader
    wraps the *train* dataset, so features come from randomly augmented
    images; ``False`` uses clean eval preprocessing (arguably better
    exemplars — kept behind ``CilConfig.herding_augmented``).
    """

    def step(params, batch_stats, x_u8, key):
        if augmented:
            x = train_augment(key, x_u8, aug_cfg)
        else:
            x = eval_preprocess(x_u8, aug_cfg)
        return model.apply(
            {"params": params, "batch_stats": batch_stats},
            x,
            train=False,
            method=model.extract_vector,
        )

    return jax.jit(step)
