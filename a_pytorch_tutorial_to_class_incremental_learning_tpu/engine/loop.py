"""The WA task loop: per-task train/eval/align/herd orchestration.

Counterpart of the reference experiment driver (``template.py:191-303``,
call stacks SURVEY.md §3.1-§3.5), re-expressed functionally: all device state
lives in one :class:`~.train.TrainState` pytree threaded through a jitted
step; between-task mutations (head growth, weight alignment, teacher
snapshot, optimizer reset) are pure host-side pytree updates.

Per task t (reference line citations):

1. cumulative val split ``scenario_val[:t+1]``        (229)
2. rehearsal injection ``add_samples(*memory.get())``  (230-231)
3. head growth (``prev_model_adaption``)               (241)
4. fresh SGD momentum + cosine schedule                (246-249)
5. epoch/step loop: CE + λ·KD, metrics                 (251-280)
6. periodic + final eval, weight alignment             (282-289)
7. teacher snapshot (frozen pytree)                    (290)
8. herding feature pass -> memory.add                  (292-302)
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from flax.core import unfreeze

from ..config import CilConfig
from ..data import (
    DevicePrefetcher,
    RehearsalMemory,
    build_scenario,
    eval_batches,
    maybe_decode,
    sequential_batches,
    train_batches,
)
from ..data.augment import AugmentConfig
from ..models import align, create_model, grow, init_backbone
from ..parallel.dist import barrier, init_distributed_mode
from ..parallel.mesh import (
    assert_process_major,
    batch_sharding,
    make_mesh,
    replicated,
    replicated_scalar,
    shard_params,
)
from ..telemetry import StallClock, Telemetry, average_incremental_accuracy
from ..utils.logging import JsonlLogger, MetricLogger
from .train import (
    Teacher,
    TrainState,
    cosine_lr,
    make_epoch_fn,
    make_eval_step,
    make_feature_step,
    make_train_step,
    sgd_init,
)


def _eval_line(totals) -> str:
    """The eval report line (reference ``template.py:186`` format), shared by
    the cumulative and slice-derived eval paths so they cannot drift."""
    loss_sum, c1, c5, n = totals
    return (
        f" Acc@1 {100.0 * c1 / max(n, 1.0):.3f}"
        f"  Acc@5 {100.0 * c5 / max(n, 1.0):.3f}"
        f"  loss {loss_sum / max(n, 1.0):.3f}"
    )


class CilTrainer:
    """Builds the mesh/model/data and runs the class-incremental experiment."""

    def __init__(self, config: CilConfig, mesh=None, init_dist: bool = True):
        if init_dist:
            init_distributed_mode(config.dist_url)
        self.config = config
        self.mesh = mesh if mesh is not None else make_mesh(config.mesh_shape)
        # The contiguous-stripe loader requires a process-major data axis;
        # fail loudly at init on exotic topologies instead of silently
        # permuting the global batch across hosts (VERDICT r2 weak #9).
        assert_process_major(self.mesh)
        # Telemetry and the experiment log come up before any heavy work so
        # the very first phase (scenario build) is already witnessed.  With a
        # telemetry dir but no explicit --log_file the run records default to
        # <telemetry_dir>/run.jsonl — one stream carries the whole run.
        # Opt-in runtime contract (--check_threads): install before the
        # telemetry stack so its locks (heartbeat, flight recorder,
        # prefetch) are created instrumented; the sink is bound below once
        # the run log exists (violations seen in between are buffered).
        self.threadcheck = None
        if config.check_threads:
            from analysis import threadcheck

            self.threadcheck = threadcheck.install()
        # Opt-in runtime contract (--check_contracts): validate every live
        # record type/field and metric name against the committed contract
        # registry — the dynamic complement of contractlint, catching names
        # the AST pass can't see because they're built at runtime.
        self.contractcheck = None
        if config.check_contracts:
            from analysis import contractcheck

            self.contractcheck = contractcheck.install()
        log_path = config.log_file
        if log_path is None and config.telemetry_dir:
            log_path = os.path.join(config.telemetry_dir, "run.jsonl")
        # Resumed runs append so the pre-crash tasks' records survive.
        self.jsonl = JsonlLogger(log_path, append=config.resume)
        if self.contractcheck is not None:
            from analysis import contractcheck

            # Wrapped *under* the Telemetry facade so the FlightSink tee's
            # records are validated too.
            self.jsonl = contractcheck.wrap_sink(self.jsonl)
        self.telemetry = Telemetry(
            telemetry_dir=config.telemetry_dir,
            heartbeat_path=config.heartbeat_path,
            heartbeat_interval_s=config.heartbeat_interval_s,
            sink=self.jsonl,
            flight_events=config.flight_events,
            metrics=config.metrics,
            metrics_interval_s=config.metrics_interval_s,
            metrics_source="train",
        )
        # With a flight recorder active the facade wrapped the logger in a
        # FlightSink tee; rebind so every engine record (epoch/task/fault)
        # also lands in the crash-forensics ring.
        self.jsonl = self.telemetry.sink
        if self.threadcheck is not None:
            self.threadcheck.bind_sink(self.jsonl)
        if self.contractcheck is not None:
            from analysis import contractcheck

            self.contractcheck.bind_sink(self.jsonl)
            self.telemetry.metrics = contractcheck.wrap_registry(
                self.telemetry.metrics)
        # Hot-path instruments resolved once here (with --no_metrics these
        # are shared no-ops), so the step loop pays one lock-protected add
        # per instrument and zero dict lookups.
        _reg = self.telemetry.metrics
        self._m_steps = _reg.counter("steps_total")
        self._m_step_ms = _reg.histogram(
            "step_latency_ms", lowest=0.5, growth=2.0, buckets=18
        )
        self._m_epochs = _reg.counter("epochs_total")
        self._m_stall = _reg.gauge("stall_frac")
        self._m_recompiles = _reg.gauge("recompiles_total")
        # Opt-in runtime contract #2 (--check_lockstep): fingerprint every
        # imminent train/eval dispatch and compare across the fleet, so a
        # divergent process surfaces as a named record on every host instead
        # of a silent pod-wide hang in the next collective.  The exchange dir
        # defaults next to the other run artifacts; construction clears this
        # process's own subdirectory, so the barrier below is load-bearing —
        # no peer may publish seq 0 before every stale file is gone.
        self.lockstep = None
        self._lockstep_digest = None
        if config.check_lockstep:
            from analysis.lockstep import LockstepSentinel, data_digest

            self._lockstep_digest = data_digest

            lockstep_dir = config.lockstep_dir
            if lockstep_dir is None and config.telemetry_dir:
                lockstep_dir = os.path.join(config.telemetry_dir, "lockstep")
            if lockstep_dir is None and config.ckpt_dir:
                lockstep_dir = os.path.join(config.ckpt_dir, "lockstep")
            self.lockstep = LockstepSentinel(
                lockstep_dir,
                process_index=jax.process_index(),
                process_count=jax.process_count(),
                sink=self.jsonl,
                on_fatal=(
                    self.telemetry.flight.fatal_dump
                    if self.telemetry.flight is not None else None
                ),
                deadline_s=config.lockstep_deadline_s,
            )
            barrier()
        # Deterministic fault injection (--fault_spec; faults/injector.py).
        # None when unset, so every hot-path site pays one identity check.
        # The ledger defaults next to the checkpoints: a supervised relaunch
        # of a killed run parses the same spec but finds the clause spent.
        self.faults = None
        if config.fault_spec:
            from faults import injector_from, rotate_ledger

            ledger = config.fault_state
            if ledger is None and config.ckpt_dir:
                ledger = os.path.join(config.ckpt_dir, "fault_ledger.jsonl")
            if not config.resume:
                # Fresh soak iteration: archive the previous run's spent
                # ledger so the spec re-arms (resumed runs keep it — the
                # spent ledger is the crash-loop guard).
                archived = rotate_ledger(ledger)
                if archived:
                    self.jsonl.log(
                        "fault_ledger_rotated", path=ledger, archived=archived
                    )
            on_fatal = (
                self.telemetry.flight.fatal_dump
                if self.telemetry.flight is not None else None
            )
            self.faults = injector_from(
                config.fault_spec, ledger_path=ledger, sink=self.jsonl,
                on_fatal=on_fatal,
            )
        with self.telemetry.span("build_scenario"):
            self.scenario_train, self.nb_classes = build_scenario(
                config, train=True
            )
            self.scenario_val, _ = build_scenario(config, train=False)

        # The run's precision policy (ops/precision.py): --precision wins,
        # --compute_dtype is its legacy alias.  Resolved once; the model
        # stack, step builders and provenance records all read this object.
        from ..ops.precision import policy_from_config

        self.policy = policy_from_config(config)
        # Persistent XLA compilation cache: with --compile_cache in the
        # config, arm it before the first trace (model init below compiles).
        # Guarded so an environment/main.py that already configured the
        # cache (e.g. a supervised relaunch passing JAX_COMPILATION_CACHE_DIR
        # through) wins.
        if config.compile_cache and jax.config.jax_compilation_cache_dir is None:
            from ..utils.platform import enable_compile_cache

            enable_compile_cache(config.compile_cache)
        # Compile-cost accounting (telemetry/compilewatch.py): snapshot
        # deltas around each task's first executed epoch price what every
        # trace actually cost — and prove a warm-cache resume cost ~nothing.
        from ..telemetry.compilewatch import CompileWatch

        self._compile_watch = CompileWatch.install()
        # 1-channel pipeline for the mnist backbone family — a family the
        # reference defines but never dispatches (template.py:72-84,
        # resnet.py:127-139); here it runs end-to-end (mnist/synthetic_mnist
        # datasets, grayscale-aware augmentation, MNIST normalize stats).
        channels = 1 if "mnist" in config.backbone else 3
        data_x = self.scenario_train._x
        lazy_paths = not (
            isinstance(data_x, np.ndarray) and data_x.dtype != object
        )
        if lazy_paths:
            # Lazy image-folder datasets decode to RGB (decode_image_batch).
            if channels != 3:
                raise ValueError(
                    f"backbone {config.backbone!r} expects {channels}-channel "
                    f"input but data_set {config.data_set!r} decodes to RGB"
                )
        else:
            if data_x.shape[-1] != channels:
                raise ValueError(
                    f"backbone {config.backbone!r} expects {channels}-channel "
                    f"input but data_set {config.data_set!r} has "
                    f"{data_x.shape[-1]} channels"
                )
            if data_x.ndim == 4 and data_x.shape[1] != config.input_size:
                raise ValueError(
                    f"data_set {config.data_set!r} images are "
                    f"{data_x.shape[1]}px but --input_size is "
                    f"{config.input_size} — pass --input_size {data_x.shape[1]}"
                )
        self.aug_cfg = AugmentConfig.from_config(config)
        if channels == 1 and self.aug_cfg.rand_augment:
            # The RandAugment color/histogram ops are RGB-defined; crop/flip/
            # jitter/erasing all handle 1 channel.
            raise ValueError(
                f"backbone {config.backbone!r} is 1-channel; RandAugment "
                "requires RGB — pass --aa none"
            )
        if config.ckpt_backend == "orbax" and config.ckpt_dir:
            # Fail before any compile, not after task 0's training run.
            import orbax.checkpoint  # noqa: F401
        # Reference parity: batch_size is per-device (the reference's per-GPU
        # 128, DataLoader-per-rank under DistributedSampler); the global batch
        # scales with the data axis like DDP's world_size * 128.
        self.channels = channels  # the serving export needs the input spec
        self.global_batch_size = config.batch_size * self.mesh.shape["data"]
        self.model, variables = create_model(
            config.backbone,
            self.nb_classes,
            width_multiple=self.mesh.shape["model"],
            input_size=config.input_size,
            channels=channels,
            bn_group_size=config.bn_group_size,
            policy=self.policy,
        )
        self.root_key = jax.random.PRNGKey(config.seed)
        init_key, self._grow_key = jax.random.split(
            jax.random.fold_in(self.root_key, 0xC11)
        )
        variables = init_backbone(
            variables, init_key, self.model, config.input_size, channels
        )
        params = shard_params(self.mesh, unfreeze(variables["params"]))
        batch_stats = shard_params(self.mesh, unfreeze(variables["batch_stats"]))
        self.state = TrainState(
            params=params,
            batch_stats=batch_stats,
            momentum=sgd_init(params),
            # Committed to the mesh at creation: bare scalars would make the
            # train programs compile a second time for their own (committed)
            # output state — the exact leak RecompileMonitor exists to catch.
            num_active=replicated_scalar(self.mesh, 0),
            known=replicated_scalar(self.mesh, 0),
        )
        self.teacher: Optional[Teacher] = None

        # Load/build the native host kernels at startup (never mid-epoch) and
        # use them only when every process has them, so the replicated
        # herding computation stays identical fleet-wide.
        from ..utils.native import native_available

        have_native = native_available()
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            have_native = bool(
                multihost_utils.process_allgather(
                    np.asarray(have_native, np.int32)
                ).min()
            )
        self.memory = RehearsalMemory(
            memory_size=config.memory_size,
            herding_method=config.herding_method,
            fixed_memory=config.fixed_memory,
            nb_total_classes=self.nb_classes if config.fixed_memory else None,
            prefer_native=have_native,
        )
        # The Pallas loss runs interpreted on CPU (partitionable) and through
        # Mosaic on TPU; on a multi-device mesh the step builders wrap it in
        # shard_map (Mosaic kernels cannot be auto-partitioned by XLA).
        use_pallas = config.use_pallas_loss
        self._steps: Dict[bool, callable] = {
            has_teacher: make_train_step(
                self.model,
                self.aug_cfg,
                label_smoothing=config.smooth,
                kd_temperature=config.kd_temperature,
                momentum=config.momentum,
                weight_decay=config.weight_decay,
                has_teacher=has_teacher,
                use_pallas_loss=use_pallas,
                mesh=self.mesh,
                policy=self.policy,
            )
            for has_teacher in (False, True)
        }
        self._epochs: Dict[bool, callable] = {
            has_teacher: make_epoch_fn(
                self.model,
                self.aug_cfg,
                label_smoothing=config.smooth,
                kd_temperature=config.kd_temperature,
                momentum=config.momentum,
                weight_decay=config.weight_decay,
                has_teacher=has_teacher,
                mesh=self.mesh,
                use_pallas_loss=use_pallas,
                policy=self.policy,
            )
            for has_teacher in (False, True)
        }
        self.eval_step = make_eval_step(self.model, self.aug_cfg)
        self.feature_step = make_feature_step(
            self.model, self.aug_cfg, augmented=config.herding_augmented
        )
        # Register every jitted program with the recompile monitor, grouped
        # by its legitimate first-compile moment (see RecompileMonitor): the
        # train programs compile on each task's first epoch, eval on the
        # first eval after a head growth, feature on the first herd after.
        rc = self.telemetry.recompiles
        for ht, fn in self._steps.items():
            rc.track(f"train_step[teacher={ht}]", fn, group="train")
        for ht, fn in self._epochs.items():
            rc.track(f"epoch_fn[teacher={ht}]", fn, group="train")
        rc.track("eval_step", self.eval_step, group="eval")
        rc.track("feature_step", self.feature_step, group="feature")
        # Opt-in runtime contract (--recompile_budget): train programs may
        # trace at most once per (task-growth, restore) event; a silent
        # re-trace raises at the task boundary instead of quietly doubling
        # compile time on hardware.  Created before the resume block below so
        # a checkpoint restore is counted as a budget-granting event.
        self.recompile_sentinel = None
        if config.recompile_budget:
            from analysis.runtime import RecompileSentinel

            self.recompile_sentinel = RecompileSentinel(
                rc, group="train", per_event=1, sink=self.jsonl
            )
        # Armed by _grow_state: a growth changes the head shape, so the next
        # eval/feature compile is expected rather than a leak.
        self._eval_fresh_shapes = True
        self._feature_fresh_shapes = True
        self._global_step = 0
        # Next-task dataset warm ring (data/prefetch.py), armed during the
        # previous task's herd phase; see _warm_next_task.
        self._task_warm = None
        # Provenance header: committed logs are only evidence if a reader can
        # see exactly what produced them.
        self.jsonl.log(
            "run",
            data_set=config.data_set,
            backbone=config.backbone,
            num_bases=config.num_bases,
            increment=config.increment,
            batch_size=config.batch_size,
            global_batch=self.global_batch_size,
            num_epochs=config.num_epochs,
            lr=config.lr,
            seed=config.seed,
            aa=config.aa,
            memory_size=config.memory_size,
            compute_dtype=config.compute_dtype,
            precision=self.policy.name,
            backend=jax.default_backend(),
            mesh=dict(self.mesh.shape),
            processes=jax.process_count(),
        )
        self.acc1s: List[float] = []
        self.acc_matrix: List[List[float]] = []  # row t = acc_per_task after task t
        self.known = 0
        self.start_task = 0
        self.start_epoch = 0  # > 0 only after an epoch-checkpoint restore
        self.resumed_from = None  # {"path", "kind": "task"|"epoch"} when resumed
        if config.resume and config.ckpt_dir:
            from ..utils.checkpoint import load_task_checkpoint

            load_task_checkpoint(self)
        if config.resume:
            # Segment marker: consumers can drop records before the last
            # resume whose task_id >= start_task (a crash between a task's
            # records and its checkpoint replays that task; with epoch
            # checkpoints the replay window shrinks to epochs > start_epoch).
            extra = {}
            if self.resumed_from is not None:
                extra = {"path": self.resumed_from["path"],
                         "kind": self.resumed_from["kind"]}
            self.jsonl.log(
                "resume",
                start_task=self.start_task,
                start_epoch=self.start_epoch,
                **extra,
            )

    # ------------------------------------------------------------------ #
    # Batch placement
    # ------------------------------------------------------------------ #

    def _put(self, *arrays, sharding=None):
        sharding = sharding or batch_sharding(self.mesh)
        out = tuple(
            jax.make_array_from_process_local_data(sharding, np.asarray(a))
            for a in arrays
        )
        return out if len(out) > 1 else out[0]

    def _decode(self, x: np.ndarray, train: bool, seed: int) -> np.ndarray:
        return maybe_decode(x, self.config.input_size, train, seed)

    # ------------------------------------------------------------------ #
    # The experiment
    # ------------------------------------------------------------------ #

    def fit(self) -> Dict:
        """Run every task; returns the reference's headline artifacts.

        The whole protocol runs under the root ``fit`` span (so depth-1
        ``task`` spans account for the loop's wall time) with the heartbeat
        thread live for its duration — the watchdog reads liveness from the
        heartbeat file instead of probing the chip blind.
        """
        tel = self.telemetry
        # A resumed process re-seeds the metrics matrix from the checkpoint
        # rows so forgetting/BWT stay computable across restarts (missing
        # rows degrade summary() to partial=True, never to wrong numbers).
        for i, row in enumerate(self.acc_matrix):
            if row and i not in tel.matrix.rows:
                tel.matrix.add_row(i, row)
        tel.heartbeat.start()
        try:
            with tel.span("fit"):
                return self._fit_tasks()
        finally:
            # A warm ring armed for a task that never ran (crash, last
            # task) must still release its thread and device buffers.
            if self._task_warm is not None:
                self._task_warm["prefetcher"].close()
                self._task_warm = None
            tel.close()

    def _fit_tasks(self) -> Dict:
        tel = self.telemetry
        increments = self.scenario_train.increments()
        for task_id, task_train in enumerate(self.scenario_train):
            if task_id < self.start_task:
                continue  # resumed past this task (checkpointing)
            nb_new = increments[task_id]
            dataset_val = self.scenario_val[: task_id + 1]
            with tel.span("task", task=task_id):
                tel.heartbeat.update(force=True, task=task_id, phase="train")
                if task_id > 0:
                    with tel.span("rehearsal_inject", task=task_id):
                        task_train.add_samples(*self.memory.get())

                # Mid-task (epoch-checkpoint) resume: the restored params are
                # already post-growth for this task — re-running _grow_state
                # would re-initialize the new head columns and destroy them.
                resume_epoch = (
                    self.start_epoch if task_id == self.start_task else 0
                )
                if resume_epoch == 0:
                    # Head growth before training (reference template.py:241).
                    with tel.span("head_grow", task=task_id):
                        self.state = self._grow_state(
                            self.state, task_id, self.known, nb_new
                        )
                t0 = time.time()
                self._fit_task(
                    task_id, task_train, dataset_val, nb_new,
                    start_epoch=resume_epoch,
                )
                if self.recompile_sentinel is not None:
                    # All legitimate train compiles for this task happened;
                    # anything beyond the granted budget is a leak.
                    self.recompile_sentinel.check(
                        where=f"task{task_id}", task_id=task_id
                    )

                # Weight alignment after training, tasks > 0
                # (template.py:285-286).
                gamma = None
                if task_id > 0:
                    with tel.span("align", task=task_id):
                        self.state, gamma = self._align_state(
                            self.state, self.known, nb_new
                        )
                    print(f"old norm / new norm ={gamma}")
                # Accuracy-matrix row: every seen task's val slice evaluated
                # separately (scenario_val[j], the same slicing the
                # reference's cumulative eval builds on, template.py:229).
                # The cumulative acc1 says *that* forgetting happened; the
                # row says *where* — per class group — making backward
                # transfer / forgetting computable from the JSONL.  The
                # evaluator is exact weighted counting, so summing the slice
                # totals reproduces the cumulative metrics without a second
                # full pass; vs the old single cumulative pass this costs
                # only the per-slice batch-boundary padding (up to task_id
                # extra padded batches).  Slice totals stay ON DEVICE until
                # all slices are evaluated — one host fetch for the whole
                # matrix row, not one per seen task (~90 ms RPC each on
                # tunneled platforms).
                tel.heartbeat.update(force=True, task=task_id, phase="eval")
                with tel.span("eval_matrix", task=task_id):
                    slice_dev = [
                        self._eval_totals_device(self.scenario_val[j])
                        for j in range(task_id + 1)
                    ]
                    slice_totals = np.asarray(jnp.stack(slice_dev))
                totals = slice_totals.sum(axis=0)
                print(_eval_line(totals))
                acc1 = float(100.0 * totals[1] / max(totals[3], 1.0))
                self.acc1s.append(acc1)
                acc_per_task = [
                    round(float(100.0 * t[1] / max(t[3], 1.0)), 5)
                    for t in slice_totals
                ]
                self.acc_matrix.append(acc_per_task)
                task_s = time.time() - t0
                print(
                    f"task id = {task_id}  @Acc1 = {acc1:.5f}, "
                    f"acc1s = {self.acc1s}  ({task_s:.1f}s)"
                )
                self.jsonl.log(
                    "task",
                    task_id=task_id,
                    acc1=acc1,
                    acc1s=list(self.acc1s),
                    acc_per_task=acc_per_task,
                    gamma=gamma,
                    nb_new=nb_new,
                    known_after=self.known + nb_new,
                    seconds=round(task_s, 1),
                )
                # The continual-learning decomposition valid at this point
                # of the protocol (forgetting/BWT need >= 2 complete rows;
                # a partial matrix is reported as such, never as numbers).
                tel.matrix.add_row(task_id, acc_per_task)
                self.jsonl.log(
                    "cil_metrics",
                    task_id=task_id,
                    avg_incremental_acc1=round(
                        average_incremental_accuracy(self.acc1s), 5
                    ),
                    **tel.matrix.summary(),
                )

                # Serving artifact: freeze the just-aligned model before the
                # teacher snapshot mutates anything (serving/artifact.py).
                if self.config.export_dir and jax.process_index() == 0:
                    with tel.span("export_artifact", task=task_id):
                        self._export_artifact(task_id, nb_new, acc_per_task)

                # Teacher snapshot (template.py:290).  Copied, not aliased:
                # the train step donates the student state's buffers, and a
                # donated buffer must not be reachable through another
                # argument.
                with tel.span("teacher_snapshot", task=task_id):
                    self.teacher = Teacher(
                        params=jax.tree_util.tree_map(jnp.copy, self.state.params),
                        batch_stats=jax.tree_util.tree_map(
                            jnp.copy, self.state.batch_stats
                        ),
                        known=replicated_scalar(self.mesh, self.known + nb_new),
                    )
                tel.heartbeat.update(force=True, task=task_id, phase="herd")
                with tel.span("herd", task=task_id):
                    self._update_memory(task_id, task_train)
                # Memory is final for the next task now: warm-start its
                # device-resident dataset on the prefetch ring so the H2D
                # transfer overlaps the checkpoint write and the next task's
                # host-side setup.
                self._warm_next_task(task_id)
                self.known += nb_new
                with tel.span("checkpoint", task=task_id):
                    self._save_checkpoint(task_id)
                # Per-device HBM at the task boundary: head growth, resident
                # fused dataset and teacher snapshot all moved (no-op on
                # XLA:CPU, which reports no memory stats).
                tel.log_hbm(task_id=task_id)
        avg_inc = float(np.mean(self.acc1s)) if self.acc1s else 0.0
        print(f"avg incremental top-1 = {avg_inc:.3f}")
        summary = tel.matrix.summary() if tel.matrix.rows else {}
        self.jsonl.log(
            "final",
            acc1s=list(self.acc1s),
            avg_incremental_acc1=avg_inc,
            **summary,
        )
        return {
            "acc1s": self.acc1s,
            "acc_matrix": self.acc_matrix,
            "avg_incremental_acc1": avg_inc,
            "forgetting": summary.get("forgetting"),
            "bwt": summary.get("bwt"),
            "nb_tasks": len(increments),
        }

    def _grow_state(self, state: TrainState, task_id: int, known: int, nb_new: int):
        variables = {"params": state.params, "batch_stats": state.batch_stats}
        variables = grow(
            variables, jax.random.fold_in(self._grow_key, task_id), known, nb_new
        )
        params = shard_params(self.mesh, unfreeze(variables["params"]))
        # The grown head is a new program shape for eval/feature too: their
        # next compile is expected, not a leak.
        self._eval_fresh_shapes = True
        self._feature_fresh_shapes = True
        if self.recompile_sentinel is not None:
            self.recompile_sentinel.note_event("task_growth", task_id=task_id)
        return state.replace(
            params=params,
            momentum=sgd_init(params),  # fresh SGD per task (template.py:246)
            num_active=replicated_scalar(self.mesh, known + nb_new),
            known=replicated_scalar(self.mesh, known),
        )

    def _align_state(self, state: TrainState, known: int, nb_new: int):
        variables, gamma = align({"params": state.params}, known, nb_new)
        params = shard_params(self.mesh, unfreeze(variables["params"]))
        return state.replace(params=params), gamma

    def _lambda_kd(self, task_id: int) -> float:
        """λ for the KD term.  The reference parses ``--dynamic_lambda_kd``
        but never implements the README's λ = n/(n+m) rule
        (SURVEY.md §5 config notes); here it is implemented for real."""
        cfg = self.config
        if not cfg.dynamic_lambda_kd or task_id == 0:
            return cfg.lambda_kd
        incs = self.scenario_train.increments()
        n = sum(incs[:task_id])
        m = incs[task_id]
        return n / (n + m)

    def _fit_task(
        self,
        task_id: int,
        task_train,
        dataset_val,
        nb_new: int = 0,
        start_epoch: int = 0,
    ) -> None:
        """Per-task epoch loop; the per-epoch work is delegated to either the
        fused-epoch program or the per-batch step loop (same scaffold:
        profiling, cosine LR, key derivation, metric logging, eval cadence).

        ``start_epoch > 0`` continues a task an epoch-checkpoint restore
        dropped us into: every epoch's key/permutation is a pure function of
        ``(seed, task, epoch)``, so skipping the completed epochs replays the
        remainder bit-for-bit.
        """
        cfg = self.config
        # Fused-epoch path: whole-epoch lax.scan with the dataset in HBM.
        # Requires pixels in memory (lazy path-based datasets decode on the
        # host per batch, so they keep the per-batch loop).
        fused = cfg.fused_epochs and task_train.x.dtype == np.uint8
        if fused:
            rep = replicated(self.mesh)
            # Dataset lives in HBM for the whole task (CIFAR-100: 150 MB).
            # The previous task's herd phase may have warm-started this
            # transfer on the prefetch ring (_warm_next_task); a verified
            # hit hands the device-resident arrays over, a miss falls back
            # to the synchronous put.
            warm = self._consume_task_warm(task_id, task_train)
            if warm is not None:
                data_x, data_y = warm
            else:
                data_x, data_y = self._put(
                    task_train.x, task_train.y, sharding=rep
                )
            # One digest per task (not per epoch): the fused program consumes
            # the whole resident dataset, so this is the finest granularity
            # the host ever sees on this path.
            task_digest = (
                self._lockstep_digest(task_train.x, task_train.y)
                if self.lockstep is not None else None
            )
        lam = self._lambda_kd(task_id)
        from ..utils.profiling import task_trace

        for epoch in range(start_epoch, cfg.num_epochs):
            # Trace the first executed epoch of each task when profiling is
            # on (the later epochs replay the same compiled program).
            profile_here = cfg.profile_dir if epoch == start_epoch else None
            # A task's first executed epoch carries every (re)compile for
            # this task's shapes; delta-snapshot the compile watch around it
            # so the compile_event record prices that cost — and proves a
            # warm persistent cache drove it to ~0.
            watch_before = (
                self._compile_watch.snapshot() if epoch == start_epoch
                else None
            )
            t_epoch = time.perf_counter()
            lr = cosine_lr(cfg.lr, epoch, cfg.num_epochs)
            epoch_key = jax.random.fold_in(
                jax.random.fold_in(self.root_key, task_id), epoch
            )
            clock = StallClock()
            with self.telemetry.span(
                "epoch", task=task_id, epoch=epoch + 1
            ), task_trace(profile_here, f"task{task_id}_epoch0") as trace_path:
                if fused:
                    pending = self._run_epoch_fused(
                        data_x, data_y, epoch_key, lr, lam, clock,
                        task_id=task_id, epoch=epoch, task_digest=task_digest,
                    )
                    # The fused epoch is one opaque device program: the
                    # per-step fire site never runs.  Settle step-level
                    # clauses host-side now that the step count is known —
                    # before the epoch-checkpoint hook, so a reconciled
                    # kill@...step<S> still resumes from the PREVIOUS
                    # epoch's checkpoint, same as a live mid-epoch kill.
                    if self.faults is not None:
                        self.faults.reconcile_steps(
                            "engine.step", task=task_id, epoch=epoch + 1,
                            steps=len(pending),
                        )
                else:
                    pending = self._run_epoch_steps(
                        task_id, task_train, epoch, epoch_key, lr, lam, clock
                    )
                if profile_here:
                    # Fence inside the trace window so the device events of
                    # the last dispatched steps land in the capture.
                    jax.block_until_ready(self.state.params)
            if trace_path:
                # The capture's location is evidence; a trace nobody can
                # find is a trace that never happened.
                print(f"profiler trace captured under {trace_path}")
                self.jsonl.log(
                    "profile_trace",
                    task_id=task_id,
                    name=f"task{task_id}_epoch0",
                    path=trace_path,
                )
            logger = MetricLogger(delimiter="  ")
            for m in pending:  # floatify once per epoch: no per-step sync
                logger.update(**m)
            logger.synchronize_between_processes()
            print(
                f"train states: epoch :[{epoch + 1}/{cfg.num_epochs}] {logger}"
            )
            # A task's first executed epoch legitimately compiles its shapes
            # (grown head, new scan length — or a fresh process after an
            # epoch-checkpoint restore); train-program growth at any later
            # epoch is the silent mid-steady-state recompile bug and warns.
            self.telemetry.recompiles.check(
                where=f"task{task_id}/epoch{epoch + 1}",
                expected=(epoch == start_epoch),
                group="train",
                task_id=task_id,
                epoch=epoch + 1,
            )
            if watch_before is not None:
                from ..telemetry.compilewatch import CompileWatch

                self.jsonl.log(
                    "compile_event",
                    task_id=task_id,
                    epoch=epoch + 1,
                    resumed=bool(self.resumed_from is not None
                                 and task_id == self.start_task),
                    **CompileWatch.delta(
                        watch_before, self._compile_watch.snapshot()
                    ),
                )
            # epoch_s makes XLA compile cost visible in the evidence log:
            # epoch 1 of a task carries any (re)compile for that task's
            # shapes; steady-state epochs are the pure step cost (r3 Weak #7).
            # host_s/device_s/stall_frac decompose it: host input-pipeline
            # time vs time spent waiting on the accelerator.
            clock_snap = clock.snapshot()
            self.jsonl.log(
                "epoch",
                task_id=task_id,
                epoch=epoch + 1,
                lr=lr,
                epoch_s=round(time.perf_counter() - t_epoch, 2),
                **clock_snap,
                **{k: m.global_avg for k, m in logger.meters.items()},
            )
            # Epoch-cadence time series: the pump derives epochs/s from the
            # counter; stall_frac and the cumulative recompile count are
            # levels, so gauges (last value wins across flushes).
            self._m_epochs.inc()
            self._m_stall.set(clock_snap.get("stall_frac", 0.0))
            self._m_recompiles.set(self.telemetry.recompiles.total())
            self.telemetry.heartbeat.update(
                force=True, task=task_id, epoch=epoch + 1
            )
            # Mid-task durability: an epoch checkpoint every E epochs bounds
            # the replay after a kill to < E epochs instead of the whole
            # task.  A *transient* save failure (full disk, flaky NFS — or
            # the injected save_ioerror) must not kill a healthy run; it
            # costs durability, not correctness, so log and continue.
            if (cfg.ckpt_dir and cfg.epoch_ckpt_every > 0
                    and (epoch + 1) % cfg.epoch_ckpt_every == 0):
                from ..utils.checkpoint import save_epoch_checkpoint

                try:
                    with self.telemetry.span(
                        "epoch_checkpoint", task=task_id, epoch=epoch + 1
                    ):
                        save_epoch_checkpoint(self, task_id, epoch + 1, nb_new)
                except OSError as e:
                    print(f"| epoch checkpoint save failed: {e!r}")
                    self.jsonl.log(
                        "ckpt_save_error", error=repr(e),
                        task_id=task_id, epoch=epoch + 1,
                    )
            # The engine.epoch injection point sits AFTER the epoch's
            # checkpoint hook on purpose: kill@taskT.epochE leaves epoch E's
            # checkpoint on disk, so the supervised relaunch resumes at
            # exactly the boundary the kill named.
            if self.faults is not None:
                self.faults.fire("engine.epoch", task=task_id, epoch=epoch + 1)
            # Reference cadence exactly (template.py:282-283): when num_epochs
            # is a multiple of eval_every_epoch this evals once more at the
            # final pre-alignment epoch, in addition to the post-alignment
            # eval in fit() — a redundant-looking but protocol-visible quirk.
            if (epoch + 1) % cfg.eval_every_epoch == 0:
                self.evaluate(dataset_val)

    def _run_epoch_steps(
        self,
        task_id: int,
        task_train,
        epoch: int,
        epoch_key,
        lr: float,
        lam: float,
        clock: Optional[StallClock] = None,
    ) -> List[Dict]:
        """One device dispatch per batch (lazy datasets / debugging).

        With ``cfg.prefetch_depth > 0`` batch production — permutation
        slice, uint8 gather, host decode, key derivation and the sharded
        ``device_put`` — runs on the prefetcher's background thread, so the
        H2D transfer of batch *k+1* overlaps the device compute of batch
        *k*; ``clock`` then accumulates only the residual (non-overlapped)
        host time.  The batch stream is byte-identical at every depth.
        """
        cfg = self.config
        clock = clock if clock is not None else StallClock()
        step_fn = self._steps[self.teacher is not None]
        hb = self.telemetry.heartbeat
        pidx, pcount = jax.process_index(), jax.process_count()
        # Same shuffle on every process (sampler.set_epoch equivalent,
        # reference template.py:253).
        shuffle_seed = hash((cfg.seed, task_id, epoch)) & 0x7FFFFFFF

        def _placed(item):
            step_idx, (xb, yb) = item
            # data.produce injection point: runs on the producer thread at
            # depth > 0 (producer_die exercises the graceful degradation
            # below; slow_batch models a hitching input pipeline).
            if self.faults is not None:
                self.faults.fire(
                    "data.produce", task=task_id, epoch=epoch + 1,
                    step=step_idx + 1,
                )
            xb = self._decode(xb, train=True, seed=shuffle_seed + step_idx)
            # Same key on every process (replicated jit operands must be
            # process-consistent); per-image randomness comes from the
            # split over the global batch inside train_augment.
            key = jax.random.fold_in(epoch_key, step_idx)
            # Lockstep digest over the HOST batch, on the producer thread:
            # free overlap with device compute at prefetch_depth > 0, and it
            # witnesses the data *this process* read — exactly the thing a
            # divergent input pipeline corrupts.
            digest = (
                self._lockstep_digest(xb, yb)
                if self.lockstep is not None else None
            )
            x, y = self._put(xb, yb)
            return x, y, key, digest

        def _degraded(exc):
            self.jsonl.log(
                "prefetch_degraded", where="train", error=repr(exc),
                task_id=task_id, epoch=epoch + 1,
            )

        source = enumerate(
            train_batches(
                task_train, self.global_batch_size, shuffle_seed, pidx, pcount
            )
        )
        pending: List[Dict] = []
        with DevicePrefetcher(
            source,
            _placed,
            cfg.prefetch_depth,
            clock=clock,
            name=f"prefetch-train-t{task_id}",
            on_degrade=_degraded,
            metrics=self.telemetry.metrics,
        ) as batches:
            step_no = 0
            for x, y, key, digest in batches:
                t_step = time.perf_counter()
                if self.lockstep is not None:
                    # BEFORE the dispatch: a mismatch must surface while every
                    # process is still on the host side of the collective.
                    self.lockstep.check(
                        "train_step",
                        program=("train_step_kd" if self.teacher is not None
                                 else "train_step"),
                        args=(x, y, key),
                        digest=digest,
                        rng=(task_id, epoch, step_no),
                        step=self._global_step + 1,
                        task=task_id,
                        epoch=epoch + 1,
                    )
                with clock.device():
                    self.state, metrics = step_fn(
                        self.state, self.teacher, x, y, key, lr, lam
                    )
                pending.append(metrics)
                self._global_step += 1
                step_no += 1
                step_ms = (time.perf_counter() - t_step) * 1e3
                self._m_steps.inc()
                self._m_step_ms.observe(step_ms)
                hb.update(
                    step=self._global_step,
                    task=task_id,
                    epoch=epoch + 1,
                    last_step_ms=round(step_ms, 2),
                )
                # engine.step fires after the step's dispatch, so a kill at
                # step S never loses steps < S from the run's metrics.
                if self.faults is not None:
                    self.faults.fire(
                        "engine.step", task=task_id, epoch=epoch + 1,
                        step=step_no,
                    )
        # ONE device->host transfer for the whole epoch's metrics: per-scalar
        # fetches cost a full RPC round trip each on tunneled TPU platforms
        # (~90 ms measured), which would dwarf the steps themselves.
        keys = sorted(pending[0])
        with clock.device():  # blocks on the whole epoch's dispatched work
            stacked = jnp.stack(
                [jnp.stack([m[k] for k in keys]) for m in pending]
            )
            host = np.asarray(stacked)  # [steps, K]
        return [dict(zip(keys, row)) for row in host]

    def _run_epoch_fused(
        self,
        data_x,
        data_y,
        epoch_key,
        lr: float,
        lam: float,
        clock: Optional[StallClock] = None,
        task_id: Optional[int] = None,
        epoch: Optional[int] = None,
        task_digest: Optional[str] = None,
    ):
        """One ``lax.scan`` program for the whole epoch (see ``make_epoch_fn``)."""
        epoch_fn = self._epochs[self.teacher is not None]
        clock = clock if clock is not None else StallClock()
        if self.lockstep is not None:
            self.lockstep.check(
                "train_epoch_fused",
                program=("epoch_fn_kd" if self.teacher is not None
                         else "epoch_fn"),
                args=(data_x, data_y, epoch_key),
                digest=task_digest,
                rng=(task_id, epoch) if task_id is not None else None,
                step=self._global_step + 1,
                task=task_id,
                epoch=(epoch + 1) if epoch is not None else None,
            )
        with clock.device():  # the epoch is one program + one blocking fetch
            self.state, metrics = epoch_fn(
                self.state,
                self.teacher,
                data_x,
                data_y,
                epoch_key,
                lr,
                lam,
                self.global_batch_size,
            )
            host = {k: np.asarray(v) for k, v in metrics.items()}
        nb_steps = next(iter(host.values())).shape[0]
        self._global_step += nb_steps
        avg_step_ms = clock.device_s / max(nb_steps, 1) * 1e3
        # The fused epoch is one opaque program: the counter advances in
        # bulk and the histogram sees one per-step average observation per
        # epoch (the per-step distribution does not exist host-side).
        self._m_steps.inc(nb_steps)
        self._m_step_ms.observe(avg_step_ms)
        self.telemetry.heartbeat.update(
            step=self._global_step,
            last_step_ms=round(avg_step_ms, 2),
        )
        with clock.host():  # row split is the path's only host-side work
            rows = [{k: v[i] for k, v in host.items()} for i in range(nb_steps)]
        return rows

    # ------------------------------------------------------------------ #
    # Eval (reference template.py:169-188)
    # ------------------------------------------------------------------ #

    def _eval_totals_device(self, dataset_val) -> jax.Array:
        """Weighted-count totals ``[loss_sum, correct1, correct5, n]`` over a
        val set, left on device (callers batch the host fetch); padding
        batches carry zero weight, so totals over disjoint slices sum
        exactly to the totals over their union."""
        pidx, pcount = jax.process_index(), jax.process_count()

        def _placed(batch):
            xb, yb, wb = batch
            xb = self._decode(xb, train=False, seed=0)
            return self._put(xb, yb, wb)

        def _degraded(exc):
            self.jsonl.log(
                "prefetch_degraded", where="eval", error=repr(exc),
            )

        totals = None
        with DevicePrefetcher(
            eval_batches(dataset_val, self.global_batch_size, pidx, pcount),
            _placed,
            self.config.prefetch_depth,
            name="prefetch-eval",
            on_degrade=_degraded,
            metrics=self.telemetry.metrics,
        ) as batches:
            for x, y, w in batches:
                if self.lockstep is not None:
                    # Shape/count lockstep only: the operands are already on
                    # device, and a digest would cost a D2H transfer per
                    # batch.  A divergent eval stream still trips here — the
                    # padded batch counts or shard shapes disagree first.
                    self.lockstep.check(
                        "eval_step",
                        program=f"eval_step@known{self.known}",
                        args=(x, y, w),
                    )
                out = self.eval_step(
                    self.state.params,
                    self.state.batch_stats,
                    x,
                    y,
                    w,
                    self.state.num_active,
                )
                # Accumulate ON DEVICE; batches dispatch back-to-back and
                # the whole eval costs exactly one device->host fetch at the
                # end (per-scalar fetches are ~90 ms RPCs on tunneled
                # platforms).
                s = jnp.stack(out)
                totals = s if totals is None else totals + s
        # First eval after a head growth legitimately compiles the new
        # classifier shape; any other eval-program growth warns.
        self.telemetry.recompiles.check(
            where=f"eval@known{self.known}",
            expected=self._eval_fresh_shapes,
            group="eval",
        )
        self._eval_fresh_shapes = False
        return totals

    def evaluate(self, dataset_val) -> float:
        totals = np.asarray(self._eval_totals_device(dataset_val))
        print(_eval_line(totals))
        return float(100.0 * totals[1] / max(totals[3], 1.0))

    # ------------------------------------------------------------------ #
    # Herding pass (reference template.py:292-302)
    # ------------------------------------------------------------------ #

    def _update_memory(self, task_id: int, task_train) -> None:
        cfg = self.config
        feats = []
        # Unsharded, unshuffled full pass replicated on every process so
        # memories stay identical without communication (the reference runs
        # its herding loader non-distributed for the same reason,
        # template.py:292-293).
        rep = replicated(self.mesh)
        feat_key = jax.random.fold_in(self.root_key, 0xFEED + task_id)

        def _placed(item):
            i, (xb, _yb) = item
            xb = self._decode(xb, train=cfg.herding_augmented, seed=i)
            x = self._put(xb, sharding=rep)
            return x, jax.random.fold_in(feat_key, i)

        def _degraded(exc):
            self.jsonl.log(
                "prefetch_degraded", where="herd", error=repr(exc),
                task_id=task_id,
            )

        with DevicePrefetcher(
            enumerate(sequential_batches(task_train, self.global_batch_size)),
            _placed,
            cfg.prefetch_depth,
            name="prefetch-herd",
            on_degrade=_degraded,
            metrics=self.telemetry.metrics,
        ) as batches:
            for x, key in batches:
                if self.lockstep is not None:
                    # Herding is replicated-by-construction (identical full
                    # pass on every process); lockstep turns "construction"
                    # into a checked invariant.
                    self.lockstep.check(
                        "feature_step",
                        program="feature_step",
                        args=(x, key),
                        task=task_id,
                    )
                f = self.feature_step(
                    self.state.params, self.state.batch_stats, x, key
                )
                feats.append(f)  # on device; one concat + one fetch below
        features = np.asarray(jnp.concatenate(feats))[: len(task_train)]
        # The herding pass's first run after a head growth compiles the new
        # shape; growth at any later herd warns.
        self.telemetry.recompiles.check(
            where=f"herd@task{task_id}",
            expected=self._feature_fresh_shapes,
            group="feature",
        )
        self._feature_fresh_shapes = False
        self.memory.add(*task_train.get_raw_samples(), features)

    # ------------------------------------------------------------------ #
    # Next-task dataset warm ring (data/prefetch.py; --prefetch_depth)
    # ------------------------------------------------------------------ #

    def _warm_next_task(self, task_id: int) -> None:
        """Arm a depth-1 prefetch ring with the NEXT task's fused dataset.

        Called from the herd phase, when the rehearsal memory is final for
        task ``task_id + 1``: the next task's injected dataset (task slice +
        exemplars) is reconstructed here and its replicated ``device_put``
        runs on the ring's producer thread, overlapping the checkpoint write
        and the next task's host-side setup.  Consumption
        (:meth:`_consume_task_warm`) verifies the warmed content against the
        dataset the task loop actually built — a mismatch is a logged miss
        that falls back to the synchronous put, never wrong data.

        Gated exactly like the async input pipeline (``--prefetch_depth``)
        and only useful on the fused-epoch path (the per-batch path streams
        its batches through its own ring already).
        """
        cfg = self.config
        nxt = task_id + 1
        if (cfg.prefetch_depth <= 0 or not cfg.fused_epochs
                or nxt >= len(self.scenario_train)):
            return
        warm_train = self.scenario_train[nxt]
        if nxt > 0:
            warm_train.add_samples(*self.memory.get())
        if warm_train.x.dtype != np.uint8:
            return  # lazy path-based dataset: stays on the per-batch loop
        rep = replicated(self.mesh)
        stride = max(1, len(warm_train.x) // 8)
        t0 = time.perf_counter()

        def _place(host):
            hx, hy = host
            return self._put(hx, hy, sharding=rep)

        self._task_warm = {
            "task_id": nxt,
            "prefetcher": DevicePrefetcher(
                iter([(warm_train.x, warm_train.y)]),
                _place,
                depth=1,
                name=f"prefetch-taskwarm-t{nxt}",
                metrics=self.telemetry.metrics,
            ),
            "t0": t0,
            "y": warm_train.y,
            "x_probe": warm_train.x[::stride].copy(),
            "probe_stride": stride,
            "nbytes": int(warm_train.x.nbytes + warm_train.y.nbytes),
        }

    def _consume_task_warm(self, task_id: int, task_train):
        """Hand over the warmed device arrays iff they match ``task_train``.

        Verification is labels-exact plus a strided pixel probe: the labels
        array is tiny and the probe covers every region of the concatenated
        (slice + exemplars) buffer, so any divergence in task slicing or
        memory content surfaces as a miss.  Every outcome emits a
        ``prefetch_warm`` record; the warm path can degrade but never
        propagate an exception into training.
        """
        warm, self._task_warm = self._task_warm, None
        if warm is None:
            return None
        pf = warm["prefetcher"]
        try:
            if warm["task_id"] != task_id:
                pf.close()
                self.jsonl.log(
                    "prefetch_warm", task_id=task_id, hit=False,
                    reason=f"armed_for_task{warm['task_id']}",
                )
                return None
            stride = warm["probe_stride"]
            matches = (
                task_train.x.dtype == np.uint8
                and np.array_equal(warm["y"], task_train.y)
                and np.array_equal(warm["x_probe"], task_train.x[::stride])
            )
            if not matches:
                pf.close()
                self.jsonl.log(
                    "prefetch_warm", task_id=task_id, hit=False,
                    reason="content_mismatch",
                )
                return None
            t_wait = time.perf_counter()
            placed = next(pf, None)
            pf.close()
            if placed is None:
                self.jsonl.log(
                    "prefetch_warm", task_id=task_id, hit=False,
                    reason="ring_empty",
                )
                return None
            self.jsonl.log(
                "prefetch_warm", task_id=task_id, hit=True,
                bytes=warm["nbytes"],
                wait_s=round(time.perf_counter() - t_wait, 4),
                warm_s=round(time.perf_counter() - warm["t0"], 4),
            )
            return placed
        except Exception as e:  # noqa: BLE001 — warm path must not kill a run
            pf.close()
            self.jsonl.log(
                "prefetch_warm", task_id=task_id, hit=False, reason=repr(e),
            )
            return None

    # ------------------------------------------------------------------ #
    # Checkpointing hook (filled in by utils.checkpoint; no-op default)
    # ------------------------------------------------------------------ #

    def _save_checkpoint(self, task_id: int) -> None:
        if self.config.ckpt_dir:
            from ..utils.checkpoint import save_task_checkpoint

            try:
                save_task_checkpoint(self, task_id)
            except OSError as e:
                # Transient save failure (or injected save_ioerror): the run
                # loses durability for this boundary, not correctness — the
                # fallback scan will resume from the newest checkpoint that
                # did land.  Logged so the evidence trail shows the gap.
                print(f"| task checkpoint save failed: {e!r}")
                self.jsonl.log(
                    "ckpt_save_error", error=repr(e), task_id=task_id
                )

    # ------------------------------------------------------------------ #
    # Serving export hook (serving/ package; --export_dir)
    # ------------------------------------------------------------------ #

    def _export_artifact(self, task_id: int, nb_new: int, acc_per_task) -> None:
        """Freeze the post-alignment model as a serving artifact.

        Same failure contract as checkpoint saves: a transient export
        failure costs this task's artifact (the server keeps the previous
        one), never the training run.
        """
        from serving.artifact import export_from_trainer

        t0 = time.time()
        try:
            path = export_from_trainer(
                self, task_id, known_after=self.known + nb_new,
                acc_per_task=acc_per_task,
            )
        except OSError as e:
            print(f"| serving artifact export failed: {e!r}")
            self.jsonl.log("serve_export", task_id=task_id, error=repr(e))
            return
        self.jsonl.log(
            "serve_export",
            task_id=task_id,
            path=path,
            known=self.known + nb_new,
            buckets=list(self.config.serve_buckets),
            seconds=round(time.time() - t0, 2),
        )
        if self.config.serve_skew_check:
            from serving.artifact import load_artifact
            from serving.skew import measure_skew

            try:
                artifact = load_artifact(path)
                measure_skew(
                    artifact, self.scenario_val, sink=self.jsonl,
                    train_acc_per_task=acc_per_task,
                )
            except OSError as e:
                # The skew check is observability, not a gate; a reload
                # failure is itself the signal worth logging.
                print(f"| serve skew check failed: {e!r}")
                self.jsonl.log("serve_export", task_id=task_id, error=repr(e))
