"""Engine (L5): compiled train/eval steps, SGD, losses, and the WA task loop.

The CIL algorithm layer of the reference (``template.py:191-303``) rebuilt as
a functional JAX engine (see ``loop.py`` / ``train.py`` docstrings).
"""

from .losses import accuracy, cross_entropy, soft_target_kd, topk_correct  # noqa: F401
from .train import (  # noqa: F401
    Teacher,
    TrainState,
    cosine_lr,
    make_eval_step,
    make_feature_step,
    make_train_step,
    sgd_init,
    sgd_update,
)
from .loop import CilTrainer  # noqa: F401
