"""Data layer (L2): datasets, class-incremental scenario, rehearsal memory,
host loaders and on-device augmentation.

Native replacement for the reference's continuum + timm + DataLoader stack
(SURVEY.md #15-#21, #24).
"""

from .datasets import (  # noqa: F401
    build_raw_dataset,
    decode_image_batch,
    load_cifar100,
    load_image_folder,
    load_synthetic,
    maybe_decode,
)
from .scenario import ClassIncremental, TaskSet  # noqa: F401
from .memory import (  # noqa: F401
    RehearsalMemory,
    herd_barycenter,
    herd_cluster,
    herd_random,
)
from .loader import eval_batches, sequential_batches, train_batches  # noqa: F401
from .prefetch import DevicePrefetcher  # noqa: F401


def build_scenario(config, train: bool):
    """Dataset flags -> ``(ClassIncremental scenario, nb_classes)``.

    Counterpart of ``build_dataset`` (reference ``utils.py:188-207``): loads
    the raw arrays and wraps them in the task-splitting scenario with the
    config's class order.
    """
    from ..config import CIFAR100_CLASS_ORDER

    (x, y), nb_classes = build_raw_dataset(
        config.data_set, config.data_path, train, config.input_size
    )
    order = config.class_order
    if order is not None and len(order) != nb_classes:
        if tuple(order) != CIFAR100_CLASS_ORDER:
            # An explicitly-supplied order that doesn't fit the dataset is a
            # misconfiguration — never silently fall back to identity.
            raise ValueError(
                f"class_order has {len(order)} entries but the dataset has "
                f"{nb_classes} classes"
            )
        order = None  # default CIFAR order on a non-100-class dataset
        # (e.g. synthetic20 smoke runs): identity order
    scenario = ClassIncremental(
        x,
        y,
        initial_increment=config.num_bases,
        increment=config.increment,
        class_order=order,
    )
    return scenario, nb_classes
