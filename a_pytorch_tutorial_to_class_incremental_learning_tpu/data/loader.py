"""Host-side input pipeline: deterministic shuffling, sharding, fixed batches.

Replaces ``DistributedSampler`` + the DataLoader worker pool (SURVEY.md #24;
reference ``template.py:232-239``).  Host work is only index arithmetic and a
uint8 gather — decode and augmentation happen on device (``data/augment.py``),
so no worker processes are needed.

Design points:

* **Static shapes.** Every batch has exactly ``batch_size`` rows so the jitted
  step compiles once.  Train epochs wrap-around-pad the permuted index list
  (the same duplication ``DistributedSampler`` uses to equalize ranks); eval
  pads the tail batch and marks padding with zero sample-weights, so eval
  metrics are *exact* — a conscious fix of the reference's padded-sample
  double counting (SURVEY.md §7 "remaining hard parts").
* **Process sharding.** Each host takes a contiguous stripe of every batch
  (``batch[i*per_proc:(i+1)*per_proc]``), matching the device order of a
  process-major mesh; per-epoch reshuffling is seeded like
  ``sampler.set_epoch`` (reference ``template.py:253``) but from the threaded
  PRNG key.
* **Synchronous by design.** These generators are pure and deterministic;
  overlap with device compute is layered on top by ``data/prefetch.py``,
  which iterates them unchanged from a background thread
  (``--prefetch_depth``), so the batch stream is identical either way.
"""

from __future__ import annotations

import time
from typing import Iterator, Tuple

import numpy as np

from ..utils.native import gather_rows
from .scenario import TaskSet


def _epoch_perm(seed: int, n: int) -> np.ndarray:
    return np.random.RandomState(seed & 0x7FFFFFFF).permutation(n)


def _per_process(batch_size: int, process_count: int) -> int:
    """Per-process stripe width.  A loud raise, not an ``assert``: these guard
    multi-process sharding and must survive ``python -O`` — an indivisible
    global batch would silently mis-shard otherwise."""
    per_proc, rem = divmod(batch_size, process_count)
    if rem:
        raise ValueError(
            f"global batch_size {batch_size} is not divisible by "
            f"process_count {process_count}"
        )
    return per_proc


def train_batches(
    task: TaskSet,
    batch_size: int,
    seed: int,
    process_index: int = 0,
    process_count: int = 1,
    clock=None,
) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Shuffled fixed-shape ``(x uint8, y)`` batches for one epoch.

    ``batch_size`` is the **global** batch; this process yields its
    ``batch_size // process_count`` stripe of every batch.  ``clock`` (a
    ``telemetry.StallClock``) charges the host-side production cost of each
    batch — the index arithmetic and the uint8 row gather — to the input-
    pipeline stall account, so data-bound epochs are measurable, not guessed.
    """
    n = len(task)
    perm = _epoch_perm(seed, n)
    nb_batches = max(1, -(-n // batch_size))  # ceil; wrap-pad the tail
    padded = np.resize(perm, nb_batches * batch_size)
    per_proc = _per_process(batch_size, process_count)
    for b in range(nb_batches):
        t0 = time.perf_counter()
        idx = padded[b * batch_size : (b + 1) * batch_size]
        idx = idx[process_index * per_proc : (process_index + 1) * per_proc]
        batch = gather_rows(task.x, idx), task.y[idx]
        if clock is not None:
            clock.add_host(time.perf_counter() - t0)
        yield batch


def eval_batches(
    task: TaskSet,
    batch_size: int,
    process_index: int = 0,
    process_count: int = 1,
    clock=None,
) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Sequential ``(x, y, weight)`` batches; padding rows carry weight 0."""
    n = len(task)
    per_proc = _per_process(batch_size, process_count)
    nb_batches = -(-n // batch_size)
    for b in range(nb_batches):
        t0 = time.perf_counter()
        idx = np.arange(b * batch_size, (b + 1) * batch_size)
        w = (idx < n).astype(np.float32)
        idx = np.minimum(idx, n - 1)
        sl = slice(process_index * per_proc, (process_index + 1) * per_proc)
        batch = gather_rows(task.x, idx[sl]), task.y[idx[sl]], w[sl]
        if clock is not None:
            clock.add_host(time.perf_counter() - t0)
        yield batch


def sequential_batches(
    task: TaskSet, batch_size: int
) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Unshuffled, unsharded full pass — the herding feature loader.

    The reference deliberately runs herding feature extraction unsharded so
    every rank sees identical features and memories stay in sync without
    communication (``template.py:292-293``); same here, on every process.
    Tail batch is wrap-padded (callers slice the result to ``len(task)``).
    """
    n = len(task)
    nb_batches = -(-n // batch_size)
    idx_all = np.resize(np.arange(n), nb_batches * batch_size)
    for b in range(nb_batches):
        idx = idx_all[b * batch_size : (b + 1) * batch_size]
        yield gather_rows(task.x, idx), task.y[idx]
