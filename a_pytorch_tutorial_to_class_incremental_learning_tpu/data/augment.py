"""On-device batched augmentation: padded crop, flip, RandAugment, normalize.

Native replacement for ``timm.data.create_transform`` + torchvision transforms
(SURVEY.md #21; reference ``utils.py:210-251``).  The reference augments on
CPU in 10 DataLoader worker processes per GPU (``template.py:236-239``);
TPU-first, the whole pipeline is a pure jittable function of
``(PRNG key, uint8 batch)`` running on device, where XLA fuses it with the
forward pass — raw uint8 batches cross PCIe, everything else stays in HBM.

Pipeline fidelity (timm 0.5.4 semantics, ``rand-m9-mstd0.5-inc1`` default):

* ``RandomCrop(32, padding=4)`` with zero fill (``utils.py:227-229``).
* ``RandomHorizontalFlip(p=0.5)``.
* When ``auto_augment`` is set, timm *skips* color-jitter (its transform
  factory's ``elif``), so the default recipe is crop/flip/RandAugment; the
  color-jitter path exists for ``aa=None``.
* RandAugment: 2 ops per image drawn uniformly from the 15-op "rand" table
  (AutoContrast, Equalize, Invert, Rotate, Posterize, Solarize, SolarizeAdd,
  Color, Contrast, Brightness, Sharpness, ShearX, ShearY, TranslateXRel,
  TranslateYRel) with the "increasing" magnitude maps, magnitude ~
  N(9, 0.5) clipped to [0, 10], random sign for signed ops, fill 128 for
  geometric ops.  Geometric resampling follows ``ra_interpolation``:
  ``"bilinear"`` (default — one fixed kernel keeps the warp single-pass on
  device); ``"bicubic"`` = reference parity (the reference passes
  ``interpolation='bicubic'`` to ``create_transform``, ``utils.py:222``,
  and timm 0.5.4 honors an explicit hint deterministically); ``"random"`` =
  timm's generic no-hint default (each applied geometric op independently
  picks bilinear or bicubic, timm's ``_RANDOM_INTERPOLATION``; costs a
  second warp pass under vmap — and is NOT the reference recipe's behavior).
* ``Normalize``: ``(x/255 - mean) / std`` with the stats chosen by
  ``CilConfig.normalization_stats()`` (preserving the reference's
  CIFAR-vs-ImageNet quirk, ``utils.py:231-233``).
* Optional RandomErasing in "pixel" mode (``reprob`` flag, default 0).

Ops emulate PIL's uint8 domain by rounding+clipping after every RandAugment
op.  All per-image ops are expressed for ``vmap``; the op choice is a
``lax.switch`` (under vmap: compute-all-and-select — 15 cheap 32x32 branches,
negligible next to the conv stack).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

FILL = 128.0  # timm's geometric fill color (128, 128, 128)


@dataclass(frozen=True)
class AugmentConfig:
    """Static augmentation knobs (hashable -> usable as a jit static arg)."""

    input_size: int = 32
    crop_padding: int = 4
    hflip: bool = True  # off for digit datasets (mirroring is label noise)
    rand_augment: bool = True
    ra_num_ops: int = 2
    ra_magnitude: float = 9.0
    ra_mag_std: float = 0.5
    ra_prob: float = 0.5  # per-op apply probability (timm AugmentOp default)
    # Geometric-op resampling: "bilinear" | "bicubic" | "random" (timm parity:
    # each applied op picks one of the two at random).
    ra_interpolation: str = "bilinear"
    color_jitter: float = 0.4  # used only when rand_augment is False
    reprob: float = 0.0
    remode: str = "pixel"  # timm modes: pixel | rand | const
    recount: int = 1
    mean: Tuple[float, float, float] = (0.485, 0.456, 0.406)
    std: Tuple[float, float, float] = (0.229, 0.224, 0.225)

    @classmethod
    def from_config(cls, config) -> "AugmentConfig":
        mean, std = config.normalization_stats()
        ra = parse_rand_augment(config.aa)
        return cls(
            input_size=config.input_size,
            # >32px inputs get host-side RandomResizedCrop at decode time
            # (datasets.decode_image_batch); the padded 4-pixel crop is the
            # <=32px replacement (reference utils.py:227-229).
            crop_padding=4 if config.input_size <= 32 else 0,
            # Standard MNIST recipes never mirror: asymmetric digits
            # (2,3,4,5,7,9) make horizontal flip structured label noise.
            hflip="mnist" not in config.data_set.lower(),
            rand_augment=ra is not None,
            ra_magnitude=ra["m"] if ra else 9.0,
            ra_num_ops=ra["n"] if ra else 2,
            ra_mag_std=ra["mstd"] if ra else 0.5,
            ra_prob=ra["p"] if ra else 0.5,
            ra_interpolation=getattr(config, "ra_interpolation", "bilinear"),
            color_jitter=config.color_jitter or 0.0,
            reprob=config.reprob,
            remode=config.remode,
            recount=config.recount,
            mean=tuple(mean),
            std=tuple(std),
        )


def parse_rand_augment(aa: Optional[str]) -> Optional[dict]:
    """Parse a timm RandAugment policy string, e.g. ``rand-m9-mstd0.5-inc1``.

    Mirrors ``timm.data.auto_augment.rand_augment_transform``'s config-string
    grammar for the knobs this pipeline supports: ``m`` (magnitude), ``n``
    (ops per image), ``mstd`` (magnitude noise std), ``p`` (per-op prob),
    ``inc`` (increasing maps — this implementation always uses them, matching
    the reference's ``inc1`` recipe; ``inc0`` is rejected rather than silently
    honored).  Returns None when ``aa`` is falsy; raises on unsupported
    policies so a requested recipe is never silently replaced.
    """
    if not aa or aa in ("none", "None"):
        return None
    parts = aa.split("-")
    if parts[0] != "rand":
        raise NotImplementedError(
            f"auto_augment policy {aa!r} not supported (only 'rand-*')"
        )
    out = {"m": 9.0, "n": 2, "mstd": 0.5, "p": 0.5}
    for tok in parts[1:]:
        for name, key, typ in (
            ("mstd", "mstd", float),
            ("inc", "inc", int),
            ("m", "m", float),
            ("n", "n", int),
            ("p", "p", float),
            ("w", "w", int),
        ):
            if tok.startswith(name):
                val = typ(tok[len(name):])
                if key == "inc":
                    if not val:
                        raise NotImplementedError(
                            "non-increasing magnitude maps (inc0) not implemented"
                        )
                elif key == "w":
                    pass  # weighted op choice: only w0 (uniform) exists in timm
                else:
                    out[key] = val
                break
        else:
            raise ValueError(f"unparsable token {tok!r} in aa policy {aa!r}")
    return out


def _round_u8(img: jax.Array) -> jax.Array:
    """Emulate PIL's uint8 quantization between ops."""
    return jnp.clip(jnp.round(img), 0.0, 255.0)


# --------------------------------------------------------------------------- #
# Geometric ops: bilinear affine resample, output->input coordinate map
# --------------------------------------------------------------------------- #


def _cubic_weight(t: jax.Array) -> jax.Array:
    """Keys cubic-convolution kernel with a = -1.0.

    PIL has two different bicubics: Resample.c (resize) uses a = -0.5, but
    Geometry.c — the transform/rotate path every timm geometric AugmentOp
    goes through — uses the a = -1 cubic (its BICUBIC macro's polynomial
    form expands to exactly this kernel; verified to max-1/255 against
    ``Image.rotate(resample=BICUBIC)`` in tests/test_augment.py)."""
    a = -1.0
    at = jnp.abs(t)
    near = ((a + 2.0) * at - (a + 3.0)) * at * at + 1.0
    far = a * (((at - 5.0) * at + 8.0) * at - 4.0)
    return jnp.where(at <= 1.0, near, jnp.where(at < 2.0, far, 0.0))


def _affine(img: jax.Array, mat: jax.Array, kernel: str = "bilinear") -> jax.Array:
    """Apply a 2x3 affine map (output pixel -> input pixel), FILL outside.
    ``img`` is [H, W, C] float in [0, 255]; ``kernel`` is ``"bilinear"``
    (4-tap) or ``"bicubic"`` (16-tap Keys a=-1, PIL Geometry.c's filter —
    see ``_cubic_weight``).  Out-of-image taps contribute FILL (both
    kernels' weights sum to 1, so fully-outside output pixels are exactly
    FILL)."""
    h, w = img.shape[0], img.shape[1]
    ys, xs = jnp.meshgrid(
        jnp.arange(h, dtype=jnp.float32), jnp.arange(w, dtype=jnp.float32),
        indexing="ij",
    )
    xin = mat[0, 0] * xs + mat[0, 1] * ys + mat[0, 2]
    yin = mat[1, 0] * xs + mat[1, 1] * ys + mat[1, 2]
    x0 = jnp.floor(xin)
    y0 = jnp.floor(yin)
    wx = xin - x0
    wy = yin - y0

    def sample(yi, xi):
        valid = (xi >= 0) & (xi <= w - 1) & (yi >= 0) & (yi <= h - 1)
        xi_c = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        yi_c = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        px = img[yi_c, xi_c]
        return jnp.where(valid[..., None], px, FILL)

    if kernel == "bilinear":
        return (
            sample(y0, x0) * ((1 - wx) * (1 - wy))[..., None]
            + sample(y0, x0 + 1) * (wx * (1 - wy))[..., None]
            + sample(y0 + 1, x0) * ((1 - wx) * wy)[..., None]
            + sample(y0 + 1, x0 + 1) * (wx * wy)[..., None]
        )
    if kernel != "bicubic":
        raise ValueError(f"unknown resampling kernel {kernel!r}")
    out = jnp.zeros_like(img)
    for dy in (-1, 0, 1, 2):
        wyv = _cubic_weight(wy - dy)
        for dx in (-1, 0, 1, 2):
            wxv = _cubic_weight(wx - dx)
            out = out + sample(y0 + dy, x0 + dx) * (wxv * wyv)[..., None]
    return out


def _rotate_matrix(img_shape, degrees: jax.Array) -> jax.Array:
    """Rotation about the image center (PIL ``img.rotate`` semantics),
    output->input: translate to center, rotate, translate back."""
    h, w = img_shape[0], img_shape[1]
    cx, cy = (w - 1) / 2.0, (h - 1) / 2.0
    rad = jnp.deg2rad(degrees)
    c, s = jnp.cos(rad), jnp.sin(rad)
    return jnp.array(
        [
            [c, -s, cx - c * cx + s * cy],
            [s, c, cy - s * cx - c * cy],
        ]
    )


def _shear_x_matrix(v: jax.Array) -> jax.Array:
    return jnp.array([[1.0, v, 0.0], [0.0, 1.0, 0.0]])


def _shear_y_matrix(v: jax.Array) -> jax.Array:
    return jnp.array([[1.0, 0.0, 0.0], [v, 1.0, 0.0]])


def _translate_x_matrix(pixels: jax.Array) -> jax.Array:
    return jnp.array([[1.0, 0.0, pixels], [0.0, 1.0, 0.0]])


def _translate_y_matrix(pixels: jax.Array) -> jax.Array:
    return jnp.array([[1.0, 0.0, 0.0], [0.0, 1.0, pixels]])


def _rotate(img: jax.Array, degrees: jax.Array) -> jax.Array:
    return _affine(img, _rotate_matrix(img.shape, degrees))


def _shear_x(img: jax.Array, v: jax.Array) -> jax.Array:
    return _affine(img, _shear_x_matrix(v))


def _shear_y(img: jax.Array, v: jax.Array) -> jax.Array:
    return _affine(img, _shear_y_matrix(v))


def _translate_x(img: jax.Array, pixels: jax.Array) -> jax.Array:
    return _affine(img, _translate_x_matrix(pixels))


def _translate_y(img: jax.Array, pixels: jax.Array) -> jax.Array:
    return _affine(img, _translate_y_matrix(pixels))


# --------------------------------------------------------------------------- #
# Color / histogram ops (PIL ImageOps / ImageEnhance semantics)
# --------------------------------------------------------------------------- #


def _grayscale(img: jax.Array) -> jax.Array:
    """ITU-R 601-2 luma, PIL ``convert('L')`` weights; identity on 1-channel."""
    if img.shape[-1] == 1:
        return img
    w = jnp.array([0.299, 0.587, 0.114], img.dtype)
    return jnp.round((img * w).sum(-1, keepdims=True))


def _blend(a: jax.Array, b: jax.Array, factor: jax.Array) -> jax.Array:
    """PIL ``Image.blend`` / enhance: a + factor * (b - a)."""
    return a + factor * (b - a)


def _color(img, factor, gray=None):  # saturation
    gray = _grayscale(img) if gray is None else gray
    return _blend(jnp.broadcast_to(gray, img.shape), img, factor)


def _contrast(img, factor, gray=None):
    gray = _grayscale(img) if gray is None else gray
    mean = jnp.round(gray.mean())
    return _blend(jnp.full_like(img, mean), img, factor)


def _brightness(img, factor):
    return img * factor


def _sharpness(img, factor):
    # PIL ImageFilter.SMOOTH: 3x3 kernel [[1,1,1],[1,5,1],[1,1,1]]/13, borders
    # copied from the source image.
    kernel = jnp.array([[1.0, 1.0, 1.0], [1.0, 5.0, 1.0], [1.0, 1.0, 1.0]]) / 13.0
    smoothed = lax.conv_general_dilated(
        img.transpose(2, 0, 1)[:, None],  # C,1,H,W
        kernel[None, None],
        (1, 1),
        "SAME",
    )[:, 0].transpose(1, 2, 0)
    smoothed = jnp.round(smoothed)
    h, w = img.shape[0], img.shape[1]
    border = (
        (jnp.arange(h)[:, None] == 0)
        | (jnp.arange(h)[:, None] == h - 1)
        | (jnp.arange(w)[None, :] == 0)
        | (jnp.arange(w)[None, :] == w - 1)
    )
    smoothed = jnp.where(border[..., None], img, smoothed)
    return _blend(smoothed, img, factor)


def _invert(img, _):
    return 255.0 - img


def _solarize(img, thresh):
    return jnp.where(img < thresh, img, 255.0 - img)


def _solarize_add(img, add):
    return jnp.where(img < 128.0, jnp.clip(img + add, 0, 255), img)


def _posterize(img, bits):
    """Keep the top ``bits`` bits.  ``bits`` is traced; express the uint8 mask
    arithmetic in float."""
    shift = 2.0 ** (8.0 - bits)
    return jnp.floor(img / shift) * shift


def _channel_hist(channel: jax.Array) -> jax.Array:
    """256-bin histogram of a rounded [H, W] channel via one-hot reduction."""
    flat = channel.reshape(-1).astype(jnp.int32)
    return jnp.zeros(256, jnp.int32).at[flat].add(1)


def _autocontrast(img, _):
    # PIL autocontrast (cutoff 0): per channel, remap [min, max] -> [0, 255].
    def per_channel(ch):
        lo = ch.min()
        hi = ch.max()
        scale = 255.0 / jnp.maximum(hi - lo, 1e-6)
        out = (ch - lo) * scale
        return jnp.where(hi > lo, out, ch)

    return jnp.stack([per_channel(img[..., c]) for c in range(3)], axis=-1)


def _equalize(img, _):
    # PIL ImageOps.equalize: per channel LUT n//step with n = step//2 +
    # cumsum(hist), step = (npixels - last_nonzero_bin) // 255.
    def per_channel(ch):
        hist = _channel_hist(ch)
        nz = hist > 0
        last_nz_idx = 255 - jnp.argmax(nz[::-1])
        last = hist[last_nz_idx]
        step = (hist.sum() - last) // 255
        csum = jnp.cumsum(hist) - hist  # exclusive cumsum
        lut = jnp.clip((step // 2 + csum) // jnp.maximum(step, 1), 0, 255)
        mapped = lut[ch.astype(jnp.int32)].astype(jnp.float32)
        return jnp.where(step > 0, mapped, ch)

    return jnp.stack([per_channel(img[..., c]) for c in range(3)], axis=-1)


# --------------------------------------------------------------------------- #
# RandAugment: op table + magnitude maps (timm "rand" transforms, increasing)
# --------------------------------------------------------------------------- #


def _geom_matrix(img_shape, op_idx: jax.Array, frac: jax.Array,
                 sign: jax.Array, size: int) -> jax.Array:
    """Per-image 2x3 affine matrix for the geometric RandAugment ops
    (identity for every non-geometric op index).

    Under vmap, ``lax.switch`` computes every branch and selects — so five
    separate bilinear warps (rotate, 2 shears, 2 translates) would each pay
    their own 4-tap gather over the whole batch.  Selecting the *matrix*
    instead is scalar work, and one shared warp serves all five ops.
    """
    rot = _rotate_matrix(img_shape, sign * frac * 30.0)
    v = sign * frac * 0.3
    shear_x = _shear_x_matrix(v)
    shear_y = _shear_y_matrix(v)
    px = sign * frac * 0.45 * size
    trans_x = _translate_x_matrix(px)
    trans_y = _translate_y_matrix(px)
    ident = jnp.array([[1.0, 0.0, 0.0], [0.0, 1.0, 0.0]])
    # Op table order (see _ra_apply): geometric ops sit at 3, 11, 12, 13, 14.
    mats = jnp.stack(
        [ident, ident, ident, rot, ident, ident, ident, ident, ident, ident,
         ident, shear_x, shear_y, trans_x, trans_y]
    )
    return mats[op_idx]


def _ra_apply(img: jax.Array, op_idx: jax.Array, magnitude: jax.Array,
              sign: jax.Array, size: int, interpolation: str = "bilinear",
              use_bicubic: Optional[jax.Array] = None) -> jax.Array:
    """Apply op ``op_idx`` at ``magnitude`` (in [0, 10]); ``sign`` is ±1.

    ``interpolation`` picks the geometric resampling kernel; for ``"random"``
    the traced bool ``use_bicubic`` selects per application (timm parity).
    """
    frac = magnitude / 10.0

    # ONE warp shared by all five geometric branches (the matrix is
    # op-selected, identity resamples exactly); grayscale shared by
    # color/contrast.  The remaining switch branches are cheap elementwise
    # passes, so compute-all-and-select stays cheap.  "random" interpolation
    # pays a second warp pass — the documented cost of exact timm parity.
    mat = _geom_matrix(img.shape, op_idx, frac, sign, size)
    if interpolation == "random":
        warped = jnp.where(
            use_bicubic, _affine(img, mat, "bicubic"), _affine(img, mat, "bilinear")
        )
    else:
        warped = _affine(img, mat, interpolation)
    gray = _grayscale(img)

    branches = [
        lambda im: _autocontrast(im, None),
        lambda im: _equalize(im, None),
        lambda im: _invert(im, None),
        lambda im: warped,  # rotate
        # Posterize "increasing": 4 - int(frac * 4) bits
        lambda im: _posterize(im, 4.0 - jnp.floor(frac * 4.0)),
        # Solarize "increasing": threshold 256 - int(frac * 256)
        lambda im: _solarize(im, 256.0 - jnp.floor(frac * 256.0)),
        lambda im: _solarize_add(im, jnp.floor(frac * 110.0)),
        lambda im: _color(im, 1.0 + sign * frac * 0.9, gray),
        lambda im: _contrast(im, 1.0 + sign * frac * 0.9, gray),
        lambda im: _brightness(im, 1.0 + sign * frac * 0.9),
        lambda im: _sharpness(im, 1.0 + sign * frac * 0.9),
        lambda im: warped,  # shear_x
        lambda im: warped,  # shear_y
        lambda im: warped,  # translate_x
        lambda im: warped,  # translate_y
    ]
    return _round_u8(lax.switch(op_idx, branches, img))


NUM_RA_OPS = 15


def _rand_augment(key: jax.Array, img: jax.Array, cfg: AugmentConfig) -> jax.Array:
    for i in range(cfg.ra_num_ops):
        # The 5-way split is the round-3 stream; the parity mode's extra
        # interpolation key is derived by fold_in so enabling it does not
        # perturb the op/magnitude/sign/apply draws of committed evidence.
        kop, kmag, ksign, kprob, key = jax.random.split(jax.random.fold_in(key, i), 5)
        use_bicubic = None
        if cfg.ra_interpolation == "random":
            use_bicubic = jax.random.bernoulli(jax.random.fold_in(kprob, 1))
        op_idx = jax.random.randint(kop, (), 0, NUM_RA_OPS)
        mag = jnp.clip(
            cfg.ra_magnitude + cfg.ra_mag_std * jax.random.normal(kmag),
            0.0,
            10.0,
        )
        sign = jnp.where(jax.random.bernoulli(ksign), 1.0, -1.0)
        # timm builds every rand AugmentOp with prob=0.5: a chosen op is
        # applied only half the time, so "n2" averages ~1 op per image.
        applied = _ra_apply(
            img, op_idx, mag, sign, cfg.input_size,
            interpolation=cfg.ra_interpolation,
            use_bicubic=use_bicubic,
        )
        img = jnp.where(jax.random.bernoulli(kprob, cfg.ra_prob), applied, img)
    return img


# --------------------------------------------------------------------------- #
# Crop / flip / jitter / erasing
# --------------------------------------------------------------------------- #


def _random_crop(key: jax.Array, img: jax.Array, padding: int) -> jax.Array:
    """torchvision ``RandomCrop(size, padding)`` with zero fill."""
    size = img.shape[0]
    padded = jnp.pad(
        img, ((padding, padding), (padding, padding), (0, 0)), constant_values=0.0
    )
    ky, kx = jax.random.split(key)
    oy = jax.random.randint(ky, (), 0, 2 * padding + 1)
    ox = jax.random.randint(kx, (), 0, 2 * padding + 1)
    return lax.dynamic_slice(padded, (oy, ox, 0), (size, size, img.shape[2]))


def _random_flip(key: jax.Array, img: jax.Array) -> jax.Array:
    return jnp.where(jax.random.bernoulli(key), img[:, ::-1, :], img)


def _color_jitter(key: jax.Array, img: jax.Array, strength: float) -> jax.Array:
    """torchvision ColorJitter(brightness=contrast=saturation=strength):
    random factor U(max(0, 1-s), 1+s) per property, random order approximated
    as fixed order (order only matters at second order)."""
    kb, kc, ks = jax.random.split(key, 3)
    lo = max(0.0, 1.0 - strength)
    hi = 1.0 + strength
    img = _round_u8(_brightness(img, jax.random.uniform(kb, (), minval=lo, maxval=hi)))
    img = _round_u8(_contrast(img, jax.random.uniform(kc, (), minval=lo, maxval=hi)))
    img = _round_u8(_color(img, jax.random.uniform(ks, (), minval=lo, maxval=hi)))
    return img


def _random_erasing(key: jax.Array, img: jax.Array, cfg: AugmentConfig) -> jax.Array:
    """timm RandomErasing in the *normalized* domain (applied after
    normalization, like timm): 'pixel' = per-pixel N(0,1) noise, 'rand' =
    one N(0,1) value per channel for the whole rectangle, 'const' = zeros."""
    if cfg.remode not in ("pixel", "rand", "const"):
        raise ValueError(f"unknown random-erasing mode {cfg.remode!r}")
    h, w = img.shape[0], img.shape[1]
    for i in range(cfg.recount):
        kp, karea, kar, ky, kx, knoise, key = jax.random.split(
            jax.random.fold_in(key, i), 7
        )
        do = jax.random.bernoulli(kp, cfg.reprob)
        area = h * w * jax.random.uniform(karea, (), minval=0.02, maxval=1 / 3)
        log_ratio = jax.random.uniform(
            kar, (), minval=jnp.log(0.3), maxval=jnp.log(10 / 3)
        )
        ratio = jnp.exp(log_ratio)
        eh = jnp.clip(jnp.round(jnp.sqrt(area * ratio)), 1, h).astype(jnp.int32)
        ew = jnp.clip(jnp.round(jnp.sqrt(area / ratio)), 1, w).astype(jnp.int32)
        oy = jax.random.randint(ky, (), 0, h)
        ox = jax.random.randint(kx, (), 0, w)
        ys = jnp.arange(h)[:, None]
        xs = jnp.arange(w)[None, :]
        inside = (ys >= oy) & (ys < oy + eh) & (xs >= ox) & (xs < ox + ew)
        if cfg.remode == "pixel":
            fill = jax.random.normal(knoise, img.shape, img.dtype)
        elif cfg.remode == "rand":
            fill = jnp.broadcast_to(
                jax.random.normal(knoise, (img.shape[-1],), img.dtype), img.shape
            )
        else:  # const
            fill = jnp.zeros_like(img)
        img = jnp.where((do & inside)[..., None], fill, img)
    return img


# --------------------------------------------------------------------------- #
# Public API
# --------------------------------------------------------------------------- #


def _normalize(img: jax.Array, cfg: AugmentConfig) -> jax.Array:
    mean = jnp.asarray(cfg.mean, jnp.float32) * 255.0
    std = jnp.asarray(cfg.std, jnp.float32) * 255.0
    return (img - mean) / std


def _augment_one(key: jax.Array, img_u8: jax.Array, cfg: AugmentConfig) -> jax.Array:
    img = img_u8.astype(jnp.float32)
    kcrop, kflip, kra, kerase = jax.random.split(key, 4)
    if cfg.crop_padding > 0:
        img = _random_crop(kcrop, img, cfg.crop_padding)
    if cfg.hflip:
        img = _random_flip(kflip, img)
    if cfg.rand_augment:
        img = _rand_augment(kra, img, cfg)
    elif cfg.color_jitter > 0:
        img = _color_jitter(kra, img, cfg.color_jitter)
    img = _normalize(img, cfg)
    if cfg.reprob > 0:
        img = _random_erasing(kerase, img, cfg)
    return img


@partial(jax.jit, static_argnames=("cfg",))
def train_augment(key: jax.Array, batch_u8: jax.Array, cfg: AugmentConfig) -> jax.Array:
    """``(key, uint8 [B,H,W,C]) -> normalized float32 [B,H,W,C]`` train pipeline."""
    keys = jax.random.split(key, batch_u8.shape[0])
    return jax.vmap(_augment_one, in_axes=(0, 0, None))(keys, batch_u8, cfg)


@partial(jax.jit, static_argnames=("cfg",))
def eval_preprocess(batch_u8: jax.Array, cfg: AugmentConfig) -> jax.Array:
    """Eval path: normalize only (resize/center-crop for >32px inputs happens
    at dataset load, reference ``utils.py:237-242``)."""
    return _normalize(batch_u8.astype(jnp.float32), cfg)
