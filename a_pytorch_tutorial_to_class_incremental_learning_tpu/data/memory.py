"""Rehearsal memory with herding exemplar selection.

Native replacement for ``continuum.rehearsal.RehearsalMemory``
(SURVEY.md #20; reference ``template.py:9,212-216,231,300-302``):
a budgeted exemplar store whose per-class quota shrinks as classes accumulate,
with iCaRL "barycenter" greedy herding as the default ranking
(the reference README derives the greedy at ``README.md:134-136``).

Semantics:

* ``add(x, y, t, features)`` ranks **every** class present in the added data
  by the herding method on the given feature vectors (computed by the
  current post-weight-align model, reference ``template.py:292-302``).  For
  old classes the candidates are exactly the stored exemplars (they were
  injected into the task data), so this re-ranks them with *current-model*
  features — continuum 1.2.2's behavior, which decides which exemplars
  survive the quota shrink.  Classes absent from the added data keep their
  old ranking and are truncated to the new quota.
* ``fixed_memory=False`` (reference default): quota = memory_size //
  nb_seen_classes.  ``True``: memory_size // total_classes fixed slots.
* ``get()`` returns concatenated ``(x, y, t)`` over all stored classes, ready
  for ``TaskSet.add_samples`` (reference ``template.py:230-231``).

Selection runs on the host in numpy: it is a once-per-task O(n·m·d) pass over
at most a few thousand feature vectors — not worth a device round-trip.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

import numpy as np


def herd_barycenter(
    features: np.ndarray, nb: int, allow_native: bool = True
) -> np.ndarray:
    """iCaRL greedy herding: rank samples so each prefix's feature mean best
    approximates the true class mean (reference ``README.md:134-136``).

    Returns the first ``nb`` selected indices, in selection order.  Dispatches
    to the C++ kernel (csrc/cil_host.cpp) when built — the greedy is
    O(nb*n*d) and this numpy version allocates an [n, d] candidate matrix per
    selection step; the native path allocates nothing.  Both paths use the
    same arithmetic (float64 accumulation over float32 inputs, divide by k+1,
    squared-distance argmin, first-index tie break); selections can differ
    only on sub-ulp near-ties from summation order.  In multi-process runs
    the trainer additionally disables the native path fleet-wide unless
    *every* process has the library, so replicated memories stay identical.
    """
    if allow_native:
        from ..utils.native import herd_barycenter_native

        native = herd_barycenter_native(np.asarray(features, np.float32), nb)
        if native is not None:
            return native
    # float32 storage, float64 accumulation — the C++ kernel's arithmetic.
    features = np.asarray(features, np.float32).astype(np.float64)
    n = len(features)
    nb = min(nb, n)
    mu = features.mean(axis=0)
    selected = np.zeros(n, bool)
    order = np.empty(nb, np.int64)
    running_sum = np.zeros_like(mu)
    for k in range(nb):
        # candidate mean if sample i joins: (running_sum + z_i) / (k+1)
        cand = (running_sum[None, :] + features) / (k + 1)
        dist = ((mu[None, :] - cand) ** 2).sum(axis=1)
        dist[selected] = np.inf
        i = int(np.argmin(dist))
        order[k] = i
        selected[i] = True
        running_sum += features[i]
    return order


def herd_random(features: np.ndarray, nb: int, seed: int = 0) -> np.ndarray:
    """Random ranking.  ``seed`` varies per class (RehearsalMemory passes a
    distinct one) so selections are independent across classes/tasks."""
    rng = np.random.RandomState(seed)
    return rng.permutation(len(features))[: min(nb, len(features))]


def herd_cluster(features: np.ndarray, nb: int, iters: int = 20) -> np.ndarray:
    """K-means the class features into ``nb`` clusters, keep the sample nearest
    each centroid (one diverse representative per cluster).

    Returned indices are in **rank order** like every herding method here —
    clusters are visited in descending population, so when
    ``RehearsalMemory.add``'s quota shrink truncates the stored prefix it
    keeps the representatives of the most-populated (highest-mass) clusters,
    not an arbitrary init-permutation subset.

    Deterministic: fixed init seed, Lloyd iterations, stable per-centroid
    nearest-unchosen assignment.  **Parity caveat**: continuum 1.2.2's
    ``"cluster"`` herding could not be byte-verified in this zero-egress
    environment (continuum is not installed here); this is a documented
    approximation of its clustering selection, covered by golden/property
    tests instead of a library-diff.  The default recipe uses
    ``barycenter`` (reference ``template.py:214``), which *is* golden- and
    C++-parity-tested, so this method never touches default-parity runs.
    """
    features = np.asarray(features, np.float64)
    n = len(features)
    nb = min(nb, n)
    rng = np.random.RandomState(0)
    centroids = features[rng.permutation(n)[:nb]].copy()

    def sq_dists(c: np.ndarray) -> np.ndarray:
        # ||x||^2 + ||c||^2 - 2 x.c -> [n, nb] without an [n, nb, d] temporary
        # (quota 2000 x a few thousand candidates would be GBs otherwise).
        d2 = (
            (features * features).sum(1)[:, None]
            + (c * c).sum(1)[None, :]
            - 2.0 * features @ c.T
        )
        return np.maximum(d2, 0.0)

    for _ in range(iters):
        assign = sq_dists(centroids).argmin(axis=1)
        for c in range(nb):
            members = features[assign == c]
            if len(members):
                centroids[c] = members.mean(axis=0)
    # Per centroid, the nearest not-yet-chosen sample; boolean mask instead
    # of the O(n * nb) `in list` scan.  Centroids are visited in descending
    # population so the output prefix covers the densest clusters first (the
    # rank-order contract add()'s quota truncation relies on).
    d2 = sq_dists(centroids)
    assign = d2.argmin(axis=1)
    pop = np.bincount(assign, minlength=nb)
    order = np.argsort(d2, axis=0, kind="stable")
    taken = np.zeros(n, bool)
    chosen = np.empty(nb, np.int64)
    for rank, c in enumerate(np.argsort(-pop, kind="stable")):
        for i in order[:, c]:
            if not taken[i]:
                chosen[rank] = i
                taken[i] = True
                break
    return chosen


_METHODS: Dict[str, Callable[..., np.ndarray]] = {
    "barycenter": herd_barycenter,
    "random": herd_random,
    "cluster": herd_cluster,
}


class RehearsalMemory:
    """Budgeted exemplar store (see module docstring)."""

    def __init__(
        self,
        memory_size: int = 2000,
        herding_method="barycenter",
        fixed_memory: bool = False,
        nb_total_classes: Optional[int] = None,
        prefer_native: bool = True,
    ):
        if isinstance(herding_method, str):
            if herding_method not in _METHODS:
                raise ValueError(
                    f"unknown herding_method {herding_method!r}; "
                    f"options: {sorted(_METHODS)} or a callable"
                )
            herding_method = _METHODS[herding_method]
        self.herd = herding_method
        self.memory_size = memory_size
        self.fixed_memory = fixed_memory
        # False forces the numpy herding path; multi-process trainers set it
        # to the fleet-wide AND of native availability so replicated memories
        # never diverge between hosts with and without the compiled library.
        self.prefer_native = prefer_native
        if fixed_memory and not nb_total_classes:
            raise ValueError("fixed_memory=True requires nb_total_classes")
        self.nb_total_classes = nb_total_classes
        # class -> (x, y, t) in herding-rank order
        self._store: Dict[int, Tuple[np.ndarray, np.ndarray, np.ndarray]] = {}

    @property
    def nb_classes(self) -> int:
        return len(self._store)

    def __len__(self) -> int:
        return sum(len(v[1]) for v in self._store.values())

    def quota(self, nb_seen_classes: int) -> int:
        if self.fixed_memory:
            return self.memory_size // int(self.nb_total_classes)
        return self.memory_size // max(nb_seen_classes, 1)

    def add(
        self,
        x: np.ndarray,
        y: np.ndarray,
        t: Optional[np.ndarray],
        features: np.ndarray,
    ) -> None:
        y = np.asarray(y)
        if t is None:
            t = np.full(len(y), -1, np.int64)
        seen_classes = np.unique(y)
        nb_after = len(set(self._store) | {int(c) for c in seen_classes})
        q = self.quota(nb_after)
        for c in seen_classes:
            idx = np.where(y == c)[0]
            if self.herd is herd_random:
                # Distinct, deterministic stream per class.
                rank = herd_random(np.asarray(features)[idx], q, seed=int(c) + 1)
            elif self.herd is herd_barycenter:
                rank = herd_barycenter(
                    np.asarray(features)[idx], q, allow_native=self.prefer_native
                )
            else:
                rank = self.herd(np.asarray(features)[idx], q)
            keep = idx[rank]
            self._store[int(c)] = (x[keep].copy(), y[keep].copy(), np.asarray(t)[keep].copy())
        # Shrink every class to the (possibly reduced) quota; rank order makes
        # truncation keep the best exemplars.
        for c, (cx, cy, ct) in list(self._store.items()):
            self._store[c] = (cx[:q], cy[:q], ct[:q])

    def get(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        if not self._store:
            raise ValueError("memory is empty")
        xs, ys, ts = zip(*(self._store[c] for c in sorted(self._store)))
        return np.concatenate(xs), np.concatenate(ys), np.concatenate(ts)
