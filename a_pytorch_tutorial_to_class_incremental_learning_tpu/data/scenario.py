"""Class-incremental scenario: task splitting with class-order label remapping.

Native replacement for ``continuum.ClassIncremental`` + ``TaskSet``
(SURVEY.md #18/#19; reference ``utils.py:198-204``, consumed at
``template.py:226-231,292-301``).  Semantics replicated exactly:

* The dataset is partitioned into T tasks along ``class_order``: task 0 gets
  the first ``initial_increment`` classes of the order (or ``increment`` when
  it is 0), each later task the next ``increment`` classes.
* Labels are **remapped to the class's position in ``class_order``**, so a
  task's classes always occupy a contiguous, highest-so-far label range —
  the invariant that makes ``logits[:, :known]`` KD slicing and
  "last nb_new columns" weight alignment correct (SURVEY.md #18).
* ``scenario[t]`` / ``scenario[:t+1]`` index or merge tasks; the cumulative
  slice is the reference's eval split (``template.py:229``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..config import compute_increments


@dataclass
class TaskSet:
    """One task's data: ``(x uint8 [N,H,W,C], y int64 remapped, t int64)``.

    Counterpart of continuum's ``TaskSet`` (SURVEY.md #19): supports in-place
    rehearsal injection (``add_samples``, reference ``template.py:230-231``)
    and raw-sample access for exemplar storage (``get_raw_samples``,
    ``template.py:301``) — exemplars are stored as raw images and re-augmented
    every epoch on device.
    """

    x: np.ndarray
    y: np.ndarray
    t: np.ndarray

    def __len__(self) -> int:
        return len(self.y)

    def add_samples(self, x: np.ndarray, y: np.ndarray, t: Optional[np.ndarray]) -> None:
        self.x = np.concatenate([self.x, x])
        self.y = np.concatenate([self.y, np.asarray(y, self.y.dtype)])
        if t is None:
            t = np.full(len(y), -1, self.t.dtype)
        self.t = np.concatenate([self.t, np.asarray(t, self.t.dtype)])

    def get_raw_samples(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        return self.x, self.y, self.t

    @property
    def nb_classes(self) -> int:
        return len(np.unique(self.y))


class ClassIncremental:
    """Task-partitioned view of a labeled dataset.

    ``increments()`` mirrors the reference's ``increment_per_task`` bookkeeping
    (``template.py:222-223``); ``class_order`` defaults to the identity
    (continuum's default) and is validated as a permutation.
    """

    def __init__(
        self,
        x: np.ndarray,
        y: np.ndarray,
        initial_increment: int,
        increment: int,
        class_order: Optional[Sequence[int]] = None,
    ):
        y = np.asarray(y, np.int64)
        self.nb_classes = int(y.max()) + 1
        if class_order is None:
            class_order = list(range(self.nb_classes))
        order = np.asarray(class_order, np.int64)
        if sorted(order.tolist()) != list(range(self.nb_classes)):
            raise ValueError("class_order must be a permutation of the class labels")
        self.class_order = order

        # remap[original_label] = position in class_order
        remap = np.empty(self.nb_classes, np.int64)
        remap[order] = np.arange(self.nb_classes)
        self._x = x
        self._y_remapped = remap[y]

        self._increments: List[int] = list(
            compute_increments(self.nb_classes, initial_increment, increment)
        )

    def increments(self) -> List[int]:
        return list(self._increments)

    def __len__(self) -> int:
        return len(self._increments)

    def _task_bounds(self, task_id: int) -> Tuple[int, int]:
        lo = sum(self._increments[:task_id])
        return lo, lo + self._increments[task_id]

    def _slice(self, lo_class: int, hi_class: int) -> TaskSet:
        sel = (self._y_remapped >= lo_class) & (self._y_remapped < hi_class)
        y = self._y_remapped[sel]
        # Per-sample task ids reconstructed from the class ranges (continuum
        # TaskSets carry them; the loaders yield (x, y, t) triplets,
        # reference template.py:255).
        bounds = np.cumsum([0] + self._increments)
        t = np.searchsorted(bounds, y, side="right") - 1
        return TaskSet(self._x[sel].copy(), y.copy(), t.astype(np.int64))

    def __getitem__(self, index):
        if isinstance(index, slice):
            tasks = range(*index.indices(len(self)))
            if len(tasks) == 0:
                raise IndexError("empty task slice")
            lo, _ = self._task_bounds(tasks[0])
            _, hi = self._task_bounds(tasks[-1])
            return self._slice(lo, hi)
        lo, hi = self._task_bounds(index)
        return self._slice(lo, hi)

    def __iter__(self):
        for t in range(len(self)):
            yield self[t]
