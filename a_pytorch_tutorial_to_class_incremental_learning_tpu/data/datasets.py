"""Raw dataset loading: in-memory ``(images uint8 NHWC, labels int64)`` arrays.

The reference delegates dataset IO to ``continuum.datasets`` (CIFAR100
auto-download, ImageFolder for ImageNet; reference ``utils.py:188-207``).
TPU-native equivalent: datasets are plain numpy arrays held in host RAM
(CIFAR-100 is 150 MB — trivially resident), batched on the host and augmented
*on device* inside the compiled step (see ``data/augment.py``), replacing the
reference's 10-process CPU DataLoader worker pool (``template.py:236-239``).

Zero-egress environments cannot auto-download, so ``cifar`` requires the
standard ``cifar-100-python`` pickle directory on disk; the ``synthetic``
dataset generates a class-separable mixture for tests/benches that must run
without data.
"""

from __future__ import annotations

import os
import pickle
import tarfile
import threading
from typing import Tuple

import numpy as np

Arrays = Tuple[np.ndarray, np.ndarray]  # (x uint8 [N,H,W,C], y int64 [N])


def load_cifar100(data_path: str, train: bool) -> Arrays:
    """Parse the standard ``cifar-100-python`` pickle distribution.

    Accepts ``data_path`` pointing at the extracted directory, its parent, or
    the ``.tar.gz`` archive.  Counterpart of ``continuum.datasets.CIFAR100``
    (reference ``utils.py:192``) minus the network download.
    """
    split = "train" if train else "test"
    candidates = [
        os.path.join(data_path, "cifar-100-python", split),
        os.path.join(data_path, split),
    ]
    for path in candidates:
        if os.path.exists(path):
            with open(path, "rb") as f:
                raw = pickle.load(f, encoding="bytes")
            return _decode_cifar(raw)
    for tar in (data_path, os.path.join(data_path, "cifar-100-python.tar.gz")):
        if os.path.isfile(tar) and tarfile.is_tarfile(tar):
            with tarfile.open(tar) as tf:
                member = tf.extractfile(f"cifar-100-python/{split}")
                assert member is not None
                raw = pickle.load(member, encoding="bytes")  # noqa: S301
            return _decode_cifar(raw)
    raise FileNotFoundError(
        f"CIFAR-100 not found under {data_path!r} (no auto-download in a "
        "zero-egress environment); use --data_set synthetic for smoke runs"
    )


def _decode_cifar(raw: dict) -> Arrays:
    x = np.asarray(raw[b"data"], np.uint8).reshape(-1, 3, 32, 32)
    x = x.transpose(0, 2, 3, 1)  # NCHW storage -> NHWC (TPU-native layout)
    y = np.asarray(raw[b"fine_labels"], np.int64)
    return np.ascontiguousarray(x), y


def load_mnist_idx(data_path: str, train: bool) -> Arrays:
    """Parse the standard MNIST IDX distribution (``train-images-idx3-ubyte``
    etc., plain or ``.gz``) into ``(x uint8 [N,28,28,1], y int64)``.

    Counterpart of continuum's ``MNIST`` dataset for the reference's
    1-channel backbone factories (``resnet.py:127-139``) minus the network
    download.  The IDX format: big-endian int32 magic (0x803 images /
    0x801 labels), dims, then raw bytes.
    """
    import gzip
    import struct

    prefix = "train" if train else "t10k"

    def read(kind: str, magic_want: int) -> np.ndarray:
        names = [f"{prefix}-{kind}", f"{prefix}-{kind}.gz"]
        roots = [data_path, os.path.join(data_path, "MNIST", "raw")]
        for root in roots:
            for name in names:
                path = os.path.join(root, name)
                if not os.path.isfile(path):
                    continue
                opener = gzip.open if path.endswith(".gz") else open
                with opener(path, "rb") as f:
                    magic, n = struct.unpack(">ii", f.read(8))
                    if magic != magic_want:
                        raise ValueError(f"{path}: bad IDX magic {magic:#x}")
                    if magic_want == 0x803:
                        h, w = struct.unpack(">ii", f.read(8))
                        data = np.frombuffer(f.read(), np.uint8)
                        return data.reshape(n, h, w, 1)
                    return np.frombuffer(f.read(), np.uint8).astype(np.int64)
        raise FileNotFoundError(
            f"MNIST IDX files not found under {data_path!r} (no auto-download "
            "in a zero-egress environment); use --data_set synthetic_mnist "
            "for smoke runs"
        )

    x = read("images-idx3-ubyte", 0x803)
    y = read("labels-idx1-ubyte", 0x801)
    if len(x) != len(y):
        raise ValueError(f"MNIST images/labels length mismatch: {len(x)}/{len(y)}")
    return x, y


def load_synthetic(
    nb_classes: int = 100,
    per_class: int = 64,
    input_size: int = 32,
    channels: int = 3,
    train: bool = True,
    seed: int = 1234,
    noise_std: float = 48.0,
) -> Arrays:
    """Class-separable synthetic data: per-class template image + pixel noise.

    Deterministic in ``seed`` (train/val draw disjoint noise), learnable well
    above chance by a small CNN — the dataset used by tests, ``bench.py`` and
    the multi-chip dry-run, where real data may not exist on disk.

    Templates are **low-frequency**: a coarse random grid upsampled 4x and
    box-blurred.  Per-pixel white-noise templates decorrelate completely under
    a 1-pixel shift, so the padded-RandomCrop augmentation (±4 px) turns each
    class into ~81 unrelated patterns and a small CNN stays at chance; smooth
    templates keep shifted crops correlated, like natural images.
    """
    rng = np.random.RandomState(seed)
    lo = max(2, input_size // 4)
    up = -(-input_size // lo)  # ceil: upsampled size covers any input_size
    coarse = rng.randint(0, 256, size=(nb_classes, lo, lo, channels))
    templates = np.kron(
        coarse.astype(np.float32), np.ones((1, up, up, 1))
    )[:, :input_size, :input_size, :]
    for axis in (1, 2):  # separable 3-tap box blur to soften block edges
        templates = (
            templates
            + np.roll(templates, 1, axis=axis)
            + np.roll(templates, -1, axis=axis)
        ) / 3.0
    noise_rng = np.random.RandomState(seed + (1 if train else 2))
    y = np.repeat(np.arange(nb_classes, dtype=np.int64), per_class)
    noise = noise_rng.normal(
        0.0, noise_std, size=(len(y), input_size, input_size, channels)
    )
    x = np.clip(templates[y] + noise, 0, 255).astype(np.uint8)
    perm = np.random.RandomState(seed + 3).permutation(len(y))
    return x[perm], y[perm]


def load_image_folder(data_path: str, train: bool) -> Arrays:
    """ImageNet-style ``train/``/``val/`` class-folder tree, loaded **lazily**.

    Counterpart of the reference's ``ImageNet1000`` (``utils.py:171-185``).
    Like continuum's ``ImageFolderDataset``, the in-memory representation is
    the array of file *paths* (object dtype) — raw samples, rehearsal
    exemplars and task slices are all path arrays; pixels are decoded
    per batch by :func:`decode_image_batch` (host) and augmented on device.
    This keeps 1.28M-image splits at a few hundred MB of RAM instead of
    hundreds of GB.
    """
    root = os.path.join(data_path, "train" if train else "val")
    if not os.path.isdir(root):
        raise FileNotFoundError(f"image-folder split not found: {root}")
    classes = sorted(
        d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))
    )
    paths, ys = [], []
    for label, cls in enumerate(classes):
        cdir = os.path.join(root, cls)
        for fname in sorted(os.listdir(cdir)):
            paths.append(os.path.join(cdir, fname))
            ys.append(label)
    return np.asarray(paths, object), np.asarray(ys, np.int64)


def _random_resized_crop(im, input_size: int, rng: np.random.RandomState):
    """torchvision ``RandomResizedCrop(input_size)``: area scale (0.08, 1.0),
    aspect ratio (3/4, 4/3), 10 attempts then center-crop fallback — the first
    transform of timm's train pipeline, kept for >32px inputs
    (reference ``utils.py:217-229``).  Host-side, like the reference's."""
    from PIL import Image

    w, h = im.size
    area = w * h
    for _ in range(10):
        target = area * rng.uniform(0.08, 1.0)
        ar = np.exp(rng.uniform(np.log(3 / 4), np.log(4 / 3)))
        cw = int(round(np.sqrt(target * ar)))
        ch = int(round(np.sqrt(target / ar)))
        if 0 < cw <= w and 0 < ch <= h:
            x0 = rng.randint(0, w - cw + 1)
            y0 = rng.randint(0, h - ch + 1)
            box = (x0, y0, x0 + cw, y0 + ch)
            return im.resize((input_size, input_size), Image.BICUBIC, box=box)
    side = min(w, h)
    x0, y0 = (w - side) // 2, (h - side) // 2
    return im.resize(
        (input_size, input_size), Image.BICUBIC, box=(x0, y0, x0 + side, y0 + side)
    )


# Shared decode pool: one process-wide executor instead of a fresh
# ThreadPoolExecutor per batch.  Per-batch pools pay thread spawn/teardown on
# every batch and, worse, under the prefetch pipeline two producer threads
# would each churn their own pools.  The lock (import-time, so never itself
# racy) guards only the lazy creation; after that the executor is only read,
# and ThreadPoolExecutor.map is itself thread-safe.
_DECODE_POOL = None
_DECODE_POOL_LOCK = threading.Lock()


def _decode_pool():
    from concurrent.futures import ThreadPoolExecutor

    global _DECODE_POOL
    with _DECODE_POOL_LOCK:
        if _DECODE_POOL is None:
            _DECODE_POOL = ThreadPoolExecutor(
                max_workers=16, thread_name_prefix="img-decode"
            )
        return _DECODE_POOL


def decode_image_batch(
    paths: np.ndarray, input_size: int, train: bool, seed: int = 0
) -> np.ndarray:
    """Decode a batch of image paths to ``uint8 [B, S, S, 3]``.

    Train: RandomResizedCrop (scale 0.08-1.0).  Eval: resize to
    ``256/224 * input_size`` shorter side + center crop (reference
    ``utils.py:237-242``).  Decoding fans out over the shared module pool
    (PIL releases the GIL) — the replacement for the DataLoader worker
    processes.  Safe to call from multiple producer threads: the prefetch
    pipeline and the serving skew probe share one executor.
    """
    from PIL import Image

    def one(i: int) -> np.ndarray:
        with Image.open(paths[i]) as im:
            im = im.convert("RGB")
            if train:
                rng = np.random.RandomState((seed + i) & 0x7FFFFFFF)
                im = _random_resized_crop(im, input_size, rng)
            else:
                resize = int((256 / 224) * input_size)
                wd, ht = im.size
                scale = resize / min(wd, ht)
                im = im.resize(
                    (max(1, round(wd * scale)), max(1, round(ht * scale))),
                    Image.BICUBIC,
                )
                left = (im.size[0] - input_size) // 2
                top = (im.size[1] - input_size) // 2
                im = im.crop((left, top, left + input_size, top + input_size))
            return np.asarray(im, np.uint8)

    return np.stack(list(_decode_pool().map(one, range(len(paths)))))


def maybe_decode(x: np.ndarray, input_size: int, train: bool, seed: int = 0) -> np.ndarray:
    """Pass through pixel batches; decode path batches (lazy datasets)."""
    if x.dtype == np.uint8:
        return x
    return decode_image_batch(x, input_size, train, seed)


def build_raw_dataset(
    data_set: str, data_path: str, train: bool, input_size: int = 32
) -> Tuple[Arrays, int]:
    """Flag-string dispatch (reference ``build_dataset``, ``utils.py:188-196``).

    Returns ``((x, y), nb_classes)``.
    """
    name = data_set.lower()
    if name == "cifar":
        x, y = load_cifar100(data_path, train)
    elif name == "mnist":
        x, y = load_mnist_idx(data_path, train)
    elif name == "synthetic_mnist":
        # 1-channel smoke dataset for the mnist backbone family.
        x, y = load_synthetic(
            nb_classes=10, input_size=input_size, channels=1, train=train
        )
    elif name == "synthetic":
        x, y = load_synthetic(train=train)
    elif name.startswith("synthetic_hard"):
        # Protocol-evidence variant: heavy pixel noise keeps a small CNN off
        # the 100% ceiling so the incremental trajectory (forgetting, WA
        # recovery) is visible in RESULTS.md, not saturated away.  A numeric
        # suffix sets the noise std directly (e.g. synthetic_hard128);
        # bare "synthetic_hard" keeps the round-3 level of 96.
        suffix = name[len("synthetic_hard"):]
        # Decimal-digits-only: a typo like "synthetic_hardx" (or "nan"/"1e3")
        # must fail as an unknown dataset, not parse as a noise level.
        # isdecimal, not isdigit: isdigit accepts superscripts float() rejects.
        if suffix and not suffix.isdecimal():
            raise ValueError(f"Unknown dataset {data_set}.")
        std = float(suffix) if suffix else 96.0
        x, y = load_synthetic(train=train, noise_std=std)
    elif name.startswith("synthetic"):  # e.g. synthetic20 for smoke runs
        suffix = name[len("synthetic"):]
        if not suffix.isdecimal():  # same typo guard as synthetic_hard above
            raise ValueError(f"Unknown dataset {data_set}.")
        x, y = load_synthetic(nb_classes=int(suffix), train=train)
    elif name == "imagenet1000":
        x, y = load_image_folder(data_path, train)
    else:
        raise ValueError(f"Unknown dataset {data_set}.")
    return (x, y), int(y.max()) + 1
