"""Asynchronous double-buffered input pipeline (ring-buffer prefetcher).

The reference hides input latency behind PyTorch's DataLoader worker pool
(SURVEY.md #24); our loaders are synchronous generators, so on the per-step
path every batch's index gather, host decode and ``device_put`` happened
while the device sat idle — exactly the host/device stall split the
:class:`~..telemetry.StallClock` makes visible.  This module eliminates it
the way pjit/TPU training stacks do (arXiv:2204.06514) and Podracer-style
producer/consumer architectures do (arXiv:2104.06272): a background thread
runs batch *production* (permutation slice, uint8 row gather, decode) and
issues the ``device_put`` toward the target ``NamedSharding`` ahead of
consumption, so the H2D DMA for batch *k+1* overlaps the device compute of
batch *k*.

Guarantees:

* **Byte-identical streams.**  The producer thread iterates the very same
  synchronous generator the caller would have iterated (same seeds, same
  order); threading changes *when* a batch is produced, never *what*.
* **Exception propagation.**  An exception anywhere in production (source
  generator or placement) is caught on the producer thread, enqueued, and
  re-raised in the consumer — after the thread has been shut down cleanly.
* **Clean shutdown.**  ``close()`` (idempotent; also invoked on exhaustion,
  on error, and by the context-manager exit) signals the producer, drains
  the ring buffer, and joins the thread — no leaked threads on early loop
  exit, and no retained device buffers.
* **Donation safety.**  A batch handed to the consumer is *popped* from the
  ring buffer and the prefetcher drops every reference to it before the
  consumer sees it, so a buffer passed on to a donating jitted step is never
  also reachable through the prefetcher.

Telemetry contract: with a ``clock`` (duck-typed ``StallClock``) attached,
only the *residual* — the time the consumer actually blocks waiting for the
ring buffer — is charged to the host bucket; fully-overlapped production
costs nothing.  At ``depth <= 0`` the prefetcher degrades to a synchronous
passthrough (no thread, no queue) and the full production cost is charged,
reproducing the pre-prefetch accounting exactly.  Ring-buffer fill is
sampled at every ``get`` and reported by :meth:`DevicePrefetcher.stats` as
``prefetch_depth_occupancy`` (1.0 = producer always ahead, the run is
compute-bound; ~0 = consumer always waiting, the run is data-bound).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Dict, Iterable, Iterator, Optional

_BATCH, _DONE, _ERROR = "batch", "done", "error"


class DevicePrefetcher:
    """Depth-N ring-buffer prefetcher over ``(source, place)``.

    ``source`` is any synchronous host-batch iterable; ``place`` maps one
    host batch to its device-resident form (decode + ``device_put`` with the
    target sharding) and runs on the producer thread when ``depth > 0``,
    inline otherwise.  Iterate the prefetcher exactly like the source; use
    it as a context manager (or ``contextlib.closing``) so early exits shut
    the producer down deterministically.
    """

    def __init__(
        self,
        source: Iterable,
        place: Optional[Callable] = None,
        depth: int = 0,
        clock=None,
        name: str = "prefetch",
    ):
        self._source = iter(source)
        self._place = place if place is not None else (lambda batch: batch)
        self.depth = max(0, int(depth))
        self._clock = clock
        self._fill_sum = 0
        self._gets = 0
        self._closed = False
        self._exhausted = False
        self._stop = threading.Event()
        self._queue: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        if self.depth > 0:
            self._queue = queue.Queue(maxsize=self.depth)
            self._thread = threading.Thread(
                target=self._produce, name=name, daemon=True
            )
            self._thread.start()

    # ------------------------------------------------------------------ #
    # Producer thread
    # ------------------------------------------------------------------ #

    def _produce(self) -> None:
        try:
            for host_batch in self._source:
                placed = (_BATCH, self._place(host_batch))
                del host_batch
                if not self._enqueue(placed):
                    return  # close() raced us; drop the reference and exit
                del placed  # donation safety: no trailing reference
            self._enqueue((_DONE, None))
        except BaseException as e:  # noqa: BLE001 — must cross the thread
            self._enqueue((_ERROR, e))

    def _enqueue(self, item) -> bool:
        """Bounded put that stays responsive to ``close()``."""
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    # ------------------------------------------------------------------ #
    # Consumer side
    # ------------------------------------------------------------------ #

    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        if self._exhausted or self._closed:
            raise StopIteration
        if self.depth == 0:
            # Synchronous passthrough: production (source + placement) runs
            # inline and its full cost is host/input-pipeline time.
            t0 = time.perf_counter()
            try:
                try:
                    host_batch = next(self._source)
                except StopIteration:
                    self._exhausted = True
                    self.close()
                    raise
                return self._place(host_batch)
            finally:
                if self._clock is not None:
                    self._clock.add_host(time.perf_counter() - t0)
        # Ring-buffer fill right before the blocking get: the occupancy
        # sample ("was a batch ready when the consumer came back?").
        self._fill_sum += self._queue.qsize()
        self._gets += 1
        t0 = time.perf_counter()
        tag, payload = self._queue.get()
        # Only the non-overlapped residual is input-pipeline stall.
        if self._clock is not None:
            self._clock.add_host(time.perf_counter() - t0)
        if tag == _BATCH:
            return payload
        self._exhausted = True
        self.close()
        if tag == _ERROR:
            raise payload
        raise StopIteration

    # ------------------------------------------------------------------ #
    # Lifecycle
    # ------------------------------------------------------------------ #

    def close(self) -> None:
        """Stop the producer, drop buffered batches, join the thread.

        Idempotent; safe at any point (mid-stream early exit included).
        Draining the queue both unblocks a producer stuck in ``put`` and
        releases every prefetched device buffer the consumer never took.
        """
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        self._drain()  # unblock a producer stuck in put
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            if self._thread.is_alive():  # pragma: no cover — defensive
                raise RuntimeError(
                    "prefetch producer thread failed to shut down"
                )
            self._thread = None
        # Drain again AFTER the join: the producer may have completed one
        # final put between the first drain and its check of the stop flag.
        self._drain()
        if self._clock is not None and hasattr(self._clock, "set_prefetch"):
            self._clock.set_prefetch(self.depth, self.occupancy())

    def _drain(self) -> None:
        if self._queue is None:
            return
        while True:
            try:
                self._queue.get_nowait()
            except queue.Empty:
                return

    def __enter__(self) -> "DevicePrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):  # pragma: no cover — belt and braces
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass

    # ------------------------------------------------------------------ #
    # Introspection
    # ------------------------------------------------------------------ #

    def occupancy(self) -> float:
        """Mean ring-buffer fill fraction sampled at each consumer get."""
        if self.depth <= 0 or self._gets == 0:
            return 0.0
        return self._fill_sum / (self._gets * self.depth)

    def stats(self) -> Dict[str, float]:
        return {
            "prefetch_depth": self.depth,
            "prefetch_depth_occupancy": round(self.occupancy(), 4),
        }
