"""Asynchronous double-buffered input pipeline (ring-buffer prefetcher).

The reference hides input latency behind PyTorch's DataLoader worker pool
(SURVEY.md #24); our loaders are synchronous generators, so on the per-step
path every batch's index gather, host decode and ``device_put`` happened
while the device sat idle — exactly the host/device stall split the
:class:`~..telemetry.StallClock` makes visible.  This module eliminates it
the way pjit/TPU training stacks do (arXiv:2204.06514) and Podracer-style
producer/consumer architectures do (arXiv:2104.06272): a background thread
runs batch *production* (permutation slice, uint8 row gather, decode) and
issues the ``device_put`` toward the target ``NamedSharding`` ahead of
consumption, so the H2D DMA for batch *k+1* overlaps the device compute of
batch *k*.

Guarantees:

* **Byte-identical streams.**  The producer thread iterates the very same
  synchronous generator the caller would have iterated (same seeds, same
  order); threading changes *when* a batch is produced, never *what*.
* **Exception propagation.**  An exception in the *source generator* is
  caught on the producer thread, enqueued, and re-raised in the consumer —
  after the thread has been shut down cleanly.
* **Graceful degradation.**  An exception in *placement* (decode /
  ``device_put`` — the part that can die transiently: OOM spike, injected
  ``producer_die`` fault) does not abort the epoch: the producer hands the
  un-placed host batch back through the queue and exits, the consumer joins
  it, invokes ``on_degrade`` (telemetry hook), retries that batch's
  placement inline, and continues the rest of the stream on the synchronous
  depth-0 path.  Queue FIFO order guarantees the stream stays
  byte-identical; only a placement failure that *also* fails the inline
  retry (deterministic, not transient) is re-raised.
* **Clean shutdown.**  ``close()`` (idempotent; also invoked on exhaustion,
  on error, and by the context-manager exit) signals the producer, drains
  the ring buffer, and joins the thread — no leaked threads on early loop
  exit, and no retained device buffers.
* **Donation safety.**  A batch handed to the consumer is *popped* from the
  ring buffer and the prefetcher drops every reference to it before the
  consumer sees it, so a buffer passed on to a donating jitted step is never
  also reachable through the prefetcher.

Telemetry contract: with a ``clock`` (duck-typed ``StallClock``) attached,
only the *residual* — the time the consumer actually blocks waiting for the
ring buffer — is charged to the host bucket; fully-overlapped production
costs nothing.  At ``depth <= 0`` the prefetcher degrades to a synchronous
passthrough (no thread, no queue) and the full production cost is charged,
reproducing the pre-prefetch accounting exactly.  Ring-buffer fill is
sampled at every ``get`` and reported by :meth:`DevicePrefetcher.stats` as
``prefetch_depth_occupancy`` (1.0 = producer always ahead, the run is
compute-bound; ~0 = consumer always waiting, the run is data-bound).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Dict, Iterable, Iterator, Optional

_BATCH, _DONE, _ERROR, _DEGRADE = "batch", "done", "error", "degrade"


class DevicePrefetcher:
    """Depth-N ring-buffer prefetcher over ``(source, place)``.

    ``source`` is any synchronous host-batch iterable; ``place`` maps one
    host batch to its device-resident form (decode + ``device_put`` with the
    target sharding) and runs on the producer thread when ``depth > 0``,
    inline otherwise.  Iterate the prefetcher exactly like the source; use
    it as a context manager (or ``contextlib.closing``) so early exits shut
    the producer down deterministically.
    """

    def __init__(
        self,
        source: Iterable,
        place: Optional[Callable] = None,
        depth: int = 0,
        clock=None,
        name: str = "prefetch",
        on_degrade: Optional[Callable] = None,
        metrics=None,
    ):
        self._source = iter(source)
        self._place = place if place is not None else (lambda batch: batch)
        self.depth = max(0, int(depth))
        self._clock = clock
        self._on_degrade = on_degrade
        # Optional time-series hook (a MetricsRegistry / NullRegistry): the
        # ring-occupancy level and the cumulative consumer-blocked time the
        # fleet scraper reads between epoch records.
        if metrics is None:
            from ..telemetry.metrics import NullRegistry

            metrics = NullRegistry()
        self._m_wait_ms = metrics.counter("prefetch_wait_ms_total")
        self._m_batches = metrics.counter("prefetch_batches_total")
        self._m_occupancy = metrics.gauge("prefetch_occupancy")
        self._degraded = False
        self._fill_sum = 0
        self._gets = 0
        self._closed = False
        self._exhausted = False
        self._stop = threading.Event()
        self._queue: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        if self.depth > 0:
            self._queue = queue.Queue(maxsize=self.depth)
            self._thread = threading.Thread(
                target=self._produce, name=name, daemon=True
            )
            self._thread.start()

    # ------------------------------------------------------------------ #
    # Producer thread
    # ------------------------------------------------------------------ #

    def _produce(self) -> None:
        while True:
            try:
                host_batch = next(self._source)
            except StopIteration:
                self._enqueue((_DONE, None))
                return
            except BaseException as e:  # noqa: BLE001 — must cross the thread
                # A broken *source* is unrecoverable (its position is lost);
                # re-raised on the consumer.
                self._enqueue((_ERROR, e))
                return
            try:
                placed = (_BATCH, self._place(host_batch))
            except BaseException as e:  # noqa: BLE001 — must cross the thread
                # Placement died, but the host batch is intact: hand it back
                # so the consumer can degrade to the synchronous path without
                # losing (or reordering) a single batch.
                self._enqueue((_DEGRADE, (e, host_batch)))
                return
            del host_batch
            if not self._enqueue(placed):
                return  # close() raced us; drop the reference and exit
            del placed  # donation safety: no trailing reference

    def _enqueue(self, item) -> bool:
        """Bounded put that stays responsive to ``close()``."""
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    # ------------------------------------------------------------------ #
    # Consumer side
    # ------------------------------------------------------------------ #

    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        if self._exhausted or self._closed:
            raise StopIteration
        if self.depth == 0 or self._degraded:
            # Synchronous passthrough: production (source + placement) runs
            # inline and its full cost is host/input-pipeline time.  Also the
            # post-degradation path: the dead producer left the shared source
            # iterator exactly one batch past the handback, so continuing it
            # here preserves the byte-identical stream.
            t0 = time.perf_counter()
            try:
                try:
                    host_batch = next(self._source)
                except StopIteration:
                    self._exhausted = True
                    self.close()
                    raise
                return self._place(host_batch)
            finally:
                if self._clock is not None:
                    self._clock.add_host(time.perf_counter() - t0)
        # Ring-buffer fill right before the blocking get: the occupancy
        # sample ("was a batch ready when the consumer came back?").
        self._fill_sum += self._queue.qsize()
        self._gets += 1
        t0 = time.perf_counter()
        tag, payload = self._queue.get()
        # Only the non-overlapped residual is input-pipeline stall.
        wait = time.perf_counter() - t0
        if self._clock is not None:
            self._clock.add_host(wait)
        self._m_wait_ms.inc(wait * 1e3)
        if tag == _BATCH:
            self._m_batches.inc()
            return payload
        if tag == _DEGRADE:
            exc, host_batch = payload
            self._note_degraded(exc)
            t0 = time.perf_counter()
            try:
                placed = self._place(host_batch)
            except BaseException:
                # The retry failing too means the placement failure is
                # deterministic, not transient — degrading cannot help.
                self._exhausted = True
                self.close()
                raise
            finally:
                if self._clock is not None:
                    self._clock.add_host(time.perf_counter() - t0)
            return placed
        self._exhausted = True
        self.close()
        if tag == _ERROR:
            raise payload
        raise StopIteration

    def _note_degraded(self, exc: BaseException) -> None:
        """Producer death observed: join the (already exiting) thread, flip
        to the synchronous path for the rest of the stream, and tell the
        owner via ``on_degrade`` (the telemetry hook that emits the
        ``prefetch_degraded`` record)."""
        self._degraded = True
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        print(f"| prefetch producer died ({exc!r}); degrading to synchronous")
        if self._on_degrade is not None:
            try:
                self._on_degrade(exc)
            except Exception as cb_err:
                # The hook is observability; it must not mask the recovery.
                print(f"| prefetch on_degrade callback failed: {cb_err!r}")

    # ------------------------------------------------------------------ #
    # Lifecycle
    # ------------------------------------------------------------------ #

    def close(self) -> None:
        """Stop the producer, drop buffered batches, join the thread.

        Idempotent; safe at any point (mid-stream early exit included).
        Draining the queue both unblocks a producer stuck in ``put`` and
        releases every prefetched device buffer the consumer never took.
        """
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        self._drain()  # unblock a producer stuck in put
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            if self._thread.is_alive():  # pragma: no cover — defensive
                raise RuntimeError(
                    "prefetch producer thread failed to shut down"
                )
            self._thread = None
        # Drain again AFTER the join: the producer may have completed one
        # final put between the first drain and its check of the stop flag.
        self._drain()
        if self._clock is not None and hasattr(self._clock, "set_prefetch"):
            self._clock.set_prefetch(self.depth, self.occupancy())
        self._m_occupancy.set(self.occupancy())

    def _drain(self) -> None:
        if self._queue is None:
            return
        while True:
            try:
                self._queue.get_nowait()
            except queue.Empty:
                return

    def __enter__(self) -> "DevicePrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):  # pragma: no cover — belt and braces
        try:
            self.close()
        except Exception:  # noqa: BLE001  # jaxlint: disable=JL302
            pass  # interpreter teardown: nothing left to report to

    # ------------------------------------------------------------------ #
    # Introspection
    # ------------------------------------------------------------------ #

    def occupancy(self) -> float:
        """Mean ring-buffer fill fraction sampled at each consumer get."""
        if self.depth <= 0 or self._gets == 0:
            return 0.0
        return self._fill_sum / (self._gets * self.depth)

    def stats(self) -> Dict[str, float]:
        return {
            "prefetch_depth": self.depth,
            "prefetch_depth_occupancy": round(self.occupancy(), 4),
            "prefetch_degraded": int(self._degraded),
        }
