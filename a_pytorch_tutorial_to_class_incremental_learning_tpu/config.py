"""Experiment configuration.

The reference exposes its ~25 experiment knobs as argparse flags and then uses
the mutable ``args`` namespace as a global blackboard (reference
``template.py:13-49`` and the runtime fields stuffed into it at
``template.py:197-303``).  Here the static experiment configuration is an
immutable dataclass; per-task runtime state (task id, known classes, ...) lives
in the engine's explicit state objects instead of a shared mutable namespace.
"""

from __future__ import annotations

import argparse
import dataclasses
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

# The standard iCaRL/PODNet class order for CIFAR-100 used by the reference
# experiment driver (reference template.py:201-202).
CIFAR100_CLASS_ORDER: Tuple[int, ...] = (
    68, 56, 78, 8, 23, 84, 90, 65, 74, 76, 40, 89, 3, 92, 55, 9, 26, 80, 43,
    38, 58, 70, 77, 1, 85, 19, 17, 50, 28, 53, 13, 81, 45, 82, 6, 59, 83, 16,
    15, 44, 91, 41, 72, 60, 79, 52, 20, 10, 31, 54, 37, 95, 14, 71, 96, 98,
    97, 2, 64, 66, 42, 22, 35, 86, 24, 34, 87, 21, 99, 0, 88, 27, 18, 94, 11,
    12, 47, 25, 30, 46, 62, 69, 36, 61, 7, 63, 75, 5, 32, 4, 51, 48, 73, 93,
    39, 67, 29, 49, 57, 33,
)

# CIFAR-100 statistics; the reference only applies these when the dataset flag
# is the exact uppercase string "CIFAR" (reference utils.py:231-233,245-247)
# while the default flag value is lowercase "cifar" (template.py:45), so the
# default run normalizes with ImageNet statistics.  We reproduce that surface
# faithfully (see `normalization_stats`).
CIFAR_MEAN = (0.5071, 0.4867, 0.4408)
CIFAR_STD = (0.2675, 0.2565, 0.2761)
IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)
MNIST_MEAN = (0.1307,)  # torchvision's standard 1-channel stats
MNIST_STD = (0.3081,)


def compute_increments(
    nb_classes: int, initial_increment: int, increment: int
) -> Tuple[int, ...]:
    """The single source of truth for the task split arithmetic.

    ``[base, increment, increment, ...]`` with ``base = initial_increment`` or
    ``increment`` when 0 (reference template.py:222-223; continuum's
    ``initial_increment=0`` convention).  Shared by :class:`CilConfig` and
    ``data.scenario.ClassIncremental`` so the config's view of the split can
    never disagree with the scenario's.
    """
    base = initial_increment if initial_increment > 0 else increment
    if base > nb_classes:
        raise ValueError(f"num_bases={base} exceeds nb_classes={nb_classes}")
    rest = nb_classes - base
    if increment <= 0 or rest % increment != 0:
        raise ValueError(
            f"increment={increment} does not evenly divide the "
            f"{rest} classes remaining after the base task"
        )
    return (base,) + (increment,) * (rest // increment)


@dataclass(frozen=True)
class CilConfig:
    """Static configuration for one class-incremental experiment.

    Field names and defaults mirror the reference CLI surface
    (reference template.py:16-48) so experiments translate one-to-one.
    """

    # Reproducibility
    seed: int = 0

    # Task split
    num_bases: int = 50
    increment: int = 10

    # Model
    backbone: str = "resnet32"

    # Optimization
    batch_size: int = 128          # per-device batch, like the reference's per-GPU 128
    lr: float = 0.1
    momentum: float = 0.9
    weight_decay: float = 5e-4
    num_epochs: int = 140
    smooth: float = 0.0            # label smoothing
    eval_every_epoch: int = 5

    # Input / augmentation (timm-style knobs, reference template.py:21-33)
    input_size: int = 32
    color_jitter: float = 0.4
    aa: Optional[str] = "rand-m9-mstd0.5-inc1"
    reprob: float = 0.0
    remode: str = "pixel"
    recount: int = 1
    resplit: bool = False          # parsed but dead in the reference too
    ra_interpolation: str = "bilinear"  # geometric RandAugment resampling:
    # "bilinear" (branch-free device default) | "bicubic" = REFERENCE parity
    # (utils.py:222 passes interpolation='bicubic' to create_transform, which
    # timm 0.5.4 honors deterministically for the geometric ops) | "random" =
    # timm's generic no-hint default (each applied op picks bilinear/bicubic
    # at random; NOT what the reference recipe does)

    # Rehearsal memory
    herding_method: str = "barycenter"
    memory_size: int = 2000
    fixed_memory: bool = False
    herding_augmented: bool = True  # the reference extracts herding features
    # from the *train-transformed* (randomly augmented) dataset
    # (template.py:292-299); False uses clean eval preprocessing instead.

    # Knowledge distillation
    lambda_kd: float = 0.5
    dynamic_lambda_kd: bool = False  # README's lambda=n/(n+m) rule; the
    # reference parses this flag but never implements it (template.py:48);
    # we implement it for real when set.
    kd_temperature: float = 2.0

    # Data
    data_set: str = "cifar"
    data_path: str = "/data/data/data/cifar100"
    class_order: Optional[Tuple[int, ...]] = CIFAR100_CLASS_ORDER

    # Distributed / mesh
    dist_url: str = "env://"       # kept for CLI parity; JAX uses its own init
    mesh_shape: Optional[Tuple[int, int]] = None  # (data, model); None = all-devices x 1

    # Precision / normalization semantics
    precision: str = ""  # named selective-precision policy (ops/precision.py):
    # "f32" | "bf16_all" | "bf16_selective".  "" defers to the legacy
    # --compute_dtype alias below ("float32" -> f32, "bfloat16" -> bf16_all).
    compute_dtype: str = "float32"  # "bfloat16" enables MXU-friendly compute
    bn_group_size: int = 0  # 0 = global-batch BN (idiomatic on TPU);
    # 128 reproduces the reference's per-GPU-128 BN statistics exactly
    # (DDP without SyncBN, SURVEY.md §7 item 2)
    use_pallas_loss: bool = False  # fused masked-CE Pallas kernel (ops/)
    compile_cache: str = ""  # persistent XLA compilation cache directory
    # (utils/platform.enable_compile_cache); a supervised relaunch or a
    # repeated task shape then loads executables instead of re-tracing.
    # "" = leave whatever the process environment configured.
    fused_epochs: bool = True  # run each epoch as ONE lax.scan program with
    # the task dataset resident on device (in-memory datasets only; lazy
    # path-based datasets fall back to the per-batch host loop)
    prefetch_depth: int = 0  # input-pipeline ring-buffer depth for the
    # per-batch paths (step loop, eval, herding): N > 0 runs host batch
    # production + device_put on a background thread so H2D transfer of
    # batch k+1 overlaps device compute of batch k (data/prefetch.py);
    # 0 = synchronous.  Batch streams are byte-identical at every depth.

    # Checkpointing
    ckpt_dir: Optional[str] = None
    ckpt_backend: str = "pickle"  # "orbax": sharded tensorstore writes/restores
    resume: bool = False
    epoch_ckpt_every: int = 0  # E > 0: also checkpoint mid-task every E epochs
    # (task_{t}_epoch_{e}.ckpt, pickle; includes momentum/teacher/memory so a
    # kill mid-task resumes at the last epoch boundary bit-for-bit); 0 = task
    # boundaries only.  Epoch checkpoints are removed once the task completes.

    # Fault injection (faults/ package; see README "Fault tolerance")
    fault_spec: Optional[str] = None  # e.g. "kill@task1.epoch3,corrupt_ckpt@task2"
    fault_state: Optional[str] = None  # fired-clause ledger path; defaults to
    # <ckpt_dir>/fault_ledger.jsonl so a supervised relaunch does not re-fire

    # Runtime contracts (analysis/runtime.py; see README "Static analysis")
    recompile_budget: bool = False  # RecompileSentinel: train programs may
    # trace at most once per (task-growth, checkpoint-restore) event; a
    # silent re-trace raises RecompileBudgetExceeded at the task boundary
    check_donation: bool = False  # after a checkpoint restore, assert the
    # device state shares no buffers with the host payload (the PR 3
    # zero-copy aliasing SIGBUS), then poison the dead host copies so any
    # missed alias fails as NaNs immediately
    check_threads: bool = False  # ThreadCheck sentinel: wrap this repo's
    # threading.Lock/RLock to detect lock-order inversions and lock-held
    # blocking calls at runtime; each emits a thread_violation record
    # (analysis/threadcheck.py; the chaos/serve smokes fail on any)
    check_contracts: bool = False  # ContractSentinel: validate every live
    # record type/field and metric instrument name against the committed
    # contract registry (analysis/contract_registry.json) at emit time;
    # each drift emits a contract_violation record
    # (analysis/contractcheck.py; the chaos/serve smokes fail on any)
    check_lockstep: bool = False  # LockstepSentinel: fingerprint every
    # train/eval program dispatch (program + arg shapes + batch digest + RNG
    # coords), exchange fingerprints across the fleet, and fail with a named
    # lockstep_violation record + flight dumps on every process *before* a
    # divergent dispatch would hang the pod (analysis/lockstep.py)
    lockstep_dir: Optional[str] = None  # fingerprint exchange directory
    # (shared across processes); defaults to <telemetry_dir>/lockstep, then
    # <ckpt_dir>/lockstep
    lockstep_deadline_s: float = 120.0  # exchange poll deadline: a peer that
    # publishes nothing for this long surfaces as kind="peer_timeout"

    # Profiling (SURVEY.md §5: absent in the reference; near-free here)
    profile_dir: Optional[str] = None  # trace each task's first epoch
    log_file: Optional[str] = None  # structured JSONL experiment log

    # Telemetry (spans + counters + heartbeat; telemetry/ package)
    telemetry_dir: Optional[str] = None  # span JSONL + Perfetto export dir;
    # also defaults log_file to <dir>/run.jsonl and the heartbeat to
    # <dir>/heartbeat.json when those are unset
    heartbeat_path: Optional[str] = None  # liveness JSON consumed by
    # scripts/tpu_watchdog.sh (atomic rewrite on a cadence)
    heartbeat_interval_s: float = 15.0
    flight_events: int = 256  # flight-recorder ring capacity (0 = off);
    # the last N telemetry events are dumped to
    # <telemetry_dir>/flight_{proc}.json on every death path
    metrics: bool = True  # time-series registry (telemetry/metrics.py):
    # counters/gauges/histograms on the hot paths; --no_metrics swaps in
    # no-op instruments (the off-leg of the perf_gate overhead comparison)
    metrics_interval_s: float = 10.0  # MetricsPump flush cadence for
    # metrics_snapshot records and the heartbeat progress digest

    # Serving (serving/ package: artifact export + hot-swap server)
    export_dir: Optional[str] = None  # after each task's weight alignment,
    # freeze the inference state and AOT-export it here as a per-task
    # serving artifact (manifest.json + task_{t:03d}/); a running
    # serving.server hot-swaps to it at the next manifest poll
    serve_buckets: Tuple[int, ...] = (1, 8, 32, 64)  # supported inference
    # batch shapes; the server pads each micro-batch up to the smallest
    # covering bucket (eval rows are independent, so padding is exact)
    serve_skew_check: bool = False  # after each export, reload the artifact
    # and re-evaluate every seen task's val slice through it, logging a
    # serve_skew record against the training-side accuracy row (costs one
    # extra eval pass per task)

    # ------------------------------------------------------------------ #

    def increments(self, nb_classes: int) -> Tuple[int, ...]:
        """Per-task class counts: ``[num_bases, increment, increment, ...]``.

        Matches reference template.py:222-223.  A ``num_bases`` of 0 means the
        first task also uses ``increment`` (the B0 benchmark convention, same
        as continuum's ``initial_increment=0``).
        """
        return compute_increments(nb_classes, self.num_bases, self.increment)

    def normalization_stats(self) -> Tuple[Tuple[float, ...], Tuple[float, ...]]:
        """Mean/std used by the input pipeline.

        Faithful to the reference quirk: CIFAR statistics apply only when
        ``data_set`` is exactly ``"CIFAR"`` and ``input_size == 32``
        (reference utils.py:231-233); everything else, including the default
        lowercase ``"cifar"``, gets ImageNet statistics.
        """
        if self.data_set == "CIFAR" and self.input_size == 32:
            return CIFAR_MEAN, CIFAR_STD
        if "mnist" in self.data_set.lower():
            return MNIST_MEAN, MNIST_STD
        return IMAGENET_MEAN, IMAGENET_STD

    def replace(self, **kw) -> "CilConfig":
        return dataclasses.replace(self, **kw)


def get_args_parser() -> argparse.ArgumentParser:
    """CLI flags with the same names/defaults as the reference driver
    (reference template.py:13-49), plus the TPU-specific additions."""
    p = argparse.ArgumentParser(
        "Class-Incremental Learning training and evaluation script (TPU)",
        add_help=False,
    )
    d = CilConfig()
    p.add_argument("--seed", default=d.seed, type=int)
    p.add_argument("--num_bases", default=d.num_bases, type=int)
    p.add_argument("--increment", default=d.increment, type=int)
    p.add_argument("--backbone", default=d.backbone, type=str)
    p.add_argument("--batch_size", default=d.batch_size, type=int)
    p.add_argument("--input_size", default=d.input_size, type=int)
    p.add_argument("--color_jitter", default=d.color_jitter, type=float)
    p.add_argument("--aa", default=d.aa, type=str,
                   help='AutoAugment policy, e.g. "rand-m9-mstd0.5-inc1" or "none"')
    p.add_argument("--reprob", default=d.reprob, type=float,
                   help="Random erase probability")
    p.add_argument("--remode", default=d.remode, type=str,
                   help="Random erase mode")
    p.add_argument("--recount", default=d.recount, type=int,
                   help="Random erase count")
    p.add_argument("--resplit", action="store_true", default=False)
    p.add_argument("--ra_interpolation", default=d.ra_interpolation, type=str,
                   choices=("bilinear", "bicubic", "random"),
                   help="geometric RandAugment resampling; 'bicubic' = "
                   "reference parity (utils.py:222 passes an explicit "
                   "bicubic hint); 'random' = timm's no-hint default "
                   "(per-op bilinear/bicubic choice)")
    p.add_argument("--herding_method", default=d.herding_method, type=str)
    p.add_argument("--memory_size", default=d.memory_size, type=int)
    p.add_argument("--fixed_memory", action="store_true", default=False)
    p.add_argument(
        "--no_herding_augmented",
        action="store_false",
        dest="herding_augmented",
        default=True,
        help="extract herding features from clean (eval-preprocessed) images "
        "instead of the reference's randomly augmented ones",
    )
    p.add_argument("--lr", default=d.lr, type=float)
    p.add_argument("--momentum", default=d.momentum, type=float)
    p.add_argument("--weight_decay", default=d.weight_decay, type=float)
    p.add_argument("--num_epochs", default=d.num_epochs, type=int)
    p.add_argument("--smooth", default=d.smooth, type=float)
    p.add_argument("--eval_every_epoch", default=d.eval_every_epoch, type=int)
    p.add_argument("--dist_url", default=d.dist_url)
    p.add_argument("--data_set", default=d.data_set)
    p.add_argument("--data_path", default=d.data_path)
    p.add_argument("--lambda_kd", default=d.lambda_kd, type=float)
    p.add_argument("--dynamic_lambda_kd", action="store_true", default=False)
    # TPU-native additions
    p.add_argument("--precision", default=d.precision,
                   choices=["", "f32", "bf16_all", "bf16_selective"],
                   help="selective mixed-precision policy (ops/precision.py): "
                   "f32 = everything float32; bf16_all = bf16 compute AND "
                   "activations (the old --compute_dtype bfloat16, ~7 pts "
                   "cheaper on avg incremental accuracy); bf16_selective = "
                   "bf16 conv/matmul compute with f32 params, BN stats, "
                   "activations-between-ops, logits and loss.  Supersedes "
                   "--compute_dtype, which remains as an alias")
    p.add_argument("--compute_dtype", default=d.compute_dtype,
                   choices=["float32", "bfloat16"],
                   help="legacy precision alias: float32 -> f32, bfloat16 -> "
                   "bf16_all; ignored when --precision is set")
    p.add_argument("--mesh_data", default=0, type=int,
                   help="data-axis size of the device mesh (0 = all devices)")
    p.add_argument("--mesh_model", default=1, type=int,
                   help="model-axis size of the device mesh")
    p.add_argument("--ckpt_dir", default=None, type=str)
    p.add_argument("--ckpt_backend", default=d.ckpt_backend,
                   choices=["pickle", "orbax"],
                   help="orbax: every process writes its own parameter "
                   "shards via tensorstore; restore places arrays directly "
                   "onto the mesh sharding (no host gather)")
    p.add_argument("--resume", action="store_true", default=False)
    p.add_argument("--epoch_ckpt_every", default=d.epoch_ckpt_every, type=int,
                   help="also write mid-task epoch checkpoints every E epochs "
                   "(task_{t}_epoch_{e}.ckpt) so --resume restarts at the "
                   "last epoch boundary instead of the task boundary; 0 = "
                   "task boundaries only")
    p.add_argument("--fault_spec", default=None, type=str,
                   help="deterministic fault injection plan, e.g. "
                   "'kill@task1.epoch3,corrupt_ckpt@task2' "
                   "(faults/injector.py; coordinates: 0-based task, 1-based "
                   "epoch/step; each clause fires once at the END of the "
                   "named unit)")
    p.add_argument("--fault_state", default=None, type=str,
                   help="fired-fault ledger path (defaults to "
                   "<ckpt_dir>/fault_ledger.jsonl); a relaunched process "
                   "skips clauses already recorded here")
    p.add_argument("--recompile_budget", action="store_true", default=False,
                   help="enforce the RecompileSentinel trace budget: train "
                   "programs may compile at most once per task growth or "
                   "checkpoint restore; a silent re-trace fails the run "
                   "(analysis/runtime.py)")
    p.add_argument("--check_donation", action="store_true", default=False,
                   help="after a checkpoint restore, assert restored device "
                   "arrays share no buffers with the host payload and poison "
                   "the dead host copies (turns silent zero-copy aliasing "
                   "into a deterministic failure)")
    p.add_argument("--check_threads", action="store_true", default=False,
                   help="install the ThreadCheck sentinel: record per-thread "
                   "held-lock sets and global acquisition order, emit a "
                   "thread_violation record on any lock-order inversion or "
                   "lock-held blocking call (analysis/threadcheck.py)")
    p.add_argument("--check_contracts", action="store_true", default=False,
                   help="install the ContractSentinel: validate every live "
                   "record type/field and metric name against the committed "
                   "contract registry, emit a contract_violation record on "
                   "any drift the static contractlint pass could not see "
                   "(analysis/contractcheck.py)")
    p.add_argument("--check_lockstep", action="store_true", default=False,
                   help="install the LockstepSentinel: fingerprint every "
                   "train/eval dispatch (program + arg shapes + batch digest "
                   "+ RNG coords), exchange across the fleet, and fail with "
                   "a named lockstep_violation + flight dumps before a "
                   "divergent dispatch hangs the pod (analysis/lockstep.py)")
    p.add_argument("--lockstep_dir", default=None, type=str,
                   help="fingerprint exchange directory shared by all "
                   "processes; defaults to <telemetry_dir>/lockstep, then "
                   "<ckpt_dir>/lockstep")
    p.add_argument("--lockstep_deadline_s", default=120.0, type=float,
                   help="lockstep exchange poll deadline: a peer silent for "
                   "this long is reported as kind=peer_timeout")
    p.add_argument("--profile_dir", default=None, type=str,
                   help="write a jax.profiler trace of each task's first epoch")
    p.add_argument("--log_file", default=None, type=str,
                   help="write a structured JSONL experiment log")
    p.add_argument("--telemetry_dir", default=None, type=str,
                   help="write host-side span telemetry (spans.jsonl + "
                   "Perfetto trace.json) here; also defaults --log_file to "
                   "<dir>/run.jsonl and --heartbeat_path to "
                   "<dir>/heartbeat.json when those are unset")
    p.add_argument("--heartbeat_path", default=None, type=str,
                   help="liveness heartbeat JSON, atomically rewritten every "
                   "--heartbeat_interval_s; consumed by "
                   "scripts/tpu_watchdog.sh instead of blind chip probing")
    p.add_argument("--heartbeat_interval_s", default=d.heartbeat_interval_s,
                   type=float,
                   help="heartbeat cadence; the file is guaranteed fresher "
                   "than 2x this during a live run")
    p.add_argument("--flight_events", default=d.flight_events, type=int,
                   help="flight-recorder ring capacity: the last N telemetry "
                   "events dumped to <telemetry_dir>/flight_{proc}.json on "
                   "crash/SIGTERM/exit for post-mortem forensics (0 = off)")
    p.add_argument("--no_metrics", dest="metrics", action="store_false",
                   default=True,
                   help="disable the time-series metrics registry "
                   "(telemetry/metrics.py); instruments become no-ops and "
                   "no metrics_snapshot records are pumped")
    p.add_argument("--metrics_interval_s", default=d.metrics_interval_s,
                   type=float,
                   help="metrics_snapshot flush cadence (and heartbeat "
                   "progress-digest refresh) of the MetricsPump")
    p.add_argument("--bn_group_size", default=0, type=int,
                   help="BatchNorm statistics group size (0 = global batch; "
                   "128 = reference per-GPU parity)")
    p.add_argument("--use_pallas_loss", action="store_true", default=False,
                   help="use the fused masked-CE Pallas kernel for the train loss")
    p.add_argument("--no_fused_epochs", action="store_false",
                   dest="fused_epochs", default=True,
                   help="dispatch one device program per batch instead of "
                   "one lax.scan program per epoch")
    p.add_argument("--prefetch_depth", default=d.prefetch_depth, type=int,
                   help="input-pipeline ring-buffer depth for the per-batch "
                   "paths: N>0 produces batches and issues device_put on a "
                   "background thread, overlapping H2D transfer with device "
                   "compute; 0 = synchronous (identical batch stream either "
                   "way)")
    p.add_argument("--platform", default="default",
                   choices=["default", "cpu", "tpu"],
                   help="JAX platform to force before backend init "
                   "(default = whatever the environment provides); 'cpu' "
                   "enables running the full CLI without an accelerator")
    p.add_argument("--host_devices", default=0, type=int,
                   help="with --platform cpu: number of virtual CPU devices "
                   "(xla_force_host_platform_device_count) for testing "
                   "multi-device meshes without hardware")
    p.add_argument("--export_dir", default=None, type=str,
                   help="freeze + AOT-export a serving artifact here after "
                   "each task's weight alignment (serving/artifact.py); a "
                   "running inference server hot-swaps to it")
    p.add_argument("--serve_buckets", default="1,8,32,64", type=str,
                   help="comma-separated batch buckets the exported predict "
                   "function is AOT-compiled for; the server pads each "
                   "micro-batch to the smallest covering bucket")
    p.add_argument("--serve_skew_check", action="store_true", default=False,
                   help="after each export, reload the artifact and "
                   "re-evaluate the seen val slices through it, logging a "
                   "serve_skew record vs the training accuracy row")
    p.add_argument("--compile_cache",
                   default="~/.cache/cil_tpu/xla_cache",
                   help="persistent XLA compilation cache directory; repeat "
                   "runs and repeated task shapes then skip compilation "
                   "('' disables)")
    return p


def parse_serve_buckets(text) -> Tuple[int, ...]:
    """``"1,8,32,64"`` -> sorted unique positive ints (the CLI surface of
    ``CilConfig.serve_buckets``)."""
    try:
        vals = sorted({int(tok) for tok in str(text).split(",") if tok.strip()})
    except ValueError:
        raise ValueError(f"bad --serve_buckets {text!r}; want e.g. '1,8,32,64'")
    if not vals or vals[0] <= 0:
        raise ValueError(f"--serve_buckets must be positive ints, got {text!r}")
    return tuple(vals)


def config_from_args(args: argparse.Namespace) -> CilConfig:
    aa = None if args.aa in (None, "none", "None", "") else args.aa
    mesh_shape = None
    if args.mesh_data or args.mesh_model != 1:
        import jax
        data = args.mesh_data or (len(jax.devices()) // max(args.mesh_model, 1))
        mesh_shape = (data, args.mesh_model)
    # --precision supersedes the --compute_dtype alias; keep compute_dtype
    # consistent with the chosen policy so provenance records and serving
    # metadata never disagree with the programs actually compiled.
    precision = getattr(args, "precision", "") or ""
    compute_dtype = args.compute_dtype
    if precision:
        compute_dtype = "bfloat16" if precision.startswith("bf16") else "float32"
    return CilConfig(
        seed=args.seed,
        num_bases=args.num_bases,
        increment=args.increment,
        backbone=args.backbone,
        batch_size=args.batch_size,
        lr=args.lr,
        momentum=args.momentum,
        weight_decay=args.weight_decay,
        num_epochs=args.num_epochs,
        smooth=args.smooth,
        eval_every_epoch=int(args.eval_every_epoch),
        input_size=args.input_size,
        color_jitter=args.color_jitter,
        aa=aa,
        reprob=args.reprob,
        remode=args.remode,
        recount=args.recount,
        resplit=args.resplit,
        ra_interpolation=args.ra_interpolation,
        herding_method=args.herding_method,
        memory_size=args.memory_size,
        fixed_memory=args.fixed_memory,
        herding_augmented=args.herding_augmented,
        lambda_kd=args.lambda_kd,
        dynamic_lambda_kd=args.dynamic_lambda_kd,
        data_set=args.data_set,
        data_path=args.data_path,
        dist_url=args.dist_url,
        mesh_shape=mesh_shape,
        precision=precision,
        compute_dtype=compute_dtype,
        bn_group_size=args.bn_group_size,
        use_pallas_loss=args.use_pallas_loss,
        compile_cache=getattr(args, "compile_cache", "") or "",
        fused_epochs=args.fused_epochs,
        prefetch_depth=args.prefetch_depth,
        ckpt_dir=args.ckpt_dir,
        ckpt_backend=args.ckpt_backend,
        resume=args.resume,
        epoch_ckpt_every=args.epoch_ckpt_every,
        fault_spec=args.fault_spec,
        fault_state=args.fault_state,
        recompile_budget=args.recompile_budget,
        check_donation=args.check_donation,
        check_threads=args.check_threads,
        check_contracts=args.check_contracts,
        check_lockstep=args.check_lockstep,
        lockstep_dir=args.lockstep_dir,
        lockstep_deadline_s=args.lockstep_deadline_s,
        profile_dir=args.profile_dir,
        log_file=args.log_file,
        telemetry_dir=args.telemetry_dir,
        heartbeat_path=args.heartbeat_path,
        heartbeat_interval_s=args.heartbeat_interval_s,
        flight_events=args.flight_events,
        metrics=args.metrics,
        metrics_interval_s=args.metrics_interval_s,
        export_dir=args.export_dir,
        serve_buckets=parse_serve_buckets(args.serve_buckets),
        serve_skew_check=args.serve_skew_check,
    )
