"""Host-side span tracer: nested named regions of the task loop.

The ``jax.profiler`` trace answers "what did the *device* do inside one
epoch"; it is heavyweight (hundreds of MB per minute) and therefore only ever
wraps task 0's first epoch (``utils/profiling.task_trace``).  This tracer is
the complement: a lightweight always-on record of what the *host* loop spent
its wall time on — build scenario, rehearsal inject, head grow, epoch, eval,
align, herd — cheap enough to run for a whole multi-hour protocol (one dict
and one JSONL line per region).

Spans nest: each carries its ``depth`` and ``parent`` id, so a reader can
reconstruct the tree and compute phase coverage (``scripts/report_run.py``
checks that depth-1 phases cover ~all of the root span's wall time — any gap
is un-attributed host time, the kind of silent stall this PR exists to make
visible).  Each span also enters a ``jax.profiler.TraceAnnotation`` so that
when a device trace *is* active the host phases appear on its timeline.

Export formats: JSONL (one ``span`` record per line, written on span exit so
a SIGKILL loses at most the open spans) and Chrome ``chrome://tracing`` /
Perfetto JSON (``export_chrome_trace``), the zero-dependency way to *see*
the loop.
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from typing import Iterator, List, Optional


class SpanTracer:
    """Context-manager span API writing ``span`` records to a JSONL file.

    Disabled (``path=None``) the tracer is a pure no-op.  Every JAX process
    traces: process 0 keeps the legacy ``spans.jsonl`` name, process *i*
    writes ``spans_p{i}.jsonl`` (``utils.logging.process_suffixed``), and
    each record carries ``process_index`` so a merged fleet report can tell
    the streams apart.  When a :class:`~.flight.FlightRecorder` is attached,
    span opens/closes feed its open-span stack — the "what was the host doing
    at death" answer a SIGKILL'd process cannot write itself.
    """

    def __init__(
        self,
        path: Optional[str],
        process_index: Optional[int] = None,
        process_count: Optional[int] = None,
        flight=None,
    ):
        if path is not None and process_index is None:
            import jax

            process_index = jax.process_index()
            process_count = jax.process_count()
        from ..utils.logging import process_suffixed

        self.process_index = int(process_index or 0)
        self.process_count = int(process_count or 1)
        self.enabled = bool(path)
        self.path = process_suffixed(path, self.process_index) if path else None
        self.flight = flight
        self._stack: List[int] = []
        self._next_id = 0
        self.completed: List[dict] = []  # in-memory copy for export/coverage
        # Monotonic epoch offset: spans are timestamped with the monotonic
        # clock (immune to NTP steps mid-run) but exported in wall time.
        self._wall0 = time.time() - time.perf_counter()
        if self.path:
            os.makedirs(os.path.dirname(os.path.abspath(self.path)), exist_ok=True)
            open(self.path, "w").close()

    @contextlib.contextmanager
    def span(self, name: str, **attrs) -> Iterator[None]:
        if not self.enabled:
            yield
            return
        import jax

        span_id = self._next_id
        self._next_id += 1
        parent = self._stack[-1] if self._stack else None
        depth = len(self._stack)
        self._stack.append(span_id)
        if self.flight is not None:
            self.flight.span_open(name, span_id, depth, **attrs)
        t0 = time.perf_counter()
        try:
            # Compose with the device profiler: when a jax.profiler.trace is
            # active the host phase shows up on the same timeline.
            with jax.profiler.TraceAnnotation(name):
                yield
        finally:
            t1 = time.perf_counter()
            self._stack.pop()
            rec = {
                "type": "span",
                "name": name,
                "span_id": span_id,
                "parent": parent,
                "depth": depth,
                "ts": round(self._wall0 + t0, 6),
                "dur_s": round(t1 - t0, 6),
                "process_index": self.process_index,
                **attrs,
            }
            self.completed.append(rec)
            with open(self.path, "a") as f:
                f.write(json.dumps(rec) + "\n")
            if self.flight is not None:
                self.flight.span_close(span_id)
                self.flight.record(rec)

    # ------------------------------------------------------------------ #
    # Analysis / export
    # ------------------------------------------------------------------ #

    def coverage(self, depth: int = 1) -> Optional[float]:
        """Fraction of the root span's wall time covered by spans at
        ``depth`` — the "is any host time unaccounted for?" number."""
        return coverage(self.completed, depth)

    def export_chrome_trace(self, path: str) -> None:
        """Write the completed spans as ``chrome://tracing`` / Perfetto JSON
        (complete-duration ``"X"`` events, microsecond timestamps).

        ``path`` is re-homed through ``process_suffixed`` (like the span
        JSONL itself), so N processes exporting the same logical name never
        race on one file: process 0 keeps ``trace.json``, process *i* writes
        ``trace_p{i}.json``."""
        if not self.enabled:
            return
        from ..utils.logging import process_suffixed

        path = process_suffixed(path, self.process_index)
        events = [
            {
                "name": rec["name"],
                "ph": "X",
                "ts": round(rec["ts"] * 1e6, 1),
                "dur": round(rec["dur_s"] * 1e6, 1),
                "pid": 0,
                "tid": 0,
                "args": {
                    k: v
                    for k, v in rec.items()
                    if k not in ("type", "name", "ts", "dur_s")
                },
            }
            for rec in self.completed
        ]
        with open(path, "w") as f:
            json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)


def coverage(spans: List[dict], depth: int = 1) -> Optional[float]:
    """Phase coverage from span records (tracer-attached or re-loaded from a
    span JSONL by ``scripts/report_run.py``): sum of ``depth``-level span
    durations over the total duration of the depth-0 roots.  Siblings at one
    depth never overlap (the tracer is single-threaded), so the plain sum is
    the union.  None when there is no root to compare against."""
    roots = [s for s in spans if s.get("depth") == 0]
    if not roots:
        return None
    total = sum(s["dur_s"] for s in roots)
    if total <= 0:
        return None
    covered = sum(s["dur_s"] for s in spans if s.get("depth") == depth)
    return covered / total


def load_spans(path: str) -> List[dict]:
    """Read a span JSONL file (tolerating a truncated last line, the normal
    state after a SIGKILL)."""
    out = []
    with open(path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("type") == "span":
                out.append(rec)
    return out
