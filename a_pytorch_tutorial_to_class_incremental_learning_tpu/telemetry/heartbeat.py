"""In-process liveness heartbeat.

Round 5's failure mode: the external watchdog probed a dead TPU for an entire
round (``experiments/tpu_watchdog.log``) because the training process had no
way to say "I am alive and on task 3 epoch 41".  The fix is the training
process itself atomically rewriting one small JSON file on a cadence —
``scripts/tpu_watchdog.sh`` then *reads* that file instead of opening a fresh
(and potentially chip-wedging) device client to probe.

Contract (consumed by the watchdog and documented in README):

* the file is a single JSON object: ``{"type": "heartbeat", "ts", "mono",
  "seq", "pid", "process_index", "step", "task", "epoch", "phase",
  "last_step_ms"}``; ``ts`` is wall-clock seconds, ``mono`` the monotonic
  clock at the same instant, ``seq`` strictly monotonic;
* it is replaced atomically (write temp + ``os.replace`` on the same
  filesystem), so a reader never sees a partial write;
* during a live run its age never exceeds ~2x the configured interval.

Long blocking calls (an XLA compile, a fused-epoch device wait) release the
GIL, so the optional background thread keeps beating through them — the loop
only has to ``update()`` the state fields; the thread owns the cadence.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional


class Heartbeat:
    """Atomic heartbeat-file emitter.

    ``update(**state)`` is called from the training loop (cheap: stores the
    fields and writes only when the interval elapsed).  ``start()`` spawns a
    daemon thread that keeps writing the latest state every ``interval_s/2``
    even while the loop is stuck inside one long call; ``stop()`` joins it
    and writes a final beat.  Disabled (``path=None``) every method is a
    no-op.  Every JAX process beats into its *own* file (process 0 keeps the
    legacy name, process *i* gets ``heartbeat_p{i}.json``), each beat tagged
    with ``process_index`` plus a monotonic-clock ``mono`` field — the
    ``(ts, mono)`` pair is what ``scripts/report_run.py`` uses to align
    clock-skewed per-process streams.  With a
    :class:`~.flight.FlightRecorder` attached, every beat also lands in the
    flight ring and triggers a periodic flight dump, so even an uncatchable
    death leaves a dump at most half an interval stale.
    """

    def __init__(
        self,
        path: Optional[str],
        interval_s: float = 15.0,
        process_index: Optional[int] = None,
        process_count: Optional[int] = None,
        flight=None,
    ):
        if path is not None and process_index is None:
            import jax

            process_index = jax.process_index()
            process_count = jax.process_count()
        from ..utils.logging import process_suffixed

        self.process_index = int(process_index or 0)
        self.process_count = int(process_count or 1)
        self.enabled = bool(path)
        self.path = process_suffixed(path, self.process_index) if path else None
        self.flight = flight
        self.interval_s = float(interval_s)
        self._seq = 0
        self._state = {}
        self._last_write = 0.0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if self.path:
            os.makedirs(os.path.dirname(os.path.abspath(self.path)), exist_ok=True)
            self._write()

    # ------------------------------------------------------------------ #

    def update(self, force: bool = False, **state) -> None:
        """Record the loop's latest position; write if the cadence is due."""
        if not self.enabled:
            return
        now = time.monotonic()
        with self._lock:
            self._state.update({k: v for k, v in state.items() if v is not None})
            # _last_write is written by the daemon thread under the lock;
            # reading it outside raced the cadence decision (jaxlint JL305).
            due = force or now - self._last_write >= self.interval_s
        if due:
            self._write()

    def start(self) -> None:
        if not self.enabled or self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="cil-heartbeat", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=self.interval_s + 5.0)
            self._thread = None
        if self.enabled:
            self._write()  # final beat: the freshest possible "last seen"

    # ------------------------------------------------------------------ #

    def _run(self) -> None:
        # Half the interval keeps worst-case staleness (a beat just missed
        # plus a full sleep) under the 2x-interval freshness contract.
        while not self._stop.wait(self.interval_s / 2.0):
            self._write()

    def _write(self) -> None:
        with self._lock:
            self._seq += 1
            payload = {
                "type": "heartbeat",
                "ts": round(time.time(), 3),
                # Monotonic stamp beside the wall stamp: (ts - mono) is a
                # per-process clock offset, so a merged report can align
                # streams whose wall clocks disagree (NTP skew across hosts).
                "mono": round(time.monotonic(), 3),
                "seq": self._seq,
                "pid": os.getpid(),
                "process_index": self.process_index,
                **self._state,
            }
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(payload, f)
                f.flush()
                os.fsync(f.fileno())
            # Same-directory rename: atomic on POSIX, so a concurrent reader
            # (the watchdog) sees either the old or the new beat, never a
            # torn write.
            os.replace(tmp, self.path)
            # Under the lock: _write runs on both the daemon thread and the
            # training loop (update/stop), and update() reads this stamp to
            # decide cadence (jaxlint JL301).
            with self._lock:
                self._last_write = time.monotonic()
            if self.flight is not None:
                self.flight.record(payload)
                self.flight.dump("heartbeat")
        except OSError:
            # A full disk must not kill training; staleness is the signal.
            try:
                os.unlink(tmp)
            except OSError:
                pass


def read_heartbeat(path: str, max_age_s: float) -> dict:
    """Watchdog-side read: the parsed beat plus ``age_s`` and ``fresh``.

    ``fresh`` is False when the file is missing, unparsable, or older than
    ``max_age_s`` (the contract says 2x the emitter's interval).
    """
    try:
        with open(path) as f:
            beat = json.load(f)
        age = time.time() - float(beat["ts"])
    except (OSError, ValueError, KeyError):
        return {"fresh": False}
    beat["age_s"] = round(age, 3)
    beat["fresh"] = age <= max_age_s
    return beat
