"""Recompile, HBM, and input-stall counters.

The three numbers TPU-scale training treats as table stakes (pjit/TPUv4
training systems, arXiv:2204.06514; Podracer, arXiv:2104.06272) and the
reference has no notion of:

* **recompiles** — an XLA recompile mid-protocol silently costs minutes; the
  monitor counts jit-cache entries across every tracked executable and warns
  when the count grows at a point where no new program shape is expected;
* **HBM** — the grown head, the resident fused-epoch dataset and the teacher
  snapshot all cost device memory; per-device ``memory_stats()`` sampled at
  task boundaries shows the trend before an OOM does;
* **stalls** — per epoch, how much wall time the host spent producing data
  vs. waiting on the device: data-bound vs. compute-bound, measurable
  per epoch instead of guessed.
"""

from __future__ import annotations

import time
import warnings
from contextlib import contextmanager
from typing import Dict, Iterable, Iterator, Optional

from ..utils.logging import NullSink, Sink


class StallClock:
    """Per-epoch host-vs-device wall-time accounting.

    The epoch loop charges every interval to exactly one bucket:
    ``host`` (batch index math, uint8 gather, host decode, device_put) or
    ``device`` (step dispatch and the final metrics fetch, i.e. time the
    host spends waiting on the accelerator).  ``host_s + device_s`` then
    accounts for ~all of the epoch's wall time (tested to tolerance —
    the remainder is loop bookkeeping), so ``stall_frac`` =
    host/(host+device) reads directly as "fraction of the epoch the chip
    was starved by the input pipeline".
    """

    def __init__(self):
        self.host_s = 0.0
        self.device_s = 0.0
        # Filled in by a DevicePrefetcher at shutdown: ring depth and mean
        # fill fraction.  None until a prefetching iterator reports in, so
        # non-prefetch epochs carry no invented zeros.
        self.prefetch_depth: Optional[int] = None
        self.prefetch_occupancy: Optional[float] = None

    @contextmanager
    def host(self) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.host_s += time.perf_counter() - t0

    @contextmanager
    def device(self) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.device_s += time.perf_counter() - t0

    def add_host(self, dt: float) -> None:
        self.host_s += dt

    def set_prefetch(self, depth: int, occupancy: float) -> None:
        """Record the input prefetcher's ring state for this epoch.

        With prefetching on, ``host_s`` holds only the *residual* (non-
        overlapped) production time — the occupancy says why: ~1.0 means the
        producer stayed ahead (compute-bound), ~0 means the consumer kept
        draining the ring dry (data-bound).
        """
        if depth > 0:
            self.prefetch_depth = int(depth)
            self.prefetch_occupancy = float(occupancy)

    @property
    def stall_frac(self) -> float:
        total = self.host_s + self.device_s
        return self.host_s / total if total > 0 else 0.0

    def snapshot(self) -> Dict[str, float]:
        snap = {
            "host_s": round(self.host_s, 4),
            "device_s": round(self.device_s, 4),
            "stall_frac": round(self.stall_frac, 4),
        }
        if self.prefetch_depth is not None:
            snap["prefetch_depth"] = self.prefetch_depth
            snap["prefetch_depth_occupancy"] = round(
                self.prefetch_occupancy or 0.0, 4
            )
        return snap


def clocked(batches: Iterable, clock: StallClock) -> Iterator:
    """Charge the production time of each batch to ``clock``'s host bucket.

    Wraps any batch iterator (``data.loader`` generators) so the time spent
    *inside* ``next()`` — index arithmetic and the uint8 row gather — is
    separated from the time the consumer spends dispatching device work.
    """
    it = iter(batches)
    while True:
        t0 = time.perf_counter()
        try:
            batch = next(it)
        except StopIteration:
            return
        finally:
            clock.add_host(time.perf_counter() - t0)
        yield batch


class RecompileMonitor:
    """Detect unexpected XLA recompiles via jit-cache growth.

    Every jitted callable of the engine is registered with ``track``; the
    total number of cache entries across them is the number of distinct
    compiled programs so far.  ``check(...)`` diffs that total against the
    last check: growth at an *expected* point (the first epoch of a task,
    which legitimately compiles the task's shapes; anything in task 0) emits
    a ``recompile`` record; growth anywhere else is the classic silent
    performance bug — a shape/dtype leak re-triggering compilation mid
    steady state — and additionally emits a ``recompile_warning`` record
    plus a Python warning.

    Executables are registered in *groups* (train / eval / feature in the
    engine) because their legitimate first-compile moments differ: the train
    programs compile on a task's first epoch, the eval program on the run's
    first evaluation, the feature program on the first herding pass.  Each
    ``check`` diffs one group, so an expected eval compile can never mask an
    unexpected train recompile in the same wall-clock window.
    """

    def __init__(self, sink: Optional[Sink] = None):
        self.sink = sink or NullSink()
        self._fns: Dict[str, object] = {}
        self._groups: Dict[str, str] = {}
        self._last: Dict[Optional[str], int] = {}

    def track(self, name: str, fn, group: str = "default") -> None:
        if hasattr(fn, "_cache_size"):
            self._fns[name] = fn
            self._groups[name] = group

    def total(self, group: Optional[str] = None) -> int:
        return sum(
            int(fn._cache_size())
            for name, fn in self._fns.items()
            if group is None or self._groups[name] == group
        )

    def check(
        self, where: str, expected: bool, group: Optional[str] = None, **attrs
    ) -> int:
        """Diff the compile count; returns the delta (0 = no new programs)."""
        total = self.total(group)
        delta = total - self._last.get(group, 0)
        self._last[group] = total
        if group is not None:
            attrs["group"] = group
        if delta > 0:
            self.sink.log(
                "recompile",
                where=where,
                new_programs=delta,
                total_programs=total,
                expected=expected,
                **attrs,
            )
            if not expected:
                self.sink.log(
                    "recompile_warning",
                    where=where,
                    new_programs=delta,
                    total_programs=total,
                    **attrs,
                )
                warnings.warn(
                    f"unexpected XLA recompile at {where}: {delta} new "
                    f"program(s), {total} total — a shape or dtype is "
                    "changing where the engine promises shape stability",
                    RuntimeWarning,
                    stacklevel=2,
                )
        return delta


def hbm_stats(devices=None) -> Dict[str, Dict[str, int]]:
    """Per-device memory statistics, keyed by device string.

    TPU/GPU backends report ``bytes_in_use`` / ``peak_bytes_in_use`` /
    ``bytes_limit`` (names vary by PJRT plugin; everything integer-valued is
    forwarded).  XLA:CPU returns None — then this returns {} and the caller
    logs nothing, rather than inventing zeros.
    """
    import jax

    out: Dict[str, Dict[str, int]] = {}
    for d in devices if devices is not None else jax.local_devices():
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 — optional PJRT surface
            stats = None
        if stats:
            out[str(d)] = {
                k: int(v) for k, v in stats.items() if isinstance(v, (int, float))
            }
    return out
