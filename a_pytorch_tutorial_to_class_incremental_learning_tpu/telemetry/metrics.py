"""Fleet metrics plane: in-process time-series registry + snapshot pump.

The event-shaped telemetry (JSONL records, spans, flight dumps) answers
"what happened"; this module answers "how fast is it happening *right now*"
— the substrate the serving fleet's scraper, the SLO burn-rate alerts, and
the watchdog's stalled-vs-progressing distinction all read from.

Three instrument kinds, Prometheus-shaped:

* :class:`Counter` — monotonic totals (requests served, steps run).
* :class:`Gauge` — last-write-wins levels (queue depth, ring occupancy).
* :class:`Histogram` — exponential-bucket latency distributions.  Buckets
  are ``lowest * growth**i`` upper bounds, so two histograms with the same
  layout merge by element-wise addition: merging is associative and
  commutative, which is what lets the fleet scraper fold N replicas'
  distributions into one aggregate in any order.

Lock discipline (threadlint JL303–JL306, ``--check_threads``): the registry
owns ONE lock shared by every instrument it creates — a single lock cannot
participate in an acquisition-order cycle — and no file/socket/sleep call
ever runs under it.  ``snapshot()`` copies every value atomically under that
lock and returns plain dicts; rendering (Prometheus text), merging, and
quantile estimation are pure functions over snapshots, so they run lock-free.

:class:`MetricsPump` is the bridge back into the event world: a daemon
thread that flushes a schema-checked ``metrics_snapshot`` record into the
run's JSONL sink on a cadence, and pushes a progress digest (step rate,
serve qps) into the heartbeat so ``scripts/supervise.py`` can tell "alive
but stalled" from "making progress" without scraping anything.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

# Heartbeat progress digest: counter series -> (absolute field, rate field).
# The pump publishes these into the heartbeat file; the supervisor's stall
# probe watches the absolute fields for freezes under a fresh heartbeat.
DIGEST_SERIES = {
    "steps_total": ("steps_total", "step_rate"),
    "serve_requests_total": ("serve_requests_total", "serve_qps"),
}


def series_name(name: str, labels: Tuple[Tuple[str, str], ...]) -> str:
    """Prometheus series key: ``name`` or ``name{k="v",...}`` (sorted)."""
    if not labels:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return f"{name}{{{inner}}}"


class Counter:
    """Monotonic counter.  ``inc()`` is the hot-path call: one shared-lock
    acquisition, one float add."""

    kind = "counter"

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Last-write-wins level."""

    kind = "gauge"

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def add(self, n: float) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Exponential-bucket histogram.

    Bucket ``i`` (0-based) counts observations ``v <= lowest * growth**i``
    not already counted by a lower bucket; one final overflow bucket counts
    the rest.  The layout ``(lowest, growth, len(buckets))`` is the merge
    key: equal layouts merge by element-wise addition.
    """

    kind = "histogram"

    def __init__(self, lock: threading.Lock, lowest: float = 1.0,
                 growth: float = 2.0, buckets: int = 20):
        if lowest <= 0 or growth <= 1.0 or buckets < 1:
            raise ValueError(
                f"bad histogram layout: lowest={lowest} growth={growth} "
                f"buckets={buckets}")
        self._lock = lock
        self.lowest = float(lowest)
        self.growth = float(growth)
        self._counts = [0] * (buckets + 1)  # + overflow
        self._sum = 0.0
        self._count = 0
        # Precomputed upper bounds; index search is log-free and branchless
        # enough for a hot path without importing math under the lock.
        self._bounds = [lowest * growth ** i for i in range(buckets)]

    def observe(self, v: float) -> None:
        v = float(v)
        # Bound search outside the lock: bounds are immutable after init.
        idx = len(self._bounds)
        for i, b in enumerate(self._bounds):
            if v <= b:
                idx = i
                break
        with self._lock:
            self._counts[idx] += 1
            self._sum += v
            self._count += 1


class MetricsRegistry:
    """Process-local instrument registry with atomic snapshots.

    One lock for everything it owns: instruments share it (so ``snapshot``
    reads every value in one critical section with no nested acquisition),
    and a single lock is structurally immune to lock-order inversion.
    Instruments are created once and cached by ``(name, labels)`` — calling
    ``counter("served_total", priority="high")`` twice returns the same
    object, so call sites can re-resolve instead of threading references.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    # ------------------------------------------------------------------ #

    def _get(self, name: str, factory, labels: dict):
        key = series_name(name, tuple(sorted(labels.items())))
        # Fast path: dict reads are atomic under the GIL, but the candidate
        # may be mid-insert on another thread — resolve under the lock.
        with self._lock:
            inst = self._metrics.get(key)
            if inst is None:
                inst = factory()
                self._metrics[key] = inst
        return inst

    def counter(self, name: str, **labels) -> Counter:
        inst = self._get(name, lambda: Counter(self._lock), labels)
        if not isinstance(inst, Counter):
            raise TypeError(f"{name!r} already registered as {inst.kind}")
        return inst

    def gauge(self, name: str, **labels) -> Gauge:
        inst = self._get(name, lambda: Gauge(self._lock), labels)
        if not isinstance(inst, Gauge):
            raise TypeError(f"{name!r} already registered as {inst.kind}")
        return inst

    def histogram(self, name: str, lowest: float = 1.0, growth: float = 2.0,
                  buckets: int = 20, **labels) -> Histogram:
        inst = self._get(
            name,
            lambda: Histogram(self._lock, lowest, growth, buckets),
            labels,
        )
        if not isinstance(inst, Histogram):
            raise TypeError(f"{name!r} already registered as {inst.kind}")
        return inst

    # ------------------------------------------------------------------ #

    def snapshot(self) -> dict:
        """Atomic copy of every instrument: one lock hold, plain dicts out.

        ``{"counters": {series: value}, "gauges": {series: value},
        "histograms": {series: {count, sum, lowest, growth, buckets}}}`` —
        JSON-ready, so the same shape flows into ``metrics_snapshot``
        records, the Prometheus renderer, and the fleet merge.
        """
        counters, gauges, histograms = {}, {}, {}
        with self._lock:
            for key, inst in self._metrics.items():
                if isinstance(inst, Counter):
                    counters[key] = inst._value
                elif isinstance(inst, Gauge):
                    gauges[key] = inst._value
                else:
                    histograms[key] = {
                        "count": inst._count,
                        "sum": round(inst._sum, 6),
                        "lowest": inst.lowest,
                        "growth": inst.growth,
                        "buckets": list(inst._counts),
                    }
        return {"counters": counters, "gauges": gauges,
                "histograms": histograms}

    def to_prometheus(self) -> str:
        return snapshot_to_prometheus(self.snapshot())


class _NullInstrument:
    """Stands in for every instrument kind when metrics are disabled."""

    kind = "null"
    value = 0.0

    def inc(self, n: float = 1.0) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def add(self, n: float) -> None:
        pass

    def observe(self, v: float) -> None:
        pass


_NULL_INSTRUMENT = _NullInstrument()


class NullRegistry:
    """Disabled metrics plane: hands out shared no-op instruments so call
    sites resolve-and-use unconditionally — the off-path the ≤3% overhead
    gate in ``scripts/perf_gate.py`` compares against."""

    def counter(self, name: str, **labels) -> _NullInstrument:
        return _NULL_INSTRUMENT

    def gauge(self, name: str, **labels) -> _NullInstrument:
        return _NULL_INSTRUMENT

    def histogram(self, name: str, lowest: float = 1.0, growth: float = 2.0,
                  buckets: int = 20, **labels) -> _NullInstrument:
        return _NULL_INSTRUMENT

    def snapshot(self) -> dict:
        return {"counters": {}, "gauges": {}, "histograms": {}}

    def to_prometheus(self) -> str:
        return ""


# --------------------------------------------------------------------------- #
# Pure functions over snapshots (lock-free by construction)
# --------------------------------------------------------------------------- #


def _split_series(series: str) -> Tuple[str, str]:
    """``name{k="v"}`` -> ``(name, 'k="v"')``; bare names get ``""``."""
    if series.endswith("}") and "{" in series:
        name, _, rest = series.partition("{")
        return name, rest[:-1]
    return series, ""


def histogram_bounds(h: dict) -> List[float]:
    """Finite upper bounds of a snapshot histogram (overflow excluded)."""
    n = len(h["buckets"]) - 1
    return [h["lowest"] * h["growth"] ** i for i in range(n)]


def histogram_quantile(h: dict, q: float) -> float:
    """Quantile estimate from a snapshot histogram: the upper bound of the
    bucket where the cumulative count crosses ``q`` (the overflow bucket
    reports the largest finite bound — the estimate saturates rather than
    inventing an unbounded number)."""
    total = h["count"]
    if total <= 0:
        return 0.0
    bounds = histogram_bounds(h)
    target = q * total
    cum = 0
    for i, c in enumerate(h["buckets"]):
        cum += c
        if cum >= target:
            return bounds[min(i, len(bounds) - 1)]
    return bounds[-1]


def merge_histograms(a: dict, b: dict) -> dict:
    """Element-wise merge of two equal-layout snapshot histograms."""
    if (a["lowest"], a["growth"], len(a["buckets"])) != (
            b["lowest"], b["growth"], len(b["buckets"])):
        raise ValueError("cannot merge histograms with different layouts")
    return {
        "count": a["count"] + b["count"],
        "sum": round(a["sum"] + b["sum"], 6),
        "lowest": a["lowest"],
        "growth": a["growth"],
        "buckets": [x + y for x, y in zip(a["buckets"], b["buckets"])],
    }


def merge_snapshots(snaps: List[dict]) -> dict:
    """Fold N snapshots into one aggregate: counters sum, histograms merge,
    gauges last-wins (levels from different processes do not add)."""
    out = {"counters": {}, "gauges": {}, "histograms": {}}
    for snap in snaps:
        for k, v in snap.get("counters", {}).items():
            out["counters"][k] = out["counters"].get(k, 0.0) + v
        for k, v in snap.get("gauges", {}).items():
            out["gauges"][k] = v
        for k, h in snap.get("histograms", {}).items():
            prev = out["histograms"].get(k)
            out["histograms"][k] = h if prev is None else merge_histograms(
                prev, h)
    return out


def sum_series(table: dict, name: str) -> float:
    """Sum every series of ``name`` across its label sets."""
    return sum(v for k, v in table.items() if _split_series(k)[0] == name)


def snapshot_to_prometheus(snap: dict) -> str:
    """Render a snapshot as Prometheus text exposition (v0.0.4).

    Histograms render the standard cumulative ``_bucket{le=...}`` series
    plus ``_sum``/``_count``; the scraper reconstructs per-bucket counts by
    differencing, and equal ``le`` ladders merge associatively.
    """
    lines: List[str] = []
    typed = set()

    def _type(name: str, kind: str) -> None:
        if name not in typed:
            typed.add(name)
            lines.append(f"# TYPE {name} {kind}")

    for series, value in snap.get("counters", {}).items():
        _type(_split_series(series)[0], "counter")
        lines.append(f"{series} {_fmt(value)}")
    for series, value in snap.get("gauges", {}).items():
        _type(_split_series(series)[0], "gauge")
        lines.append(f"{series} {_fmt(value)}")
    for series, h in snap.get("histograms", {}).items():
        name, labels = _split_series(series)
        _type(name, "histogram")
        prefix = f"{name}_bucket{{{labels + ',' if labels else ''}"
        cum = 0
        for bound, c in zip(histogram_bounds(h), h["buckets"]):
            cum += c
            lines.append(f'{prefix}le="{_fmt(bound)}"}} {cum}')
        lines.append(f'{prefix}le="+Inf"}} {h["count"]}')
        suffix = f"{{{labels}}}" if labels else ""
        lines.append(f"{name}_sum{suffix} {_fmt(h['sum'])}")
        lines.append(f"{name}_count{suffix} {h['count']}")
    return "\n".join(lines) + "\n"


def _fmt(v: float) -> str:
    """Float format without spurious exponent/trailing noise: integral
    values render as integers so counter lines stay exact."""
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


# --------------------------------------------------------------------------- #
# MetricsPump: registry -> JSONL records + heartbeat digest
# --------------------------------------------------------------------------- #


class MetricsPump:
    """Daemon thread flushing periodic ``metrics_snapshot`` records.

    Each flush takes one atomic registry snapshot, derives per-second rates
    against the previous flush, logs the record through the sink (append-
    mode JSONL — never while holding any lock), and pushes the progress
    digest (``DIGEST_SERIES``) into the heartbeat.  ``stop()`` joins the
    thread and flushes one final snapshot so a clean exit never loses the
    tail of the series.
    """

    def __init__(self, registry: MetricsRegistry, sink, interval_s: float = 10.0,
                 source: str = "train", heartbeat=None):
        self.registry = registry
        self.sink = sink
        self.interval_s = float(interval_s)
        self.source = source
        self.heartbeat = heartbeat
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._seq = 0
        self._last_mono = 0.0
        self._last_counters: Dict[str, float] = {}

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="cil-metrics-pump", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=self.interval_s + 5.0)
            self._thread = None
        self.flush()  # final snapshot: the freshest possible series tail

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.flush()

    def flush(self) -> None:
        snap = self.registry.snapshot()
        now = time.monotonic()
        with self._lock:
            self._seq += 1
            seq = self._seq
            prev_mono, prev = self._last_mono, self._last_counters
            self._last_mono, self._last_counters = now, snap["counters"]
        rates: Dict[str, float] = {}
        dt = now - prev_mono
        if prev_mono > 0 and dt > 0:
            rates = {
                k: round((v - prev.get(k, 0.0)) / dt, 6)
                for k, v in snap["counters"].items()
            }
        # Sink + heartbeat writes run with an empty lockset: the JSONL
        # append and the heartbeat's tmp+replace both block on disk.
        self.sink.log(
            "metrics_snapshot",
            source=self.source,
            seq=seq,
            interval_s=self.interval_s,
            counters=snap["counters"],
            gauges=snap["gauges"],
            histograms=snap["histograms"],
            rates=rates,
        )
        if self.heartbeat is not None:
            digest = {}
            for series, (abs_field, rate_field) in DIGEST_SERIES.items():
                present = any(_split_series(k)[0] == series
                              for k in snap["counters"])
                if present:
                    total = sum_series(snap["counters"], series)
                    digest[abs_field] = round(total, 3)
                    digest[rate_field] = round(
                        sum(r for k, r in rates.items()
                            if _split_series(k)[0] == series), 3)
            if digest:
                self.heartbeat.update(**digest)
