"""Unified telemetry: spans, recompile/HBM/stall counters, heartbeat, CIL
metrics.

The reference's only output channel is rank-0 stdout; this package gives the
task loop the observability a TPU-scale system treats as table stakes — see
the module docstrings of :mod:`.spans`, :mod:`.counters`, :mod:`.heartbeat`,
:mod:`.cil_metrics`.  Everything funnels into the one :class:`~..utils.
logging.Sink` record vocabulary validated by
``scripts/check_telemetry_schema.py`` and rendered by
``scripts/report_run.py``.

:class:`Telemetry` is the facade the engine threads through the loop; with no
``telemetry_dir``/``heartbeat_path`` configured every call is a no-op, so the
hot path carries no conditional clutter.
"""

from __future__ import annotations

import os
from typing import Optional

from ..utils.logging import NullSink, Sink
from .cil_metrics import (  # noqa: F401
    AccuracyMatrix,
    average_incremental_accuracy,
    backward_transfer,
    per_task_forgetting,
)
from .compilewatch import CompileWatch  # noqa: F401
from .counters import RecompileMonitor, StallClock, clocked, hbm_stats  # noqa: F401
from .flight import FlightRecorder, FlightSink  # noqa: F401
from .heartbeat import Heartbeat, read_heartbeat  # noqa: F401
from .metrics import (  # noqa: F401
    MetricsPump,
    MetricsRegistry,
    NullRegistry,
    histogram_quantile,
    merge_histograms,
    merge_snapshots,
    snapshot_to_prometheus,
)
from .spans import SpanTracer, coverage, load_spans  # noqa: F401


class Telemetry:
    """One handle over the telemetry subsystem, built from config flags.

    * ``telemetry_dir`` — spans land in ``<dir>/spans.jsonl`` (plus a
      Chrome-trace export at close); default heartbeat location; the flight
      recorder dumps to ``<dir>/flight_{process_index}.json``.
    * ``heartbeat_path`` — overrides the heartbeat file location (can be
      enabled without a telemetry dir, e.g. just for the watchdog).
    * ``sink`` — where counter and metric *records* go; the engine passes
      its experiment ``JsonlLogger`` so one JSONL stream carries the whole
      run (sink unification).  With a telemetry dir the facade wraps it in a
      :class:`FlightSink`, so every record also lands in the crash-forensics
      ring — the engine reads the wrapped sink back from ``self.sink``.
    * ``flight_events`` — ring capacity (``--flight_events``; 0 disables).

    Every sub-component is process-aware: process identity is resolved once
    here (``jax.process_index()`` when distributed, 0 otherwise) and pushed
    down, so a pod writes one stream *per process* instead of silencing all
    but process 0 (the pre-PR 6 behaviour).
    """

    def __init__(
        self,
        telemetry_dir: Optional[str] = None,
        heartbeat_path: Optional[str] = None,
        heartbeat_interval_s: float = 15.0,
        sink: Optional[Sink] = None,
        flight_events: int = 256,
        process_index: Optional[int] = None,
        process_count: Optional[int] = None,
        metrics: bool = True,
        metrics_interval_s: float = 10.0,
        metrics_source: str = "train",
    ):
        self.dir = telemetry_dir
        self.sink = sink or NullSink()
        self.flight: Optional[FlightRecorder] = None
        if process_index is None and (telemetry_dir or heartbeat_path):
            import jax

            process_index = jax.process_index()
            process_count = jax.process_count()
        process_index = int(process_index or 0)
        process_count = int(process_count or 1)
        if telemetry_dir:
            os.makedirs(telemetry_dir, exist_ok=True)
            if heartbeat_path is None:
                heartbeat_path = os.path.join(telemetry_dir, "heartbeat.json")
            if flight_events > 0:
                import socket

                self.flight = FlightRecorder(
                    os.path.join(
                        telemetry_dir, f"flight_{process_index}.json"
                    ),
                    capacity=flight_events,
                    process_index=process_index,
                    process_count=process_count,
                    host_id=socket.gethostname(),
                )
                self.flight.install()
                self.sink = FlightSink(self.sink, self.flight)
        self.spans = SpanTracer(
            os.path.join(telemetry_dir, "spans.jsonl") if telemetry_dir else None,
            process_index=process_index,
            process_count=process_count,
            flight=self.flight,
        )
        self.heartbeat = Heartbeat(
            heartbeat_path,
            heartbeat_interval_s,
            process_index=process_index,
            process_count=process_count,
            flight=self.flight,
        )
        self.recompiles = RecompileMonitor(self.sink)
        self.matrix = AccuracyMatrix()
        # Metrics plane: the registry is cheap enough to keep on by default
        # (one shared lock; pre-resolved instruments); metrics=False swaps
        # in no-op instruments so the hot path stays branch-free either way.
        # The pump only runs when its output goes somewhere — a real sink
        # (metrics_snapshot records) or an enabled heartbeat (progress
        # digest for the supervisor's stall probe).
        self.metrics = MetricsRegistry() if metrics else NullRegistry()
        self.pump: Optional[MetricsPump] = None
        real_sink = sink is not None and not isinstance(sink, NullSink)
        if metrics and (self.heartbeat.enabled or real_sink):
            self.pump = MetricsPump(
                self.metrics,
                self.sink,
                interval_s=metrics_interval_s,
                source=metrics_source,
                heartbeat=self.heartbeat,
            )
            self.pump.start()

    @property
    def enabled(self) -> bool:
        return self.spans.enabled or self.heartbeat.enabled

    def span(self, name: str, **attrs):
        return self.spans.span(name, **attrs)

    def log_hbm(self, **attrs) -> None:
        """Sample per-device memory at a task boundary (no-op on XLA:CPU,
        which reports no memory statistics — absence over invented zeros)."""
        stats = hbm_stats()
        if stats:
            self.sink.log("hbm", devices=stats, **attrs)

    def close(self) -> None:
        """End of run: stop the heartbeat thread (final beat), export the
        Perfetto-compatible trace next to the span JSONL, and leave a final
        flight dump (then unhook the death paths, so tests that build many
        Telemetry objects in one process don't stack handlers)."""
        if self.pump is not None:
            # Final metrics flush (and heartbeat digest) before the
            # heartbeat writes its last beat below.
            self.pump.stop()
        self.heartbeat.stop()
        if self.spans.enabled:
            # export_chrome_trace applies process_suffixed itself: process 0
            # keeps trace.json, process i writes trace_p{i}.json.
            self.spans.export_chrome_trace(os.path.join(self.dir, "trace.json"))
        if self.flight is not None:
            self.flight.dump("close")
            self.flight.uninstall()
