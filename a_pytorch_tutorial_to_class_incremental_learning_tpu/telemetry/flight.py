"""Flight recorder: a bounded in-memory ring of the last N telemetry events,
dumped to disk on every death path.

The main sink (``utils.logging.JsonlLogger``) is durable for everything it
managed to write, but a crash tells its story in what was *about* to be
written: the span still open, the heartbeat that never landed, the fault that
fired one line before SIGKILL.  This module keeps the last ``capacity``
span/counter/heartbeat/fault events in a ring buffer and writes them to
``flight_{process_index}.json`` whenever the process is dying:

* **fatal exception** — ``sys.excepthook`` wrapper (dump, then chain to the
  previous hook so the traceback still prints),
* **SIGTERM** — handler dumps, restores the previous disposition and
  re-delivers the signal so the exit status stays ``killed by SIGTERM``,
* **atexit** — clean exits leave a final dump too (it is the *steady-state*
  forensic artifact: Podracer-style supervisors treat kill-and-relaunch as
  the normal lifecycle, so crash-time observability must be always on),
* **injected kill** — ``faults.FaultInjector`` accepts an ``on_fatal``
  callback the engine points at :meth:`FlightRecorder.fatal_dump`, invoked
  after the ledger write but before ``os.kill(SIGKILL)`` (SIGKILL itself is
  uncatchable),
* **heartbeat cadence** — ``telemetry.Heartbeat`` calls :meth:`dump` on every
  beat, so even an uncatchable death (OOM-killer, power loss) leaves a dump
  at most half a heartbeat interval stale.

Python signal handlers run between bytecodes on the main thread — no
async-signal-safety minefield — and every dump is an atomic same-directory
``os.replace`` so ``scripts/supervise.py`` never harvests a torn file.

Stdlib-only on purpose: the dump path must work exactly when the process is
least healthy, so it must not touch jax (process identity is passed in by the
:class:`~.Telemetry` facade, which already resolved it for the sink).
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import sys
import threading
import time
from collections import deque
from typing import List, Optional

from ..utils.logging import Sink


class FlightRecorder:
    """Ring buffer of recent telemetry events + the open-span stack.

    ``record(event)`` is O(1) and lock-guarded (the heartbeat daemon thread
    and the training loop both feed it).  ``dump(reason)`` snapshots the ring
    and the spans currently open and atomically writes one ``flight_dump``
    JSON record — schema-checked like every other record this repo emits.
    """

    def __init__(
        self,
        path: str,
        capacity: int = 256,
        process_index: int = 0,
        process_count: int = 1,
        host_id: Optional[str] = None,
    ):
        self.path = path
        self.capacity = int(capacity)
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self.host_id = host_id
        self._events: deque = deque(maxlen=self.capacity)
        self._open_spans: List[dict] = []
        self._lock = threading.Lock()
        # Serializes the publish step (freeze re-check + os.replace) so a
        # periodic dump that snapshotted *before* a fatal dump can never
        # overwrite the forensic file *after* it.  Acquisition order is
        # always _io_lock -> _lock, never the reverse (jaxlint JL303); the
        # slow tmp-file write happens under neither (JL304).
        self._io_lock = threading.Lock()
        self._seq = 0          # total events ever recorded (dropped = seq - len)
        self._fatal = False    # a fatal dump already captured the death state
        #                        (guarded by _lock; jaxlint JL305)
        self._installed = False
        self._prev_excepthook = None
        self._prev_sigterm = None

    # ------------------------------------------------------------------ #
    # Recording
    # ------------------------------------------------------------------ #

    def record(self, event: dict) -> None:
        with self._lock:
            self._seq += 1
            self._events.append(event)

    def span_open(self, name: str, span_id: int, depth: int, **attrs) -> None:
        entry = {"name": name, "span_id": span_id, "depth": depth, **attrs}
        with self._lock:
            self._open_spans.append(entry)
            self._seq += 1
            self._events.append({
                # ring-internal forensic event, never written through the
                # sink — not part of the schema vocabulary by design
                "type": "span_open",  # jaxlint: disable=JL501
                "ts": round(time.time(), 3),
                **entry,
            })

    def span_close(self, span_id: int) -> None:
        with self._lock:
            self._open_spans = [
                s for s in self._open_spans if s["span_id"] != span_id
            ]

    def open_spans(self) -> List[dict]:
        with self._lock:
            return [dict(s) for s in self._open_spans]

    # ------------------------------------------------------------------ #
    # Dumping
    # ------------------------------------------------------------------ #

    def dump(self, reason: str = "periodic") -> Optional[dict]:
        """Periodic/close dump: atomically write the current tail as a
        ``flight_dump`` record; returns the payload (None when skipped or the
        write failed — a full disk while dying must not mask the original
        death).  A no-op once a fatal dump captured the death state: the
        heartbeat daemon keeps running for a few ms after an injected kill's
        dump, and its cadence dump must not overwrite the forensic tail."""
        return self._write_dump(reason, fatal=False)

    def fatal_dump(self, reason: str = "fatal") -> Optional[dict]:
        """Death-path dump (injected kill, SIGTERM, unhandled exception):
        freezes the on-disk tail — later periodic/atexit dumps are skipped so
        the post-mortem artifact is the state *at death*."""
        return self._write_dump(reason, fatal=True)

    def _write_dump(self, reason: str, fatal: bool = False) -> Optional[dict]:
        with self._lock:
            # The freeze gate and flag live under the lock: dump() runs on
            # the heartbeat daemon while fatal_dump() runs on whichever
            # thread is dying (jaxlint JL305 flagged the bare flag).
            if self._fatal and not fatal:
                return None
            if fatal:
                self._fatal = True
            events = list(self._events)
            open_spans = [dict(s) for s in self._open_spans]
            seq = self._seq
        payload = {
            "type": "flight_dump",
            "ts": round(time.time(), 3),
            "reason": reason,
            "pid": os.getpid(),
            "process_index": self.process_index,
            "process_count": self.process_count,
            "capacity": self.capacity,
            "dropped": max(0, seq - len(events)),
            "events": events,
            "open_spans": open_spans,
            "last_open_span": open_spans[-1]["name"] if open_spans else None,
        }
        if self.host_id is not None:
            payload["host_id"] = self.host_id
        tmp = f"{self.path}.tmp.{os.getpid()}.{threading.get_ident()}"
        try:
            os.makedirs(
                os.path.dirname(os.path.abspath(self.path)), exist_ok=True
            )
            with open(tmp, "w") as f:
                json.dump(payload, f)
                f.flush()
                os.fsync(f.fileno())
            # Atomic publish: re-check the freeze under _io_lock so the
            # ordering "fatal dump replaced the file" -> "every later
            # periodic replace is suppressed" is airtight even when this
            # dump snapshotted before the fatal one landed.
            with self._io_lock:
                with self._lock:
                    frozen = self._fatal and not fatal
                if frozen:
                    os.unlink(tmp)
                    return None
                os.replace(tmp, self.path)  # jaxlint: disable=JL402 -- self.path is per-process by construction: the telemetry facade names it flight_{process_index}.json, and the supervisor's flight_*.json harvest glob depends on exactly that naming
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
        return payload

    # ------------------------------------------------------------------ #
    # Death-path installation
    # ------------------------------------------------------------------ #

    def install(self) -> None:
        """Hook the fatal-exception, SIGTERM and atexit paths (idempotent)."""
        if self._installed:
            return
        self._installed = True

        self._prev_excepthook = sys.excepthook

        def _hook(exc_type, exc, tb):
            self.fatal_dump(f"exception:{exc_type.__name__}")
            (self._prev_excepthook or sys.__excepthook__)(exc_type, exc, tb)

        sys.excepthook = _hook

        def _on_sigterm(signum, frame):
            self.fatal_dump("sigterm")
            # Restore the previous disposition and re-deliver so the exit
            # status the supervisor sees is still "killed by SIGTERM".
            signal.signal(signal.SIGTERM, self._prev_sigterm or signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)

        try:
            self._prev_sigterm = signal.signal(signal.SIGTERM, _on_sigterm)
        except ValueError:
            self._prev_sigterm = None  # not the main thread: skip the handler

        atexit.register(self._atexit_dump)

    def _atexit_dump(self) -> None:
        self.dump("atexit")  # the freeze gate in _write_dump handles fatal

    def uninstall(self) -> None:
        """Undo :meth:`install` (facade close; also keeps tests that build
        many Telemetry objects in one process from stacking hooks)."""
        if not self._installed:
            return
        self._installed = False
        if self._prev_excepthook is not None:
            sys.excepthook = self._prev_excepthook
        try:
            signal.signal(signal.SIGTERM, self._prev_sigterm or signal.SIG_DFL)
        except ValueError:
            pass  # not the main thread; install() never hooked it either
        atexit.unregister(self._atexit_dump)


class FlightSink(Sink):
    """Tee sink: every record goes to the wrapped sink *and* the flight ring.

    The engine rebinds ``self.jsonl`` to this wrapper, so everything the run
    emits (epoch/task/fault/recompile records) is in the crash tail without
    any call site changing.  Unknown attributes delegate to the inner sink —
    ``utils/checkpoint.py`` duck-types the trainer's logger (``.log`` only
    today, but delegation keeps the wrapper transparent).
    """

    def __init__(self, inner: Sink, flight: FlightRecorder):
        self.inner = inner
        self.flight = flight

    def log(self, record_type: str, **fields) -> None:
        self.flight.record({
            "type": record_type, "ts": round(time.time(), 3), **fields,
        })
        self.inner.log(record_type, **fields)

    def __getattr__(self, name):
        return getattr(self.inner, name)
