"""Compile-time accounting via ``jax.monitoring`` duration events.

``RecompileMonitor`` (telemetry/counters.py) counts *traces* — how many times
a jitted wrapper's cache grew.  This module prices what each trace actually
*cost*: XLA fires a ``/jax/core/compile/backend_compile_duration`` event for
every backend compilation, and — when the persistent compilation cache
(utils/platform.enable_compile_cache) serves the executable — an additional
``/jax/compilation_cache/cache_retrieval_time_sec`` event whose duration is
essentially the whole "compile".  The real XLA work of a window is therefore

    compile_s  =  Σ backend_compile_duration  −  Σ cache_retrieval_time

which is ≈0 for a warm-cache resume: that number is what the ``compile_event``
telemetry record carries per task-growth event and what
``scripts/perf_gate.py --compile`` gates against BASELINE.json.

``jax.monitoring`` listeners cannot be unregistered, so the watch is a
process-wide singleton; readers take :meth:`snapshot` deltas around the
window they care about (task's first epoch, artifact AOT load, ...).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

_BACKEND_COMPILE = "/jax/core/compile/backend_compile_duration"
_CACHE_RETRIEVAL = "/jax/compilation_cache/cache_retrieval_time_sec"


class CompileWatch:
    """Process-wide accumulator of XLA compile / cache-retrieval durations."""

    _instance: Optional["CompileWatch"] = None
    _instance_lock = threading.Lock()

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.backend_compile_s = 0.0
        self.cache_retrieval_s = 0.0
        self.compiles = 0
        self.cache_hits = 0
        import jax.monitoring

        jax.monitoring.register_event_duration_secs_listener(self._on_event)

    @classmethod
    def install(cls) -> "CompileWatch":
        """Idempotent: one listener per process, however many callers."""
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    # Listener signature: (event_name, duration_secs, **kwargs).  Never raise
    # from here — this runs inside every jit compile in the process.
    def _on_event(self, event: str, duration: float, **_kw) -> None:
        with self._lock:
            if event == _BACKEND_COMPILE:
                self.backend_compile_s += float(duration)
                self.compiles += 1
            elif event == _CACHE_RETRIEVAL:
                self.cache_retrieval_s += float(duration)
                self.cache_hits += 1

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {
                "backend_compile_s": self.backend_compile_s,
                "cache_retrieval_s": self.cache_retrieval_s,
                "compiles": self.compiles,
                "cache_hits": self.cache_hits,
            }

    @staticmethod
    def delta(before: Dict[str, float], after: Dict[str, float]) -> Dict[str, float]:
        """Window accounting between two snapshots.

        ``compile_s`` is the net XLA work: backend time minus the share the
        persistent cache served (clamped at 0 — retrieval bookkeeping can
        slightly exceed the reported backend duration on a fully warm load).
        """
        backend = after["backend_compile_s"] - before["backend_compile_s"]
        retrieval = after["cache_retrieval_s"] - before["cache_retrieval_s"]
        return {
            "compile_s": round(max(0.0, backend - retrieval), 4),
            "backend_compile_s": round(backend, 4),
            "cache_retrieval_s": round(retrieval, 4),
            "compiles": int(after["compiles"] - before["compiles"]),
            "cache_hits": int(after["cache_hits"] - before["cache_hits"]),
        }
