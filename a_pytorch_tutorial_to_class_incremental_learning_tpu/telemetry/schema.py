"""The telemetry record vocabulary: one table, three consumers.

Single source of truth for every record type the unified sink can emit
(``utils.logging.JsonlLogger`` via ``engine/loop.py`` and the telemetry
package), plus the span file and the heartbeat file.  Consumed by:

* ``scripts/check_telemetry_schema.py`` — the CI lint over committed
  evidence logs (imports this table instead of carrying its own copy);
* ``analysis/contracts.py`` — contractlint's JL501/JL502 cross-artifact
  pass (emitted-type vs schema, consumer-field vs schema), which parses
  this file's AST so the lint stage stays stdlib-only;
* ``analysis/contractcheck.py`` — the ``--check_contracts`` runtime
  sentinel validating live record types at emit time.

Deliberately dependency-free and importable standalone (the lint scripts
load it by file path with ``importlib`` so they never trigger the package
``__init__`` — which would drag in jax).  Keep it that way: constants and
pure functions only.
"""

from __future__ import annotations

NUM = (int, float)

# type -> (required {field: pytypes}, optional {field: pytypes}, extras)
# extras: None = no undeclared fields allowed; "any" = any extra field;
# "numeric" = extra fields allowed if numeric (the epoch record carries
# whatever meters the train step emits).
SCHEMA = {
    "run": ({"data_set": str, "backbone": str, "seed": NUM}, {}, "any"),
    "resume": (
        {"start_task": NUM},
        {"start_epoch": NUM, "path": str, "kind": str},
        None,
    ),
    # Fault injection (faults/injector.py): one record per fired clause.
    # reconciled=True marks a step-level clause settled at the fused-epoch
    # boundary (reconcile_steps) rather than live at the per-batch site.
    "fault_injected": (
        {"site": str, "action": str, "spec": str},
        {"task": NUM, "epoch": NUM, "step": NUM, "reconciled": bool},
        None,
    ),
    # ThreadCheck sentinel (analysis/threadcheck.py, --check_threads): a
    # lock-order inversion or lock-held blocking call observed at runtime.
    # kind is lock_order_inversion (lock/other/witness set) or
    # lock_held_blocking (call set); the chaos/serve smokes fail on any.
    "thread_violation": (
        {"kind": str, "thread": str, "site": str},
        {"lock": str, "other": str, "witness": str, "call": str,
         "held": list},
        None,
    ),
    # ContractSentinel (analysis/contractcheck.py, --check_contracts): a
    # live record type or metric instrument name that the committed
    # contract registry (analysis/contract_registry.json) does not know —
    # the dynamically-constructed drift the static JL501/JL505 pass cannot
    # see.  kind is unknown_record_type / unknown_record_field /
    # unknown_metric / metric_label_drift; the chaos/serve smokes fail on
    # any.
    "contract_violation": (
        {"kind": str, "name": str},
        {"field": str, "detail": str, "labels": list},
        None,
    ),
    # Lockstep sentinel (analysis/lockstep.py, --check_lockstep): one
    # fingerprint record per imminent train/eval dispatch.  unit is the
    # dispatch site (train_step/train_epoch_fused/eval_step/feature_step),
    # hash covers the cross-process-compared fields; digest/rng/step/task/
    # epoch are present when the site provides them (None fields are
    # stripped before logging).
    "lockstep_fingerprint": (
        {"unit": str, "program": str, "seq": NUM, "hash": str},
        {"arg_sig": str, "digest": str, "rng": list, "step": NUM,
         "task": NUM, "epoch": NUM},
        None,
    ),
    # A process observed the fleet diverging (or a peer dead) at a dispatch
    # boundary.  kind is fingerprint_mismatch (fields/mine/theirs name the
    # disagreement) or peer_timeout (deadline_s elapsed with no peer
    # fingerprint); emitted on every live process before any collective
    # could hang, alongside a flight-recorder fatal dump.
    "lockstep_violation": (
        {"kind": str, "unit": str, "seq": NUM, "peer": NUM},
        {"fields": list, "mine": dict, "theirs": dict, "deadline_s": NUM,
         "step": NUM, "task": NUM, "epoch": NUM, "program": str},
        None,
    ),
    # Prefetch producer death -> synchronous-path degradation
    # (data/prefetch.py on_degrade hook, wired in engine/loop.py).
    "prefetch_degraded": (
        {"where": str, "error": str},
        {"task_id": NUM, "epoch": NUM},
        None,
    ),
    # A checkpoint save failed transiently; the run continued (durability
    # gap, logged so the evidence trail shows it).
    "ckpt_save_error": (
        {"error": str},
        {"path": str, "task_id": NUM, "epoch": NUM},
        None,
    ),
    # Restore skipped an invalid (truncated/corrupt) checkpoint and fell
    # back to the next-newest valid candidate.
    "ckpt_fallback": ({"skipped": str, "reason": str}, {}, None),
    "epoch": (
        {"task_id": NUM, "epoch": NUM, "lr": NUM},
        {
            "epoch_s": NUM,
            "host_s": NUM,
            "device_s": NUM,
            "stall_frac": NUM,
        },
        "numeric",
    ),
    "task": (
        {
            "task_id": NUM,
            "acc1": NUM,
            "acc1s": list,
            "nb_new": NUM,
            "known_after": NUM,
            "seconds": NUM,
        },
        {"gamma": (int, float, type(None)), "acc_per_task": list},
        None,
    ),
    "final": (
        {"acc1s": list, "avg_incremental_acc1": NUM},
        {
            "nb_tasks": NUM,
            "forgetting": (list, type(None)),
            "bwt": (int, float, type(None)),
            "partial": bool,
            "tasks": list,
        },
        None,
    ),
    "cil_metrics": (
        {"task_id": NUM, "avg_incremental_acc1": NUM},
        {
            "nb_tasks": NUM,
            "forgetting": (list, type(None)),
            "bwt": (int, float, type(None)),
            "partial": bool,
            "tasks": list,
        },
        None,
    ),
    "hbm": ({"devices": dict}, {"task_id": NUM}, None),
    "profile_trace": (
        {"path": str},
        {"task_id": NUM, "name": str},
        None,
    ),
    "recompile": (
        {
            "where": str,
            "new_programs": NUM,
            "total_programs": NUM,
            "expected": bool,
        },
        {"group": str, "task_id": NUM, "epoch": NUM},
        None,
    ),
    "recompile_warning": (
        {"where": str, "new_programs": NUM, "total_programs": NUM},
        {"group": str, "task_id": NUM, "epoch": NUM},
        None,
    ),
    # RecompileSentinel (analysis/runtime.py): trace-budget verdict at every
    # check point — programs compiled vs the budget granted by task-growth /
    # restore events.
    "recompile_budget": (
        {"where": str, "budget": NUM, "programs": NUM, "events": NUM,
         "ok": bool},
        {"group": str, "task_id": NUM},
        None,
    ),
    # Compile-cost accounting (telemetry/compilewatch.py): net XLA work in a
    # window — a task's first executed epoch (engine/loop.py) or a serving
    # replica's AOT load (serving/replica.py, source="replica").  compile_s
    # is backend compile time minus the share the persistent compilation
    # cache served; ≈0 on a warm-cache resume, which is what
    # scripts/perf_gate.py --compile and scripts/warmcache_smoke.py assert.
    "compile_event": (
        {"task_id": NUM, "compile_s": NUM, "backend_compile_s": NUM,
         "cache_retrieval_s": NUM, "compiles": NUM, "cache_hits": NUM},
        {"epoch": NUM, "resumed": bool, "source": str},
        None,
    ),
    # Next-task device warm-start (engine/loop.py _warm_next_task): outcome
    # of consuming the ring armed during the previous task's eval/herd
    # window.  hit=True carries the placed bytes + how long the consumer
    # waited; hit=False carries why the warm path degraded to the
    # synchronous transfer (never fatal).
    "prefetch_warm": (
        {"task_id": NUM, "hit": bool},
        {"reason": str, "bytes": NUM, "wait_s": NUM, "warm_s": NUM},
        None,
    ),
    # bench.py --precision sweep: one record per run with a per-preset row
    # (step_ms, loss_finite, short accuracy probe) under `results`.
    "precision_ablation": (
        {"results": list},
        {"backend": str, "global_batch": NUM, "iters": NUM, "metric": str,
         "selective_not_slower": bool, "reduced_cpu_fallback": bool},
        None,
    ),
    # A fresh (non-resume) run archived the previous soak's spent fire
    # ledger so the --fault_spec re-armed (faults.rotate_ledger).
    "fault_ledger_rotated": ({"path": str, "archived": str}, {}, None),
    "span": (
        {"name": str, "span_id": NUM, "depth": NUM, "ts": NUM, "dur_s": NUM},
        {"parent": (int, float, type(None))},
        "any",  # span attrs (task=, epoch=, ...) ride along freely
    ),
    "heartbeat": (
        {"ts": NUM, "seq": NUM, "pid": NUM},
        {
            "mono": NUM,  # monotonic stamp for cross-process clock alignment
            "step": NUM,
            "task": NUM,
            "epoch": NUM,
            "phase": str,
            "last_step_ms": NUM,
            "age_s": NUM,
            "fresh": bool,
            # Registry progress digest (telemetry/metrics.py MetricsPump):
            # absolute counters + derived rates, so the supervisor's stall
            # probe can tell "alive but stalled" (fresh beat, frozen
            # counters) from "making progress" without scraping anything.
            "steps_total": NUM,
            "step_rate": NUM,
            "serve_requests_total": NUM,
            "serve_qps": NUM,
        },
        None,
    ),
    # Metrics-plane snapshot (telemetry/metrics.py MetricsPump): one atomic
    # registry copy per cadence.  counters/gauges map Prometheus-style
    # series names to values; histograms map them to exponential-bucket
    # payloads ({count, sum, lowest, growth, buckets}); rates carries the
    # per-second counter deltas vs the previous flush.
    "metrics_snapshot": (
        {"source": str, "counters": dict, "gauges": dict,
         "histograms": dict},
        {"seq": NUM, "interval_s": NUM, "rates": dict, "replica": NUM,
         "up": dict},
        None,
    ),
    # SLO burn-rate alert (scripts/metrics_agent.py): multi-window burn-rate
    # evaluation tripped — the error budget is burning `burn_rate` times
    # faster than the objective allows over both the long and short window.
    "slo_burn": (
        {"slo": str, "burn_rate": NUM, "threshold": NUM, "window_s": NUM},
        {"severity": str, "short_window_s": NUM, "short_burn_rate": NUM,
         "objective": NUM, "bad": NUM, "total": NUM},
        None,
    ),
    # Flight recorder (telemetry/flight.py): the ring-buffer tail dumped on
    # every death path (and each heartbeat).  `events` holds raw sink/span/
    # heartbeat records — they are forensic payload, not re-validated here
    # (a crash tail legitimately contains torn or partial records).
    "flight_dump": (
        {"reason": str, "pid": NUM, "events": list},
        {
            "capacity": NUM,
            "dropped": NUM,
            "open_spans": list,
            "last_open_span": (str, type(None)),
        },
        None,
    ),
    # Supervisor harvest (scripts/supervise.py): flight dumps + heartbeats +
    # fault ledger gathered into one artifact before each relaunch.
    "crash_report": (
        {"returncode": NUM, "hung": bool, "attempt": NUM},
        {
            "uptime_s": NUM,
            "telemetry_dir": str,
            "flight_dumps": list,
            "heartbeats": list,
            "fault_ledger": list,
        },
        None,
    ),
    # Serving (serving/ + engine/loop.py export hook).  One serve_export per
    # task with --export_dir: either the artifact landed (path/known/...) or
    # the export failed and training continued (error).
    "serve_export": (
        {"task_id": NUM},
        {"path": str, "known": NUM, "buckets": list, "seconds": NUM,
         "error": str},
        None,
    ),
    # A successful artifact (hot-)swap; from_task is None for the initial
    # load at server start.
    "serve_swap": (
        {"from_task": (int, float, type(None)), "to_task": NUM,
         "load_ms": NUM, "compile_ms": NUM, "path": str},
        {},
        None,
    ),
    # A swap attempt failed (corrupt artifact, injected IOError): the server
    # kept the current artifact and will retry at the next manifest poll.
    "serve_swap_failed": ({"task_id": NUM, "error": str}, {}, None),
    # Training/serving skew (serving/skew.py): accuracy re-measured through
    # the exported artifact vs the trainer's accuracy row.  Zero skew is the
    # healthy state — the exported program is the same computation.
    "serve_skew": (
        {"task_id": NUM, "served_acc1": NUM, "served_acc_per_task": list,
         "n": NUM},
        {"train_acc_per_task": (list, type(None)),
         "skew_abs_max": (int, float, type(None))},
        None,
    ),
    # Front-end admission control (serving/frontend.py): a request was
    # rejected at admission.  Rate-limited (~2/s per class) with shed_total
    # carrying the cumulative count, so overload does not amplify itself
    # through its own telemetry.
    "serve_shed": (
        {"priority": str, "queued": NUM, "capacity": NUM},
        {"shed_total": NUM},
        None,
    ),
    # Fleet health transitions (serving/health.py): event is "eject" (the
    # consecutive-error breaker tripped, or the replica's heartbeat went
    # stale) or "readmit" (the out-of-band warm probe passed).
    "replica_ejected": (
        {"replica": NUM, "event": str, "reason": str},
        {"consecutive_errors": NUM, "heartbeat_age_s": NUM},
        None,
    ),
    # A skew-gated swap was refused and the replica kept (rolled back to)
    # its previous artifact; emitted by the replica's swap_to and by the
    # front end's rollout driver when a wave halts.
    "serve_rollback": (
        {"task_id": NUM, "rolled_back_to": (int, float, type(None)),
         "reason": str},
        {"replica": NUM, "probe_max_abs": NUM, "probe_checked": bool},
        None,
    ),
    # One failed dispatch attempt inside a request's failover chain
    # (serving/frontend.py); the request itself may still succeed.
    "frontend_retry": (
        {"replica": NUM, "attempt": NUM, "error": str},
        {},
        None,
    ),
    # Rolling latency window from the inference server's batcher.
    "serve_latency": (
        {"count": NUM, "p50_ms": NUM, "p95_ms": NUM, "p99_ms": NUM,
         "throughput_rps": NUM},
        {"bucket_occupancy": NUM, "batches": NUM, "task_id": NUM},
        None,
    ),
}

# Every JsonlLogger record carries a writer timestamp; spans/heartbeats
# stamp their own.  "ts" is therefore universally required.
ALWAYS_REQUIRED = {"ts": NUM}

# Process-identity tags every record may carry since PR 6 (JsonlLogger
# stamps all three; spans/heartbeats stamp process_index): optional so the
# committed pre-fleet evidence logs stay valid.
ALWAYS_OPTIONAL = {
    "process_index": NUM,
    "process_count": NUM,
    "host_id": str,
}


def known_fields(rtype: str) -> frozenset:
    """Every field name a record of ``rtype`` may legally carry (``type``
    included); empty frozenset for an unknown type."""
    spec = SCHEMA.get(rtype)
    if spec is None:
        return frozenset()
    required, optional, _ = spec
    return frozenset(required) | frozenset(optional) | \
        frozenset(ALWAYS_REQUIRED) | frozenset(ALWAYS_OPTIONAL) | {"type"}


def check_record(rec: dict, where: str) -> list:
    """Validate one record dict; returns a list of violation strings."""
    errs = []
    rtype = rec.get("type")
    if rtype not in SCHEMA:
        return [f"{where}: unknown record type {rtype!r}"]
    required, optional, extras = SCHEMA[rtype]
    required = {**ALWAYS_REQUIRED, **required}
    optional = {**ALWAYS_OPTIONAL, **optional}
    for field, types in required.items():
        if field not in rec:
            errs.append(f"{where}: {rtype} record missing required {field!r}")
        elif not isinstance(rec[field], types):
            errs.append(
                f"{where}: {rtype}.{field} has type "
                f"{type(rec[field]).__name__}, want {types}"
            )
    for field, value in rec.items():
        if field == "type" or field in required:
            continue
        if field in optional:
            if not isinstance(value, optional[field]):
                errs.append(
                    f"{where}: {rtype}.{field} has type "
                    f"{type(value).__name__}, want {optional[field]}"
                )
        elif extras is None:
            errs.append(f"{where}: {rtype} record has undeclared field {field!r}")
        elif extras == "numeric" and not isinstance(value, NUM):
            errs.append(
                f"{where}: {rtype} extra field {field!r} must be numeric, "
                f"got {type(value).__name__}"
            )
    return errs
