"""Class-incremental learning metrics from the task x task accuracy matrix.

Row ``t`` of the matrix is the per-slice top-1 after training task ``t``
(column ``j`` = task ``j``'s own val slice, the same slicing the reference's
cumulative eval builds on, template.py:229).  From it the standard continual
-learning decomposition (Chaudhry et al., Lopez-Paz & Ranzato):

* **average incremental accuracy** — mean of the cumulative top-1 after each
  task (the reference's headline number, template.py:225);
* **forgetting** per slice ``j`` — best accuracy any earlier row achieved on
  ``j`` minus the final row's accuracy on ``j`` (how much of task ``j`` was
  lost, wherever the peak was);
* **backward transfer (BWT)** — mean over ``j < T-1`` of final minus
  diagonal accuracy (signed: negative = forgetting, positive = later tasks
  improved earlier ones).

The same math backs ``engine/loop.py``'s per-task ``cil_metrics`` records and
``scripts/report_run.py``'s rendering, so the two can never disagree.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence


def average_incremental_accuracy(acc1s: Sequence[float]) -> float:
    """Mean cumulative top-1 over tasks (reference template.py:225)."""
    return float(sum(acc1s) / len(acc1s)) if acc1s else 0.0


def per_task_forgetting(matrix: Sequence[Sequence[float]]) -> Optional[List[float]]:
    """``f_j = max_{t in [j, T-2]} A[t][j] - A[T-1][j]`` for ``j < T-1``.

    None for a matrix with fewer than two complete rows (nothing can have
    been forgotten yet).
    """
    T = len(matrix)
    if T < 2 or any(len(matrix[t]) != t + 1 for t in range(T)):
        return None
    final = matrix[T - 1]
    return [
        round(max(matrix[t][j] for t in range(j, T - 1)) - final[j], 5)
        for j in range(T - 1)
    ]


def backward_transfer(matrix: Sequence[Sequence[float]]) -> Optional[float]:
    """``BWT = mean_{j < T-1} (A[T-1][j] - A[j][j])`` — signed, negative
    means net forgetting.  None below two complete rows."""
    T = len(matrix)
    if T < 2 or any(len(matrix[t]) != t + 1 for t in range(T)):
        return None
    final = matrix[T - 1]
    return round(
        sum(final[j] - matrix[j][j] for j in range(T - 1)) / (T - 1), 5
    )


class AccuracyMatrix:
    """Incrementally built lower-triangular task x task accuracy matrix.

    The loop appends one row per trained task; ``summary()`` derives the
    metrics valid *at that point* (after task t the matrix's first t+1 rows
    are a complete protocol prefix, so forgetting/BWT are well defined for
    it).  Rows are keyed by task id so a resumed run starting mid-protocol
    degrades to partial=True instead of silently computing wrong metrics —
    the same rule ``scripts/summarize_results.py`` enforces when rendering.
    """

    def __init__(self):
        self.rows: Dict[int, List[float]] = {}

    def add_row(self, task_id: int, acc_per_task: Sequence[float]) -> None:
        if len(acc_per_task) != task_id + 1:
            raise ValueError(
                f"row for task {task_id} must have {task_id + 1} slice "
                f"accuracies, got {len(acc_per_task)}"
            )
        self.rows[task_id] = [float(a) for a in acc_per_task]

    @property
    def complete(self) -> bool:
        """True when rows 0..T-1 are all present (no mid-protocol resume
        into a fresh process without the earlier rows)."""
        return bool(self.rows) and sorted(self.rows) == list(
            range(max(self.rows) + 1)
        )

    def as_list(self) -> List[List[float]]:
        return [self.rows[t] for t in sorted(self.rows)]

    def summary(self) -> dict:
        if not self.complete:
            return {"partial": True, "tasks": sorted(self.rows)}
        m = self.as_list()
        return {
            "nb_tasks": len(m),
            "forgetting": per_task_forgetting(m),
            "bwt": backward_transfer(m),
        }
