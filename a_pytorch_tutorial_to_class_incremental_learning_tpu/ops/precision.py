"""Selective mixed-precision policy layer (ROADMAP item 3).

One :class:`Policy` object names the three dtype knobs the model/engine stack
actually has, instead of the single ``--compute_dtype`` blanket:

* ``compute_dtype`` — the dtype conv/matmul *compute* runs in (the Flax module
  ``dtype``: ``promote_dtype`` casts operands at the op boundary, so on TPU a
  bf16 compute_dtype lands the contraction on the MXU in its native precision).
* ``act_dtype`` — the dtype activations *flow between ops* in.  With
  ``act_dtype == float32`` every conv output is cast back up, so the
  numerically sensitive pointwise work (BatchNorm arithmetic, ReLU, residual
  adds, average pooling) accumulates in f32 while the matmuls stay bf16.
* ``head_dtype`` — the operand dtype of the classifier head matmul.  The
  output (logits) is always accumulated to f32 via ``preferred_element_type``.

Everything else is **not** a knob; it is the policy layer's contract,
regardless of preset:

* master parameters and optimizer momentum are float32 (``PARAM_DTYPE``) —
  Flax params are created f32 and the SGD update never downcasts them;
* BatchNorm running statistics are float32 (``STAT_DTYPE``);
* logits handed to the losses are float32 (``LOGITS_DTYPE``);
* the CE / KD loss accumulation is float32 (``LOSS_DTYPE``) — WA's knowledge
  distillation term (arXiv:1911.07053) divides by a temperature-scaled
  softmax, exactly the place bf16's 8-bit mantissa visibly hurts.

Presets
-------
``f32``
    Everything float32.  The accuracy reference.
``bf16_all``
    The pre-policy ``--compute_dtype bfloat16`` behavior, bit-for-bit:
    compute *and* activations bf16 (so BN arithmetic, residual adds and
    pooling all round to bf16 between ops).  Measured ~7 points of average
    incremental accuracy below f32 on the synthetic_hard128 protocol
    (RESULTS.md) — kept as a named preset precisely so that cost stays
    priced, not as a recommendation.
``bf16_selective``
    The default candidate: bf16 conv/matmul compute and a bf16 head matmul
    (with f32 accumulation), f32 everything else.  Casts are applied at the
    matmul boundary, not the parameter store — params stay f32 and each
    compiled program casts them on the way into the contraction.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, FrozenSet

import jax.numpy as jnp

# The policy layer's fixed points (see module docstring).  These are
# deliberately constants, not Policy fields: a preset that downcast any of
# them would be the exact hazard jaxlint JL104 exists to flag.
PARAM_DTYPE = jnp.float32   # master params + optimizer momentum
STAT_DTYPE = jnp.float32    # BatchNorm running statistics
LOGITS_DTYPE = jnp.float32  # logits as seen by the losses
LOSS_DTYPE = jnp.float32    # CE / KD accumulation


@dataclasses.dataclass(frozen=True)
class Policy:
    """A named selective-precision configuration (see module docstring)."""

    name: str
    compute_dtype: Any  # conv/matmul compute (Flax module dtype)
    act_dtype: Any      # inter-op activation flow
    head_dtype: Any     # classifier head matmul operands

    @property
    def jax_compute_dtype(self):
        return self.compute_dtype

    def describe(self) -> Dict[str, str]:
        """JSON-friendly summary for telemetry/provenance records."""
        return {
            "name": self.name,
            "compute_dtype": jnp.dtype(self.compute_dtype).name,
            "act_dtype": jnp.dtype(self.act_dtype).name,
            "head_dtype": jnp.dtype(self.head_dtype).name,
            "param_dtype": jnp.dtype(PARAM_DTYPE).name,
            "logits_dtype": jnp.dtype(LOGITS_DTYPE).name,
        }


PRESETS: Dict[str, Policy] = {
    "f32": Policy(
        "f32",
        compute_dtype=jnp.float32, act_dtype=jnp.float32,
        head_dtype=jnp.float32,
    ),
    "bf16_all": Policy(
        "bf16_all",
        compute_dtype=jnp.bfloat16, act_dtype=jnp.bfloat16,
        head_dtype=jnp.float32,
    ),
    "bf16_selective": Policy(
        "bf16_selective",
        compute_dtype=jnp.bfloat16, act_dtype=jnp.float32,
        head_dtype=jnp.bfloat16,
    ),
}

# --compute_dtype is kept as an alias flag (config.py); these are its two
# legal values mapped onto the preset table.
_COMPUTE_DTYPE_ALIASES = {
    "float32": "f32",
    "bfloat16": "bf16_all",
}


def get_policy(name: str) -> Policy:
    """Preset name (or ``--compute_dtype`` alias) -> :class:`Policy`."""
    key = _COMPUTE_DTYPE_ALIASES.get(name, name)
    try:
        return PRESETS[key]
    except KeyError:
        raise ValueError(
            f"unknown precision policy {name!r}; "
            f"choose from {sorted(PRESETS)}"
        ) from None


def policy_from_config(config) -> Policy:
    """Resolve the run's policy from a CilConfig (or anything duck-typed).

    ``--precision`` wins when set; otherwise the legacy ``--compute_dtype``
    alias keeps old command lines and checkpointed configs working.
    """
    precision = getattr(config, "precision", "") or ""
    if precision:
        return get_policy(precision)
    return get_policy(getattr(config, "compute_dtype", "float32"))


# --------------------------------------------------------------------------- #
# Policy-compatible kernel registry
# --------------------------------------------------------------------------- #
# Custom kernels (Pallas and friends) opt in per policy: a kernel is
# *policy-compatible* when its numerics honor the contract above (f32 loss
# accumulation over f32 logits) under that policy's activation/compute dtypes.
# The registry keeps the armed-but-unused kernels honest — bench/tests consult
# it instead of assuming.

_KERNEL_REGISTRY: Dict[str, FrozenSet[str]] = {}


def register_policy_kernel(kernel_name: str, *policy_names: str) -> None:
    """Declare ``kernel_name`` numerically valid under the named presets."""
    for p in policy_names:
        if p not in PRESETS:
            raise ValueError(f"unknown policy {p!r} for kernel {kernel_name!r}")
    _KERNEL_REGISTRY[kernel_name] = frozenset(policy_names)


def kernel_policies(kernel_name: str) -> FrozenSet[str]:
    """The policies a kernel is registered for (empty set = unregistered)."""
    return _KERNEL_REGISTRY.get(kernel_name, frozenset())


def kernel_policy_compatible(kernel_name: str, policy: Policy) -> bool:
    return policy.name in _KERNEL_REGISTRY.get(kernel_name, frozenset())
