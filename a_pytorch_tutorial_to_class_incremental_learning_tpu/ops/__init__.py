"""Custom TPU ops (Pallas kernels).

The compute path of this framework is XLA-compiled Flax (SURVEY.md §2c: at
CIFAR-ResNet scale XLA fusion is already near peak), so Pallas is reserved
for ops where generic fusion demonstrably leaves passes on the table — the
fused masked-CE loss block is the reference pattern.
"""

from .fused_loss import (  # noqa: F401
    fused_masked_cross_entropy,
    sharded_fused_masked_cross_entropy,
)
from .precision import (  # noqa: F401
    PRESETS,
    Policy,
    get_policy,
    kernel_policy_compatible,
    policy_from_config,
    register_policy_kernel,
)
