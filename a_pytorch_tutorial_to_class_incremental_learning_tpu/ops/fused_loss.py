"""Pallas TPU kernel: fused masked cross-entropy with label smoothing.

The train step's loss block over ``[B, width]`` masked logits
(``engine/losses.cross_entropy``) lowers in XLA to several elementwise/reduce
passes (mask, max, exp-sum, gather, smoothing-sum).  This kernel fuses the
whole thing into one VMEM-resident pass per batch tile — forward produces the
per-sample loss, and a custom VJP computes ``dlogits = p - target`` in a
second single pass, never materializing intermediate ``[B, width]`` arrays in
HBM.

Numerically identical semantics to the reference's
``CrossEntropyLoss(label_smoothing=s)`` over the active slice
(reference ``template.py:219,259``): masked columns hold ``NEG_INF`` so the
softmax is exactly the active-slice softmax; the smoothing target is
``(1-s)·one-hot + s/num_active`` over active columns.

Usage is optional (``CilConfig.use_pallas_loss``): the default path relies on
XLA fusion, which at CIFAR scale is already near peak — this kernel exists
for wide-head regimes (the loss block scales with ``B × width`` while the
backbone does not) and as the framework's Pallas reference pattern.  Both
paths are tested against each other (interpret mode on CPU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..models.classifier import NEG_INF

LANE = 128  # TPU lane width: last-dim blocks must be multiples of this


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


# --------------------------------------------------------------------------- #
# Kernels (one batch tile per grid step)
# --------------------------------------------------------------------------- #


def _fwd_kernel(num_active_ref, logits_ref, labels_ref, loss_ref, *, smoothing):
    x = logits_ref[:]  # [Bt, Wp] f32, inactive columns already NEG_INF
    labels = labels_ref[:]  # [Bt, 1] i32
    num_active = num_active_ref[0]

    col = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    active = col < num_active

    m = jnp.max(x, axis=1, keepdims=True)
    e = jnp.exp(x - m)
    lse = m[:, 0] + jnp.log(jnp.sum(e, axis=1))
    logp = x - lse[:, None]

    onehot = col == labels
    nll = -jnp.sum(jnp.where(onehot, logp, 0.0), axis=1)
    if smoothing:
        smooth = -jnp.sum(jnp.where(active, logp, 0.0), axis=1) / num_active.astype(
            x.dtype
        )
        loss = (1.0 - smoothing) * nll + smoothing * smooth
    else:
        loss = nll
    loss_ref[:] = loss[:, None]


def _bwd_kernel(num_active_ref, logits_ref, labels_ref, g_ref, dx_ref, *, smoothing):
    x = logits_ref[:]
    labels = labels_ref[:]
    g = g_ref[:]  # [Bt, 1] upstream cotangent per sample
    num_active = num_active_ref[0]

    col = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    active = col < num_active

    m = jnp.max(x, axis=1, keepdims=True)
    e = jnp.exp(x - m)
    p = e / jnp.sum(e, axis=1, keepdims=True)  # masked cols: exactly 0

    onehot = (col == labels).astype(x.dtype)
    target = (1.0 - smoothing) * onehot
    if smoothing:
        target = target + jnp.where(active, smoothing / num_active.astype(x.dtype), 0.0)
    dx_ref[:] = (p - target) * g


# --------------------------------------------------------------------------- #
# Host-side wrapper with custom VJP
# --------------------------------------------------------------------------- #


def _pad_logits(logits: jax.Array) -> jax.Array:
    wp = _round_up(logits.shape[1], LANE)
    if wp == logits.shape[1]:
        return logits
    # NEG_INF padding is exactly the masking convention: padded columns carry
    # zero probability and zero gradient.
    return jnp.pad(logits, ((0, 0), (0, wp - logits.shape[1])),
                   constant_values=NEG_INF)


def _call(kernel, out_shape, num_active, logits, labels, *extra, interpret):
    import math

    b, wp = logits.shape
    # Largest tile <= 256 that divides the batch (any b works; odd batches
    # just get smaller tiles).
    bt = math.gcd(b, 256)
    grid = (b // bt,)
    extra_specs = [
        pl.BlockSpec((bt, 1), lambda i: (i, 0), memory_space=pltpu.VMEM)
        for _ in extra
    ]
    return pl.pallas_call(
        kernel,
        grid=grid,
        out_shape=out_shape,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # num_active [1]
            pl.BlockSpec((bt, wp), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bt, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            *extra_specs,
        ],
        out_specs=pl.BlockSpec((bt, out_shape.shape[1]), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(num_active, logits, labels, *extra)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_masked_cross_entropy(
    logits: jax.Array,
    labels: jax.Array,
    num_active: jax.Array,
    label_smoothing: float = 0.0,
    interpret: bool = False,
) -> jax.Array:
    """Mean masked CE with label smoothing, fused in one Pallas pass.

    Same contract as ``engine.losses.cross_entropy`` (without sample
    weights).  ``interpret=True`` runs the kernel in the Pallas interpreter
    (any backend — used by the CPU test suite).
    """
    loss, _ = _fwd(logits, labels, num_active, label_smoothing, interpret)
    return loss


def _fwd(logits, labels, num_active, label_smoothing, interpret):
    b = logits.shape[0]
    padded = _pad_logits(logits.astype(jnp.float32))
    na = jnp.asarray(num_active, jnp.int32).reshape(1)
    lab = labels.astype(jnp.int32).reshape(b, 1)
    per = _call(
        functools.partial(_fwd_kernel, smoothing=label_smoothing),
        jax.ShapeDtypeStruct((b, 1), jnp.float32),
        na,
        padded,
        lab,
        interpret=interpret,
    )
    return per[:, 0].mean(), (logits, labels, num_active)


def _bwd(label_smoothing, interpret, residuals, g):
    logits, labels, num_active = residuals
    b, w = logits.shape
    padded = _pad_logits(logits.astype(jnp.float32))
    na = jnp.asarray(num_active, jnp.int32).reshape(1)
    lab = labels.astype(jnp.int32).reshape(b, 1)
    gcol = jnp.full((b, 1), g / b, jnp.float32)  # d(mean)/d(per-sample)
    dx = _call(
        functools.partial(_bwd_kernel, smoothing=label_smoothing),
        jax.ShapeDtypeStruct((b, padded.shape[1]), jnp.float32),
        na,
        padded,
        lab,
        gcol,
        interpret=interpret,
    )
    return dx[:, :w].astype(logits.dtype), None, None


fused_masked_cross_entropy.defvjp(_fwd, _bwd)


def sharded_fused_masked_cross_entropy(
    mesh,
    logits: jax.Array,
    labels: jax.Array,
    num_active: jax.Array,
    label_smoothing: float = 0.0,
    interpret: bool = False,
) -> jax.Array:
    """Multi-device form of :func:`fused_masked_cross_entropy`.

    Mosaic kernels cannot be auto-partitioned by XLA, so on a mesh the kernel
    is wrapped in ``shard_map``: each device runs the fused pass over its own
    batch stripe (full head width — XLA all-gathers the ``model``-sharded
    columns into the shard, exactly what the softmax needs), and the equal
    per-shard means are combined with one scalar ``pmean`` over the data
    axis.  Differentiable: the custom VJP runs per shard, the cotangent of
    ``pmean`` distributes the upstream 1/num_shards factor.
    """
    from ..parallel.mesh import DATA_AXIS
    from jax.sharding import PartitionSpec as P

    def body(lg, lb, na):
        local = fused_masked_cross_entropy(
            lg, lb, na, label_smoothing, interpret
        )
        return jax.lax.pmean(local, DATA_AXIS)

    return jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P()),
        out_specs=P(),
        check_vma=False,  # pallas_call has no replication rule
    )(logits, labels, num_active)


# The kernel casts its logits to f32 at entry (``_fwd``/``_bwd`` pad in f32)
# and accumulates the loss in f32 — the ops/precision LOSS_DTYPE contract —
# so it is numerically valid under every preset, including bf16_selective
# where the surrounding matmuls run bf16.  Registration keeps the
# armed-but-optional kernel priced into the policy layer (engine/train.py
# consults this before enabling the Pallas path).
from .precision import register_policy_kernel  # noqa: E402

register_policy_kernel(
    "fused_masked_cross_entropy", "f32", "bf16_all", "bf16_selective"
)
