"""CIFAR ResNet backbone as a Flax module (TPU-native, NHWC layout).

Behavioural counterpart of the reference backbone (reference ``resnet.py:9-159``):
a 3x3 stem conv -> BN -> ReLU, three stages of basic blocks at widths 16/32/64
with strides 1/2/2, an 8x8 average pool and a flatten to a 64-d feature vector;
depth must be 6n+2.  The residual shortcut is "option A" (reference
``resnet.py:9-17``): a stride-2 1x1 average pool (i.e. spatial subsampling)
followed by channel doubling via concatenation with zeros — no learned
projection.

TPU-first design notes (not a port):

* NHWC layout throughout — the native layout for XLA:TPU convolutions; the
  reference's NCHW is a CUDA convention.
* Initialization matches the reference numerically: conv weights are drawn
  from ``Normal(0, sqrt(2 / (kh*kw*out_ch)))`` (reference ``resnet.py:82-85``),
  BatchNorm starts at scale=1 / bias=0 (``resnet.py:86-88``).
* BatchNorm statistics are computed over the **global** (sharded) batch when
  the step is jitted over a mesh — XLA inserts the cross-device reductions.
  The reference uses per-replica statistics (DDP without SyncBN); global
  statistics are the idiomatic and slightly better-behaved choice on TPU
  (SURVEY.md §7 item 2).
* ``compute_dtype`` allows bfloat16 activations so convs land on the MXU in
  its native precision; parameters and BN statistics stay float32.
* ``act_dtype`` (ops/precision.py) decouples the inter-op activation dtype
  from the conv compute dtype: under the ``bf16_selective`` policy convs
  compute in bf16 (operands cast at the matmul boundary by Flax's
  ``promote_dtype``) but their outputs are cast back to f32, so BatchNorm
  arithmetic, ReLU, residual adds and the average pool all run in f32.
  ``act_dtype=None`` means "same as dtype", which makes every new cast a
  no-op and keeps the ``f32``/``bf16_all`` presets bit-identical to the
  pre-policy behavior.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

from .norm import GroupedBatchNorm

# Matches torch's ``weight.data.normal_(0, sqrt(2/n))`` with
# n = kh*kw*out_channels (reference resnet.py:83-85): variance-scaling with
# scale 2.0 over fan-out; "normal" here is the untruncated normal with
# stddev sqrt(2/fan_out), exactly torch's normal_.
he_normal_torchlike = nn.initializers.variance_scaling(2.0, "fan_out", "normal")


class DownsampleA(nn.Module):
    """Option-A shortcut: spatial stride-2 subsample + zero-channel concat.

    Reference ``resnet.py:9-17``: ``AvgPool2d(kernel_size=1, stride=2)`` is
    exactly a ``x[:, ::2, ::2, :]`` subsample in NHWC, and the channel count
    doubles by concatenating a zero tensor.  Parameter-free.
    """

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = x[:, ::2, ::2, :]
        return jnp.concatenate([x, jnp.zeros_like(x)], axis=-1)


def _norm(
    bn_group_size: int, train: bool, dtype, name: str
) -> Callable[[jax.Array], jax.Array]:
    """BatchNorm constructor: global-batch statistics by default, fixed-size
    group statistics (the reference's per-replica BN, SURVEY.md §7 item 2)
    when ``bn_group_size > 0``.  Both variants share parameter/stat names, so
    checkpoints and teachers are interchangeable."""
    if bn_group_size > 0:
        gbn = GroupedBatchNorm(
            group_size=bn_group_size,
            momentum=0.9,
            epsilon=1e-5,
            dtype=dtype,
            name=name,
        )
        return lambda x: gbn(x, use_running_average=not train)
    return nn.BatchNorm(
        use_running_average=not train,
        momentum=0.9,
        epsilon=1e-5,
        dtype=dtype,
        name=name,
    )


class BasicBlock(nn.Module):
    """conv3x3-BN-ReLU-conv3x3-BN + shortcut, post-add ReLU.

    Reference ``resnet.py:20-53``.  ``downsample=True`` selects the option-A
    shortcut (set on the first block of stages 2/3).
    """

    planes: int
    stride: int = 1
    downsample: bool = False
    dtype: Any = jnp.float32
    bn_group_size: int = 0
    act_dtype: Any = None  # None = same as dtype (casts below are no-ops)

    @nn.compact
    def __call__(self, x: jax.Array, train: bool) -> jax.Array:
        act = self.dtype if self.act_dtype is None else self.act_dtype
        residual = x
        y = nn.Conv(
            self.planes,
            (3, 3),
            strides=(self.stride, self.stride),
            padding=1,
            use_bias=False,
            kernel_init=he_normal_torchlike,
            dtype=self.dtype,
            name="conv_a",
        )(x)
        y = _norm(self.bn_group_size, train, act, "bn_a")(y.astype(act))
        y = nn.relu(y)
        y = nn.Conv(
            self.planes,
            (3, 3),
            strides=(1, 1),
            padding=1,
            use_bias=False,
            kernel_init=he_normal_torchlike,
            dtype=self.dtype,
            name="conv_b",
        )(y)
        y = _norm(self.bn_group_size, train, act, "bn_b")(y.astype(act))
        if self.downsample:
            residual = DownsampleA(name="shortcut")(x)
        return nn.relu(residual + y)


class CifarResNet(nn.Module):
    """6n+2 CIFAR ResNet producing a pooled feature vector.

    ``__call__`` returns the flattened ``[B, 64]`` feature (the reference
    backbone's only output, ``resnet.py:107-116``); classification heads live
    in :class:`~..models.classifier.CilClassifier`.
    """

    depth: int = 32
    channels: int = 3  # 1 for the MNIST variants (reference resnet.py:127-139)
    dtype: Any = jnp.float32
    bn_group_size: int = 0  # 0 = global-batch BN; e.g. 128 = per-replica parity
    act_dtype: Any = None  # inter-op activation dtype; None = same as dtype

    @property
    def out_dim(self) -> int:
        return 64

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        assert (self.depth - 2) % 6 == 0, "depth should be one of 20, 32, 44, 56, 110"
        assert x.shape[-1] == self.channels, (
            f"expected {self.channels}-channel input (NHWC), got shape {x.shape}"
        )
        act = self.dtype if self.act_dtype is None else self.act_dtype
        n = (self.depth - 2) // 6
        x = x.astype(act)
        x = nn.Conv(
            16,
            (3, 3),
            strides=(1, 1),
            padding=1,
            use_bias=False,
            kernel_init=he_normal_torchlike,
            dtype=self.dtype,
            name="conv_1_3x3",
        )(x)
        x = _norm(self.bn_group_size, train, act, "bn_1")(x.astype(act))
        x = nn.relu(x)
        for stage, (planes, stride) in enumerate(((16, 1), (32, 2), (64, 2)), start=1):
            for i in range(n):
                first = i == 0
                x = BasicBlock(
                    planes=planes,
                    stride=stride if first else 1,
                    downsample=first and stage > 1,
                    dtype=self.dtype,
                    bn_group_size=self.bn_group_size,
                    act_dtype=self.act_dtype,
                    name=f"stage_{stage}_block_{i}",
                )(x, train=train)
        # Global 8x8 average pool + flatten -> [B, 64] feature vector
        # (reference resnet.py:79,114-116).
        x = jnp.mean(x, axis=(1, 2))
        return x.astype(jnp.float32)


def _factory(depth: int, channels: int = 3) -> Callable[..., CifarResNet]:
    def make(
        dtype: Any = jnp.float32, bn_group_size: int = 0, act_dtype: Any = None
    ) -> CifarResNet:
        return CifarResNet(
            depth=depth, channels=channels, dtype=dtype,
            bn_group_size=bn_group_size, act_dtype=act_dtype,
        )

    return make


# Factory table mirroring the reference's constructors (resnet.py:122-159)
# plus the backbone-flag dispatch (template.py:72-84).
resnet20 = _factory(20)
resnet32 = _factory(32)
resnet44 = _factory(44)
resnet56 = _factory(56)
resnet110 = _factory(110)
resnet10mnist = _factory(10, channels=1)
resnet20mnist = _factory(20, channels=1)
resnet32mnist = _factory(32, channels=1)

_BACKBONES = {
    "resnet20": resnet20,
    "resnet32": resnet32,
    "resnet44": resnet44,
    "resnet56": resnet56,
    "resnet110": resnet110,
    "resnet10mnist": resnet10mnist,
    "resnet20mnist": resnet20mnist,
    "resnet32mnist": resnet32mnist,
}


def get_backbone(
    name: str, dtype: Any = jnp.float32, bn_group_size: int = 0,
    act_dtype: Any = None,
) -> CifarResNet:
    """Flag-string -> backbone module (reference ``template.py:72-84``)."""
    try:
        return _BACKBONES[name](
            dtype=dtype, bn_group_size=bn_group_size, act_dtype=act_dtype
        )
    except KeyError:
        raise NotImplementedError(f"Unknown backbone {name}") from None
