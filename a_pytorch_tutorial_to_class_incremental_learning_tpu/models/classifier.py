"""Growable multi-head classifier re-designed for XLA: one static masked matrix.

The reference grows an ``nn.ModuleList`` of per-task ``Linear(64, k_t)`` heads
and concatenates their outputs (reference ``template.py:87-104``), which under
XLA would change the logits shape every task and force a recompile of the
train step.  TPU-first redesign (SURVEY.md §7 hard-part 1, option b): allocate
the full-width weight matrix ``[feat_dim, width]`` up front, treat the column
range ``[0, num_active)`` as the live classes, and mask the rest to a large
negative value.  ``num_active`` is a *traced* scalar, so a single compilation
serves the whole 10-task run; growth is a host-side in-place column
initialization, not a new module.

Because new classes always occupy the highest label indices (continuum's
label remapping, SURVEY.md #18), "the newest head" is exactly the column
slice ``[known, known+nb_new)`` — which makes weight alignment
(reference ``template.py:156-166``) a tiny pure function over column slices.

``width`` may be rounded up beyond ``nb_classes`` so the class dimension can
be sharded over a model axis of the mesh (padding columns are permanently
masked).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

# Finite stand-in for -inf: keeps softmax/top-k exact for the active columns
# without generating NaNs in masked reductions.
NEG_INF = -1e9


def round_up(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def torch_linear_init(
    key: jax.Array, feat_dim: int, nb_new: int
) -> Tuple[jax.Array, jax.Array]:
    """Per-head init matching ``torch.nn.Linear``'s default.

    The reference creates each head as a fresh ``nn.Linear`` (reference
    ``template.py:91,104``), whose default init is kaiming-uniform with
    a=sqrt(5): weight and bias both ~ U(-1/sqrt(fan_in), 1/sqrt(fan_in)).
    Returns ``(kernel_cols [feat_dim, nb_new], bias_cols [nb_new])``.
    """
    bound = 1.0 / (feat_dim ** 0.5)
    wk, bk = jax.random.split(key)
    kernel = jax.random.uniform(
        wk, (feat_dim, nb_new), minval=-bound, maxval=bound, dtype=jnp.float32
    )
    bias = jax.random.uniform(
        bk, (nb_new,), minval=-bound, maxval=bound, dtype=jnp.float32
    )
    return kernel, bias


def grow_head(
    fc_params: dict, key: jax.Array, known: int, nb_new: int
) -> dict:
    """Initialize the column slice for a new task's head.

    Equivalent of ``CilClassifier.adaption`` / lazy first-head construction
    (reference ``template.py:103-104,146-150``) without changing any array
    shape.  Host-side, once per task — never inside the compiled step.
    """
    kernel, bias = fc_params["kernel"], fc_params["bias"]
    feat_dim, width = kernel.shape
    if known + nb_new > width:
        raise ValueError(
            f"head overflow: known={known} + new={nb_new} > width={width}"
        )
    new_k, new_b = torch_linear_init(key, feat_dim, nb_new)
    kernel = kernel.at[:, known : known + nb_new].set(new_k)
    bias = bias.at[known : known + nb_new].set(new_b)
    return {"kernel": kernel, "bias": bias}


def masked_logits(
    features: jax.Array, fc_params: dict, num_active: jax.Array,
    head_dtype=None,
) -> jax.Array:
    """``[B, feat] -> [B, width]`` logits with columns >= num_active masked.

    The concat-of-heads forward (reference ``template.py:99-101``) collapses
    to one MXU-friendly matmul; masking replaces shape growth.

    ``head_dtype`` (ops/precision.py) casts the matmul *operands* — the f32
    master kernel is cast at the contraction boundary, never in the parameter
    store — while ``preferred_element_type`` keeps the accumulation and the
    logits themselves f32 (the policy layer's ``LOGITS_DTYPE`` contract: WA's
    alignment and the KD loss read these).
    """
    if head_dtype is not None and jnp.dtype(head_dtype) != jnp.float32:
        logits = jnp.matmul(
            features.astype(head_dtype),
            fc_params["kernel"].astype(head_dtype),
            preferred_element_type=jnp.float32,
        ) + fc_params["bias"]
    else:
        logits = features @ fc_params["kernel"] + fc_params["bias"]
    mask = jnp.arange(logits.shape[-1]) < num_active
    return jnp.where(mask, logits, NEG_INF)


def weight_align(
    fc_params: dict, known: int, nb_new: int
) -> Tuple[dict, jax.Array]:
    """The WA method: rescale the newest head to the old heads' mean norm.

    Reference ``CilModel.weight_align`` (``template.py:156-166``):
    per-class L2 norms of the stacked head weights, gamma =
    mean(old-class norms) / mean(new-class norms), newest head's weight
    (not bias) scaled by gamma.  Pure ``W -> W`` function; runs once per
    task on the host.  Returns ``(new_fc_params, gamma)``.
    """
    if known <= 0 or nb_new <= 0:
        # The reference gates alignment on task_id > 0 (template.py:152-154);
        # enforce the contract here too — known=0 would make gamma a NaN.
        raise ValueError(
            f"weight_align needs old and new classes (known={known}, nb_new={nb_new})"
        )
    kernel = fc_params["kernel"]
    norms = jnp.linalg.norm(kernel[:, : known + nb_new], axis=0)
    gamma = jnp.mean(norms[:known]) / jnp.mean(norms[known:])
    new_cols = kernel[:, known : known + nb_new] * gamma
    kernel = kernel.at[:, known : known + nb_new].set(new_cols)
    return {"kernel": kernel, "bias": fc_params["bias"]}, gamma
