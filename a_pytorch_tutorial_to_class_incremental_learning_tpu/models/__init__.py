"""Model layer: Flax CIFAR ResNet backbone + static masked CIL classifier.

L3/L4 of the layer map (SURVEY.md §1): the reference's ``resnet.py`` backbone
and ``CilModel``/``CilClassifier`` (reference ``template.py:87-166``),
re-designed shape-static for XLA (see ``classifier.py`` module docstring).
"""

from .resnet import (  # noqa: F401
    BasicBlock,
    CifarResNet,
    DownsampleA,
    get_backbone,
    resnet10mnist,
    resnet20,
    resnet20mnist,
    resnet32,
    resnet32mnist,
    resnet44,
    resnet56,
    resnet110,
)
from .classifier import (  # noqa: F401
    NEG_INF,
    grow_head,
    masked_logits,
    round_up,
    torch_linear_init,
    weight_align,
)
from .cil_model import (  # noqa: F401
    CilModel,
    align,
    create_model,
    freeze_mask,
    grow,
    init_backbone,
)
