"""The CIL model: backbone + static masked classifier, as a Flax module.

Counterpart of the reference ``CilModel`` (reference ``template.py:107-166``):
``forward(x) -> (logits, features)``, ``extract_vector`` = backbone features
only, per-task head growth, post-task weight alignment.  Differences that are
deliberate TPU-first design, not omissions:

* ``copy()``/``freeze()`` (reference ``template.py:125-144``) vanish: JAX
  pytrees are immutable, so the teacher snapshot is simply the variables
  pytree held at the end of the previous task — no deepcopy, no
  requires_grad bookkeeping.  Gradients never flow to the teacher because
  the loss is differentiated only with respect to the student's params.
* ``prev_model_adaption``/``after_model_adaption`` become pure functions over
  the variables pytree (:func:`grow`, :func:`align`), run host-side between
  tasks; array shapes never change, so the jitted train step compiles once.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn
from flax.core import freeze, unfreeze

from .classifier import grow_head, masked_logits, round_up, weight_align
from .resnet import get_backbone


class CilModel(nn.Module):
    """Backbone + full-width masked classification head.

    ``width`` is the static logits width: at least ``nb_classes``, optionally
    rounded up (e.g. to a multiple of the mesh model-axis) for sharding.
    """

    backbone_name: str = "resnet32"
    width: int = 100
    dtype: Any = jnp.float32
    bn_group_size: int = 0  # reference per-replica BN parity (models/norm.py)
    # Selective-precision knobs (ops/precision.py); None = same as dtype /
    # f32 head, which reproduces the pre-policy behavior exactly.
    act_dtype: Any = None
    head_dtype: Any = None

    def setup(self):
        self.backbone = get_backbone(
            self.backbone_name, dtype=self.dtype,
            bn_group_size=self.bn_group_size, act_dtype=self.act_dtype,
        )
        # Allocated zero; live columns are filled per task by `grow` with the
        # torch-Linear-equivalent init (classifier.py).
        self.fc_kernel = self.param(
            "fc_kernel",
            nn.initializers.zeros_init(),
            (self.backbone.out_dim, self.width),
        )
        self.fc_bias = self.param(
            "fc_bias", nn.initializers.zeros_init(), (self.width,)
        )

    def __call__(
        self, x: jax.Array, num_active: jax.Array, train: bool = False
    ) -> Tuple[jax.Array, jax.Array]:
        """``(images, num_active) -> (masked logits [B, width], features [B, 64])``.

        Reference ``CilModel.forward`` (``template.py:120-123``).
        """
        feats = self.backbone(x, train=train)
        fc = {"kernel": self.fc_kernel, "bias": self.fc_bias}
        return masked_logits(feats, fc, num_active, self.head_dtype), feats

    def extract_vector(self, x: jax.Array, train: bool = False) -> jax.Array:
        """Backbone features only (reference ``template.py:117-118``)."""
        return self.backbone(x, train=train)

    @property
    def feature_dim(self) -> int:
        return 64


# --------------------------------------------------------------------------- #
# Host-side lifecycle helpers (between-task, never inside the compiled step)
# --------------------------------------------------------------------------- #


def create_model(
    backbone_name: str,
    nb_classes: int,
    dtype: Any = jnp.float32,
    width_multiple: int = 1,
    input_size: int = 32,
    channels: int = 3,
    bn_group_size: int = 0,
    policy=None,
) -> Tuple[CilModel, dict]:
    """Build the module and its zero-head variables.

    Returns ``(model, variables)`` where ``variables`` holds ``params`` and
    ``batch_stats``.  The head starts fully inactive (``num_active=0``);
    :func:`grow` activates column ranges per task.

    ``policy`` (ops/precision.Policy) supersedes the bare ``dtype``: it sets
    the conv compute dtype plus the selective activation/head dtypes.  The
    bare ``dtype`` path is kept for callers predating the policy layer.
    """
    if policy is not None:
        dtype = policy.compute_dtype
        act_dtype, head_dtype = policy.act_dtype, policy.head_dtype
    else:
        act_dtype = head_dtype = None
    width = round_up(nb_classes, max(width_multiple, 1))
    model = CilModel(
        backbone_name=backbone_name, width=width, dtype=dtype,
        bn_group_size=bn_group_size, act_dtype=act_dtype,
        head_dtype=head_dtype,
    )
    dummy = jnp.zeros((1, input_size, input_size, channels), jnp.float32)
    variables = model.init(
        jax.random.PRNGKey(0), dummy, num_active=jnp.int32(0), train=False
    )
    return model, variables


def init_backbone(variables: dict, key: jax.Array, model: CilModel,
                  input_size: int = 32, channels: int = 3) -> dict:
    """Re-draw backbone params from ``key`` (the seeded experiment key).

    ``create_model`` uses a fixed key for shape inference; this replaces the
    backbone params with ones drawn from the experiment seed, leaving the
    (zero) head untouched.
    """
    dummy = jnp.zeros((1, input_size, input_size, channels), jnp.float32)
    fresh = model.init(key, dummy, num_active=jnp.int32(0), train=False)
    fresh = unfreeze(fresh)
    old = unfreeze(variables)
    fresh["params"]["fc_kernel"] = old["params"]["fc_kernel"]
    fresh["params"]["fc_bias"] = old["params"]["fc_bias"]
    return freeze(fresh)


def _get_fc(variables: dict) -> dict:
    return {
        "kernel": variables["params"]["fc_kernel"],
        "bias": variables["params"]["fc_bias"],
    }


def _set_fc(variables: dict, fc: dict) -> dict:
    v = unfreeze(variables)
    v["params"]["fc_kernel"] = fc["kernel"]
    v["params"]["fc_bias"] = fc["bias"]
    return freeze(v)


def grow(variables: dict, key: jax.Array, known: int, nb_new: int) -> dict:
    """Activate (initialize) the next task's head columns.

    Equivalent of ``prev_model_adaption`` (reference ``template.py:146-150``).
    """
    return _set_fc(variables, grow_head(_get_fc(variables), key, known, nb_new))


def freeze_mask(params: dict, names=("all",)) -> dict:
    """Boolean pytree marking frozen parameters (True = no updates).

    Counterpart of ``freeze_parameters`` / ``CilModel.freeze(names)``
    (reference ``template.py:61-69,128-144``): ``"fc"`` freezes the
    classifier head, ``"backbone"`` the feature extractor, ``"all"``
    everything.  In JAX "requires_grad" does not exist — the optimizer
    consumes this mask instead (``engine.sgd_update(frozen=...)``), and the
    teacher needs no mask at all because gradients are only ever taken with
    respect to the student.
    """
    valid = {"fc", "backbone", "all"}
    for name in names:
        if name not in valid:
            raise NotImplementedError(f"Unknown module name to freeze {name}")

    def mark(path, _leaf):
        top = getattr(path[0], "key", getattr(path[0], "name", str(path[0])))
        if "all" in names:
            return True
        if "fc" in names and top in ("fc_kernel", "fc_bias"):
            return True
        if "backbone" in names and top == "backbone":
            return True
        return False

    import jax.tree_util as jtu

    return jtu.tree_map_with_path(mark, params)


def align(variables: dict, known: int, nb_new: int) -> Tuple[dict, float]:
    """Post-task weight alignment; no-op gate lives with the caller.

    Equivalent of ``after_model_adaption`` -> ``weight_align``
    (reference ``template.py:152-166``).  Returns ``(variables, gamma)``.
    """
    fc, gamma = weight_align(_get_fc(variables), known, nb_new)
    return _set_fc(variables, fc), float(gamma)
