"""Grouped (per-replica-style) BatchNorm.

The reference trains with DDP and **no** SyncBN: every GPU normalizes its own
128-sample sub-batch (SURVEY.md §7 hard-part 2).  The default here is
global-batch statistics — the idiomatic choice under a jitted mesh program —
but exact replication of the reference's statistics is available by
normalizing in fixed-size groups along the batch axis: ``group_size=128``
reproduces per-GPU-128 BN regardless of how many devices the batch is
actually sharded over.  When groups align with device shards XLA keeps the
reductions device-local (no collectives), which is also a (minor) speedup.

Running averages aggregate the group statistics exactly the way N independent
torch replicas would: each replica updates its running stats from its own
batch stats, and DDP keeps replicas identical only because the *updates* are
identical after the initial broadcast — which holds only in expectation.
Here there is one set of running stats, updated with the mean over groups
(the ensemble average of the reference's per-replica stats).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn


class GroupedBatchNorm(nn.Module):
    """BatchNorm over fixed-size batch groups (``group_size=0`` = whole batch).

    Drop-in for ``nn.BatchNorm(use_running_average=...)`` in NHWC networks.
    """

    group_size: int = 0
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, use_running_average: bool) -> jax.Array:
        features = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones_init(), (features,))
        bias = self.param("bias", nn.initializers.zeros_init(), (features,))
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros(features, jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones(features, jnp.float32)
        )

        if use_running_average:
            y = (x.astype(jnp.float32) - ra_mean.value) * jax.lax.rsqrt(
                ra_var.value + self.epsilon
            )
            return (y * scale + bias).astype(self.dtype)

        b = x.shape[0]
        gs = self.group_size if self.group_size > 0 else b
        if b % gs != 0:
            raise ValueError(
                f"batch {b} not divisible by bn group size {gs}"
            )
        g = b // gs
        # Statistics in float32 regardless of compute dtype (flax BatchNorm
        # does the same); only the normalized output drops to self.dtype.
        xg = x.reshape((g, gs) + x.shape[1:]).astype(jnp.float32)
        # Per-group statistics over (group-batch, H, W), like each DDP
        # replica computing its own sub-batch stats.
        axes = tuple(range(1, xg.ndim - 1))
        mean_g = xg.mean(axis=axes, keepdims=True)
        centered = xg - mean_g
        var_g = (centered ** 2).mean(axis=axes, keepdims=True)
        y = (centered * jax.lax.rsqrt(var_g + self.epsilon)).reshape(x.shape)

        if not self.is_initializing():
            n = gs * int(np.prod(x.shape[1:-1]))
            # torch updates running_var with the *unbiased* batch variance
            # (Bessel n/(n-1)) while normalizing with the biased one.
            bessel = n / max(n - 1, 1)
            ra_mean.value = (
                self.momentum * ra_mean.value
                + (1 - self.momentum) * mean_g.mean(axis=0).reshape(features)
            )
            ra_var.value = (
                self.momentum * ra_var.value
                + (1 - self.momentum)
                * (var_g.mean(axis=0).reshape(features) * bessel)
            )
        return (y * scale + bias).astype(self.dtype)
