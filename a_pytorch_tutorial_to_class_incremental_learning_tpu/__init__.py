"""TPU-native class-incremental learning framework.

A ground-up JAX/XLA re-design of the capabilities of
``G-U-N/a-PyTorch-Tutorial-to-Class-Incremental-Learning`` (the WA method,
"Maintaining Discrimination and Fairness in Class Incremental Learning"):
rehearsal memory with herding exemplar selection, knowledge distillation from
the previous-task model, a growing multi-head classifier re-expressed as one
statically-shaped masked weight matrix (a single XLA compilation covers every
task), post-task weight alignment, and data-parallel training over a
``jax.sharding.Mesh`` instead of DDP/NCCL.

Import as ``import a_pytorch_tutorial_to_class_incremental_learning_tpu as cil_tpu``
or use the ``cil_tpu`` alias module at the repo root.
"""

__version__ = "0.1.0"

from .config import CilConfig  # noqa: F401
