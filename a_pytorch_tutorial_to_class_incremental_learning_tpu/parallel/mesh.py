"""Device mesh and sharding helpers.

The reference's entire distribution story is replicated-parameter data
parallelism: DDP gradient all-reduce over NCCL plus a per-step barrier
(reference template.py:243-244,272; utils.py:147-152).  The TPU-native
equivalent is *compiler-scheduled* SPMD: one ``jax.sharding.Mesh`` over all
devices with a ``data`` axis (and a ``model`` axis reserved for wider
models), batch arrays sharded over ``data``, parameters replicated (or
sharded over ``model``), and XLA inserting/overlapping the ICI all-reduces
inside the single compiled train step — no explicit collectives, no
barriers.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(
    mesh_shape: Optional[Tuple[int, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the 2-D ``(data, model)`` mesh.

    ``mesh_shape=None`` puts every visible device on the data axis — the
    parity configuration with the reference's pure-DP world (inventory #23).
    Device order follows ``jax.devices()`` so the data axis rides ICI within
    a slice and DCN across slices, keeping gradient reduction on the fast
    interconnect.
    """
    devices = list(devices if devices is not None else jax.devices())
    if mesh_shape is None:
        mesh_shape = (len(devices), 1)
    data, model = mesh_shape
    if data * model != len(devices):
        raise ValueError(
            f"mesh shape {mesh_shape} does not cover {len(devices)} devices"
        )
    arr = np.asarray(devices).reshape(data, model)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def assert_process_major(mesh: Mesh) -> None:
    """Fail loudly when the mesh's data axis is not process-major.

    ``data/loader.py`` hands each process the contiguous stripe
    ``[pidx·per_proc, (pidx+1)·per_proc)`` of every global batch, and
    ``jax.make_array_from_process_local_data`` assembles the global array in
    the sharding's device order — the two agree only when process ``p`` owns
    exactly the ``p``-th contiguous block of data-axis rows.  That holds for
    every standard mesh (``jax.devices()`` is process-major), but an exotic
    topology would silently permute the global batch across hosts
    (accuracy-neutral, parity-relevant) or, with a model axis spanning
    processes, feed replicated shards divergent content.  Checked once at
    trainer init.
    """
    nrows = mesh.devices.shape[0]
    owners = []  # per data-row: the set of owning processes
    for row in mesh.devices.reshape(nrows, -1):
        procs = {d.process_index for d in row}
        if len(procs) > 1:
            raise RuntimeError(
                "mesh data-axis row spans processes "
                f"{sorted(procs)}: the model axis crosses hosts, which the "
                "contiguous-stripe loader (data/loader.py) cannot feed — "
                "reshape the mesh so each host owns whole data rows"
            )
        owners.append(procs.pop())
    if any(b < a for a, b in zip(owners, owners[1:])):
        raise RuntimeError(
            f"mesh data axis is not process-major (row owners {owners}): "
            "the contiguous-stripe loader would permute the global batch "
            "across hosts — order devices process-major when building the "
            "mesh"
        )


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading-axis (batch) sharding over the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def global_put(value, sharding: NamedSharding):
    """Place one host value onto a (possibly multi-process) sharding.

    ``jax.device_put`` of a host value to a non-fully-addressable sharding
    runs ``multihost_utils.assert_equal`` — a device-collective broadcast
    the XLA CPU backend rejects outright (and a per-placement synchronous
    collective everywhere else).  Each process instead assembles its
    addressable shards straight from its own host copy, the same trust-based
    contract as the batch path (``make_array_from_process_local_data``):
    every process is *assumed* to hold the same value.  That assumption is
    exactly what ``--check_lockstep`` verifies at every dispatch boundary,
    with a named violation instead of an opaque placement-time crash.
    """
    if sharding.is_fully_addressable:
        return jax.device_put(value, sharding)
    arr = np.asarray(value)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx]
    )


def replicated_scalar(mesh: Mesh, value, dtype=None):
    """An int32 (or ``dtype``) scalar committed to the replicated mesh sharding.

    Scalar TrainState leaves must be created like this, not as bare
    ``jnp.int32(...)``: an uncommitted single-device scalar and the committed
    mesh-replicated scalar a jitted program hands back are different cache
    keys, so a bare scalar makes every program that carries it through
    (train step, fused epoch) silently compile twice — once for the fresh
    state, once for its own output.
    """
    import jax.numpy as jnp

    return global_put(
        jnp.asarray(value, dtype or jnp.int32), replicated(mesh)
    )


# Exact param-path components that carry a class dimension as their last axis
# (the CilModel masked head, models/cil_model.py); sharded over the model axis.
_CLASS_DIM_PARAMS = ("fc_kernel", "fc_bias")


def param_sharding(mesh: Mesh, path: Tuple[str, ...], value) -> NamedSharding:
    """Sharding rule for one parameter leaf.

    At the reference's model scale (a 0.46M-param CNN) everything is
    replicated; the classifier head (class dimension last) is sharded over
    the ``model`` axis when it is wider than 1 so the design scales to
    larger heads without code changes.  Matching is by exact path component
    (not substring), and falls back to replication when the class dimension
    does not divide the model-axis size — ``create_model(width_multiple=...)``
    pads the head width so it does.
    """
    model_dim = mesh.shape[MODEL_AXIS]
    if (
        model_dim > 1
        and any(p in _CLASS_DIM_PARAMS for p in path)
        and getattr(value, "ndim", 0) >= 1
        and value.shape[-1] % model_dim == 0
    ):
        spec = (None,) * (value.ndim - 1) + (MODEL_AXIS,)
        return NamedSharding(mesh, P(*spec))
    return NamedSharding(mesh, P())


def shard_params(mesh: Mesh, tree):
    """Place a parameter pytree on the mesh according to `param_sharding`."""
    import jax.tree_util as jtu

    def place(path, leaf):
        names = tuple(
            getattr(k, "key", getattr(k, "name", str(k))) for k in path
        )
        return global_put(leaf, param_sharding(mesh, names, leaf))

    return jtu.tree_map_with_path(place, tree)
