"""Multi-process (multi-host) runtime utilities.

TPU-native counterpart of the reference's NCCL bootstrap
(``init_distributed_mode``/``setup_for_distributed``, reference
utils.py:135-168).  On TPU the device mesh and collectives are handled by
XLA under ``jax.jit``; this module only covers the *host-side* process group:

* :func:`init_distributed_mode` — calls ``jax.distributed.initialize`` when a
  multi-host environment is detected (never hard-fails in single-process mode,
  unlike the reference which raises, utils.py:140-144 — single host is the
  common TPU development case).
* :func:`setup_for_distributed` — process-0-only ``print`` with a ``force``
  escape hatch (reference utils.py:160-168).
* :func:`barrier` — explicit sync point built from a tiny device allreduce;
  only needed around host-side phases (checkpoint IO), never inside the
  compiled step the way the reference barriers every optimizer step
  (template.py:272).
"""

from __future__ import annotations

import builtins
import os
from typing import Optional

import jax
import numpy as np

_printer_installed = False
_dist_initialized = False

# Environment markers of multi-host launches.  Pure env inspection — nothing
# here may touch a JAX backend, because ``jax.distributed.initialize`` must
# run before the first backend use.
_EXPLICIT_COORD_VARS = ("COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS")
# Comma-separated host lists: multi-host only when more than one entry
# (single-host TPU VMs set these too, e.g. TPU_WORKER_HOSTNAMES=localhost).
_HOST_LIST_VARS = ("TPU_WORKER_HOSTNAMES", "TPU_PROCESS_ADDRESSES")


def is_dist_env() -> bool:
    """True when launched in a recognizable multi-host environment."""
    if any(k in os.environ for k in _EXPLICIT_COORD_VARS):
        return True
    if "MEGASCALE_COORDINATOR_ADDRESS" in os.environ:  # multi-slice Cloud TPU
        return True
    if int(os.environ.get("SLURM_JOB_NUM_NODES", "1")) > 1:
        return True
    return any(
        "," in os.environ.get(k, "") for k in _HOST_LIST_VARS
    )


def init_distributed_mode(dist_url: Optional[str] = None) -> None:
    """Initialize the JAX process group when running multi-host.

    Counterpart of the reference's NCCL bootstrap (utils.py:135-153), with two
    deliberate differences: single-process mode is fully supported (the
    reference hard-raises without torchrun, utils.py:140-144), and the guard
    is **pure env inspection** — ``jax.distributed.initialize`` must be the
    first JAX call, so nothing here may query process_count/devices before it
    (doing so initializes the local backend and makes initialize() raise).
    """
    global _dist_initialized
    if not _dist_initialized and is_dist_env():
        _dist_initialized = True
        # Multi-process on the CPU platform needs an explicit collectives
        # implementation: without one the XLA CPU client rejects every
        # cross-process computation ("Multiprocess computations aren't
        # implemented").  Config-only — the backend is not touched.
        if "cpu" in str(jax.config.jax_platforms or "").split(","):
            try:
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo"
                )
            except (AttributeError, ValueError):
                pass  # older/newer jax: flag absent or gloo not built in
        coord = os.environ.get("JAX_COORDINATOR_ADDRESS") or os.environ.get(
            "COORDINATOR_ADDRESS"
        )
        num = os.environ.get("JAX_NUM_PROCESSES") or os.environ.get("NUM_PROCESSES")
        pid = os.environ.get("JAX_PROCESS_ID") or os.environ.get("PROCESS_ID")
        explicit = coord is not None
        try:
            if coord and num is not None and pid is not None:
                jax.distributed.initialize(
                    coordinator_address=coord,
                    num_processes=int(num),
                    process_id=int(pid),
                )
            elif coord:
                # Coordinator given; num_processes/process_id from env
                # auto-detection (Cloud TPU metadata, Slurm).
                jax.distributed.initialize(coordinator_address=coord)
            else:
                # No explicit coordinator: fully auto-detected clusters.
                jax.distributed.initialize()
        except (RuntimeError, ValueError) as e:
            if explicit:
                # The user explicitly asked for multi-host; degrading to N
                # independent single-process runs would silently duplicate
                # training and corrupt shared checkpoints.  Fail fast.
                raise
            # Heuristic markers only (e.g. TPU metadata that merely *looks*
            # multi-host) with an already-touched backend: degrade to
            # single-process rather than kill a run that never needed
            # coordination.
            import sys

            sys.stderr.write(f"| multi-host init skipped: {e}\n")
    setup_for_distributed(jax.process_index() == 0)
    if jax.process_index() == 0:
        print(
            f"| runtime init: process {jax.process_index()}/{jax.process_count()}, "
            f"{jax.device_count()} device(s), backend={jax.default_backend()}"
        )


def is_main_process() -> bool:
    return jax.process_index() == 0


def setup_for_distributed(is_master: bool) -> None:
    """Install a process-0-only ``print`` (reference utils.py:160-168)."""
    global _printer_installed
    if _printer_installed:
        return
    _printer_installed = True
    builtin_print = builtins.print

    def print_(*args, **kwargs):
        force = kwargs.pop("force", False)
        if is_master or force:
            builtin_print(*args, **kwargs)

    builtins.print = print_


# Each barrier use needs a fresh id on the coordination service (a passed
# barrier cannot be re-waited).  Every process executes the same barrier
# sequence (SPMD), so a plain counter agrees fleet-wide.
_barrier_seq = 0


def barrier(timeout_s: float = 600.0) -> None:
    """Block until every process reaches this point.

    Preferred path: the ``jax.distributed`` coordination service — a pure
    host-side TCP rendezvous that works on every backend (the XLA CPU
    backend rejects cross-process device computations, so a device-collective
    barrier would crash exactly where the CPU test clusters need it).
    Fallback: a scalar ``process_allgather``, the idiomatic device-level
    replacement for ``dist.barrier()`` (reference utils.py:152,
    template.py:210).  No-op single-process.
    """
    if jax.process_count() == 1:
        return
    client = None
    try:
        from jax._src import distributed

        client = distributed.global_state.client
    except (ImportError, AttributeError):  # pragma: no cover - jax internals
        client = None
    if client is not None:
        global _barrier_seq
        _barrier_seq += 1
        client.wait_at_barrier(
            f"cil_barrier_{_barrier_seq}", timeout_in_ms=int(timeout_s * 1e3)
        )
        return
    from jax.experimental import multihost_utils

    multihost_utils.process_allgather(np.zeros((), dtype=np.int32))
