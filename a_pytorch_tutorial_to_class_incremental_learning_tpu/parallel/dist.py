"""Multi-process (multi-host) runtime utilities.

TPU-native counterpart of the reference's NCCL bootstrap
(``init_distributed_mode``/``setup_for_distributed``, reference
utils.py:135-168).  On TPU the device mesh and collectives are handled by
XLA under ``jax.jit``; this module only covers the *host-side* process group:

* :func:`init_distributed_mode` — calls ``jax.distributed.initialize`` when a
  multi-host environment is detected (never hard-fails in single-process mode,
  unlike the reference which raises, utils.py:140-144 — single host is the
  common TPU development case).
* :func:`setup_for_distributed` — process-0-only ``print`` with a ``force``
  escape hatch (reference utils.py:160-168).
* :func:`barrier` — explicit sync point built from a tiny device allreduce;
  only needed around host-side phases (checkpoint IO), never inside the
  compiled step the way the reference barriers every optimizer step
  (template.py:272).
"""

from __future__ import annotations

import builtins
import os
from typing import Optional

import jax
import numpy as np

_printer_installed = False


def is_dist_env() -> bool:
    """True when launched under a multi-host coordinator (e.g. via
    ``JAX_COORDINATOR_ADDRESS``/GKE/slurm env)."""
    return any(
        k in os.environ
        for k in ("COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS")
    )


def init_distributed_mode(dist_url: Optional[str] = None) -> None:
    """Initialize the JAX process group when running multi-host.

    Single-process mode is fully supported (a deliberate fix of the
    reference's mandatory-torchrun behaviour, utils.py:140-144).
    """
    if is_dist_env() and jax.process_count() == 1:
        coord = os.environ.get("JAX_COORDINATOR_ADDRESS") or os.environ.get(
            "COORDINATOR_ADDRESS"
        )
        jax.distributed.initialize(coordinator_address=coord)
    setup_for_distributed(jax.process_index() == 0)
    if jax.process_index() == 0:
        print(
            f"| runtime init: process {jax.process_index()}/{jax.process_count()}, "
            f"{jax.device_count()} device(s), backend={jax.default_backend()}"
        )


def is_main_process() -> bool:
    return jax.process_index() == 0


def setup_for_distributed(is_master: bool) -> None:
    """Install a process-0-only ``print`` (reference utils.py:160-168)."""
    global _printer_installed
    if _printer_installed:
        return
    _printer_installed = True
    builtin_print = builtins.print

    def print_(*args, **kwargs):
        force = kwargs.pop("force", False)
        if is_master or force:
            builtin_print(*args, **kwargs)

    builtins.print = print_


def barrier() -> None:
    """Block until every process reaches this point.

    Implemented as a host-level allgather of a scalar — the idiomatic JAX
    replacement for ``dist.barrier()`` (reference utils.py:152,
    template.py:210).  No-op single-process.
    """
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.process_allgather(np.zeros((), dtype=np.int32))
