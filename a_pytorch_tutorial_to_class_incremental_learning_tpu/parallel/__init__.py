"""Parallel runtime (L1): device mesh, sharding rules, multi-host bootstrap.

TPU-native counterpart of the reference's NCCL/DDP layer (SURVEY.md #14,
#23, #25): a ``data x model`` ``jax.sharding.Mesh`` with XLA-scheduled
collectives replaces process groups, barriers and gradient hooks.
"""

from .mesh import (  # noqa: F401
    DATA_AXIS,
    MODEL_AXIS,
    batch_sharding,
    global_put,
    make_mesh,
    param_sharding,
    replicated,
    shard_params,
)
from .dist import (  # noqa: F401
    barrier,
    init_distributed_mode,
    is_main_process,
    setup_for_distributed,
)
