from .mesh import (  # noqa: F401
    DATA_AXIS,
    MODEL_AXIS,
    batch_sharding,
    make_mesh,
    param_sharding,
    replicated,
    shard_params,
)
from .dist import (  # noqa: F401
    barrier,
    init_distributed_mode,
    is_main_process,
    setup_for_distributed,
)
