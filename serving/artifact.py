"""Per-task serving artifacts: freeze, AOT-export, verify, reload.

One artifact = one directory ``<export_dir>/task_{t:03d}/`` holding

* ``weights.pkl`` (+ ``.sha256`` sidecar) — host pytree of params + batch
  stats + task metadata, written with the same atomic-rename + checksum
  machinery the checkpoint layer uses (``utils/checkpoint.py``): payload tmp
  → sidecar → ``os.replace``, so every crash window leaves either a complete
  artifact or an orphan readers ignore.
* ``exported_b{B:03d}.bin`` (+ sidecars) — the predict function serialized
  with ``jax.export``, one per supported batch bucket.  Weights are
  *arguments* of the exported program, not baked-in constants: the head is
  statically full-width (``models/cil_model.py``), so the program is
  byte-identical across tasks and every task after the first hits the
  persistent XLA compilation cache (``utils/platform.py``) at both export
  and load time.
* ``meta.json`` — task id, active-class count, class map (head column →
  original label), bucket list, and enough model/normalization description
  to rebuild the live flax module for bit-identity parity checks
  (:func:`rebuild_model`).

``manifest.json`` at the export-dir root is the publication point: it is
rewritten atomically (tmp + ``os.replace``) after the artifact directory is
complete, so a server watching the manifest can never observe a half-written
artifact.  Loading verifies every sidecar, then AOT-compiles each bucket's
deserialized program via ``jit(...).lower(...).compile()`` — an AOT compile
never populates a jit trace cache, which is what makes the server's
zero-retrace contract enforceable (tests/test_serving.py).
"""

from __future__ import annotations

import io
import json
import os
import shutil
import time
from collections.abc import Mapping
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import export as jax_export

from a_pytorch_tutorial_to_class_incremental_learning_tpu.data.augment import (
    AugmentConfig,
    eval_preprocess,
)
from a_pytorch_tutorial_to_class_incremental_learning_tpu.utils.checkpoint import (
    _read_payload,
    _sha256_file,
    _write_pickle_atomic,
    _write_sidecar,
)

DEFAULT_BUCKETS: Tuple[int, ...] = (1, 8, 32, 64)

_MANIFEST = "manifest.json"
_WEIGHTS = "weights.pkl"
_META = "meta.json"
_PROBE = "probe.npz"


def _exported_name(bucket: int) -> str:
    return f"exported_b{bucket:03d}.bin"


def _plain(tree):
    """Recursively rebuild mappings as plain dicts.

    ``jax.export`` refuses pytrees containing unregistered container types
    (flax ``FrozenDict``), and the weights pickle must have the *same* tree
    structure the exported program was traced with — so both go through this
    normalization.
    """
    if isinstance(tree, Mapping):
        return {k: _plain(v) for k, v in tree.items()}
    return tree


def _host(tree):
    return jax.tree_util.tree_map(
        lambda a: np.asarray(jax.device_get(a)), _plain(tree)
    )


def _specs_of(tree):
    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(np.shape(a), np.asarray(a).dtype), tree
    )


def make_predict_fn(model, aug_cfg: AugmentConfig):
    """The inference program: uint8 pixels in, full-width logits out.

    Same computation as the trainer's eval step (``engine/train.py``):
    normalize-only preprocessing, then the model in eval mode (BatchNorm
    running statistics — every output row depends only on its input row,
    which is what makes pad-to-bucket dispatch exact).  Weights ride as
    arguments so the exported program is task-independent.
    """

    def predict(params, batch_stats, num_active, x_u8):
        x = eval_preprocess(x_u8, aug_cfg)
        logits, _ = model.apply(
            {"params": params, "batch_stats": batch_stats},
            x,
            num_active=num_active,
            train=False,
        )
        return logits

    return jax.jit(predict)


def _write_bytes_atomic(path: str, data: bytes) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    _write_sidecar(path, tmp)
    os.replace(tmp, path)


# --------------------------------------------------------------------- #
# Manifest
# --------------------------------------------------------------------- #


def read_manifest(export_dir: str) -> dict:
    path = os.path.join(export_dir, _MANIFEST)
    if not os.path.exists(path):
        return {}
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        # os.replace makes torn manifests near-impossible; treat a transient
        # read failure as "nothing new" rather than crashing the watcher.
        return {}


def register_artifact(export_dir: str, task_id: int, entry: dict) -> None:
    """Publish an artifact: read-modify-replace of ``manifest.json``.

    The replace is the linearization point — a watcher sees either the old
    manifest or the new one, never a mix.
    """
    man = read_manifest(export_dir)
    man.setdefault("version", 1)
    artifacts = man.setdefault("artifacts", {})
    artifacts[str(task_id)] = entry
    man["latest"] = max(int(t) for t in artifacts)
    man["updated_ts"] = round(time.time(), 3)
    path = os.path.join(export_dir, _MANIFEST)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(man, f, indent=2, sort_keys=True)
    os.replace(tmp, path)


def latest_artifact(export_dir: str) -> Optional[Tuple[int, str]]:
    """``(task_id, artifact_dir)`` of the newest published artifact."""
    man = read_manifest(export_dir)
    latest = man.get("latest")
    if latest is None:
        return None
    entry = man.get("artifacts", {}).get(str(latest))
    if entry is None:
        return None
    return int(latest), os.path.join(export_dir, entry["path"])


# --------------------------------------------------------------------- #
# Export
# --------------------------------------------------------------------- #


def export_artifact(
    export_dir: str,
    task_id: int,
    model,
    aug_cfg: AugmentConfig,
    params,
    batch_stats,
    known: int,
    class_order: Sequence[int],
    input_size: int,
    channels: int,
    buckets: Sequence[int] = DEFAULT_BUCKETS,
    acc_per_task: Optional[Sequence[float]] = None,
    model_meta: Optional[dict] = None,
) -> str:
    """Freeze + AOT-export one task's inference state; returns the artifact dir.

    The directory is built under a ``.tmp`` name and renamed into place
    before the manifest update, so the manifest only ever points at complete
    artifacts.  Each bucket's program is additionally ``lower().compile()``d
    here — partly validation (a program that cannot compile must fail the
    export, not the first query), partly cache warming: the compile lands in
    the persistent XLA cache the server's load will hit.
    """
    buckets = tuple(sorted({int(b) for b in buckets}))
    if not buckets or buckets[0] <= 0:
        raise ValueError(f"serve buckets must be positive ints, got {buckets!r}")
    host_params = _host(params)
    host_stats = _host(batch_stats)
    final = os.path.join(export_dir, f"task_{task_id:03d}")
    tmp_dir = final + ".tmp"
    if os.path.exists(tmp_dir):
        shutil.rmtree(tmp_dir)
    os.makedirs(tmp_dir)

    _write_pickle_atomic(
        os.path.join(tmp_dir, _WEIGHTS),
        {
            "task_id": task_id,
            "known": int(known),
            "params": host_params,
            "batch_stats": host_stats,
        },
    )

    predict = make_predict_fn(model, aug_cfg)
    p_spec = _specs_of(host_params)
    bs_spec = _specs_of(host_stats)
    na_spec = jax.ShapeDtypeStruct((), jnp.int32)
    exported_files: Dict[str, str] = {}
    for bucket in buckets:
        x_spec = jax.ShapeDtypeStruct(
            (bucket, input_size, input_size, channels), jnp.uint8
        )
        exp = jax_export.export(predict)(p_spec, bs_spec, na_spec, x_spec)
        predict.lower(p_spec, bs_spec, na_spec, x_spec).compile()
        name = _exported_name(bucket)
        _write_bytes_atomic(os.path.join(tmp_dir, name), exp.serialize())
        exported_files[str(bucket)] = name

    # Golden probe: a deterministic input + this export's own logits for it,
    # frozen into the artifact.  A post-swap server replays the probe through
    # the freshly loaded executables and demands exact equality
    # (serving/skew.py probe_artifact) — the cheap, offline-free skew gate
    # that decides promote-vs-rollback during rolling fleet swaps.
    probe_bucket = buckets[0]
    probe_x = np.random.RandomState(0).randint(
        0, 256, (probe_bucket, input_size, input_size, channels)
    ).astype(np.uint8)
    probe_logits = np.asarray(predict(
        host_params, host_stats, jnp.asarray(int(known), jnp.int32),
        jnp.asarray(probe_x),
    ))
    buf = io.BytesIO()
    np.savez(buf, x=probe_x, logits=probe_logits,
             bucket=np.asarray(probe_bucket))
    _write_bytes_atomic(os.path.join(tmp_dir, _PROBE), buf.getvalue())

    meta = {
        "version": 1,
        "task_id": int(task_id),
        "known": int(known),
        "class_map": [int(c) for c in list(class_order)[: int(known)]],
        "buckets": list(buckets),
        "input_size": int(input_size),
        "channels": int(channels),
        "mean": [float(m) for m in aug_cfg.mean],
        "std": [float(s) for s in aug_cfg.std],
        "model": dict(model_meta or {}),
        "backend": jax.default_backend(),
        "acc_per_task": (
            [float(a) for a in acc_per_task] if acc_per_task is not None else None
        ),
        "files": {"weights": _WEIGHTS, "exported": exported_files,
                  "probe": _PROBE},
        "created_ts": round(time.time(), 3),
    }
    meta_tmp = os.path.join(tmp_dir, _META + ".tmp")
    with open(meta_tmp, "w") as f:
        json.dump(meta, f, indent=2, sort_keys=True)
    os.replace(meta_tmp, os.path.join(tmp_dir, _META))

    if os.path.exists(final):
        shutil.rmtree(final)  # re-export of the same task supersedes it
    os.rename(tmp_dir, final)
    register_artifact(
        export_dir,
        task_id,
        {
            "path": os.path.basename(final),
            "known": int(known),
            "buckets": list(buckets),
            "updated_ts": round(time.time(), 3),
        },
    )
    return final


def export_from_trainer(trainer, task_id: int, known_after: int,
                        acc_per_task=None) -> str:
    """Trainer-side convenience: gather everything the export needs from a
    live ``CilTrainer`` right after weight alignment."""
    cfg = trainer.config
    params = trainer.state.params
    fc_bias = np.asarray(jax.device_get(params["fc_bias"]))
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.ops.precision import (
        policy_from_config,
    )

    model_meta = {
        "backbone": cfg.backbone,
        "width": int(fc_bias.shape[0]),
        "compute_dtype": cfg.compute_dtype,
        "precision": policy_from_config(cfg).name,
        "bn_group_size": int(cfg.bn_group_size),
    }
    return export_artifact(
        cfg.export_dir,
        task_id,
        trainer.model,
        trainer.aug_cfg,
        params,
        trainer.state.batch_stats,
        known=known_after,
        class_order=trainer.scenario_train.class_order,
        input_size=cfg.input_size,
        channels=trainer.channels,
        buckets=cfg.serve_buckets,
        acc_per_task=acc_per_task,
        model_meta=model_meta,
    )


# --------------------------------------------------------------------- #
# Load
# --------------------------------------------------------------------- #


class ServingArtifact:
    """One loaded task artifact: verified weights + AOT-compiled programs.

    ``predict``/``predict_padded`` only ever invoke the pre-compiled
    executables — no jit dispatch, no tracing.  The per-bucket jit wrappers
    are kept (never called) so a ``RecompileMonitor`` can watch their trace
    caches stay at zero (:meth:`register_recompiles`).
    """

    def __init__(self, path: str, meta: dict, params, batch_stats,
                 num_active, compiled: Dict[int, object],
                 jit_fns: Dict[int, object], load_ms: float,
                 compile_ms: float):
        self.path = path
        self.meta = meta
        self.task_id = int(meta["task_id"])
        self.known = int(meta["known"])
        self.class_map = list(meta["class_map"])
        self.buckets = tuple(sorted(compiled))
        self.params = params
        self.batch_stats = batch_stats
        self.num_active = num_active
        self.load_ms = load_ms
        self.compile_ms = compile_ms
        self._compiled = compiled
        self._jit_fns = jit_fns

    def bucket_for(self, n: int) -> Optional[int]:
        for bucket in self.buckets:
            if bucket >= n:
                return bucket
        return None

    def predict_padded(self, x_u8: np.ndarray, bucket: int) -> np.ndarray:
        """Full-bucket logits for a batch already padded to ``bucket`` rows."""
        out = self._compiled[bucket](
            self.params, self.batch_stats, self.num_active, jnp.asarray(x_u8)
        )
        return np.asarray(out)

    def predict(self, x_u8: np.ndarray) -> np.ndarray:
        """Logits for ``n`` images: pad to the smallest covering bucket (rows
        are independent in eval mode, so padding never changes real rows),
        chunk by the largest bucket when ``n`` exceeds it."""
        x = np.ascontiguousarray(x_u8, dtype=np.uint8)
        n = x.shape[0]
        max_bucket = self.buckets[-1]
        outs = []
        for lo in range(0, n, max_bucket):
            chunk = x[lo:lo + max_bucket]
            m = chunk.shape[0]
            bucket = self.bucket_for(m)
            if m < bucket:
                pad = np.zeros((bucket - m,) + chunk.shape[1:], np.uint8)
                chunk = np.concatenate([chunk, pad])
            outs.append(self.predict_padded(chunk, bucket)[:m])
        return outs[0] if len(outs) == 1 else np.concatenate(outs)

    def register_recompiles(self, monitor, group: str = "serve") -> None:
        """Track the (never-called) jit wrappers: ``monitor.total(group)``
        staying at 0 is the proof that serving never traced."""
        for bucket, fn in sorted(self._jit_fns.items()):
            monitor.track(f"serve_b{bucket}[task{self.task_id}]", fn, group=group)


def load_artifact(path: str) -> ServingArtifact:
    """Verify and load one artifact directory; AOT-compile every bucket.

    Raises ``OSError`` on any integrity failure (missing/corrupt weights or
    exported blob) — the server treats that as a failed swap and keeps
    serving its current artifact.
    """
    t0 = time.perf_counter()
    meta_path = os.path.join(path, _META)
    try:
        with open(meta_path) as f:
            meta = json.load(f)
    except (OSError, ValueError) as e:
        raise OSError(f"unreadable artifact meta {meta_path}: {e!r}")
    payload, why = _read_payload(os.path.join(path, meta["files"]["weights"]))
    if payload is None:
        raise OSError(f"invalid artifact weights in {path}: {why}")
    params = jax.device_put(payload["params"])
    batch_stats = jax.device_put(payload["batch_stats"])
    num_active = jnp.asarray(meta["known"], jnp.int32)
    p_spec = _specs_of(payload["params"])
    bs_spec = _specs_of(payload["batch_stats"])
    na_spec = jax.ShapeDtypeStruct((), jnp.int32)

    compiled: Dict[int, object] = {}
    jit_fns: Dict[int, object] = {}
    t_compile = 0.0
    for bucket_s, name in sorted(
        meta["files"]["exported"].items(), key=lambda kv: int(kv[0])
    ):
        bucket = int(bucket_s)
        blob_path = os.path.join(path, name)
        sidecar = blob_path + ".sha256"
        if os.path.exists(sidecar):
            with open(sidecar) as f:
                want = f.read().strip()
            got = _sha256_file(blob_path)
            if got != want:
                raise OSError(
                    f"checksum mismatch for {blob_path} "
                    f"(want {want[:12]}, got {got[:12]})"
                )
        with open(blob_path, "rb") as f:
            exp = jax_export.deserialize(bytearray(f.read()))
        fn = jax.jit(exp.call)
        x_spec = jax.ShapeDtypeStruct(
            (bucket, meta["input_size"], meta["input_size"], meta["channels"]),
            jnp.uint8,
        )
        tc = time.perf_counter()
        compiled[bucket] = fn.lower(p_spec, bs_spec, na_spec, x_spec).compile()
        t_compile += time.perf_counter() - tc
        jit_fns[bucket] = fn
    return ServingArtifact(
        path, meta, params, batch_stats, num_active, compiled, jit_fns,
        load_ms=round((time.perf_counter() - t0) * 1000.0, 3),
        compile_ms=round(t_compile * 1000.0, 3),
    )


# --------------------------------------------------------------------- #
# Parity: rebuild the live model from an artifact (tests / smoke only)
# --------------------------------------------------------------------- #


def rebuild_model(meta: dict):
    """Fresh flax module + eval AugmentConfig equivalent to the exported
    program — the 'direct model call' side of the bit-identity checks."""
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.models import (
        create_model,
    )
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.ops.precision import (
        get_policy,
    )

    mm = meta["model"]
    # New artifacts carry the policy name; pre-policy artifacts only have
    # compute_dtype, which get_policy accepts as an alias.
    policy = get_policy(
        mm.get("precision") or mm.get("compute_dtype", "float32")
    )
    model, _ = create_model(
        mm["backbone"],
        mm["width"],
        width_multiple=1,
        input_size=meta["input_size"],
        channels=meta["channels"],
        bn_group_size=mm.get("bn_group_size", 0),
        policy=policy,
    )
    aug_cfg = AugmentConfig(
        input_size=meta["input_size"],
        mean=tuple(meta["mean"]),
        std=tuple(meta["std"]),
    )
    return model, aug_cfg


def direct_predict(path: str, x_u8: np.ndarray) -> np.ndarray:
    """Logits from a freshly rebuilt (non-exported) model over the artifact's
    weights, at exactly the given batch shape.  This call *traces* — it is
    the reference side of the parity check, never part of the serving path."""
    with open(os.path.join(path, _META)) as f:
        meta = json.load(f)
    payload, why = _read_payload(os.path.join(path, meta["files"]["weights"]))
    if payload is None:
        raise OSError(f"invalid artifact weights in {path}: {why}")
    model, aug_cfg = rebuild_model(meta)
    predict = make_predict_fn(model, aug_cfg)
    out = predict(
        payload["params"],
        payload["batch_stats"],
        jnp.asarray(meta["known"], jnp.int32),
        jnp.asarray(np.ascontiguousarray(x_u8, np.uint8)),
    )
    return np.asarray(out)
