"""Hot-swapping micro-batching inference server over exported artifacts.

Stdlib threading only (the accelerator work all lives in the AOT-compiled
artifact programs):

* **Batcher thread** — drains the request queue into micro-batches: the
  first request opens a batch, further requests join until either the
  largest bucket fills or the max-wait deadline passes; the batch is padded
  to the smallest covering bucket and dispatched on ONE pre-compiled
  executable call.  Every response carries the model task-id that produced
  it (the skew story depends on knowing *which* model answered).
* **Watcher thread** — polls ``manifest.json``; when a newer task's artifact
  is published it loads + AOT-compiles the new artifact *outside* the lock,
  then swaps the artifact reference atomically under it.  In-flight batches
  hold a local reference and finish on the old artifact; a failed load
  (corrupt payload, injected ``swap_ioerror``) emits ``serve_swap_failed``
  and keeps serving the current artifact — graceful degradation, retried at
  the next poll.

Lock discipline follows ``data/prefetch.py`` (and jaxlint's JL301 rule):
every attribute shared between the worker threads and the caller-facing
methods is written under ``self._lock``; requests and results travel through
the queue / per-request futures.  Telemetry funnels into the same ``Sink``
vocabulary as training (``serve_swap`` / ``serve_swap_failed`` /
``serve_latency``), and passing a ``Telemetry`` facade means the records
also ring through its ``FlightRecorder`` — a server crash leaves the same
forensics a trainer crash does.

The serving hot path never traces: queries run pre-compiled executables
only.  ``trace_count()`` exposes the jit-cache total of every loaded
program (through a ``RecompileMonitor``) so tests can pin it at zero across
warm restarts.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional

import numpy as np

from a_pytorch_tutorial_to_class_incremental_learning_tpu.telemetry import (
    NullRegistry,
    RecompileMonitor,
)
from a_pytorch_tutorial_to_class_incremental_learning_tpu.utils.logging import (
    NullSink,
)

from .artifact import ServingArtifact, load_artifact, read_manifest


class InferenceServer:
    """Batched inference over the newest artifact in ``export_dir``.

    ``submit(x)`` returns a ``concurrent.futures.Future`` resolving to
    ``{"logits", "task_id", "latency_ms"}``.  ``stop()`` drains: every
    accepted request is answered before the threads exit — a clean shutdown
    drops nothing.
    """

    def __init__(
        self,
        export_dir: str,
        max_wait_ms: float = 5.0,
        poll_s: float = 0.25,
        telemetry=None,
        sink=None,
        faults=None,
        monitor: Optional[RecompileMonitor] = None,
        latency_log_every: int = 256,
        auto_swap: bool = True,
        replica_id: Optional[int] = None,
        metrics=None,
    ):
        self.export_dir = export_dir
        self.max_wait_s = max(float(max_wait_ms), 0.0) / 1000.0
        self.poll_s = float(poll_s)
        # auto_swap=False puts swaps under external control (the fleet front
        # end rolls replicas one at a time via swap_to); the watcher thread
        # is simply not started.  replica_id tags this server's telemetry in
        # fleet runs.
        self.auto_swap = bool(auto_swap)
        self.replica_id = replica_id
        self._telemetry = telemetry
        self._sink = (telemetry.sink if telemetry is not None else sink) or NullSink()
        self._faults = faults
        self.monitor = monitor if monitor is not None else RecompileMonitor(self._sink)
        self.latency_log_every = int(latency_log_every)
        # Time-series registry (telemetry/metrics.py): explicit > the
        # telemetry facade's > no-op.  Instrument updates always run OUTSIDE
        # self._lock — the registry has its own lock and the two must never
        # nest (lock-order discipline, threadlint JL303).
        if metrics is None and telemetry is not None:
            metrics = getattr(telemetry, "metrics", None)
        self.metrics = metrics if metrics is not None else NullRegistry()
        self._m_requests = self.metrics.counter("serve_requests_total")
        self._m_failed = self.metrics.counter("serve_failed_total")
        self._m_batches = self.metrics.counter("serve_batches_total")
        self._m_queue_depth = self.metrics.gauge("serve_queue_depth")
        self._m_bucket_occ = self.metrics.gauge("serve_bucket_occupancy")

        self._lock = threading.Lock()
        self._queue: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self._artifact: Optional[ServingArtifact] = None
        self._batcher: Optional[threading.Thread] = None
        self._watcher: Optional[threading.Thread] = None
        # Stats (all guarded by _lock; threads and callers both touch them).
        self._latencies_ms: List[float] = []
        self._served = 0
        self._failed = 0
        self._batches = 0
        self._slots = 0
        self._bucket_counts: Dict[int, int] = {}
        self._swaps = 0
        self._swap_failures = 0
        self._rollbacks = 0
        self._window_start = time.perf_counter()
        self._window_served = 0
        self._t0 = time.perf_counter()

    # ------------------------------------------------------------------ #
    # Lifecycle
    # ------------------------------------------------------------------ #

    def start(self) -> "InferenceServer":
        latest = read_manifest(self.export_dir).get("latest")
        if latest is None:
            raise FileNotFoundError(
                f"no artifact published in {self.export_dir!r} "
                "(manifest.json missing or empty)"
            )
        art = self._load(int(latest))
        with self._lock:
            self._artifact = art
        self._sink.log(
            "serve_swap", from_task=None, to_task=art.task_id,
            load_ms=art.load_ms, compile_ms=art.compile_ms, path=art.path,
        )
        self._batcher = threading.Thread(
            target=self._batcher_loop, name="serve-batcher", daemon=True
        )
        self._batcher.start()
        if self.auto_swap:
            self._watcher = threading.Thread(
                target=self._watcher_loop, name="serve-watcher", daemon=True
            )
            self._watcher.start()
        return self

    def stop(self) -> None:
        """Drain and join.  The batcher keeps dispatching while the queue is
        non-empty, so every request accepted before ``stop()`` resolves; the
        post-join sweep catches a submit that raced the flag."""
        self._stop.set()
        if self._batcher is not None:
            self._batcher.join()
        if self._watcher is not None:
            self._watcher.join()
        with self._lock:
            art = self._artifact
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            self._dispatch(art, [item])
        self._flush_latency(force=True)

    # ------------------------------------------------------------------ #
    # Requests
    # ------------------------------------------------------------------ #

    def submit(self, x_u8: np.ndarray) -> Future:
        """Enqueue one image ``[H, W, C] uint8``; resolves to logits +
        the serving model's task id + measured latency."""
        if self._stop.is_set():
            raise RuntimeError("server is stopped")
        fut: Future = Future()
        self._queue.put((np.ascontiguousarray(x_u8, np.uint8), fut,
                         time.perf_counter()))
        return fut

    # ------------------------------------------------------------------ #
    # Introspection
    # ------------------------------------------------------------------ #

    @property
    def task_id(self) -> Optional[int]:
        with self._lock:
            return self._artifact.task_id if self._artifact else None

    def trace_count(self, group: str = "serve") -> int:
        """Total traced programs across every loaded artifact's jit wrappers
        — the number a warm restart must keep at zero."""
        return self.monitor.total(group)

    def stats(self) -> dict:
        with self._lock:
            lat = np.asarray(self._latencies_ms, np.float64)
            elapsed = max(time.perf_counter() - self._t0, 1e-9)
            return {
                "served": self._served,
                "failed": self._failed,
                "batches": self._batches,
                "task_id": self._artifact.task_id if self._artifact else None,
                "swaps": self._swaps,
                "swap_failures": self._swap_failures,
                "rollbacks": self._rollbacks,
                "bucket_counts": dict(self._bucket_counts),
                "bucket_occupancy": (
                    round(self._served / self._slots, 4) if self._slots else 0.0
                ),
                "p50_ms": float(np.percentile(lat, 50)) if lat.size else 0.0,
                "p95_ms": float(np.percentile(lat, 95)) if lat.size else 0.0,
                "p99_ms": float(np.percentile(lat, 99)) if lat.size else 0.0,
                "throughput_rps": round(self._served / elapsed, 2),
            }

    # ------------------------------------------------------------------ #
    # Worker threads
    # ------------------------------------------------------------------ #

    def _batcher_loop(self) -> None:
        while True:
            try:
                first = self._queue.get(timeout=0.05)
            except queue.Empty:
                if self._stop.is_set():
                    return
                continue
            with self._lock:
                art = self._artifact
            batch = [first]
            deadline = time.perf_counter() + self.max_wait_s
            max_bucket = art.buckets[-1]
            while len(batch) < max_bucket:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    batch.append(self._queue.get(timeout=remaining))
                except queue.Empty:
                    break
            self._dispatch(art, batch)

    def _dispatch(self, art: ServingArtifact, batch) -> None:
        n = len(batch)
        xs = np.stack([item[0] for item in batch])
        bucket = art.bucket_for(n)
        try:
            if n < bucket:
                xs = np.concatenate(
                    [xs, np.zeros((bucket - n,) + xs.shape[1:], np.uint8)]
                )
            logits = art.predict_padded(xs, bucket)
        except Exception as e:
            for _item in batch:
                _item[1].set_exception(e)
            with self._lock:
                self._failed += n
            self._m_failed.inc(n)
            print(f"| serve: batch of {n} failed: {e!r}")
            return
        done = time.perf_counter()
        for i, (_x, fut, t_enq) in enumerate(batch):
            fut.set_result({
                "logits": logits[i],
                "task_id": art.task_id,
                "latency_ms": (done - t_enq) * 1000.0,
            })
        with self._lock:
            self._latencies_ms.extend(
                (done - item[2]) * 1000.0 for item in batch
            )
            if len(self._latencies_ms) > 16384:
                # Percentiles over the recent tail; a long-lived server must
                # not grow the sample list without bound.
                del self._latencies_ms[:-8192]
            self._served += n
            self._window_served += n
            self._batches += 1
            self._slots += bucket
            self._bucket_counts[bucket] = self._bucket_counts.get(bucket, 0) + 1
            flush = self._window_served >= self.latency_log_every
            occupancy = self._served / self._slots if self._slots else 0.0
        # Registry updates after self._lock is released (never nested).
        self._m_requests.inc(n)
        self._m_batches.inc()
        self._m_queue_depth.set(self._queue.qsize())
        self._m_bucket_occ.set(occupancy)
        hist = self.metrics.histogram(
            "serve_batch_latency_ms", lowest=0.5, growth=2.0, buckets=18,
            bucket=str(bucket),
        )
        for item in batch:
            hist.observe((done - item[2]) * 1000.0)
        if flush:
            self._flush_latency()

    def _flush_latency(self, force: bool = False) -> None:
        with self._lock:
            if self._window_served == 0 and not force:
                return
            if not self._latencies_ms:
                return
            lat = np.asarray(self._latencies_ms, np.float64)
            elapsed = max(time.perf_counter() - self._window_start, 1e-9)
            record = dict(
                count=int(lat.size),
                p50_ms=round(float(np.percentile(lat, 50)), 3),
                p95_ms=round(float(np.percentile(lat, 95)), 3),
                p99_ms=round(float(np.percentile(lat, 99)), 3),
                throughput_rps=round(self._window_served / elapsed, 2),
                bucket_occupancy=(
                    round(self._served / self._slots, 4) if self._slots else 0.0
                ),
                batches=self._batches,
                task_id=self._artifact.task_id if self._artifact else -1,
            )
            self._window_served = 0
            self._window_start = time.perf_counter()
        self._sink.log("serve_latency", **record)

    def _watcher_loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            self._maybe_swap()

    def _maybe_swap(self) -> None:
        man = read_manifest(self.export_dir)
        latest = man.get("latest")
        if latest is None:
            return
        latest = int(latest)
        with self._lock:
            current = self._artifact.task_id if self._artifact else None
        if current == latest:
            return
        try:
            if self._faults is not None:
                actions = self._faults.fire("serve.swap", task=latest)
                if "swap_ioerror" in actions:
                    raise OSError(
                        f"fault-injected swap failure (task {latest})"
                    )
            art = self._load(latest, manifest=man)
        except Exception as e:
            with self._lock:
                self._swap_failures += 1
            self._sink.log(
                "serve_swap_failed", task_id=latest, error=repr(e),
            )
            print(
                f"| serve: swap to task {latest} failed ({e!r}); "
                f"still serving task {current}"
            )
            return
        # Load + compile happened entirely outside the lock; the swap itself
        # is one reference assignment.  In-flight batches keep their local
        # reference and finish on the old artifact.
        with self._lock:
            self._artifact = art
            self._swaps += 1
        self._sink.log(
            "serve_swap", from_task=current, to_task=art.task_id,
            load_ms=art.load_ms, compile_ms=art.compile_ms, path=art.path,
        )
        print(
            f"| serve: swapped task {current} -> {art.task_id} "
            f"(load {art.load_ms:.0f} ms, compile {art.compile_ms:.0f} ms)"
        )

    def swap_to(self, task_id: int) -> dict:
        """Externally driven, skew-gated swap (the fleet's rolling-update
        primitive; requires ``auto_swap=False`` only by convention — the
        caller owns the cadence).

        Load + AOT-compile the target artifact, then replay its golden
        probe (``serving/skew.py probe_artifact``) through the freshly
        compiled executables BEFORE promotion.  Any failure — injected
        ``swap_ioerror``, unreadable artifact, probe mismatch — keeps the
        current artifact serving, emits ``serve_rollback``, and reports
        ``ok=False``; the rest of the fleet is the caller's problem, this
        replica just refuses to get worse.  In-flight batches always finish
        on the artifact they started with.
        """
        task_id = int(task_id)
        with self._lock:
            current = self._artifact.task_id if self._artifact else None
        if current == task_id:
            return {"ok": True, "task_id": task_id, "noop": True}
        probe = None
        try:
            # task coordinate = swap TARGET (same as the auto-swap path);
            # per-replica injection comes from each replica owning its own
            # injector + ledger, not from the coordinate.
            if self._faults is not None:
                actions = self._faults.fire("serve.swap", task=task_id)
                if "swap_ioerror" in actions:
                    raise OSError(
                        f"fault-injected swap failure (task {task_id})"
                    )
            art = self._load(task_id)
            from .skew import probe_artifact

            probe = probe_artifact(art)
            if not probe["ok"]:
                raise OSError(
                    f"post-swap probe mismatch "
                    f"(max_abs={probe['max_abs']}, "
                    f"{probe.get('error', 'logits differ')})"
                )
        except Exception as e:
            with self._lock:
                self._swap_failures += 1
                self._rollbacks += 1
            record = dict(task_id=task_id, rolled_back_to=current,
                          reason=repr(e))
            if self.replica_id is not None:
                record["replica"] = self.replica_id
            if probe is not None:
                record["probe_checked"] = bool(probe.get("checked"))
                if probe.get("max_abs", 0.0) not in (None, float("inf")):
                    record["probe_max_abs"] = float(probe["max_abs"])
            self._sink.log("serve_rollback", **record)
            print(
                f"| serve: swap to task {task_id} rolled back ({e!r}); "
                f"still serving task {current}"
            )
            return {"ok": False, "task_id": current, "target": task_id,
                    "error": repr(e)}
        with self._lock:
            self._artifact = art
            self._swaps += 1
        self._sink.log(
            "serve_swap", from_task=current, to_task=art.task_id,
            load_ms=art.load_ms, compile_ms=art.compile_ms, path=art.path,
        )
        print(
            f"| serve: swapped task {current} -> {art.task_id} "
            f"(probe {'ok' if probe and probe['checked'] else 'absent'})"
        )
        return {"ok": True, "task_id": art.task_id,
                "probe_checked": bool(probe and probe.get("checked"))}

    def _load(self, task_id: int, manifest: Optional[dict] = None
              ) -> ServingArtifact:
        man = manifest if manifest is not None else read_manifest(self.export_dir)
        entry = man.get("artifacts", {}).get(str(task_id))
        if entry is None:
            raise OSError(f"task {task_id} not in manifest of {self.export_dir}")
        art = load_artifact(os.path.join(self.export_dir, entry["path"]))
        art.register_recompiles(self.monitor)
        return art


def main(argv=None) -> int:
    """Standalone entry: ``python -m serving.server --export_dir DIR``.

    Serves until interrupted; prints a stats line every ``--report_s``."""
    import argparse

    p = argparse.ArgumentParser("cil-tpu inference server")
    p.add_argument("--export_dir", required=True)
    p.add_argument("--serve_max_wait_ms", default=5.0, type=float,
                   help="micro-batch max-wait deadline")
    p.add_argument("--serve_poll_s", default=0.25, type=float,
                   help="manifest poll cadence for hot swaps")
    p.add_argument("--telemetry_dir", default=None,
                   help="serve telemetry (run.jsonl + flight ring) here")
    p.add_argument("--report_s", default=10.0, type=float)
    args = p.parse_args(argv)

    telemetry = None
    if args.telemetry_dir:
        from a_pytorch_tutorial_to_class_incremental_learning_tpu.telemetry import (
            Telemetry,
        )
        from a_pytorch_tutorial_to_class_incremental_learning_tpu.utils.logging import (
            JsonlLogger,
        )

        os.makedirs(args.telemetry_dir, exist_ok=True)
        telemetry = Telemetry(
            telemetry_dir=args.telemetry_dir,
            sink=JsonlLogger(os.path.join(args.telemetry_dir, "run.jsonl")),
        )
    server = InferenceServer(
        args.export_dir,
        max_wait_ms=args.serve_max_wait_ms,
        poll_s=args.serve_poll_s,
        telemetry=telemetry,
    ).start()
    print(f"| serving task {server.task_id} from {args.export_dir}")
    try:
        while True:
            time.sleep(args.report_s)
            print(f"| serve stats: {server.stats()}")
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
        if telemetry is not None:
            telemetry.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
