"""Fleet health: per-replica circuit breakers + heartbeat staleness.

The front end must never learn a replica is dead by timing out a user's
request twice.  This module keeps the per-replica verdict the router reads
on every dispatch:

* **Consecutive-error breaker** — every failed dispatch bumps the replica's
  consecutive-error count; at ``error_threshold`` the replica is ejected
  from rotation.  Any success resets the count (errors must be
  *consecutive* — a 1%% flake rate on a busy replica is noise, not death).
* **Heartbeat staleness** — replicas run as supervised subprocesses, each
  beating into its own ``heartbeat.json`` (``telemetry/heartbeat.py``).  A
  beat older than ``heartbeat_max_age_s`` ejects the replica even though
  its TCP port may still accept connections (a wedged jax runtime accepts
  and hangs; the heartbeat is the liveness signal that cannot lie).
* **Re-admission** — ejection is never final: the supervisor relaunches the
  replica, and the front end's monitor probes ejected replicas out-of-band
  (``/healthz`` + warm-up flag).  ``note_ready`` puts a probed-healthy
  replica back in rotation.

Every transition emits one ``replica_ejected`` record
(``event: "eject" | "readmit"``) so the fleet-health timeline in
``report_run.py`` reconstructs exactly when capacity dipped and recovered.

Stdlib-only, and every shared field lives under one lock; the heartbeat
``os.stat`` happens outside it (threadcheck: never hold a lock across a
blocking call — a stat on wedged NFS can block for minutes).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional


class FleetHealth:
    """Rotation membership for ``n`` replicas (ids ``0..n-1``)."""

    def __init__(
        self,
        n: int,
        error_threshold: int = 3,
        heartbeat_max_age_s: float = 0.0,
        heartbeat_paths: Optional[List[str]] = None,
        sink=None,
    ):
        if n <= 0:
            raise ValueError(f"fleet needs at least one replica, got {n}")
        self.n = int(n)
        self.error_threshold = int(error_threshold)
        self.heartbeat_max_age_s = float(heartbeat_max_age_s)
        self.heartbeat_paths = list(heartbeat_paths or [])
        self._sink = sink
        self._lock = threading.Lock()
        self._consecutive: Dict[int, int] = {i: 0 for i in range(self.n)}
        self._ejected: Dict[int, bool] = {i: False for i in range(self.n)}
        self._ejections = 0
        self._readmissions = 0

    # ------------------------------------------------------------------ #
    # Dispatch feedback
    # ------------------------------------------------------------------ #

    def note_ok(self, replica: int) -> None:
        """A dispatch to ``replica`` succeeded: reset its breaker."""
        with self._lock:
            self._consecutive[replica] = 0

    def note_error(self, replica: int) -> bool:
        """A dispatch failed; returns True when this error ejects it."""
        with self._lock:
            self._consecutive[replica] += 1
            count = self._consecutive[replica]
            trip = (not self._ejected[replica]
                    and count >= self.error_threshold)
            if trip:
                self._ejected[replica] = True
                self._ejections += 1
        if trip:
            self._emit(replica, "eject", "consecutive_errors",
                       consecutive_errors=count)
        return trip

    def note_ready(self, replica: int) -> bool:
        """An out-of-band probe found the replica healthy; re-admit it.
        Returns True when this call changed its state."""
        with self._lock:
            changed = self._ejected[replica]
            self._ejected[replica] = False
            self._consecutive[replica] = 0
            if changed:
                self._readmissions += 1
        if changed:
            self._emit(replica, "readmit", "probe_ok")
        return changed

    # ------------------------------------------------------------------ #
    # Heartbeat staleness
    # ------------------------------------------------------------------ #

    def check_heartbeats(self) -> List[int]:
        """Eject every replica whose heartbeat file is stale; returns the
        replicas ejected by THIS sweep.  Disabled unless both a positive
        ``heartbeat_max_age_s`` and per-replica paths were configured.  A
        missing file is not stale (the replica may still be starting; the
        consecutive-error breaker covers a replica that never comes up)."""
        if self.heartbeat_max_age_s <= 0 or not self.heartbeat_paths:
            return []
        now = time.time()
        stale: List[tuple] = []
        for replica, path in enumerate(self.heartbeat_paths[: self.n]):
            try:
                age = now - os.stat(path).st_mtime
            except OSError:
                continue
            if age > self.heartbeat_max_age_s:
                stale.append((replica, age))
        tripped: List[int] = []
        for replica, age in stale:
            with self._lock:
                trip = not self._ejected[replica]
                if trip:
                    self._ejected[replica] = True
                    self._ejections += 1
            if trip:
                tripped.append(replica)
                self._emit(replica, "eject", "heartbeat_stale",
                           heartbeat_age_s=round(age, 1))
        return tripped

    # ------------------------------------------------------------------ #
    # Introspection
    # ------------------------------------------------------------------ #

    def healthy(self) -> List[int]:
        with self._lock:
            return [i for i in range(self.n) if not self._ejected[i]]

    def ejected(self) -> List[int]:
        with self._lock:
            return [i for i in range(self.n) if self._ejected[i]]

    def is_healthy(self, replica: int) -> bool:
        with self._lock:
            return not self._ejected[replica]

    def stats(self) -> dict:
        with self._lock:
            return {
                "healthy": [i for i in range(self.n)
                            if not self._ejected[i]],
                "ejected": [i for i in range(self.n) if self._ejected[i]],
                "ejections": self._ejections,
                "readmissions": self._readmissions,
                "consecutive_errors": dict(self._consecutive),
            }

    # ------------------------------------------------------------------ #

    def _emit(self, replica: int, event: str, reason: str, **extra) -> None:
        # Outside the lock on every path: a sink write is file I/O.
        if self._sink is not None:
            self._sink.log("replica_ejected", replica=replica, event=event,
                           reason=reason, **extra)
        print(f"| fleet: replica {replica} {event} ({reason})")
