"""Fleet front end: admission control, shedding, failover, rolling swaps.

The replicas (``serving/replica.py``) own the accelerator work; this module
owns *availability*.  It is deliberately stdlib-only — no jax, no numpy —
because it must keep routing while any replica's runtime is wedged, and
because the chaos smoke runs it in-process under the ThreadCheck sentinel
(``analysis/threadcheck.py``): no lock is ever held across a socket read,
a ``Future.result`` or a queue operation.

* **Admission + shedding** — two priority classes (``X-Priority: high`` /
  ``low``) share one bounded in-flight budget.  Low is admitted only below
  ``low_watermark``, high up to ``capacity``; beyond that the request is
  shed with HTTP 503 and a rate-limited ``serve_shed`` record.  Shedding
  low first keeps the high-priority p99 flat through overload — the
  batching/latency tradeoff the Gemma serving comparison (arXiv:2605.25645)
  frames — and an explicit 503 beats an implicit timeout: the client knows
  *now* and can back off.
* **Failover** — a dispatch error marks the replica in the circuit breaker
  (``serving/health.py``) and retries the next healthy replica with a
  short growing backoff (``frontend_retry`` records), all inside the
  request's deadline.  A SIGKILL'd replica costs the fleet one retry per
  in-flight request, never a failed client request.
* **Hedging** — optionally, when the primary attempt is still pending at
  the hedge point, the same request is dispatched to a second replica and
  the first success wins (the tail-at-scale move: p99 of one replica
  becomes ~p99² of two).
* **Rolling swaps** — when the artifact store publishes a newer task, the
  rollout driver swaps ONE replica at a time via its skew-gated ``/swap``
  (``InferenceServer.swap_to``).  A refused swap (injected ``swap_ioerror``,
  probe mismatch) leaves that replica on the old artifact, emits
  ``serve_rollback``, and halts the wave — the rest of the fleet keeps
  serving, and the next poll retries.  Fleet availability never depends on
  a swap succeeding.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Sequence, Tuple

from .health import FleetHealth

PRIORITIES = ("high", "low")


def _read_manifest(export_dir: str) -> dict:
    """Local mirror of ``serving.artifact.read_manifest`` — same file, same
    torn-read tolerance — so this module never imports the jax-backed
    artifact machinery."""
    import os

    path = os.path.join(export_dir, "manifest.json")
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(int(q / 100.0 * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[idx]


class _Shed(Exception):
    """Request rejected at admission (becomes the 503)."""


class Frontend:
    """HTTP front end over a fleet of replica endpoints.

    ``replicas`` is the fixed address list ``[(host, port), ...]`` — replica
    i's supervisor relaunches it on the same port, so addresses are stable
    identities and health state (not the address list) tracks liveness.
    """

    def __init__(
        self,
        replicas: Sequence[Tuple[str, int]],
        port: int = 0,
        host: str = "127.0.0.1",
        capacity: int = 32,
        low_watermark: Optional[int] = None,
        default_deadline_ms: float = 2000.0,
        max_attempts: int = 4,
        retry_backoff_s: float = 0.02,
        hedge_ms: Optional[float] = None,
        error_threshold: int = 3,
        heartbeat_max_age_s: float = 0.0,
        heartbeat_paths: Optional[List[str]] = None,
        probe_s: float = 0.5,
        export_dir: Optional[str] = None,
        rollout_poll_s: Optional[float] = None,
        sink=None,
        faults=None,
        metrics=None,
    ):
        # Lazy import: telemetry/metrics.py is itself stdlib-only, but its
        # package __init__ pulls numpy — resolving it here keeps this
        # *module* importable with nothing but the stdlib.
        if metrics is None:
            from a_pytorch_tutorial_to_class_incremental_learning_tpu.telemetry.metrics import (  # noqa: E501
                MetricsRegistry,
            )

            metrics = MetricsRegistry()
        self.metrics = metrics
        self.replicas = [(h, int(p)) for h, p in replicas]
        self.capacity = int(capacity)
        self.low_watermark = (int(low_watermark) if low_watermark is not None
                              else max(self.capacity // 2, 1))
        self.default_deadline_ms = float(default_deadline_ms)
        self.max_attempts = int(max_attempts)
        self.retry_backoff_s = float(retry_backoff_s)
        self.hedge_ms = float(hedge_ms) if hedge_ms is not None else None
        self.probe_s = float(probe_s)
        self.export_dir = export_dir
        self.rollout_poll_s = (float(rollout_poll_s)
                               if rollout_poll_s is not None else None)
        self._sink = sink
        self._faults = faults
        self.health = FleetHealth(
            len(self.replicas),
            error_threshold=error_threshold,
            heartbeat_max_age_s=heartbeat_max_age_s,
            heartbeat_paths=heartbeat_paths,
            sink=sink,
        )

        self._lock = threading.Lock()
        self._inflight = {"high": 0, "low": 0}
        self._rr = 0  # round-robin cursor
        self._last_shed_emit: Dict[str, float] = {p: 0.0 for p in PRIORITIES}
        self._latencies: Dict[str, List[float]] = {p: [] for p in PRIORITIES}
        # Fleet counters live in the registry (the /metrics exposition the
        # scraper polls; /stats reads the same instruments).  Registry
        # updates always run OUTSIDE self._lock: the registry has its own
        # lock and the two must never nest (threadlint JL303).
        reg = self.metrics
        self._m_served = {
            p: reg.counter("fe_requests_total", priority=p)
            for p in PRIORITIES
        }
        self._m_failed = {
            p: reg.counter("fe_failed_total", priority=p) for p in PRIORITIES
        }
        self._m_shed = {
            p: reg.counter("fe_shed_total", priority=p) for p in PRIORITIES
        }
        self._m_latency = {
            p: reg.histogram("fe_latency_ms", lowest=0.5, growth=2.0,
                             buckets=18, priority=p)
            for p in PRIORITIES
        }
        self._m_inflight = {
            p: reg.gauge("fe_inflight", priority=p) for p in PRIORITIES
        }
        self._m_retries = reg.counter("fe_retries_total")
        self._m_hedges = reg.counter("fe_hedges_total")
        self._m_hedge_wins = reg.counter("fe_hedge_wins_total")
        self._m_rollout_swaps = reg.counter("fe_rollout_swaps_total")
        self._m_rollout_rollbacks = reg.counter("fe_rollout_rollbacks_total")
        self._m_ejected = reg.gauge("fe_ejected_replicas")
        self._m_ejections = reg.counter("fe_ejections_total")

        self._stop = threading.Event()
        # Hedged attempts need a second thread per request; cap the pool so
        # a hedge storm cannot spawn unbounded threads.
        self._hedge_pool = ThreadPoolExecutor(
            max_workers=max(2 * len(self.replicas), 4),
            thread_name_prefix="frontend-hedge",
        )
        self._monitor: Optional[threading.Thread] = None
        self._rollout: Optional[threading.Thread] = None

        frontend = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: ARG002
                pass

            def _reply(self, code, body, ctype="application/json",
                       headers=None):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/stats":
                    self._reply(200, json.dumps(frontend.stats()).encode())
                elif self.path == "/healthz":
                    self._reply(200, json.dumps(
                        {"replicas": frontend.health.stats()}).encode())
                elif self.path == "/metrics":
                    self._reply(200, frontend.metrics.to_prometheus().encode(),
                                ctype="text/plain; version=0.0.4")
                else:
                    self._reply(404, b'{"error": "no route"}')

            def do_POST(self):
                if self.path != "/predict":
                    self._reply(404, b'{"error": "no route"}')
                    return
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n) if n else b""
                priority = self.headers.get("X-Priority", "high").lower()
                if priority not in PRIORITIES:
                    priority = "high"
                deadline_ms = float(self.headers.get(
                    "X-Deadline-Ms", frontend.default_deadline_ms))
                try:
                    payload, hdrs = frontend.handle(body, priority,
                                                    deadline_ms)
                except _Shed as e:
                    self._reply(503, json.dumps(
                        {"shed": True, "priority": priority,
                         "reason": str(e)}).encode())
                except Exception as e:  # noqa: BLE001 — becomes the 502
                    self._reply(502, json.dumps(
                        {"error": repr(e), "priority": priority}).encode())
                else:
                    self._reply(200, payload,
                                ctype="application/octet-stream",
                                headers=hdrs)

        self._httpd = ThreadingHTTPServer((host, int(port)), Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._http_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------ #
    # Lifecycle
    # ------------------------------------------------------------------ #

    def start(self) -> "Frontend":
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="frontend-http",
            daemon=True,
        )
        self._http_thread.start()
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="frontend-monitor", daemon=True,
        )
        self._monitor.start()
        if self.rollout_poll_s is not None and self.export_dir:
            self._rollout = threading.Thread(
                target=self._rollout_loop, name="frontend-rollout",
                daemon=True,
            )
            self._rollout.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._http_thread is not None:
            # shutdown() blocks on an event only serve_forever() sets; on a
            # never-started front end it would wait forever.
            self._httpd.shutdown()
            self._http_thread.join()
        self._httpd.server_close()
        if self._monitor is not None:
            self._monitor.join()
        if self._rollout is not None:
            self._rollout.join()
        self._hedge_pool.shutdown(wait=True)

    # ------------------------------------------------------------------ #
    # Request path
    # ------------------------------------------------------------------ #

    def handle(self, body: bytes, priority: str,
               deadline_ms: float) -> Tuple[bytes, dict]:
        """Admission → (hedged) dispatch-with-failover → response bytes.
        Raises :class:`_Shed` on admission reject, the last dispatch error
        on total failure."""
        self._admit(priority)
        t0 = time.perf_counter()
        deadline = t0 + max(deadline_ms, 1.0) / 1000.0
        try:
            payload, hdrs = self._dispatch_hedged(body, deadline)
            lat_ms = (time.perf_counter() - t0) * 1000.0
            with self._lock:
                lat = self._latencies[priority]
                lat.append(lat_ms)
                if len(lat) > 16384:
                    del lat[:-8192]
            self._m_served[priority].inc()
            self._m_latency[priority].observe(lat_ms)
            hdrs["X-Priority"] = priority
            return payload, hdrs
        except _Shed:
            raise
        except Exception:
            self._m_failed[priority].inc()
            raise
        finally:
            with self._lock:
                self._inflight[priority] -= 1
                left = self._inflight[priority]
            self._m_inflight[priority].set(left)

    def _admit(self, priority: str) -> None:
        now = time.monotonic()
        with self._lock:
            total = self._inflight["high"] + self._inflight["low"]
            limit = (self.capacity if priority == "high"
                     else self.low_watermark)
            if total < limit:
                self._inflight[priority] += 1
                now_inflight = self._inflight[priority]
            else:
                now_inflight = None
                emit = now - self._last_shed_emit[priority] > 0.5
                if emit:
                    self._last_shed_emit[priority] = now
        if now_inflight is not None:
            self._m_inflight[priority].set(now_inflight)
            return
        shed = self._m_shed[priority]
        shed.inc()
        # Sheds are per-request events at overload rates — emit at most ~2/s
        # per class, carrying the cumulative count, so the telemetry stream
        # does not amplify the very overload it reports.
        if emit and self._sink is not None:
            self._sink.log("serve_shed", priority=priority, queued=total,
                           capacity=limit, shed_total=int(shed.value))
        raise _Shed(f"over {priority} admission limit ({total}/{limit})")

    def _pick(self, exclude: frozenset) -> Optional[int]:
        """Next healthy replica after the round-robin cursor; falls back to
        any non-excluded replica when the whole fleet looks ejected (a
        wrong breaker verdict must degrade to trying, not to refusing)."""
        healthy = [i for i in self.health.healthy() if i not in exclude]
        pool = healthy or [i for i in range(len(self.replicas))
                           if i not in exclude]
        if not pool:
            return None
        with self._lock:
            self._rr += 1
            return pool[self._rr % len(pool)]

    def _dispatch_once(self, replica: int, body: bytes,
                       timeout_s: float) -> Tuple[bytes, dict]:
        if self._faults is not None:
            actions = self._faults.fire("serve.frontend", task=replica)
            if "frontend_ioerror" in actions:
                raise OSError(
                    f"fault-injected dispatch failure (replica {replica})"
                )
        host, port = self.replicas[replica]
        conn = http.client.HTTPConnection(host, port,
                                          timeout=max(timeout_s, 0.05))
        try:
            conn.request("POST", "/predict", body=body, headers={
                "Content-Type": "application/octet-stream",
            })
            resp = conn.getresponse()
            payload = resp.read()
            if resp.status != 200:
                raise OSError(
                    f"replica {replica} returned {resp.status}: "
                    f"{payload[:128]!r}"
                )
            return payload, {
                "X-Task-Id": resp.headers.get("X-Task-Id", ""),
                "X-Replica": str(replica),
            }
        finally:
            conn.close()

    def _dispatch_chain(self, body: bytes, deadline: float,
                        exclude: frozenset, chosen: List[int],
                        ) -> Tuple[bytes, dict]:
        """Retry-with-backoff across healthy replicas until the deadline.
        ``chosen`` collects the replicas tried (the hedge excludes them)."""
        last: Optional[Exception] = None
        backoff = self.retry_backoff_s
        for attempt in range(1, self.max_attempts + 1):
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                break
            replica = self._pick(exclude | frozenset(chosen[-1:]))
            if replica is None:
                replica = self._pick(exclude)
            if replica is None:
                raise OSError("no replica available")
            chosen.append(replica)
            try:
                payload, hdrs = self._dispatch_once(replica, body, remaining)
            except Exception as e:  # noqa: BLE001 — every flavor fails over
                last = e
                self.health.note_error(replica)
                self._m_retries.inc()
                if self._sink is not None:
                    self._sink.log("frontend_retry", replica=replica,
                                   attempt=attempt, error=repr(e))
                time.sleep(min(backoff, max(deadline - time.perf_counter(),
                                            0.0)))
                backoff *= 2
                continue
            self.health.note_ok(replica)
            return payload, hdrs
        raise last if last is not None else OSError("request deadline hit")

    def _dispatch_hedged(self, body: bytes,
                         deadline: float) -> Tuple[bytes, dict]:
        chosen: List[int] = []
        if self.hedge_ms is None or len(self.replicas) < 2:
            return self._dispatch_chain(body, deadline, frozenset(), chosen)
        primary = self._hedge_pool.submit(
            self._dispatch_chain, body, deadline, frozenset(), chosen)
        done, _ = wait([primary], timeout=self.hedge_ms / 1000.0)
        if done:
            return primary.result()
        # Primary still pending at the hedge point: race a second attempt
        # on a different replica; first success wins, the loser's result
        # is discarded (replicas are stateless per-request).
        self._m_hedges.inc()
        hedge = self._hedge_pool.submit(
            self._dispatch_chain, body, deadline,
            frozenset(chosen[:1]), [])
        futures = {primary, hedge}
        last: Optional[Exception] = None
        while futures:
            remaining = deadline - time.perf_counter() + 1.0
            done, futures = wait(futures, timeout=max(remaining, 0.05),
                                 return_when=FIRST_COMPLETED)
            if not done:
                break
            for fut in done:
                try:
                    payload, hdrs = fut.result()
                except Exception as e:  # noqa: BLE001 — other fut may win
                    last = e
                    continue
                if fut is hedge:
                    self._m_hedge_wins.inc()
                return payload, hdrs
        raise last if last is not None else OSError("request deadline hit")

    # ------------------------------------------------------------------ #
    # Health monitor + rolling swaps
    # ------------------------------------------------------------------ #

    def _monitor_loop(self) -> None:
        known_ejected: set = set()
        while not self._stop.wait(self.probe_s):
            self.health.check_heartbeats()
            ejected = set(self.health.ejected())
            # Transition counting stays local to this (single) thread; the
            # registry carries the level and the cumulative eject count.
            fresh = ejected - known_ejected
            if fresh:
                self._m_ejections.inc(len(fresh))
            self._m_ejected.set(len(ejected))
            known_ejected = ejected
            for replica in sorted(ejected):
                if self._probe_ready(replica):
                    self.health.note_ready(replica)
                    known_ejected.discard(replica)

    def _probe_ready(self, replica: int) -> bool:
        """Out-of-band ``/healthz`` probe: the replica must answer AND be
        warm (post-relaunch it accepts TCP before its programs compile)."""
        host, port = self.replicas[replica]
        conn = http.client.HTTPConnection(host, port, timeout=2.0)
        try:
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            if resp.status != 200:
                return False
            info = json.loads(resp.read())
            return bool(info.get("warm"))
        except OSError:
            return False
        finally:
            conn.close()

    def _replica_task(self, replica: int) -> Optional[int]:
        host, port = self.replicas[replica]
        conn = http.client.HTTPConnection(host, port, timeout=2.0)
        try:
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            if resp.status != 200:
                return None
            task = json.loads(resp.read()).get("task_id")
            return int(task) if task is not None else None
        except (OSError, ValueError):
            return None
        finally:
            conn.close()

    def _rollout_loop(self) -> None:
        while not self._stop.wait(self.rollout_poll_s):
            try:
                self.rollout_once()
            except Exception as e:  # noqa: BLE001 — rollout must not die
                print(f"| frontend: rollout pass failed: {e!r}")

    def rollout_once(self) -> dict:
        """One rolling-swap wave: move every healthy replica that is behind
        the manifest's latest task, one at a time, halting the wave at the
        first refusal.  Idempotent — call it until it reports converged."""
        man = _read_manifest(self.export_dir) if self.export_dir else {}
        latest = man.get("latest")
        if latest is None:
            return {"converged": True, "latest": None}
        latest = int(latest)
        moved, behind = [], []
        for replica in range(len(self.replicas)):
            if not self.health.is_healthy(replica):
                behind.append(replica)  # swept into a later wave
                continue
            current = self._replica_task(replica)
            if current == latest:
                continue
            if current is None:
                # Unreachable but not (yet) ejected: liveness is the
                # breaker's verdict to make, not the rollout's — swapping
                # a dead endpoint would read as a rollback.
                behind.append(replica)
                continue
            ok, detail = self._swap_replica(replica, latest)
            if not ok:
                behind.append(replica)
                self._m_rollout_rollbacks.inc()
                if self._sink is not None:
                    self._sink.log(
                        "serve_rollback", task_id=latest,
                        rolled_back_to=current, replica=replica,
                        reason=detail,
                    )
                print(f"| frontend: replica {replica} refused swap to "
                      f"task {latest} ({detail}); wave halted")
                # One replica at a time ALSO means one failure stops the
                # wave: if the artifact itself is bad, the rest of the
                # fleet must not march into it.
                break
            moved.append(replica)
            self._m_rollout_swaps.inc()
        return {"converged": not behind and not moved, "latest": latest,
                "moved": moved, "behind": behind}

    def _swap_replica(self, replica: int, task_id: int) -> Tuple[bool, str]:
        host, port = self.replicas[replica]
        conn = http.client.HTTPConnection(host, port, timeout=120.0)
        try:
            conn.request(
                "POST", "/swap",
                body=json.dumps({"task_id": task_id}).encode(),
                headers={"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            payload = resp.read()
            if resp.status == 200:
                return True, ""
            try:
                detail = json.loads(payload).get("error", payload[:128])
            except ValueError:
                detail = repr(payload[:128])
            return False, str(detail)
        except OSError as e:
            return False, repr(e)
        finally:
            conn.close()

    # ------------------------------------------------------------------ #
    # Introspection
    # ------------------------------------------------------------------ #

    def stats(self) -> dict:
        """Same dict shape as ever; the counts are now *read back* from the
        registry (the single source of truth /metrics also exposes), while
        the percentiles keep coming from the exact latency samples."""
        with self._lock:
            inflight = dict(self._inflight)
            sorted_lat = {p: sorted(self._latencies[p]) for p in PRIORITIES}
        # Registry reads happen after self._lock is released (never nested).
        out = {
            "served": {p: int(self._m_served[p].value) for p in PRIORITIES},
            "failed": {p: int(self._m_failed[p].value) for p in PRIORITIES},
            "shed": {p: int(self._m_shed[p].value) for p in PRIORITIES},
            "retries": int(self._m_retries.value),
            "hedges": int(self._m_hedges.value),
            "hedge_wins": int(self._m_hedge_wins.value),
            "rollout_swaps": int(self._m_rollout_swaps.value),
            "rollout_rollbacks": int(self._m_rollout_rollbacks.value),
            "inflight": inflight,
            "latency_ms": {},
        }
        for p in PRIORITIES:
            vals = sorted_lat[p]
            out["latency_ms"][p] = {
                "count": len(vals),
                "p50": round(_percentile(vals, 50), 3),
                "p95": round(_percentile(vals, 95), 3),
                "p99": round(_percentile(vals, 99), 3),
            }
        out["health"] = self.health.stats()
        return out
