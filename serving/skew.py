"""Training/serving skew: re-measure accuracy *through the artifact*.

The trainer's accuracy matrix says what the live model scored right after
weight alignment; this module asks the question production actually cares
about — does the *served* model (export → serialize → reload → AOT program)
still score that?  Any gap (a stale artifact after a failed swap, a
normalization mismatch between the exported preprocessing and training
eval, a corrupted weights payload that still unpickles) shows up as skew.

``measure_skew`` evaluates every seen task's validation slice through
``ServingArtifact.predict`` and emits one ``serve_skew`` record comparing
the per-task served accuracies with the training-side row (the ``task``
record's ``acc_per_task``).  For a healthy artifact the skew is exactly
zero: the exported program is the same computation as the trainer's eval
step at the same batch shapes.

``probe_artifact`` is the *online* flavor of the same question: the export
froze a golden ``probe.npz`` (deterministic input + the logits the program
produced at export time, ``serving/artifact.py``), and a freshly swapped-in
replica replays it through its own AOT executables demanding exact
equality.  It needs no validation set, runs in one bucket-sized inference,
and is the promotion gate of the fleet's rolling swaps — a probe miss rolls
that replica back (``serve_rollback``) instead of serving skewed logits.
"""

from __future__ import annotations

import io
import os
from typing import Optional, Sequence

import numpy as np

from a_pytorch_tutorial_to_class_incremental_learning_tpu.data.datasets import (
    maybe_decode,
)
from a_pytorch_tutorial_to_class_incremental_learning_tpu.utils.checkpoint import (
    _sha256_file,
)


def _slice_accuracy(artifact, x: np.ndarray, y: np.ndarray) -> float:
    logits = artifact.predict(x)
    top1 = np.argmax(logits[:, : artifact.known], axis=-1)
    return float(100.0 * np.mean(top1 == np.asarray(y)))


def measure_skew(
    artifact,
    scenario_val,
    sink=None,
    train_acc_per_task: Optional[Sequence[float]] = None,
    seed: int = 0,
) -> dict:
    """Per-seen-task served accuracy vs the training row; one record.

    ``scenario_val`` is the validation ``ClassIncremental`` scenario; the
    artifact's ``known`` determines how many of its tasks the served head
    covers.  Returns the record fields (also logged to ``sink`` when given).
    """
    increments = scenario_val.increments()
    seen, cum = 0, 0
    for inc in increments:
        if cum + inc > artifact.known:
            break
        cum += inc
        seen += 1
    served, weights = [], []
    for j in range(seen):
        task = scenario_val[j]
        x = maybe_decode(task.x, artifact.meta["input_size"], train=False,
                         seed=seed)
        served.append(round(_slice_accuracy(artifact, x, task.y), 5))
        weights.append(len(task.y))
    total = max(sum(weights), 1)
    served_acc1 = round(
        float(sum(a * w for a, w in zip(served, weights)) / total), 5
    )
    train_row = (
        [float(a) for a in train_acc_per_task[:seen]]
        if train_acc_per_task is not None else None
    )
    skew_abs_max = (
        round(max(abs(s - t) for s, t in zip(served, train_row)), 5)
        if train_row else None
    )
    record = dict(
        task_id=artifact.task_id,
        served_acc1=served_acc1,
        served_acc_per_task=served,
        train_acc_per_task=train_row,
        skew_abs_max=skew_abs_max,
        n=int(total),
    )
    if sink is not None:
        sink.log("serve_skew", **record)
    return record


def probe_artifact(artifact) -> dict:
    """Replay the artifact's golden probe through its loaded executables.

    Returns ``{"ok": bool, "checked": bool, "max_abs": float, ...}``.
    ``ok`` is the promotion verdict: exact bit-equality with the logits the
    export froze (the exported program is deterministic — any difference
    means the artifact on disk is not the artifact that was exported, or the
    load resolved to different code).  Artifacts from before the probe
    existed pass with ``checked=False`` — absence of evidence is not skew.
    A corrupt probe file (checksum/read failure) FAILS: during a rolling
    swap, an unverifiable artifact must not be promoted.
    """
    probe_name = artifact.meta.get("files", {}).get("probe")
    if not probe_name:
        return {"ok": True, "checked": False, "max_abs": 0.0}
    path = os.path.join(artifact.path, probe_name)
    try:
        sidecar = path + ".sha256"
        if os.path.exists(sidecar):
            with open(sidecar) as f:
                want = f.read().strip()
            got = _sha256_file(path)
            if got != want:
                return {"ok": False, "checked": True, "max_abs": float("inf"),
                        "error": f"probe checksum mismatch ({got[:12]})"}
        with open(path, "rb") as f:
            blob = np.load(io.BytesIO(f.read()))
        probe_x = blob["x"]
        want_logits = blob["logits"]
        bucket = int(blob["bucket"])
    except (OSError, ValueError, KeyError) as e:
        return {"ok": False, "checked": True, "max_abs": float("inf"),
                "error": f"unreadable probe: {e!r}"}
    if bucket not in artifact.buckets:
        return {"ok": False, "checked": True, "max_abs": float("inf"),
                "error": f"probe bucket {bucket} not loaded"}
    got_logits = artifact.predict_padded(probe_x, bucket)
    max_abs = float(np.max(np.abs(
        got_logits.astype(np.float64) - want_logits.astype(np.float64)
    )))
    return {
        "ok": bool(np.array_equal(got_logits, want_logits)),
        "checked": True,
        "max_abs": max_abs,
    }
