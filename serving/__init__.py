"""Serving: AOT-exported per-task artifacts + a hot-swapping batched server.

The production story for class-incremental learning is a model that keeps
*serving* while it keeps *learning*: WA (PAPER.md) grows a new head every
task, but training alone cannot answer a query — everything the trainer
computes dies when ``train.py`` exits.  This package is the inference half:

* :mod:`.artifact` — after each task's weight alignment the trainer freezes
  an inference-only pytree (params + batch stats + task metadata + class
  map), AOT-lowers the predict function per supported batch bucket, and
  serializes it with ``jax.export`` next to a sha256-sidecar'd weights
  payload; a ``manifest.json`` names the newest task atomically.
* :mod:`.server` — a stdlib-threaded micro-batching server over those
  artifacts: pad-to-bucket dispatch with a max-wait deadline, and an atomic
  hot swap when a new task's artifact lands in the manifest.
* :mod:`.skew` — served-model accuracy re-measured through the artifact and
  compared against the training-side accuracy matrix (``serve_skew``), plus
  the golden-probe replay (``probe_artifact``) that gates fleet swaps.
* :mod:`.replica` / :mod:`.frontend` / :mod:`.health` — the resilience
  tier: N supervised replica subprocesses behind a stdlib HTTP front end
  with admission control, priority shedding, circuit-breaker failover,
  hedged dispatch and skew-gated rolling swaps with per-replica rollback.

Serving never traces: artifacts are loaded by AOT-compiling the deserialized
exported programs, so a warm server restart (same artifacts, persistent XLA
compilation cache) performs zero re-traces — provable with the same
``RecompileSentinel`` contract the trainer uses (tests/test_serving.py).
"""

from .artifact import (  # noqa: F401
    DEFAULT_BUCKETS,
    ServingArtifact,
    direct_predict,
    export_artifact,
    export_from_trainer,
    latest_artifact,
    load_artifact,
    make_predict_fn,
    read_manifest,
    rebuild_model,
    register_artifact,
)
from .frontend import Frontend  # noqa: F401
from .health import FleetHealth  # noqa: F401
from .replica import ReplicaServer, supervised_replica_cmd  # noqa: F401
from .server import InferenceServer  # noqa: F401
from .skew import measure_skew, probe_artifact  # noqa: F401
