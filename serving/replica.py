"""One fleet replica: an ``InferenceServer`` behind a tiny HTTP transport.

The resilience tier runs N of these as *supervised subprocesses*
(``scripts/supervise.py``) off one shared artifact store, so a replica
dying — SIGKILL'd by a preemption or the injected ``replica_die`` fault —
is a routine lifecycle event (Podracer, arXiv:2104.06272): the supervisor
relaunches it with decorrelated-jitter backoff, it rebinds its fixed port
(``allow_reuse_address``), warms up, and the front end's probe re-admits
it.  Each replica beats into its own ``<telemetry_dir>/replica_<i>/``
heartbeat + flight ring, which is exactly what the front end's staleness
breaker and the supervisor's hang detection watch.

Transport is stdlib ``http.server`` with a thread per connection; payloads
are raw ``.npy`` bytes (``encode_image`` / ``decode_logits``), so a client
needs numpy and nothing else:

* ``POST /predict``  — uint8 image ``.npy`` in, logits ``.npy`` out, with
  ``X-Task-Id`` / ``X-Latency-Ms`` response headers.  Fires the
  ``serve.replica`` fault site (``replica_die`` / ``slow_replica``) before
  touching the queue — the fault strikes the replica, never the client.
* ``GET /healthz``   — ``{replica, task_id, warm, served, pid}``; ``warm``
  flips true after the post-start self-inference, and the front end's
  re-admission probe requires it (a replica that accepts TCP but has not
  compiled its programs yet would eat real traffic).
* ``POST /swap``     — ``{"task_id": T}`` → skew-gated ``swap_to`` on the
  wrapped server; HTTP 409 on rollback so the rollout driver sees the
  verdict in-band.  Replicas run ``auto_swap=False``: the fleet rolls one
  replica at a time, a watcher-per-replica racing the rollout would not.
* ``GET /stats``     — the server's stats dict + ``trace_count``.
"""

from __future__ import annotations

import io
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


def encode_image(x) -> bytes:
    """uint8 image array -> ``.npy`` bytes (the /predict request body)."""
    import numpy as np

    buf = io.BytesIO()
    np.save(buf, np.ascontiguousarray(x, np.uint8))
    return buf.getvalue()


def decode_logits(body: bytes):
    """/predict response body -> logits array."""
    import numpy as np

    return np.load(io.BytesIO(body))


class ReplicaServer:
    """HTTP wrapper around one ``InferenceServer``; serves until stopped."""

    def __init__(
        self,
        export_dir: str,
        replica_id: int,
        port: int = 0,
        host: str = "127.0.0.1",
        max_wait_ms: float = 2.0,
        telemetry=None,
        sink=None,
        faults=None,
        request_timeout_s: float = 30.0,
        metrics=None,
    ):
        from a_pytorch_tutorial_to_class_incremental_learning_tpu.telemetry import (  # noqa: E501
            MetricsRegistry,
        )

        from .server import InferenceServer

        self.replica_id = int(replica_id)
        self.request_timeout_s = float(request_timeout_s)
        self._faults = faults
        self._telemetry = telemetry
        self._warm = threading.Event()
        # A replica always carries a live registry (the /metrics exposition
        # the fleet scraper polls) unless the telemetry facade was built
        # with --no_metrics, in which case its NullRegistry wins.
        if metrics is None and telemetry is not None:
            metrics = getattr(telemetry, "metrics", None)
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self.server = InferenceServer(
            export_dir,
            max_wait_ms=max_wait_ms,
            telemetry=telemetry,
            sink=sink,
            faults=faults,
            auto_swap=False,
            replica_id=self.replica_id,
            metrics=self.metrics,
        )
        replica = self

        class Handler(BaseHTTPRequestHandler):
            # One replica serves many short requests; per-request log lines
            # on stderr would swamp the supervisor's event stream.
            def log_message(self, fmt, *args):  # noqa: ARG002
                pass

            def _reply(self, code: int, body: bytes,
                       ctype: str = "application/json",
                       headers: Optional[dict] = None) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _reply_json(self, code: int, obj: dict) -> None:
                self._reply(code, json.dumps(obj).encode())

            def _body(self) -> bytes:
                n = int(self.headers.get("Content-Length", 0))
                return self.rfile.read(n) if n else b""

            def do_GET(self):
                if self.path == "/healthz":
                    self._reply_json(200, replica.healthz())
                elif self.path == "/stats":
                    stats = replica.server.stats()
                    stats["replica"] = replica.replica_id
                    stats["trace_count"] = replica.server.trace_count()
                    self._reply_json(200, stats)
                elif self.path == "/metrics":
                    self._reply(
                        200,
                        replica.metrics.to_prometheus().encode(),
                        ctype="text/plain; version=0.0.4",
                    )
                else:
                    self._reply_json(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                if self.path == "/predict":
                    self._predict()
                elif self.path == "/swap":
                    self._swap()
                else:
                    self._reply_json(404, {"error": f"no route {self.path}"})

            def _predict(self):
                body = self._body()
                try:
                    # The fault strikes before the queue: replica_die
                    # SIGKILLs this process (the supervisor relaunches),
                    # slow_replica stalls just this request.
                    if replica._faults is not None:
                        replica._faults.fire(
                            "serve.replica", task=replica.replica_id
                        )
                    x = decode_logits(body)  # same .npy codec both ways
                    fut = replica.server.submit(x)
                    res = fut.result(timeout=replica.request_timeout_s)
                except Exception as e:  # noqa: BLE001 — becomes a 500
                    self._reply_json(500, {"error": repr(e),
                                           "replica": replica.replica_id})
                    return
                import numpy as np

                out = io.BytesIO()
                np.save(out, res["logits"])
                self._reply(
                    200, out.getvalue(), ctype="application/octet-stream",
                    headers={
                        "X-Task-Id": str(res["task_id"]),
                        "X-Replica": str(replica.replica_id),
                        "X-Latency-Ms": f"{res['latency_ms']:.3f}",
                    },
                )

            def _swap(self):
                try:
                    req = json.loads(self._body() or b"{}")
                    result = replica.server.swap_to(int(req["task_id"]))
                except Exception as e:  # noqa: BLE001 — becomes a 500
                    self._reply_json(500, {"error": repr(e)})
                    return
                result["replica"] = replica.replica_id
                self._reply_json(200 if result.get("ok") else 409, result)

        self._httpd = ThreadingHTTPServer((host, int(port)), Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._http_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------ #

    def start(self) -> "ReplicaServer":
        self.server.start()
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"replica-{self.replica_id}-http", daemon=True,
        )
        self._http_thread.start()
        self._warmup()
        return self

    def _warmup(self) -> None:
        """One self-inference so the first real request never pays a cold
        queue + executable page-in; ``warm`` gates front-end re-admission."""
        import numpy as np

        meta = self.server._artifact.meta  # artifact is set post-start
        x = np.zeros(
            (meta["input_size"], meta["input_size"], meta["channels"]),
            np.uint8,
        )
        self.server.submit(x).result(timeout=60.0)
        self._warm.set()

    def healthz(self) -> dict:
        return {
            "replica": self.replica_id,
            "task_id": self.server.task_id,
            "warm": self._warm.is_set(),
            "served": self.server.stats()["served"],
            "pid": os.getpid(),
        }

    def stop(self) -> None:
        if self._http_thread is not None:
            # shutdown() blocks on an event only serve_forever() sets; on a
            # never-started replica it would wait forever.
            self._httpd.shutdown()
            self._http_thread.join()
        self._httpd.server_close()
        self.server.stop()


# --------------------------------------------------------------------- #
# Supervised fleet launcher (subprocess side)
# --------------------------------------------------------------------- #


def supervised_replica_cmd(
    repo_root: str,
    export_dir: str,
    replica_id: int,
    port: int,
    telemetry_dir: str,
    fault_spec: Optional[str] = None,
    max_age_s: float = 15.0,
    backoff_base: float = 0.2,
    backoff_max: float = 2.0,
    check_threads: bool = False,
    check_contracts: bool = False,
    python: Optional[str] = None,
    compile_cache: Optional[str] = None,
) -> list:
    """The ``scripts/supervise.py`` command line that runs one replica as a
    supervised subprocess — the same relaunch machinery training uses, so a
    SIGKILL'd replica comes back on its own with jittered backoff.  The
    replica's heartbeat lives under ``<telemetry_dir>/replica_<i>/``; the
    resume flag is disabled (a replica has no checkpoint to resume)."""
    import sys

    py = python or sys.executable
    rdir = os.path.join(telemetry_dir, f"replica_{replica_id}")
    child = [
        py, "-m", "serving.replica",
        "--export_dir", export_dir,
        "--replica_id", str(replica_id),
        "--port", str(port),
        "--telemetry_dir", rdir,
    ]
    if fault_spec:
        child += ["--fault_spec", fault_spec,
                  "--fault_ledger", os.path.join(rdir, "fault_ledger.jsonl")]
    if check_threads:
        child.append("--check_threads")
    if check_contracts:
        child.append("--check_contracts")
    if compile_cache:
        # Both sides: the child flag arms the persistent cache for a direct
        # launch, the supervisor flag exports JAX_COMPILATION_CACHE_DIR so a
        # *relaunched* replica re-fetches its serving executables instead of
        # re-compiling them (trace-free failover).
        child += ["--compile_cache", compile_cache]
    sup_extra = (["--compile_cache", compile_cache] if compile_cache else [])
    return [
        py, os.path.join(repo_root, "scripts", "supervise.py"),
    ] + sup_extra + [
        "--heartbeat", os.path.join(rdir, "heartbeat.json"),
        "--max_age", str(max_age_s),
        "--poll", "0.5", "--grace", "20",
        "--backoff_base", str(backoff_base),
        "--backoff_max", str(backoff_max),
        "--backoff_seed", str(1000 + replica_id),
        "--max_failures", "10", "--failure_window", "600",
        "--resume_flag", "",
        "--telemetry_dir", rdir,
        "--log", os.path.join(rdir, "supervisor.jsonl"),
        "--",
    ] + child


def main(argv=None) -> int:
    """``python -m serving.replica`` — one replica process, serves until
    SIGTERM/SIGKILL.  Run under ``scripts/supervise.py`` in fleets."""
    import argparse

    p = argparse.ArgumentParser("cil-tpu serving replica")
    p.add_argument("--export_dir", required=True)
    p.add_argument("--replica_id", type=int, required=True)
    p.add_argument("--port", type=int, required=True,
                   help="fixed port: the supervisor's relaunch must rebind "
                   "the address the front end already routes to")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--max_wait_ms", type=float, default=2.0)
    p.add_argument("--telemetry_dir", default=None)
    p.add_argument("--fault_spec", default=None)
    p.add_argument("--fault_ledger", default=None)
    p.add_argument("--check_threads", action="store_true")
    p.add_argument("--check_contracts", action="store_true")
    p.add_argument("--compile_cache", default=None,
                   help="persistent XLA compile-cache directory; a replica "
                   "armed with the cache its trainer populated loads the "
                   "serving executable without re-compiling (trace-free "
                   "model swap / failover)")
    p.add_argument("--heartbeat_s", type=float, default=2.0)
    p.add_argument("--metrics_interval_s", type=float, default=2.0,
                   help="MetricsPump flush cadence for metrics_snapshot "
                   "records + the heartbeat's serve-qps digest")
    args = p.parse_args(argv)

    check = None
    if args.check_threads:
        from analysis import threadcheck

        check = threadcheck.install()
    contracts = None
    if args.check_contracts:
        from analysis import contractcheck

        contracts = contractcheck.install()

    telemetry = None
    sink = None
    if args.telemetry_dir:
        from a_pytorch_tutorial_to_class_incremental_learning_tpu.telemetry import (  # noqa: E501
            Telemetry,
        )
        from a_pytorch_tutorial_to_class_incremental_learning_tpu.utils.logging import (  # noqa: E501
            JsonlLogger,
        )

        os.makedirs(args.telemetry_dir, exist_ok=True)
        sink = JsonlLogger(os.path.join(args.telemetry_dir, "run.jsonl"))
        if contracts is not None:
            from analysis import contractcheck

            sink = contractcheck.wrap_sink(sink)
        telemetry = Telemetry(
            telemetry_dir=args.telemetry_dir, sink=sink,
            heartbeat_interval_s=args.heartbeat_s,
            metrics_interval_s=args.metrics_interval_s,
            metrics_source="replica",
        )
        if check is not None:
            check.bind_sink(telemetry.sink)
        if contracts is not None:
            from analysis import contractcheck

            contracts.bind_sink(telemetry.sink)
            telemetry.metrics = contractcheck.wrap_registry(
                telemetry.metrics)

    if args.compile_cache:
        from a_pytorch_tutorial_to_class_incremental_learning_tpu.utils.platform import (  # noqa: E501
            enable_compile_cache,
        )

        enable_compile_cache(args.compile_cache)
    # Price the AOT load + warmup: with a warm persistent cache compile_s
    # must be ≈0 (scripts/warmcache_smoke.py asserts it; perf_gate gates it).
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.telemetry import (  # noqa: E501
        CompileWatch,
    )

    watch = CompileWatch.install()
    watch_before = watch.snapshot()

    faults = None
    if args.fault_spec:
        from faults.injector import injector_from

        faults = injector_from(
            args.fault_spec, ledger_path=args.fault_ledger,
            sink=telemetry.sink if telemetry is not None else sink,
        )

    replica = ReplicaServer(
        args.export_dir,
        replica_id=args.replica_id,
        port=args.port,
        host=args.host,
        max_wait_ms=args.max_wait_ms,
        telemetry=telemetry,
        sink=sink,
        faults=faults,
    ).start()
    compile_delta = CompileWatch.delta(watch_before, watch.snapshot())
    if sink is not None:
        sink.log("compile_event", task_id=int(replica.server.task_id or 0),
                 source="replica", **compile_delta)
    if telemetry is not None:
        telemetry.heartbeat.update(force=True, phase="serve",
                                   task=replica.server.task_id or 0)
        telemetry.heartbeat.start()
    print(f"| replica {args.replica_id} serving task "
          f"{replica.server.task_id} on {replica.host}:{replica.port} "
          f"(compile_s={compile_delta['compile_s']})",
          flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        replica.stop()
        if telemetry is not None:
            telemetry.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
