"""Single-chip benchmark of the hot loop: the KD train step.

Measures steady-state wall time of the compiled train step (augmentation +
student forward + teacher forward + backward + SGD, i.e. the tasks>=1 hot
loop, reference ``template.py:251-280``) for ResNet-32 at the reference's
global batch, and derives images/sec and an MFU estimate.

Baseline derivation (BASELINE.md): the reference runs CIFAR-100 B50-inc10
(6 tasks x 140 epochs, global batch 512 on 4x RTX 3090) in ~30 min.  Total
trained images ~= 140 * (25000 + 5 * ~7000) ~= 8.4M, so the reference's
end-to-end training throughput is ~4700 img/s across 4 GPUs.  ``vs_baseline``
is ours/theirs on that number — a deliberately conservative comparison:
per chip, our step includes everything (their 30 min also buys eval/herding,
but their step excludes augmentation, which runs on CPU workers).

MFU comes from XLA's own per-executable ``cost_analysis()`` FLOP count, not
a hand model (a hand-derived 4x-forward estimate implied >100% MFU in an
earlier round — the estimate, not the chip, was wrong).

Timing methodology (tunneled-TPU safe): on this environment's tunneled TPU
platform ``block_until_ready`` returns before remote execution finishes (it
"fenced" a 1.3 ms number for a step that, measured honestly, takes ~2x
longer — and 8000 TFLOP/s for a bare matmul), and every device->host fetch
pays a fixed ~90 ms RPC round trip.  So each measurement (a) fences with a
device->host scalar fetch through the threaded state — the only barrier
that provably waits — and (b) runs two fetch-fenced loops of different
lengths and takes the slope, cancelling the fixed round-trip cost exactly.
Slope-timed matmuls reproduce ~94% of the chip's 197 TFLOP/s bf16 peak, so
the methodology reads true device time.

Robustness contract: this script ALWAYS prints exactly one JSON line on
stdout and exits 0, even when the accelerator backend is unreachable — the
backend is probed in a subprocess with a timeout first, and measurement
falls back to CPU (``"backend": "cpu"``) or, on total failure, to an error
line with ``"value": 0``.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time

import numpy as np

REFERENCE_IMG_PER_SEC = 4700.0  # 4x3090, see module docstring

# Per-chip peak for MFU bookkeeping (bf16 MXU peak for v5e); only used for
# the est_mfu extra, never for the headline metric.
PEAK_FLOPS = {"tpu": 197e12}


def probe_backend(timeout_s: float = 90.0) -> str:
    """Return the default backend name, probed OUT of process.

    A wedged accelerator plugin can hang ``jax.devices()`` forever inside
    this process (round-2 failure mode: rc=1/rc=124 artifacts, no JSON).
    Probing in a killable subprocess turns that hang into a clean CPU
    fallback.
    """
    try:
        out = subprocess.run(
            [sys.executable, "-c", "import jax; print(jax.default_backend())"],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
        backend = out.stdout.strip().splitlines()[-1] if out.stdout.strip() else ""
        if out.returncode == 0 and backend:
            return backend
    except (subprocess.TimeoutExpired, OSError):
        pass
    return "cpu"


def force_cpu() -> None:
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.utils.platform import (
        force_platform,
    )

    # Same persistent compile cache as conftest/dryrun: the fallback must not
    # repay the multi-minute XLA:CPU compile on every driver invocation.
    # CIL_BENCH_CACHE_DIR overrides it so perf_gate.py --compile can point
    # cold/warm runs at a cache dir whose state it controls.
    cache = os.environ.get("CIL_BENCH_CACHE_DIR") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tests", ".jax_cache")
    force_platform("cpu", compile_cache_dir=cache)


def _extract_flops(compiled) -> float | None:
    """Total FLOPs of one executable per XLA's cost analysis, if exposed."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    flops = ca.get("flops") if hasattr(ca, "get") else None
    return float(flops) if flops and np.isfinite(flops) and flops > 0 else None


def bench_step(trainer, Teacher, iters: int):
    """Steady-state per-step timing via the AOT-compiled executable.

    Returns ``(img_per_s, step_dt, compile_s, flops_per_step_or_None,
    metrics, overhead_s, compiled)`` — ``overhead_s`` is the estimated fixed
    dispatch cost the slope timing cancels, ``compiled`` the AOT executable
    so trace_crosscheck profiles the very program that was timed.
    """
    import jax
    import jax.numpy as jnp

    from a_pytorch_tutorial_to_class_incremental_learning_tpu.parallel.mesh import (
        replicated_scalar,
    )

    # Task-1 shape: 50 known classes, 10 new -> the KD step variant.
    trainer.state = trainer._grow_state(trainer.state, 0, 0, 50)
    trainer.teacher = Teacher(
        params=jax.tree_util.tree_map(jnp.copy, trainer.state.params),
        batch_stats=jax.tree_util.tree_map(jnp.copy, trainer.state.batch_stats),
        # Committed, not a bare jnp.int32: an uncommitted scalar re-traces
        # every program taking it on its second call (jaxlint JL101).
        known=replicated_scalar(trainer.mesh, 50),
    )
    trainer.state = trainer._grow_state(trainer.state, 1, 50, 10)

    rng = np.random.RandomState(0)
    bs = trainer.global_batch_size
    x = rng.randint(0, 256, (bs, 32, 32, 3)).astype(np.uint8)
    y = rng.randint(0, 60, bs).astype(np.int64)
    xd, yd = trainer._put(x, y)
    step = trainer._steps[True]
    key = jax.random.PRNGKey(0)

    # AOT-compile once; the same executable is timed and cost-analysed, so
    # the FLOP count describes exactly the program being measured.
    t0 = time.perf_counter()
    lowered = step.lower(trainer.state, trainer.teacher, xd, yd, key, 0.1, 0.5)
    compiled = lowered.compile()
    compile_s = time.perf_counter() - t0
    flops = _extract_flops(compiled)

    def run(n, state):
        """n steps then a host fetch of the last metrics scalar: the fetch is
        the execution fence (state threading orders every step before it)."""
        t0 = time.perf_counter()
        m = None
        for _ in range(n):
            state, m = compiled(state, trainer.teacher, xd, yd, key, 0.1, 0.5)
        fence = float(np.asarray(m["loss"]))
        return time.perf_counter() - t0, state, fence

    state = trainer.state
    _, state, _ = run(5, state)  # warmup
    base = max(5, iters // 10)
    t_small, state, _ = run(base, state)
    t_large, state, loss = run(base + iters, state)
    dt = (t_large - t_small) / iters  # slope: fixed RPC cost cancels
    overhead_s = max(0.0, t_small - base * dt)
    trainer.state = state
    m = {"loss": loss}
    return bs / dt, dt, compile_s, flops, m, overhead_s, compiled


def trace_crosscheck(trainer, compiled, steps: int, flops, dt: float) -> dict:
    """Independent witness for the slope timing: rerun the warm KD step under
    ``jax.profiler.trace`` and read per-step device time from the XLA device
    events (utils/profiling.py).  ``compiled`` is bench_step's AOT executable
    — tracing the very program that was slope-timed, with no hidden second
    compile inside the profiled region.  Returns {} when no device plane
    exists (XLA:CPU) — "no witness", not agreement.  VERDICT r2 weak #3:
    est_mfu must be cross-checked against a profiler trace, in the artifact
    itself.
    """
    import shutil
    import tempfile

    import jax

    from a_pytorch_tutorial_to_class_incremental_learning_tpu.utils.profiling import (
        trace_device_step_ms,
    )

    out: dict = {}
    trace_dir = tempfile.mkdtemp(prefix="cil_bench_trace_")
    try:
        rng = np.random.RandomState(0)
        bs = trainer.global_batch_size
        xd, yd = trainer._put(
            rng.randint(0, 256, (bs, 32, 32, 3)).astype(np.uint8),
            rng.randint(0, 60, bs).astype(np.int64),
        )
        key = jax.random.PRNGKey(0)
        state = trainer.state
        with jax.profiler.trace(trace_dir):
            m = None
            for _ in range(steps):
                state, m = compiled(state, trainer.teacher, xd, yd, key, 0.1, 0.5)
            float(np.asarray(m["loss"]))  # host fetch = execution fence
        # The loop donated trainer.state's buffers into `compiled`; leave the
        # trainer pointing at the live state or the next caller
        # (bench_fused_epoch) reads deleted arrays (jaxlint JL001).
        trainer.state = state
        out = trace_device_step_ms(trace_dir, steps)
        if out.get("trace_step_ms", 0) > 0:
            out["agreement"] = round(dt * 1e3 / out["trace_step_ms"], 3)
            peak = PEAK_FLOPS.get(jax.default_backend())
            if flops and peak:
                out["est_mfu_trace"] = round(
                    flops / (out["trace_step_ms"] / 1e3) / peak, 4
                )
    except Exception as e:  # noqa: BLE001 — the witness is optional
        out = {"trace_error": f"{type(e).__name__}: {e}"}
    finally:
        shutil.rmtree(trace_dir, ignore_errors=True)
    return out


def bench_fused_epoch(trainer, iters: int, fused_n: int):
    """Fused-epoch path (default execution mode): whole epoch as one
    lax.scan with the dataset in HBM — end-to-end epoch throughput
    including on-device shuffle and gather."""
    import jax

    from a_pytorch_tutorial_to_class_incremental_learning_tpu.parallel.mesh import (
        replicated,
    )

    rng = np.random.RandomState(1)
    bs = trainer.global_batch_size
    n = fused_n  # default: task>=1 dataset size in B50-inc10 (5000 + 2000)
    dx, dy = trainer._put(
        rng.randint(0, 256, (n, 32, 32, 3)).astype(np.uint8),
        rng.randint(0, 60, n).astype(np.int64),
        sharding=replicated(trainer.mesh),
    )
    epoch_fn = trainer._epochs[True]
    key = jax.random.PRNGKey(1)

    def run(reps, state):
        t0 = time.perf_counter()
        m = None
        for _ in range(reps):
            state, m = epoch_fn(state, trainer.teacher, dx, dy, key, 0.1, 0.5, bs)
        fence = float(np.asarray(m["loss"][-1]))  # host fetch = fence
        return time.perf_counter() - t0, state, fence

    _, state, _ = run(1, trainer.state)  # warmup/compile
    reps = max(3, iters // 10)
    t_small, state, _ = run(1, state)
    t_large, state, _ = run(1 + reps, state)
    trainer.state = state
    epoch_dt = (t_large - t_small) / reps  # slope: fixed RPC cost cancels
    # Same step-count rule as make_epoch_fn (wrap-around padding, >= 1 step).
    steps_per_epoch = max(1, -(-n // bs))
    return steps_per_epoch * bs / epoch_dt, epoch_dt


def _bind_trainer_metrics(trainer, registry) -> None:
    """Rebind every step-path instrument handle to ``registry``.

    The trainer caches its counter/histogram handles at init and the
    prefetcher reads ``telemetry.metrics`` at construction, so swapping the
    facade attribute plus the cached handles is a complete on/off toggle —
    the compiled step itself is untouched.
    """
    trainer.telemetry.metrics = registry
    trainer._m_steps = registry.counter("steps_total")
    trainer._m_step_ms = registry.histogram(
        "step_latency_ms", lowest=0.5, growth=2.0, buckets=18
    )
    trainer._m_epochs = registry.counter("epochs_total")
    trainer._m_stall = registry.gauge("stall_frac")
    trainer._m_recompiles = registry.gauge("recompiles_total")


def measure_metrics_overhead(batch_size: int = 64, epochs: int = 2,
                             steps_cap: int = 8, passes: int = 3) -> dict:
    """Registry-on vs registry-off cost of the metrics plane on the hot path.

    Runs the identical compiled per-step epoch with the live
    ``MetricsRegistry`` (one counter inc + one histogram observe per step,
    plus the prefetcher's wait/batch counters) and with the branch-free
    ``NullRegistry``, alternating on/off passes so slow drift on a shared
    host hits both modes equally, and taking the per-mode *minimum* wall
    time (min-of-passes is robust to scheduler noise in a way means are
    not).  ``perf_gate.py --metrics-overhead`` fails the build if
    ``overhead_frac`` exceeds its gate (3%): observability must stay
    effectively free or it gets turned off in production runs.
    """
    import jax
    import jax.numpy as jnp

    from a_pytorch_tutorial_to_class_incremental_learning_tpu.config import CilConfig
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.data.scenario import (
        TaskSet,
    )
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.engine import CilTrainer
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.telemetry import (
        StallClock,
    )
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.telemetry.metrics import (
        MetricsRegistry,
        NullRegistry,
    )

    trainer = CilTrainer(
        CilConfig(
            data_set="synthetic",
            num_bases=50,
            increment=10,
            backbone="resnet32",
            batch_size=batch_size,
            fused_epochs=False,
            seed=0,
        ),
        init_dist=False,
    )
    trainer.state = trainer._grow_state(trainer.state, 0, 0, 50)
    task = trainer.scenario_train[0]
    n = min(len(task), steps_cap * trainer.global_batch_size)
    task = TaskSet(x=task.x[:n], y=task.y[:n], t=task.t[:n])
    steps = max(1, -(-n // trainer.global_batch_size))
    epoch_key = jax.random.fold_in(trainer.root_key, 0)
    state0 = jax.tree_util.tree_map(jnp.copy, trainer.state)

    def run_pass():
        trainer.state = jax.tree_util.tree_map(jnp.copy, state0)
        clock = StallClock()
        t0 = time.perf_counter()
        for _ in range(epochs):
            trainer._run_epoch_steps(0, task, 0, epoch_key, 0.1, 0.5, clock)
        return time.perf_counter() - t0

    registries = {"on": MetricsRegistry(), "off": NullRegistry()}
    _bind_trainer_metrics(trainer, registries["on"])
    run_pass()  # warmup: compile once, outside every timing
    walls = {"on": [], "off": []}
    for _ in range(max(1, passes)):
        for mode in ("on", "off"):
            _bind_trainer_metrics(trainer, registries[mode])
            walls[mode].append(run_pass())
    total_steps = steps * epochs
    step_ms = {
        mode: min(ws) / total_steps * 1e3 for mode, ws in walls.items()
    }
    overhead = step_ms["on"] / step_ms["off"] - 1.0
    return {
        "metric": "metrics_overhead",
        "value": round(overhead, 4),
        "unit": "frac",
        "overhead_frac": round(overhead, 4),
        "step_ms_on": round(step_ms["on"], 3),
        "step_ms_off": round(step_ms["off"], 3),
        "passes": passes,
        "epochs_per_pass": epochs,
        "steps_per_epoch": steps,
        "global_batch": trainer.global_batch_size,
        "backend": jax.default_backend(),
        "devices": jax.device_count(),
    }


def measure_step_path(batch_size: int, epochs: int, depths, steps_cap: int,
                      metrics: str = "on") -> dict:
    """Per-step-path benchmark: the same epoch at several prefetch depths.

    Runs ``CilTrainer._run_epoch_steps`` — the real per-batch training path,
    host gather + device_put + jitted step — over an identical synthetic
    task at each ring depth, restarting from a copied state snapshot so
    every depth sees byte-identical batches AND parameters.  Reports per
    depth: img/s, ``fetch_overhead_ms`` (residual non-overlapped host time
    per step, the number prefetching exists to shrink), the epoch stall
    share, ring occupancy, and whether the loss stream matched depth 0
    exactly (determinism).
    """
    import jax
    import jax.numpy as jnp

    from a_pytorch_tutorial_to_class_incremental_learning_tpu.config import CilConfig
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.data.scenario import (
        TaskSet,
    )
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.engine import CilTrainer
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.telemetry import (
        StallClock,
    )

    trainer = CilTrainer(
        CilConfig(
            data_set="synthetic",
            num_bases=50,
            increment=10,
            backbone="resnet32",
            batch_size=batch_size,
            fused_epochs=False,
            seed=0,
        ),
        init_dist=False,
    )
    # Task-0 head (50 classes), no teacher: the plain-CE step variant.
    trainer.state = trainer._grow_state(trainer.state, 0, 0, 50)
    if metrics == "off":
        from a_pytorch_tutorial_to_class_incremental_learning_tpu.telemetry.metrics import (
            NullRegistry,
        )

        _bind_trainer_metrics(trainer, NullRegistry())
    task = trainer.scenario_train[0]
    n = min(len(task), steps_cap * trainer.global_batch_size)
    task = TaskSet(x=task.x[:n], y=task.y[:n], t=task.t[:n])
    steps = max(1, -(-n // trainer.global_batch_size))
    epoch_key = jax.random.fold_in(trainer.root_key, 0)

    state0 = jax.tree_util.tree_map(jnp.copy, trainer.state)

    def run_epochs(depth):
        """`epochs` epochs at one depth from the shared state snapshot."""
        trainer.state = jax.tree_util.tree_map(jnp.copy, state0)
        trainer.config = trainer.config.replace(prefetch_depth=depth)
        clock = StallClock()
        losses = []
        t0 = time.perf_counter()
        for _ in range(epochs):
            pending = trainer._run_epoch_steps(
                0, task, 0, epoch_key, 0.1, 0.5, clock
            )
            losses.extend(round(float(m["loss"]), 6) for m in pending)
        wall = time.perf_counter() - t0
        return wall, clock, losses

    run_epochs(depths[0])  # warmup: compile once, outside every timing
    rows, losses0 = [], None
    for depth in depths:
        wall, clock, losses = run_epochs(depth)
        if losses0 is None:
            losses0 = losses
        total_steps = steps * epochs
        row = {
            "prefetch_depth": depth,
            "img_s": round(total_steps * trainer.global_batch_size / wall, 1),
            "wall_s": round(wall, 3),
            "fetch_overhead_ms": round(clock.host_s / total_steps * 1e3, 3),
            "stall_frac": round(clock.stall_frac, 4),
            "host_s": round(clock.host_s, 4),
            "device_s": round(clock.device_s, 4),
            "loss_identical_to_depth0": losses == losses0,
        }
        if clock.prefetch_depth is not None:
            row["prefetch_depth_occupancy"] = round(
                clock.prefetch_occupancy, 4
            )
        rows.append(row)
    base = next(r for r in rows if r["prefetch_depth"] == depths[0])
    best = max(rows, key=lambda r: r["img_s"])
    return {
        "metric": "step_path_prefetch",
        "value": best["img_s"],
        "unit": "img/s",
        "best_depth": best["prefetch_depth"],
        "global_batch": trainer.global_batch_size,
        "steps_per_epoch": steps,
        "epochs": epochs,
        "backend": jax.default_backend(),
        "devices": jax.device_count(),
        # The overlap win, stated directly: how much of the depth-0 stall
        # share the deepest ring removed.
        "stall_frac_depth0": base["stall_frac"],
        "stall_frac_best": best["stall_frac"],
        "depths": rows,
    }


def measure_serve(duration_s: float = 4.0, workers: int = 8,
                  buckets=(1, 8, 32), max_wait_ms: float = 3.0,
                  open_rps: float = 100.0) -> dict:
    """Serving load harness: export one artifact, drive the batched server.

    Two traffic shapes against the same server:

    * **closed-loop** — ``workers`` threads each submit-and-wait in a tight
      loop for ``duration_s``; measures saturated throughput (the batcher
      should fill large buckets) and the latency distribution under it.
    * **open-loop** — requests arrive on a fixed ``open_rps`` clock whether
      or not earlier ones finished, the shape that exposes queueing delay a
      closed loop hides; percentiles come from the per-request latencies.

    The headline ``value`` is closed-loop req/s.  Two p99s come out: the
    exact ``p99_ms`` from the per-request sample list (ramp excluded), and
    ``hist_p99_ms`` scraped from the server's own
    ``serve_batch_latency_ms`` registry histograms — the same series the
    fleet scraper reads off ``/metrics``.  ``perf_gate.py --serve`` gates
    on the scraped histogram when the baseline recorded one (quantized to
    the exponential ladder, so the gate is rung-based), falling back to
    the exact samples otherwise.
    """
    import shutil
    import tempfile
    import threading

    import jax

    from a_pytorch_tutorial_to_class_incremental_learning_tpu.data.augment import (
        AugmentConfig,
    )
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.models import (
        create_model,
        grow,
    )
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.telemetry.metrics import (
        MetricsRegistry,
        _split_series,
        histogram_quantile,
        merge_histograms,
    )
    from serving import InferenceServer, export_artifact

    nb = 20
    buckets = tuple(sorted({int(b) for b in buckets}))
    export_dir = tempfile.mkdtemp(prefix="cil_serve_bench_")
    try:
        model, variables = create_model("resnet20", nb)
        variables = grow(variables, jax.random.PRNGKey(0), 0, nb)
        aug = AugmentConfig()
        t0 = time.perf_counter()
        export_artifact(
            export_dir, 0, model, aug,
            variables["params"], variables["batch_stats"],
            known=nb, class_order=list(range(nb)),
            input_size=32, channels=3, buckets=buckets,
        )
        export_s = time.perf_counter() - t0
        registry = MetricsRegistry()
        server = InferenceServer(export_dir, max_wait_ms=max_wait_ms,
                                 metrics=registry).start()
        try:
            rng = np.random.RandomState(0)
            img = rng.randint(0, 256, (32, 32, 3)).astype(np.uint8)
            # Warmup: every bucket's executable gets one dispatch before
            # anything is timed.
            for f in [server.submit(img) for _ in range(max(buckets))]:
                f.result(timeout=60)

            # Closed loop.  Percentiles come from per-request latencies with
            # the ramp excluded: the first fraction of the window measures
            # queue buildup while the workers outpace a cold batcher, which
            # made raw p99 swing ~60% run to run.
            ramp_s = min(1.0, duration_s / 4)
            t0 = time.perf_counter()
            stop_at = t0 + duration_s
            counts = [0] * workers
            lat_per_worker = [[] for _ in range(workers)]

            def closed(w: int) -> None:
                while time.perf_counter() < stop_at:
                    res = server.submit(img).result(timeout=60)
                    counts[w] += 1
                    if time.perf_counter() - t0 > ramp_s:
                        lat_per_worker[w].append(res["latency_ms"])

            threads = [threading.Thread(target=closed, args=(w,))
                       for w in range(workers)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            closed_wall = time.perf_counter() - t0
            closed_n = sum(counts)
            closed_lat = np.asarray(
                [ms for lats in lat_per_worker for ms in lats], np.float64
            )
            closed_stats = server.stats()
            # The scraped view of the same window: per-bucket latency
            # histograms off the server's registry (warmup + ramp included
            # — cumulative series, exactly what /metrics would expose).
            hist_p99 = None
            hist_growth = None
            lat_hists = [
                h for k, h in registry.snapshot()["histograms"].items()
                if _split_series(k)[0] == "serve_batch_latency_ms"
            ]
            if lat_hists:
                merged_hist = lat_hists[0]
                for h in lat_hists[1:]:
                    merged_hist = merge_histograms(merged_hist, h)
                hist_p99 = round(histogram_quantile(merged_hist, 0.99), 3)
                hist_growth = merged_hist["growth"]

            # Open loop: fixed arrival clock, latencies from the responses.
            futs = []
            period = 1.0 / max(open_rps, 1e-9)
            open_until = time.perf_counter() + duration_s / 2
            next_t = time.perf_counter()
            while time.perf_counter() < open_until:
                futs.append(server.submit(img))
                next_t += period
                pause = next_t - time.perf_counter()
                if pause > 0:
                    time.sleep(pause)
            open_lat = np.asarray(
                [f.result(timeout=60)["latency_ms"] for f in futs], np.float64
            )
        finally:
            server.stop()
        result = {
            "metric": "serve_throughput",
            "value": round(closed_n / closed_wall, 1),
            "unit": "req/s",
            "p50_ms": round(float(np.percentile(closed_lat, 50)), 3),
            "p95_ms": round(float(np.percentile(closed_lat, 95)), 3),
            "p99_ms": round(float(np.percentile(closed_lat, 99)), 3),
            "hist_p99_ms": hist_p99,
            "hist_growth": hist_growth,
            "open_rps": open_rps,
            "open_p50_ms": round(float(np.percentile(open_lat, 50)), 3),
            "open_p99_ms": round(float(np.percentile(open_lat, 99)), 3),
            "open_n": int(open_lat.size),
            "bucket_occupancy": closed_stats["bucket_occupancy"],
            "bucket_counts": {str(k): v for k, v in
                              sorted(closed_stats["bucket_counts"].items())},
            "buckets": list(buckets),
            "max_wait_ms": max_wait_ms,
            "workers": workers,
            "served": closed_stats["served"],
            "failed": closed_stats["failed"],
            "export_s": round(export_s, 1),
            "backend": jax.default_backend(),
            "devices": jax.device_count(),
            "host_id": socket.gethostname(),
        }
        return result
    finally:
        shutil.rmtree(export_dir, ignore_errors=True)


def measure_serve_overload(duration_s: float = 6.0, buckets=(1, 8),
                           max_wait_ms: float = 3.0, pattern: str = "bursty",
                           rps: float = 120.0, replicas: int = 2,
                           high_frac: float = 0.3, capacity: int = 24,
                           seed: int = 0) -> dict:
    """Fleet traffic generator: bursty/diurnal arrivals + a priority mix
    against in-process replicas behind the admission-controlled front end.

    Open-loop by construction — arrivals follow a seeded Poisson clock whose
    rate ``lambda(t)`` is modulated by ``pattern``:

    * ``steady``  — constant ``rps``.
    * ``bursty``  — on/off: 3x ``rps`` for the first 30% of every second,
      ``rps``/3 otherwise (mean ~1.2x ``rps``); the shape that exercises
      shedding and the high-class p99 under queue spikes.
    * ``diurnal`` — one sinusoidal day compressed into the run:
      ``rps * (1 + 0.9 sin(2 pi t / duration))``.

    Each request is ``high`` priority with probability ``high_frac``, else
    ``low``.  Reported per class: p50/p95/p99 of *successful* requests,
    shed rate (HTTP 503 at admission), and errors (anything else — a
    healthy fleet reports zero).  ``perf_gate.py --serve-overload`` gates
    the high-class tail — ``hist_p99_high_ms`` scraped from the front
    end's registry when the baseline recorded one, the exact
    ``p99_high_ms`` otherwise: the whole point of shedding low first is
    that the high-class tail stays flat through overload.
    """
    import math
    import shutil
    import tempfile
    import threading
    from concurrent.futures import ThreadPoolExecutor

    import jax

    from a_pytorch_tutorial_to_class_incremental_learning_tpu.data.augment import (
        AugmentConfig,
    )
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.models import (
        create_model,
        grow,
    )
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.telemetry.metrics import (
        MetricsRegistry,
        _split_series,
        histogram_quantile,
    )
    from serving import export_artifact
    from serving.frontend import Frontend
    from serving.replica import ReplicaServer, encode_image

    nb = 20
    buckets = tuple(sorted({int(b) for b in buckets}))
    export_dir = tempfile.mkdtemp(prefix="cil_serve_overload_")
    fleet, frontend = [], None
    try:
        model, variables = create_model("resnet20", nb)
        variables = grow(variables, jax.random.PRNGKey(0), 0, nb)
        export_artifact(
            export_dir, 0, model, AugmentConfig(),
            variables["params"], variables["batch_stats"],
            known=nb, class_order=list(range(nb)),
            input_size=32, channels=3, buckets=buckets,
        )
        fleet = [
            ReplicaServer(export_dir, replica_id=i,
                          max_wait_ms=max_wait_ms).start()
            for i in range(int(replicas))
        ]
        registry = MetricsRegistry()
        frontend = Frontend(
            [(r.host, r.port) for r in fleet],
            capacity=int(capacity),
            default_deadline_ms=10000.0,
            metrics=registry,
        ).start()

        rng = np.random.RandomState(seed)
        body = encode_image(
            rng.randint(0, 256, (32, 32, 3)).astype(np.uint8))
        results = []
        lock = threading.Lock()

        def one(priority: str) -> None:
            import http.client

            t_req = time.perf_counter()
            try:
                conn = http.client.HTTPConnection(
                    frontend.host, frontend.port, timeout=30.0)
                try:
                    conn.request("POST", "/predict", body=body, headers={
                        "X-Priority": priority,
                        "X-Deadline-Ms": "10000",
                    })
                    status = conn.getresponse()
                    status.read()
                    code = status.status
                finally:
                    conn.close()
            except OSError:
                code = -1
            lat = (time.perf_counter() - t_req) * 1000.0
            with lock:
                results.append((priority, code, lat))

        # Warm the whole path (connections, codec, batcher) untimed.
        for _ in range(4):
            one("high")
        with lock:
            results.clear()

        pool = ThreadPoolExecutor(max_workers=64,
                                  thread_name_prefix="bench-client")
        t_start = time.perf_counter()
        t = 0.0
        sent = 0
        while t < duration_s:
            if pattern == "bursty":
                lam = rps * 3.0 if (t % 1.0) < 0.3 else rps / 3.0
            elif pattern == "diurnal":
                lam = max(
                    rps * (1.0 + 0.9 * math.sin(
                        2.0 * math.pi * t / duration_s)),
                    1.0,
                )
            else:
                lam = rps
            t += float(rng.exponential(1.0 / max(lam, 1e-9)))
            pause = (t_start + t) - time.perf_counter()
            if pause > 0:
                time.sleep(pause)
            priority = "high" if rng.uniform() < high_frac else "low"
            pool.submit(one, priority)
            sent += 1
        pool.shutdown(wait=True)
        wall = time.perf_counter() - t_start
        fe_stats = frontend.stats()
        # Scraped high-class tail: the front end's own fe_latency_ms
        # histogram for priority=high — the series the fleet scraper and
        # the rung-based overload gate consume.
        hist_p99_high = None
        hist_growth = None
        for k, h in registry.snapshot()["histograms"].items():
            name, labels = _split_series(k)
            if name == "fe_latency_ms" and 'priority="high"' in labels:
                hist_p99_high = round(histogram_quantile(h, 0.99), 3)
                hist_growth = h["growth"]
                break

        by_class = {}
        errors = 0
        for p in ("high", "low"):
            lat = np.asarray([ms for pr, code, ms in results
                              if pr == p and code == 200], np.float64)
            shed = sum(1 for pr, code, _ in results
                       if pr == p and code == 503)
            errs = sum(1 for pr, code, _ in results
                       if pr == p and code not in (200, 503))
            errors += errs
            n = max(lat.size + shed + errs, 1)
            by_class[p] = {
                "served": int(lat.size),
                "shed": shed,
                "errors": errs,
                "shed_rate": round(shed / n, 4),
                "p50_ms": (round(float(np.percentile(lat, 50)), 3)
                           if lat.size else 0.0),
                "p95_ms": (round(float(np.percentile(lat, 95)), 3)
                           if lat.size else 0.0),
                "p99_ms": (round(float(np.percentile(lat, 99)), 3)
                           if lat.size else 0.0),
            }
        return {
            "metric": "serve_overload",
            "value": by_class["high"]["p99_ms"],
            "unit": "ms",
            "p99_high_ms": by_class["high"]["p99_ms"],
            "hist_p99_high_ms": hist_p99_high,
            "hist_growth": hist_growth,
            "pattern": pattern,
            "rps": rps,
            "achieved_rps": round(sent / max(wall, 1e-9), 1),
            "replicas": int(replicas),
            "capacity": int(capacity),
            "high_frac": high_frac,
            "classes": by_class,
            "errors": errors,
            "retries": fe_stats["retries"],
            "hedges": fe_stats["hedges"],
            "buckets": list(buckets),
            "max_wait_ms": max_wait_ms,
            "duration_s": duration_s,
            "backend": jax.default_backend(),
            "devices": jax.device_count(),
            "host_id": socket.gethostname(),
        }
    finally:
        if frontend is not None:
            frontend.stop()
        for r in fleet:
            r.stop()
        shutil.rmtree(export_dir, ignore_errors=True)


def measure(batch_size: int, iters: int, compute_dtype: str, fused_n: int,
            with_bf16: bool) -> dict:
    import jax

    from a_pytorch_tutorial_to_class_incremental_learning_tpu.config import CilConfig
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.engine import (
        CilTrainer,
        Teacher,
    )

    def make_trainer(dtype, use_pallas_loss=False):
        cfg = CilConfig(
            data_set="synthetic",  # 100 classes; content is irrelevant here
            num_bases=50,
            increment=10,
            backbone="resnet32",
            batch_size=batch_size,
            compute_dtype=dtype,
            use_pallas_loss=use_pallas_loss,
            seed=0,
        )
        return CilTrainer(cfg, init_dist=False)

    from a_pytorch_tutorial_to_class_incremental_learning_tpu.telemetry import (
        CompileWatch,
    )

    watch = CompileWatch.install()
    watch_before = watch.snapshot()
    trainer = make_trainer(compute_dtype)
    img_s, dt, compile_s, flops, m, overhead_s, compiled = bench_step(
        trainer, Teacher, iters
    )
    # Net XLA work behind the AOT compile (jax.monitoring): near zero when
    # the persistent cache served the executable.  This — not the wall-clock
    # compile_s, which still pays trace+lower — is what perf_gate --compile
    # gates cold vs warm.
    compile_delta = CompileWatch.delta(watch_before, watch.snapshot())
    # XLA:CPU emits no device plane, so the witness there is guaranteed-empty;
    # skip the ~20 extra profiled steps and only trace on a real accelerator.
    if jax.default_backend() != "cpu":
        trace_extras = trace_crosscheck(trainer, compiled, min(iters, 20), flops, dt)
    else:
        trace_extras = {}
    if fused_n > 0:
        fused_img_s, epoch_dt = bench_fused_epoch(trainer, iters, fused_n)
    else:
        fused_img_s = epoch_dt = 0.0

    backend = jax.default_backend()
    result = {
        "metric": "train_step_throughput",
        "value": round(img_s, 1),
        "unit": "img/s",
        "vs_baseline": round(img_s / REFERENCE_IMG_PER_SEC, 3),
        # The denominator is a *derivation* from the reference README's
        # "~30 min on 4x3090" claim (module docstring), not a measured run;
        # the honest race is wall-clock per task on the same protocol.
        "baseline_kind": "derived-from-readme-wallclock",
        "step_ms": round(dt * 1e3, 3),
        "global_batch": trainer.global_batch_size,
        "compile_s": round(compile_s, 1),
        "fused_epoch_img_s": round(fused_img_s, 1),
        "fused_epoch_ms": round(epoch_dt * 1e3, 2),
        "backend": backend,
        "devices": jax.device_count(),
        # Which host/process measured: a fleet's bench lines must be
        # attributable the same way its telemetry records are.
        "host_id": socket.gethostname(),
        "process_index": jax.process_index(),
        "compute_dtype": compute_dtype,
        "xla_compile_s": compile_delta["compile_s"],
        "xla_cache_hits": compile_delta["cache_hits"],
        "loss_finite": bool(np.isfinite(float(m["loss"]))),
        # Fixed per-fetch RPC cost removed by the slope timing (transparency).
        "fetch_overhead_ms": round(overhead_s * 1e3, 1),
    }
    # Profiler-trace witness: trace_step_ms / agreement / est_mfu_trace
    # (empty on XLA:CPU, which emits no device plane).
    result.update(trace_extras)
    try:
        from a_pytorch_tutorial_to_class_incremental_learning_tpu.telemetry import (
            hbm_stats,
        )

        hbm = hbm_stats()
        if hbm:
            # Peak HBM across devices: the number that says whether the
            # benched batch even fits at the next size up.  Absent on
            # XLA:CPU, which reports no memory stats.
            result["hbm_peak_bytes"] = max(
                s.get("peak_bytes_in_use", s.get("bytes_in_use", 0))
                for s in hbm.values()
            )
            result["hbm_bytes_in_use"] = max(
                s.get("bytes_in_use", 0) for s in hbm.values()
            )
    except Exception:  # noqa: BLE001 — extras must never break the one-line contract
        pass
    if flops is not None:
        result["flops_per_step_xla"] = round(flops)
        peak = PEAK_FLOPS.get(backend)
        if peak:
            # MFU from XLA's own FLOP count for the measured executable.
            mfu = flops / dt / peak
            result["est_mfu"] = round(mfu, 4)
            # >100% MFU means the timing (not the chip) is wrong; flag it
            # rather than publish it as a win (round-2 lesson).
            if mfu > 1.0:
                result["est_mfu_suspect"] = True
    if with_bf16 and compute_dtype != "bfloat16":
        bf = make_trainer("bfloat16")
        bf_img_s, bf_dt, _, _, bf_m, _, _ = bench_step(bf, Teacher, iters)
        result["bf16_img_s"] = round(bf_img_s, 1)
        result["bf16_step_ms"] = round(bf_dt * 1e3, 3)
        result["bf16_loss_finite"] = bool(np.isfinite(float(bf_m["loss"])))
    if backend == "tpu":
        # Prove the Pallas fused masked-CE kernel on the real chip, in the
        # driver artifact itself (VERDICT r2 weak #4: it had only ever run
        # single-chip / interpret-mode before).
        try:
            pl = make_trainer(compute_dtype, use_pallas_loss=True)
            pl_img_s, pl_dt, _, _, pl_m, _, _ = bench_step(pl, Teacher, iters)
            result["pallas_img_s"] = round(pl_img_s, 1)
            result["pallas_step_ms"] = round(pl_dt * 1e3, 3)
            result["pallas_loss_finite"] = bool(np.isfinite(float(pl_m["loss"])))
        except Exception as e:  # noqa: BLE001 — optional row, never fatal
            result["pallas_error"] = f"{type(e).__name__}: {e}"
    return result


def measure_precision_ablation(batch_size: int, iters: int, presets) -> dict:
    """Per-preset sweep of the KD step under the precision policy layer
    (ops/precision.py): steady-state ``step_ms`` via the same slope-timed
    ``bench_step``, ``loss_finite``, and a short accuracy probe — after the
    timed steps trained the fixed batch, the eval step re-reads it and
    reports top-1 (a memorization/numerics signal: a preset whose low-
    precision arithmetic breaks training memorizes visibly slower than f32
    at identical step count and data).

    One row per preset under ``results``; the headline acceptance is
    ``bf16_selective.step_ms <= f32.step_ms`` (matmuls still compute in
    bf16) with the accuracy story carried by the e2e twin test.
    """
    import jax

    from a_pytorch_tutorial_to_class_incremental_learning_tpu.config import CilConfig
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.engine import (
        CilTrainer,
        Teacher,
    )
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.parallel.mesh import (
        replicated_scalar,
    )

    rows = []
    global_batch = None
    for name in presets:
        cfg = CilConfig(
            data_set="synthetic",  # 100 classes; content is irrelevant here
            num_bases=50,
            increment=10,
            backbone="resnet32",
            batch_size=batch_size,
            precision=name,
            compute_dtype=("bfloat16" if name.startswith("bf16")
                           else "float32"),
            seed=0,
        )
        trainer = CilTrainer(cfg, init_dist=False)
        global_batch = trainer.global_batch_size
        img_s, dt, compile_s, _flops, m, _overhead, _ = bench_step(
            trainer, Teacher, iters
        )
        row = {
            "precision": name,
            "img_s": round(img_s, 1),
            "step_ms": round(dt * 1e3, 3),
            "compile_s": round(compile_s, 2),
            "loss_finite": bool(np.isfinite(float(m["loss"]))),
            "final_loss": round(float(m["loss"]), 4),
        }
        try:
            # bench_step trained on RandomState(0)'s fixed batch; re-read it.
            rng = np.random.RandomState(0)
            bs = trainer.global_batch_size
            x = rng.randint(0, 256, (bs, 32, 32, 3)).astype(np.uint8)
            y = rng.randint(0, 60, bs).astype(np.int64)
            xd, yd = trainer._put(x, y)
            _, c1, _, wsum = trainer.eval_step(
                trainer.state.params, trainer.state.batch_stats,
                xd, yd, np.ones(bs, np.float32),
                replicated_scalar(trainer.mesh, 60),
            )
            row["probe_acc1"] = round(float(c1) / max(float(wsum), 1.0), 4)
        except Exception as e:  # noqa: BLE001 — probe is an extra, not the metric
            row["probe_error"] = f"{type(e).__name__}: {e}"
        rows.append(row)

    by_name = {r["precision"]: r for r in rows}
    result = {
        "type": "precision_ablation",
        "ts": round(time.time(), 3),
        "metric": "precision_ablation",
        "results": rows,
        "backend": jax.default_backend(),
        "global_batch": global_batch,
        "iters": iters,
    }
    if "f32" in by_name and "bf16_selective" in by_name:
        # The acceptance headline, precomputed so perf_gate/CI read one bool.
        result["selective_not_slower"] = bool(
            by_name["bf16_selective"]["step_ms"] <= by_name["f32"]["step_ms"]
        )
    return result


def main(batch_size: int = 512, iters: int = 50, compute_dtype: str = "float32",
         fused_n: int = 7000, with_bf16: bool = True, cpu_full: bool = False,
         step_path: bool = False, prefetch_depths=(0, 2, 4),
         step_path_epochs: int = 3, step_path_steps: int = 8,
         serve: bool = False, serve_duration_s: float = 4.0,
         serve_buckets=(1, 8, 32), serve_max_wait_ms: float = 3.0,
         serve_pattern=None, serve_rps: float = 120.0,
         serve_replicas: int = 2, serve_high_frac: float = 0.3,
         serve_capacity: int = 24, metrics: str = "on",
         precision: str = ""):
    """``batch_size`` defaults to 512 — the reference's *global* batch
    (4 GPUs x 128), which fits comfortably on one v5e chip; a multi-chip mesh
    would use the per-device 128 of the config instead.

    ``step_path=True`` switches to the per-step-path input-pipeline
    benchmark: the same epoch at prefetch depths ``prefetch_depths``,
    reporting per-depth img/s and ``fetch_overhead_ms`` (residual host
    time the ring buffer failed to overlap).

    ``serve=True`` switches to the serving load harness: export one
    artifact, drive the micro-batching server closed- and open-loop,
    report req/s + latency percentiles + bucket occupancy.  With
    ``serve_pattern`` set it becomes the fleet traffic generator
    (``measure_serve_overload``): bursty/diurnal arrivals + a priority mix
    against replicas behind the front end, reporting per-class percentiles
    and shed rate.
    """
    backend = probe_backend()
    reduced = False
    try:
        if backend == "cpu":
            force_cpu()
            # CPU is a liveness fallback, not a perf target: the full
            # TPU-sized workload would run for hours there (and XLA:CPU
            # serializes the fused-epoch scan body, ~20x per-step slowdown),
            # so shrink it to keep the run well under any driver timeout.
            # --cpu_full opts out for a deliberate full CPU benchmark.
            if not cpu_full:
                reduced = True
                batch_size = min(batch_size, 64)
                iters = min(iters, 5)
                fused_n = 0
                with_bf16 = False
                step_path_epochs = min(step_path_epochs, 2)
                step_path_steps = min(step_path_steps, 6)
                serve_duration_s = min(serve_duration_s,
                                       4.0 if serve_pattern else 3.0)
                serve_rps = min(serve_rps, 80.0)
        if precision:
            presets = [s.strip() for s in precision.split(",") if s.strip()]
            result = measure_precision_ablation(batch_size, iters, presets)
        elif serve and serve_pattern:
            result = measure_serve_overload(
                duration_s=serve_duration_s, buckets=tuple(serve_buckets),
                max_wait_ms=serve_max_wait_ms, pattern=serve_pattern,
                rps=serve_rps, replicas=serve_replicas,
                high_frac=serve_high_frac, capacity=serve_capacity,
            )
        elif serve:
            result = measure_serve(
                duration_s=serve_duration_s, buckets=tuple(serve_buckets),
                max_wait_ms=serve_max_wait_ms,
            )
        elif metrics == "paired":
            result = measure_metrics_overhead(
                batch_size=min(batch_size, 64), epochs=step_path_epochs,
                steps_cap=step_path_steps,
            )
        elif step_path:
            result = measure_step_path(
                batch_size, step_path_epochs, tuple(prefetch_depths),
                step_path_steps, metrics=metrics,
            )
        else:
            result = measure(batch_size, iters, compute_dtype, fused_n,
                             with_bf16)
        if reduced:
            result["reduced_cpu_fallback"] = True
    except Exception as e:  # noqa: BLE001 — the JSON line must always appear
        result = {
            "metric": ("precision_ablation" if precision
                       else "serve_overload" if serve and serve_pattern
                       else "serve_throughput" if serve
                       else "metrics_overhead" if metrics == "paired"
                       else "step_path_prefetch" if step_path
                       else "train_step_throughput"),
            "value": 0.0,
            "unit": ("ms" if serve and serve_pattern
                     else "req/s" if serve
                     else "frac" if metrics == "paired" else "img/s"),
            "vs_baseline": 0.0,
            "backend": backend,
            "error": f"{type(e).__name__}: {e}",
        }
    print(json.dumps(result))


if __name__ == "__main__":
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--batch_size", type=int, default=512)
    p.add_argument("--iters", type=int, default=50)
    p.add_argument("--compute_dtype", default="float32",
                   choices=["float32", "bfloat16"])
    p.add_argument("--fused_n", type=int, default=7000,
                   help="dataset size for the fused-epoch measurement")
    p.add_argument("--no_bf16", action="store_true",
                   help="skip the extra bfloat16 step measurement")
    p.add_argument("--cpu_full", action="store_true",
                   help="run the full requested workload even on the CPU "
                   "fallback (default shrinks it to stay under timeouts)")
    p.add_argument("--step_path", action="store_true",
                   help="benchmark the per-step input-pipeline path at "
                   "several --prefetch_depths instead of the fused step")
    p.add_argument("--prefetch_depths", default="0,2,4",
                   help="comma-separated ring depths for --step_path")
    p.add_argument("--step_path_epochs", type=int, default=3,
                   help="timed epochs per depth for --step_path")
    p.add_argument("--step_path_steps", type=int, default=8,
                   help="steps per epoch cap for --step_path")
    p.add_argument("--serve", action="store_true",
                   help="benchmark the inference server (serving/) instead "
                   "of the train step: req/s + latency percentiles")
    p.add_argument("--serve_duration_s", type=float, default=4.0,
                   help="closed-loop traffic duration for --serve")
    p.add_argument("--serve_buckets", default="1,8,32",
                   help="comma-separated batch buckets for --serve")
    p.add_argument("--serve_max_wait_ms", type=float, default=3.0,
                   help="micro-batch max-wait deadline for --serve")
    p.add_argument("--serve_pattern", default=None,
                   choices=["steady", "bursty", "diurnal"],
                   help="with --serve: run the fleet traffic generator "
                   "with this arrival pattern instead of the single-server "
                   "closed/open loops")
    p.add_argument("--serve_rps", type=float, default=120.0,
                   help="base arrival rate for --serve_pattern")
    p.add_argument("--serve_replicas", type=int, default=2,
                   help="in-process replicas behind the front end")
    p.add_argument("--serve_high_frac", type=float, default=0.3,
                   help="fraction of requests sent high-priority")
    p.add_argument("--serve_capacity", type=int, default=24,
                   help="front-end in-flight admission capacity")
    p.add_argument("--precision", default="",
                   help="comma-separated precision presets "
                   "(f32,bf16_all,bf16_selective) to sweep instead of the "
                   "single-dtype step benchmark: per-preset step_ms + "
                   "loss_finite + a short accuracy probe, one "
                   "precision_ablation JSON line")
    p.add_argument("--metrics", choices=["on", "off", "paired"],
                   default="on",
                   help="metrics-registry toggle for the step-path modes: "
                   "'off' swaps in the no-op NullRegistry, 'paired' runs "
                   "the on-vs-off overhead measurement the CI metrics "
                   "overhead gate consumes")
    a = p.parse_args()
    main(a.batch_size, a.iters, a.compute_dtype, a.fused_n, not a.no_bf16,
         a.cpu_full, a.step_path,
         tuple(int(d) for d in a.prefetch_depths.split(",")),
         a.step_path_epochs, a.step_path_steps,
         a.serve, a.serve_duration_s,
         tuple(int(b) for b in a.serve_buckets.split(",")),
         a.serve_max_wait_ms, a.serve_pattern, a.serve_rps,
         a.serve_replicas, a.serve_high_frac, a.serve_capacity,
         a.metrics, a.precision)
