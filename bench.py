"""Single-chip benchmark of the hot loop: the KD train step.

Measures steady-state wall time of the compiled train step (augmentation +
student forward + teacher forward + backward + SGD, i.e. the tasks>=1 hot
loop, reference ``template.py:251-280``) for ResNet-32 at per-device batch
128, and derives images/sec and an MFU estimate.

Baseline derivation (BASELINE.md): the reference runs CIFAR-100 B50-inc10
(6 tasks x 140 epochs, global batch 512 on 4x RTX 3090) in ~30 min.  Total
trained images ~= 140 * (25000 + 5 * ~7000) ~= 8.4M, so the reference's
end-to-end training throughput is ~4700 img/s across 4 GPUs.  ``vs_baseline``
is ours/theirs on that number — a deliberately conservative comparison:
per chip, our step includes everything (their 30 min also buys eval/herding,
but their step excludes augmentation, which runs on CPU workers).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
"""

from __future__ import annotations

import json
import time

import numpy as np

REFERENCE_IMG_PER_SEC = 4700.0  # 4x3090, see module docstring

# ResNet-32 CIFAR forward: ~69.4M MACs = ~138.8M FLOPs per image.  Train step
# = student fwd + bwd (~3x fwd) + teacher fwd (1x) = ~4x fwd FLOPs.
FLOPS_PER_IMAGE_STEP = 4 * 138.8e6
TPU_V5E_PEAK_BF16 = 197e12  # per chip


def main(batch_size: int = 512, iters: int = 50, compute_dtype: str = "float32",
         fused_n: int = 7000):
    """``batch_size`` defaults to 512 — the reference's *global* batch
    (4 GPUs x 128), which fits comfortably on one v5e chip; a multi-chip mesh
    would use the per-device 128 of the config instead."""
    import jax
    import jax.numpy as jnp

    from a_pytorch_tutorial_to_class_incremental_learning_tpu.config import CilConfig
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.engine import (
        CilTrainer,
        Teacher,
    )

    cfg = CilConfig(
        data_set="synthetic",  # 100 classes; content is irrelevant to timing
        num_bases=50,
        increment=10,
        backbone="resnet32",
        batch_size=batch_size,
        compute_dtype=compute_dtype,
        seed=0,
    )
    trainer = CilTrainer(cfg, init_dist=False)
    # Task-1 shape: 50 known classes, 10 new -> KD step variant.
    trainer.state = trainer._grow_state(trainer.state, 0, 0, 50)
    trainer.teacher = Teacher(
        params=jax.tree_util.tree_map(jnp.copy, trainer.state.params),
        batch_stats=jax.tree_util.tree_map(jnp.copy, trainer.state.batch_stats),
        known=jnp.int32(50),
    )
    trainer.state = trainer._grow_state(trainer.state, 1, 50, 10)

    rng = np.random.RandomState(0)
    bs = trainer.global_batch_size
    x = rng.randint(0, 256, (bs, 32, 32, 3)).astype(np.uint8)
    y = rng.randint(0, 60, bs).astype(np.int64)
    xd, yd = trainer._put(x, y)
    step = trainer._steps[True]
    key = jax.random.PRNGKey(0)

    # Compile + warmup.
    t0 = time.time()
    trainer.state, m = step(trainer.state, trainer.teacher, xd, yd, key, 0.1, 0.5)
    jax.block_until_ready(trainer.state.params)
    compile_s = time.time() - t0
    for _ in range(5):
        trainer.state, m = step(
            trainer.state, trainer.teacher, xd, yd, key, 0.1, 0.5
        )
    jax.block_until_ready(trainer.state.params)

    t0 = time.time()
    for _ in range(iters):
        trainer.state, m = step(
            trainer.state, trainer.teacher, xd, yd, key, 0.1, 0.5
        )
    jax.block_until_ready(trainer.state.params)
    dt = (time.time() - t0) / iters

    img_s = bs / dt
    mfu = img_s * FLOPS_PER_IMAGE_STEP / TPU_V5E_PEAK_BF16

    # Fused-epoch path (the default execution mode): whole epoch as one
    # lax.scan with the dataset in HBM — measures end-to-end epoch
    # throughput including on-device shuffle and gather.
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.parallel.mesh import (
        replicated,
    )

    n = fused_n  # default: task>=1 dataset size in B50-inc10 (5000 + 2000)
    dx, dy = trainer._put(
        rng.randint(0, 256, (n, 32, 32, 3)).astype(np.uint8),
        rng.randint(0, 60, n).astype(np.int64),
        sharding=replicated(trainer.mesh),
    )
    epoch_fn = trainer._epochs[True]
    trainer.state, _ = epoch_fn(
        trainer.state, trainer.teacher, dx, dy, key, 0.1, 0.5, bs
    )
    jax.block_until_ready(trainer.state.params)
    reps = max(3, iters // 10)
    t0 = time.time()
    for _ in range(reps):
        trainer.state, _ = epoch_fn(
            trainer.state, trainer.teacher, dx, dy, key, 0.1, 0.5, bs
        )
    jax.block_until_ready(trainer.state.params)
    epoch_dt = (time.time() - t0) / reps
    # Same step-count rule as make_epoch_fn (wrap-around padding, >= 1 step).
    steps_per_epoch = max(1, -(-n // bs))
    fused_img_s = steps_per_epoch * bs / epoch_dt
    print(
        json.dumps(
            {
                "metric": "train_step_throughput",
                "value": round(img_s, 1),
                "unit": "img/s",
                "vs_baseline": round(img_s / REFERENCE_IMG_PER_SEC, 3),
                "step_ms": round(dt * 1e3, 3),
                "global_batch": bs,
                "compile_s": round(compile_s, 1),
                # Estimate only: assumes fwd=2*69.4M MACs, bwd=2x fwd,
                # teacher=1x fwd, against the 197 TFLOP/s bf16 chip peak
                # (XLA runs f32 convs through the MXU's bf16 path by
                # default); convention error is easily +/-2x.
                "est_mfu": round(mfu, 4),
                "fused_epoch_img_s": round(fused_img_s, 1),
                "fused_epoch_ms": round(epoch_dt * 1e3, 2),
                "backend": jax.default_backend(),
                "devices": jax.device_count(),
                "compute_dtype": compute_dtype,
                "loss_finite": bool(np.isfinite(float(m["loss"]))),
            }
        )
    )


if __name__ == "__main__":
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--batch_size", type=int, default=512)
    p.add_argument("--iters", type=int, default=50)
    p.add_argument("--compute_dtype", default="float32",
                   choices=["float32", "bfloat16"])
    p.add_argument("--fused_n", type=int, default=7000,
                   help="dataset size for the fused-epoch measurement")
    a = p.parse_args()
    main(a.batch_size, a.iters, a.compute_dtype, a.fused_n)
