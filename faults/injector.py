"""Deterministic fault injection: named injection points on the hot paths.

On TPU pods preemption is the common case, not the exception — Podracer-style
architectures (arXiv:2104.06272) treat kill-and-relaunch as the normal
lifecycle — yet none of this repo's recovery paths (resume, heartbeat
supervision, prefetch producer death, checkpoint corruption) would ever run
in CI unless something *injects* the failure.  This module makes faults
reproducible: a ``--fault_spec`` names exact coordinates (task/epoch/step)
where a specific failure fires, once, with a durable ledger so a relaunched
process does not re-fire the same fault into a crash loop.

Spec grammar (comma-separated clauses)::

    <action>@task<T>[.epoch<E>[.step<S>]]

    kill@task1.epoch3          SIGKILL after task 1's 3rd epoch completes
    raise@task0.epoch2.step7   raise FaultInjected after step 7 of the epoch
    producer_die@task1.epoch1.step3   prefetch producer thread dies there
    slow_batch@task0.epoch1.step2     producer sleeps 0.25 s on that batch
    corrupt_ckpt@task2         bit-flip the first checkpoint saved for task 2
    truncate_ckpt@task1.epoch2 truncate that epoch checkpoint's payload
    save_ioerror@task0         transient OSError on task 0's checkpoint save
    swap_ioerror@task1         the serving hot-swap TO task 1's artifact fails
    slow_swap@task1            that swap stalls for slow_s before loading
    replica_die@task0          serving replica 0 SIGKILLs itself on a request
    slow_replica@task1         replica 1 stalls one request for slow_s
    frontend_ioerror@task2     the front end's dispatch to replica 2 errors

Coordinates use the run-log numbering: ``task`` is the 0-based ``task_id``,
``epoch``/``step`` are 1-based like the ``epoch`` records.  The serving-fleet
sites (``serve.replica``, ``serve.frontend``) reuse the ``task`` coordinate
as the *replica id* — the grammar stays one-dimensional and the ledger
semantics (one-shot, durable across a replica relaunch) carry over
unchanged.  Unspecified coordinates are wildcards (``kill@task1`` fires at
the end of task 1's first epoch); a kill/raise clause without a ``step``
coordinate never fires at the per-step site — mid-epoch would strike before
the named epoch's checkpoint exists.  Engine coordinates fire at the *end*
of the named unit — after the epoch's checkpoint hook, after the step's
dispatch — so a kill at ``task1.epoch3`` leaves the epoch-3 checkpoint on
disk and the resumed twin replays from exactly there.

``step``-level clauses fire on both execution paths: live at the per-batch
``engine.step`` site (``--no_fused_epochs``), and under fused epochs —
where the whole epoch is one opaque device program and no host code runs
between steps — via end-of-epoch *reconciliation* (:meth:`reconcile_steps`):
once the fused program returns and the host knows how many steps ran, every
armed step clause inside that epoch fires in step order, marked
``reconciled`` in the ledger and telemetry.  The observable timing shifts to
the epoch boundary (before the epoch-checkpoint hook), but the clause
fires exactly once either way.  ``data.produce`` remains per-batch-only:
there is no producer thread inside a fused program.

Each clause fires **once**.  With a ledger path (defaulted to
``<ckpt_dir>/fault_ledger.jsonl`` by the trainer), the firing is recorded
durably *before* the action executes, so a SIGKILL'd-and-relaunched process
parses the same ``--fault_spec`` but finds the clause already spent — the
relaunch runs clean instead of crash-looping.  Every firing also emits a
schema-checked ``fault_injected`` record to the run log.

Zero overhead when unset: without ``--fault_spec`` the trainer holds ``None``
and the hot paths pay one identity check per site.
"""

from __future__ import annotations

import json
import os
import re
import signal
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

# action -> sites where it may fire.  Sites are the code locations that call
# ``fire(site, ...)``:
#   engine.epoch   engine/loop.py, end of each epoch (after the epoch-
#                  checkpoint hook)            coords: task, epoch
#   engine.step    engine/loop.py, after each per-batch train step
#                                              coords: task, epoch, step
#   data.produce   the prefetch producer path (engine/loop.py ``_placed``,
#                  runs on the producer thread at depth > 0)
#                                              coords: task, epoch, step
#   ckpt.save      utils/checkpoint.py, before/after each checkpoint write
#                                              coords: task[, epoch]
#   serve.swap     serving/server.py, before the watcher applies a manifest
#                  hot-swap                    coords: task (the swap TARGET)
#   serve.replica  serving/replica.py, before a replica handles a /predict
#                  request                     coords: task (= replica id)
#   serve.frontend serving/frontend.py, before the front end dispatches to a
#                  replica                     coords: task (= replica id)
ACTIONS: Dict[str, frozenset] = {
    "kill": frozenset({"engine.epoch", "engine.step"}),
    "raise": frozenset({"engine.epoch", "engine.step"}),
    "producer_die": frozenset({"data.produce"}),
    "slow_batch": frozenset({"data.produce"}),
    "corrupt_ckpt": frozenset({"ckpt.save"}),
    "truncate_ckpt": frozenset({"ckpt.save"}),
    "save_ioerror": frozenset({"ckpt.save"}),
    "swap_ioerror": frozenset({"serve.swap"}),
    "slow_swap": frozenset({"serve.swap"}),
    "replica_die": frozenset({"serve.replica"}),
    "slow_replica": frozenset({"serve.replica"}),
    "frontend_ioerror": frozenset({"serve.frontend"}),
}

# Actions fire() performs itself vs. actions the call site must apply (a
# checkpoint file can only be corrupted by the code that knows its path;
# a swap can only be failed by the server that owns the swap; a dispatch can
# only be failed by the front end that owns the connection).
COOPERATIVE = frozenset({
    "corrupt_ckpt", "truncate_ckpt", "save_ioerror", "swap_ioerror",
    "frontend_ioerror",
})

# step nests inside epoch (a step coordinate without its epoch is ambiguous
# across epochs, so the grammar forbids it).
_CLAUSE_RE = re.compile(
    r"(?P<action>[a-z_]+)@task(?P<task>\d+)"
    r"(?:\.epoch(?P<epoch>\d+)(?:\.step(?P<step>\d+))?)?$"
)


class FaultInjected(RuntimeError):
    """The injected failure itself (``raise`` / ``producer_die`` actions)."""

    def __init__(self, clause: "FaultClause", site: str, coords: dict):
        self.clause = clause
        self.site = site
        self.coords = dict(coords)
        super().__init__(f"injected fault {clause.spec} fired at {site} {coords}")


@dataclass(frozen=True)
class FaultClause:
    spec: str          # the clause text, verbatim — also the ledger key
    action: str
    task: int
    epoch: Optional[int] = None   # None = wildcard
    step: Optional[int] = None    # None = wildcard

    def matches(self, site: str, coords: dict) -> bool:
        if site not in ACTIONS[self.action]:
            return False
        if site == "engine.step" and self.step is None:
            # An epoch- or task-granular kill/raise names the END of its
            # unit: it fires at the engine.epoch site (after that epoch's
            # checkpoint hook), never mid-epoch at the first step reached —
            # otherwise kill@taskT.epochE would strike before epoch E's
            # checkpoint exists and the resume could not be epoch-exact.
            return False
        for field in ("task", "epoch", "step"):
            want = getattr(self, field)
            if want is not None and coords.get(field) != want:
                return False
        return True


def parse_fault_spec(spec: str) -> List[FaultClause]:
    """Parse a ``--fault_spec`` string; raises ``ValueError`` on any bad
    clause (a typo'd fault plan silently never firing would defeat the whole
    point of deterministic injection)."""
    clauses: List[FaultClause] = []
    for raw in spec.split(","):
        text = raw.strip()
        if not text:
            continue
        m = _CLAUSE_RE.fullmatch(text)
        if not m:
            raise ValueError(
                f"bad fault clause {text!r}; expected "
                "<action>@task<T>[.epoch<E>[.step<S>]]"
            )
        action = m.group("action")
        if action not in ACTIONS:
            raise ValueError(
                f"unknown fault action {action!r}; options: {sorted(ACTIONS)}"
            )
        clauses.append(FaultClause(
            spec=text,
            action=action,
            task=int(m.group("task")),
            epoch=int(m.group("epoch")) if m.group("epoch") else None,
            step=int(m.group("step")) if m.group("step") else None,
        ))
    if not clauses:
        raise ValueError(f"fault spec {spec!r} contains no clauses")
    return clauses


class FaultInjector:
    """Armed fault clauses + the durable fired-ledger.

    ``fire(site, **coords)`` checks every armed clause against the site and
    coordinates; each match is recorded (ledger first — it must survive a
    SIGKILL — then the ``fault_injected`` telemetry record) and then executed:
    ``kill`` SIGKILLs this process, ``raise``/``producer_die`` raise
    :class:`FaultInjected`, ``slow_batch`` sleeps; the cooperative checkpoint
    actions are *returned* for the call site to apply.  Clauses are one-shot.
    """

    def __init__(
        self,
        clauses: List[FaultClause],
        ledger_path: Optional[str] = None,
        sink=None,
        slow_s: float = 0.25,
        on_fatal=None,
    ):
        self.ledger_path = ledger_path
        self.sink = sink
        self.slow_s = slow_s
        # Called (no args) after the ledger write but before an uncatchable
        # ``kill`` executes — the engine points this at the flight recorder's
        # fatal dump so the crash tail survives the SIGKILL.  A callback (not
        # an import) because faults/ is stdlib-only by contract.
        self.on_fatal = on_fatal
        spent = self._load_ledger()
        self._armed: List[FaultClause] = []
        for c in clauses:
            if spent.get(c.spec, 0) > 0:
                spent[c.spec] -= 1  # duplicate clauses spend ledger entries 1:1
            else:
                self._armed.append(c)

    # ------------------------------------------------------------------ #

    @property
    def armed(self) -> Tuple[FaultClause, ...]:
        return tuple(self._armed)

    def fire(self, site: str, **coords) -> Tuple[str, ...]:
        """Fire every armed clause matching ``(site, coords)``.

        Returns the matched :data:`COOPERATIVE` action names for the caller
        to apply; non-cooperative actions are performed here (and ``kill`` /
        ``raise`` never return).
        """
        if not self._armed:
            return ()
        matched = [c for c in self._armed if c.matches(site, coords)]
        if not matched:
            return ()
        cooperative: List[str] = []
        for clause in matched:
            self._armed.remove(clause)
            self._record(clause, site, coords)
            self._execute(clause, site, coords, cooperative)
        return tuple(cooperative)

    def reconcile_steps(
        self, site: str, task: int, epoch: int, steps: int
    ) -> Tuple[str, ...]:
        """End-of-epoch step reconciliation for the fused-epoch path.

        The fused program runs the whole epoch on-device, so the per-step
        ``fire`` sites never execute; once it returns, the host knows how
        many steps ran and settles the bill: every armed step-level clause
        matching ``site``/``task``/``epoch`` with ``step <= steps`` fires
        now, in step order, tagged ``reconciled`` in the ledger and the
        ``fault_injected`` record.  Clauses aimed past the epoch's end stay
        armed.  Same one-shot/ledger/action semantics as :meth:`fire`.
        """
        if not self._armed:
            return ()
        matched = sorted(
            (c for c in self._armed
             if c.step is not None and c.step <= steps
             and c.matches(site, {"task": task, "epoch": epoch,
                                  "step": c.step})),
            key=lambda c: c.step,
        )
        cooperative: List[str] = []
        for clause in matched:
            coords = {"task": task, "epoch": epoch, "step": clause.step}
            self._armed.remove(clause)
            self._record(clause, site, coords, reconciled=True)
            self._execute(clause, site, coords, cooperative)
        return tuple(cooperative)

    # ------------------------------------------------------------------ #

    def _execute(
        self, clause: FaultClause, site: str, coords: dict,
        cooperative: List[str],
    ) -> None:
        if clause.action in ("kill", "replica_die"):
            if self.on_fatal is not None:
                try:
                    self.on_fatal()
                except Exception:  # jaxlint: disable=JL302
                    pass  # forensics must never block the injected death
            os.kill(os.getpid(), signal.SIGKILL)
        elif clause.action in ("raise", "producer_die"):
            raise FaultInjected(clause, site, coords)
        elif clause.action in ("slow_batch", "slow_swap", "slow_replica"):
            time.sleep(self.slow_s)
        else:
            cooperative.append(clause.action)

    def _record(
        self, clause: FaultClause, site: str, coords: dict,
        reconciled: bool = False,
    ) -> None:
        # Ledger strictly before the action: a SIGKILL between the two writes
        # must lose the telemetry record, never the disarm.
        if self.ledger_path:
            os.makedirs(
                os.path.dirname(os.path.abspath(self.ledger_path)), exist_ok=True
            )
            entry = {
                "spec": clause.spec, "site": site,
                "ts": round(time.time(), 3), "pid": os.getpid(), **coords,
            }
            if reconciled:
                entry["reconciled"] = True
            with open(self.ledger_path, "a") as f:
                f.write(json.dumps(entry) + "\n")
                f.flush()
                os.fsync(f.fileno())
        if self.sink is not None:
            extra = {"reconciled": True} if reconciled else {}
            self.sink.log(
                "fault_injected", site=site, action=clause.action,
                spec=clause.spec,
                **{k: v for k, v in coords.items() if v is not None},
                **extra,
            )
        print(f"| FAULT INJECTED: {clause.spec} at {site} {coords}"
              + (" (reconciled)" if reconciled else ""))

    def _load_ledger(self) -> Dict[str, int]:
        spent: Dict[str, int] = {}
        if not self.ledger_path or not os.path.exists(self.ledger_path):
            return spent
        with open(self.ledger_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn trailing line of a killed process
                spec = rec.get("spec")
                if spec:
                    spent[spec] = spent.get(spec, 0) + 1
        return spent


def rotate_ledger(path: Optional[str]) -> Optional[str]:
    """Archive a spent fire-ledger to ``<path>.<n>`` (lowest free n).

    A *fresh* (non-``--resume``) run with a ``--fault_spec`` wants its
    clauses armed — but a leftover ledger from the previous soak iteration
    would mark them spent, and deleting it by hand defeats repeatable chaos
    soaks.  Rotation keeps the history (every archived ledger is forensic
    evidence) while re-arming the spec.  Resumed runs must NOT rotate: the
    spent ledger is exactly what keeps a relaunch out of a crash loop.

    Returns the archive path, or None when there was nothing to rotate.
    """
    if not path or not os.path.exists(path):
        return None
    n = 1
    while os.path.exists(f"{path}.{n}"):
        n += 1
    os.replace(path, f"{path}.{n}")
    return f"{path}.{n}"


def injector_from(
    spec: Optional[str],
    ledger_path: Optional[str] = None,
    sink=None,
    on_fatal=None,
) -> Optional[FaultInjector]:
    """The trainer's entry point: ``None`` when no spec is configured, so the
    hot paths pay exactly one ``is not None`` check."""
    if not spec:
        return None
    return FaultInjector(
        parse_fault_spec(spec), ledger_path=ledger_path, sink=sink,
        on_fatal=on_fatal,
    )
