"""Deterministic fault injection (see :mod:`faults.injector`).

Stdlib-only, like ``analysis/``: the injector must be importable (and its
specs parseable) without jax, so the supervisor and tests can reason about
fault plans outside a training process.
"""

from .injector import (  # noqa: F401
    ACTIONS,
    FaultClause,
    FaultInjected,
    FaultInjector,
    injector_from,
    parse_fault_spec,
    rotate_ledger,
)
