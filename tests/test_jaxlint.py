"""jaxlint: per-rule positive/negative fixtures, suppressions, baseline
behaviour, the CLI exit-code contract, and the runtime-contract half
(RecompileSentinel budget math, buffer-alias detection on real CPU arrays).

The fixture snippets are *strings written to tmp_path* — they are analyzed
by the stdlib-only AST pass, never imported or executed, so they reference
names (jax, state, ...) freely and deliberately contain the hazards the
linter exists for.  The analysis package itself must import without jax.
"""

import json
import subprocess
import sys
import textwrap

import pytest

from analysis import Baseline, lint_paths
from analysis.findings import Finding, is_suppressed, parse_suppressions

REPO = __file__.rsplit("/tests/", 1)[0]


def run_lint(tmp_path, source, name="snippet.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    return lint_paths([str(p)], root=str(tmp_path))


def rules_of(findings):
    return sorted({f.rule for f in findings})


# --------------------------------------------------------------------------- #
# JL001 — read after donate
# --------------------------------------------------------------------------- #


def test_jl001_read_after_donate(tmp_path):
    findings = run_lint(tmp_path, """
        import jax

        step = jax.jit(lambda s, b: s, donate_argnums=(0,))

        def train(state, batch):
            new_state = step(state, batch)
            loss = state.params  # read of donated buffer
            return new_state, loss
        """)
    assert rules_of(findings) == ["JL001"]
    (f,) = findings
    assert f.line == 8 and "donated" in f.message


def test_jl001_rebind_is_clean(tmp_path):
    findings = run_lint(tmp_path, """
        import jax

        step = jax.jit(lambda s, b: s, donate_argnums=(0,))

        def train(state, batch):
            state = step(state, batch)  # rebound: the old buffer is gone
            return state.params
        """)
    assert findings == []


def test_jl001_escape_of_donated_attribute(tmp_path):
    # The bench.py trace_crosscheck bug: self.state donated into a profiled
    # call and never rebound before the function returns.
    findings = run_lint(tmp_path, """
        import jax

        def profile(trainer, batch):
            step = jax.jit(lambda s, b: s, donate_argnums=(0,))
            state = trainer.state
            state = step(state, batch)
            out = step(trainer.state, batch)  # donates trainer.state
            return out
        """)
    assert "JL001" in rules_of(findings)


# --------------------------------------------------------------------------- #
# JL002 — restored host buffer into donating program (the PR 3 regression)
# --------------------------------------------------------------------------- #

PR3_REGRESSION = """
    import pickle
    import jax
    import jax.numpy as jnp
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.parallel.mesh import shard_params

    step = jax.jit(lambda s, b: s, donate_argnums=(0,))

    def load_task_checkpoint(trainer, path):
        with open(path, "rb") as f:
            payload = pickle.load(f)
        params = shard_params(trainer.mesh, payload["params"])
        trainer.state = trainer.state.replace(params=params)
        return True
    """


def test_jl002_pr3_restore_aliasing_regression(tmp_path):
    """The exact PR 3 shape: pickle.load -> shard_params -> state.replace
    without jnp.copy.  Must flag with the right file, line and rule id."""
    p = tmp_path / "ckpt.py"
    p.write_text(textwrap.dedent(PR3_REGRESSION))
    findings = lint_paths([str(p)], root=str(tmp_path))
    assert [f.rule for f in findings] == ["JL002"]
    (f,) = findings
    assert f.path == "ckpt.py"
    assert f.line == 13  # the state.replace(params=params) line
    assert "jnp.copy" in f.message
    assert f.render().startswith("ckpt.py:13:")


def test_jl002_copy_sanitizes(tmp_path):
    findings = run_lint(tmp_path, """
        import pickle
        import jax
        import jax.numpy as jnp

        def load(trainer, path):
            with open(path, "rb") as f:
                payload = pickle.load(f)
            params = jax.tree_util.tree_map(jnp.copy, payload["params"])
            trainer.state = trainer.state.replace(params=params)
        """)
    assert findings == []


def test_jl002_orbax_restore_tainted(tmp_path):
    findings = run_lint(tmp_path, """
        import jax

        def load(trainer, ckptr, path, template):
            restored = ckptr.restore(path, template)
            trainer.state = trainer.state.replace(params=restored["params"])
        """)
    assert rules_of(findings) == ["JL002"]


# --------------------------------------------------------------------------- #
# JL101 — uncommitted scalars
# --------------------------------------------------------------------------- #


def test_jl101_uncommitted_scalar(tmp_path):
    findings = run_lint(tmp_path, """
        import jax.numpy as jnp

        def grow(trainer, known):
            trainer.state = trainer.state.replace(num_active=jnp.int32(known))
        """)
    assert rules_of(findings) == ["JL101"]


def test_jl101_replicated_scalar_is_clean(tmp_path):
    findings = run_lint(tmp_path, """
        from a_pytorch_tutorial_to_class_incremental_learning_tpu.parallel.mesh import replicated_scalar

        def grow(trainer, known):
            trainer.state = trainer.state.replace(
                num_active=replicated_scalar(trainer.mesh, known)
            )
        """)
    assert findings == []


# --------------------------------------------------------------------------- #
# JL102 — branch on tracer
# --------------------------------------------------------------------------- #


def test_jl102_branch_on_tracer(tmp_path):
    findings = run_lint(tmp_path, """
        import jax

        def step(state, batch):
            if batch["y"] > 0:
                return state
            return state

        step = jax.jit(step)
        """)
    assert rules_of(findings) == ["JL102"]


def test_jl102_static_argnums_excluded(tmp_path):
    findings = run_lint(tmp_path, """
        import jax

        def step(state, use_teacher):
            if use_teacher:
                return state
            return state

        step = jax.jit(step, static_argnums=(1,))
        """)
    assert findings == []


def test_jl102_is_none_test_allowed(tmp_path):
    findings = run_lint(tmp_path, """
        import jax

        def step(state, teacher):
            if teacher is None:
                return state
            return state

        step = jax.jit(step)
        """)
    assert findings == []


# --------------------------------------------------------------------------- #
# JL103 — shape-polymorphic batch into a jitted program inside a loop
# --------------------------------------------------------------------------- #


def test_jl103_dynamic_slice_in_loop(tmp_path):
    findings = run_lint(tmp_path, """
        import jax

        step = jax.jit(lambda b: b)

        def run(batches, n):
            for b in batches:
                step(b[:n])  # ragged final batch: recompile per length
        """)
    assert rules_of(findings) == ["JL103"]
    (f,) = findings
    assert "`n`" in f.message and "recompile" in f.message


def test_jl103_decorated_jit_in_while_loop(tmp_path):
    findings = run_lint(tmp_path, """
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def step(x):
            return x

        def run(bs, n):
            i = 0
            while i < 10:
                step(bs[i:n])
                i += 1
        """)
    assert rules_of(findings) == ["JL103"]


def test_jl103_constant_bounds_are_clean(tmp_path):
    findings = run_lint(tmp_path, """
        import jax

        step = jax.jit(lambda b: b)

        def run(batches):
            for b in batches:
                step(b[:64])   # fixed shape
                step(b[:-1])   # constant negative bound: still one shape
                step(b[1:8])
        """)
    assert findings == []


def test_jl103_outside_loop_or_unjitted_is_clean(tmp_path):
    findings = run_lint(tmp_path, """
        import jax

        step = jax.jit(lambda b: b)

        def plain(b):
            return b

        def run(batches, n):
            step(batches[0][:n])   # one-shot slice outside any loop
            for b in batches:
                plain(b[:n])       # callee is not jitted
        """)
    assert findings == []


def test_jl103_suppression_comment(tmp_path):
    findings = run_lint(tmp_path, """
        import jax

        step = jax.jit(lambda b: b)

        def run(batches, n):
            for b in batches:
                step(b[:n])  # jaxlint: disable=JL103 -- bounded retrace
        """)
    assert findings == []


# --------------------------------------------------------------------------- #
# JL104 — f32 master state cast down to bf16
# --------------------------------------------------------------------------- #


def test_jl104_momentum_astype(tmp_path):
    findings = run_lint(tmp_path, """
        import jax.numpy as jnp

        def shrink(state):
            return state.momentum.astype(jnp.bfloat16)
        """)
    assert rules_of(findings) == ["JL104"]
    (f,) = findings
    assert "momentum" in f.message and "float32" in f.message


def test_jl104_asarray_batch_stats(tmp_path):
    findings = run_lint(tmp_path, """
        import jax.numpy as jnp

        def pack(batch_stats):
            return jnp.asarray(batch_stats, jnp.bfloat16)
        """)
    assert rules_of(findings) == ["JL104"]


def test_jl104_tree_map_lambda_on_opt_state(tmp_path):
    findings = run_lint(tmp_path, """
        import jax
        import jax.numpy as jnp

        def halve(opt_state):
            return jax.tree_util.tree_map(
                lambda t: t.astype(jnp.bfloat16), opt_state)
        """)
    assert rules_of(findings) == ["JL104"]


def test_jl104_loss_convert_element_type(tmp_path):
    findings = run_lint(tmp_path, """
        import jax

        def acc(loss_sum):
            return jax.lax.convert_element_type(loss_sum, "bfloat16")
        """)
    assert rules_of(findings) == ["JL104"]


def test_jl104_upcast_and_unguarded_names_are_clean(tmp_path):
    # Upcasting master state to f32 is the contract; down-casting
    # activations/params at the matmul boundary is exactly what selective
    # precision prescribes — neither may flag.
    findings = run_lint(tmp_path, """
        import jax
        import jax.numpy as jnp

        def fine(state, x, params):
            m = state.momentum.astype(jnp.float32)
            y = x.astype(jnp.bfloat16)  # activation at the boundary
            w = jax.tree_util.tree_map(
                lambda t: t.astype(jnp.bfloat16), params)
            return m, y, w
        """)
    assert findings == []


def test_jl104_suppression_comment(tmp_path):
    findings = run_lint(tmp_path, """
        import jax.numpy as jnp

        def export(batch_stats):
            return jnp.asarray(batch_stats, jnp.bfloat16)  # jaxlint: disable=JL104 -- serialization only
        """)
    assert findings == []


# --------------------------------------------------------------------------- #
# JL201 — host sync in hot loop
# --------------------------------------------------------------------------- #


def test_jl201_item_in_batch_loop(tmp_path):
    findings = run_lint(tmp_path, """
        import jax

        def run_epoch(step, state, batches):
            total = 0.0
            for batch in batches:
                state, loss = step(state, batch)
                total += loss.item()  # per-step device sync
            return state, total
        """)
    assert rules_of(findings) == ["JL201"]


def test_jl201_sync_after_loop_is_clean(tmp_path):
    findings = run_lint(tmp_path, """
        import jax

        def run_epoch(step, state, batches):
            losses = []
            for batch in batches:
                state, loss = step(state, batch)
                losses.append(loss)
            return state, [x.item() for x in losses]
        """)
    assert findings == []


# --------------------------------------------------------------------------- #
# JL301 — thread-shared state
# --------------------------------------------------------------------------- #


def test_jl301_unlocked_shared_attribute(tmp_path):
    findings = run_lint(tmp_path, """
        import threading

        class Beat:
            def __init__(self):
                self._lock = threading.Lock()
                self._step = 0
                self._t = threading.Thread(target=self._run, daemon=True)

            def _run(self):
                while True:
                    self._step += 1  # producer write, no lock

            def read(self):
                return self._step  # consumer write elsewhere

            def update(self, n):
                self._step = n
        """)
    assert rules_of(findings) == ["JL301"]


def test_jl301_locked_writes_are_clean(tmp_path):
    findings = run_lint(tmp_path, """
        import threading

        class Beat:
            def __init__(self):
                self._lock = threading.Lock()
                self._step = 0
                self._t = threading.Thread(target=self._run, daemon=True)

            def _run(self):
                while True:
                    with self._lock:
                        self._step += 1

            def update(self, n):
                with self._lock:
                    self._step = n
        """)
    assert findings == []


# --------------------------------------------------------------------------- #
# JL302 — swallowed broad exceptions
# --------------------------------------------------------------------------- #


def test_jl302_bare_except_pass(tmp_path):
    findings = run_lint(tmp_path, """
        def save(path, data):
            try:
                open(path, "w").write(data)
            except:
                pass
        """)
    assert rules_of(findings) == ["JL302"]
    (f,) = findings
    assert "bare except" in f.message


def test_jl302_broad_except_swallowing_result(tmp_path):
    findings = run_lint(tmp_path, """
        def probe(dev):
            try:
                return dev.memory_stats()
            except Exception:
                return None
        """)
    assert rules_of(findings) == ["JL302"]


def test_jl302_tuple_with_broad_member(tmp_path):
    findings = run_lint(tmp_path, """
        def probe(dev):
            try:
                return dev.memory_stats()
            except (OSError, BaseException):
                return None
        """)
    assert rules_of(findings) == ["JL302"]


def test_jl302_narrow_except_is_clean(tmp_path):
    findings = run_lint(tmp_path, """
        import os

        def cleanup(path):
            try:
                os.remove(path)
            except OSError:
                pass
        """)
    assert findings == []


def test_jl302_reraise_read_or_report_are_clean(tmp_path):
    findings = run_lint(tmp_path, """
        import logging

        def a(fn):
            try:
                fn()
            except Exception:
                raise            # re-raised: nothing swallowed

        def b(fn):
            try:
                fn()
            except Exception as e:
                return repr(e)   # the error is read

        def c(fn):
            try:
                fn()
            except Exception:
                logging.warning("fn failed")  # reported
        """)
    assert findings == []


def test_jl302_suppression_comment(tmp_path):
    findings = run_lint(tmp_path, """
        def teardown(res):
            try:
                res.close()
            except Exception:  # jaxlint: disable=JL302 -- interpreter exit
                pass
        """)
    assert findings == []


# --------------------------------------------------------------------------- #
# JL303 — lock-order inversion (interprocedural acquisition-order graph)
# --------------------------------------------------------------------------- #


def test_jl303_abba_inversion(tmp_path):
    findings = run_lint(tmp_path, """
        import threading

        class Swap:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._b:
                    with self._a:
                        pass
        """)
    assert rules_of(findings) == ["JL303"]
    # Both directions of the cycle are reported, each at its acquire site.
    assert sorted(f.line for f in findings) == [11, 16]
    assert all("inversion" in f.message for f in findings)


def test_jl303_inversion_through_self_call(tmp_path):
    # The second lock is taken in a *callee*, not lexically — the edge must
    # come from the interprocedural transitive-acquire set.
    findings = run_lint(tmp_path, """
        import threading

        class Swap:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    self._locked_b()

            def _locked_b(self):
                with self._b:
                    pass

            def two(self):
                with self._b:
                    with self._a:
                        pass
        """)
    assert rules_of(findings) == ["JL303"]
    assert 19 in {f.line for f in findings}  # the reverse acquire in two()


def test_jl303_consistent_order_is_clean(tmp_path):
    findings = run_lint(tmp_path, """
        import threading

        class Swap:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._a:
                    with self._b:
                        pass
        """)
    assert findings == []


# --------------------------------------------------------------------------- #
# JL304 — blocking call while holding a lock
# --------------------------------------------------------------------------- #


def test_jl304_queue_get_under_lock(tmp_path):
    findings = run_lint(tmp_path, """
        import queue
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self._queue = queue.Queue()

            def drain(self):
                with self._lock:
                    return self._queue.get()
        """)
    assert rules_of(findings) == ["JL304"]
    (f,) = findings
    assert f.line == 12 and "Worker._lock" in f.message


def test_jl304_get_outside_lock_is_clean(tmp_path):
    findings = run_lint(tmp_path, """
        import queue
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self._queue = queue.Queue()

            def drain(self):
                item = self._queue.get()
                with self._lock:
                    return item
        """)
    assert findings == []


def test_jl304_join_and_file_io_under_lock(tmp_path):
    findings = run_lint(tmp_path, """
        import threading

        class Owner:
            def __init__(self):
                self._lock = threading.Lock()
                self._t = threading.Thread(target=self._run)

            def _run(self):
                pass

            def stop(self):
                with self._lock:
                    self._t.join()

            def dump(self, path):
                with self._lock:
                    with open(path, "w") as f:
                        f.write("x")
        """)
    assert rules_of(findings) == ["JL304"]
    assert sorted(f.line for f in findings) == [14, 18]


def test_jl304_str_join_is_clean(tmp_path):
    # str.join / os.path.join are not thread joins.
    findings = run_lint(tmp_path, """
        import os
        import threading

        class Owner:
            def __init__(self):
                self._lock = threading.Lock()

            def render(self, parts):
                with self._lock:
                    return os.path.join("/tmp", ", ".join(parts))
        """)
    assert findings == []


# --------------------------------------------------------------------------- #
# JL305 — inconsistent locksets (interprocedural JL301)
# --------------------------------------------------------------------------- #


def test_jl305_unlocked_read_races_cadence(tmp_path):
    # The telemetry/heartbeat.py bug this rule caught in the real tree:
    # the daemon writes `_last` under the lock, update() read it bare.
    findings = run_lint(tmp_path, """
        import threading
        import time

        class Beat:
            def __init__(self):
                self._lock = threading.Lock()
                self._last = 0.0
                self._t = threading.Thread(target=self._run, daemon=True)

            def _run(self):
                while True:
                    with self._lock:
                        self._last = time.monotonic()

            def update(self):
                if time.monotonic() - self._last > 1.0:
                    with self._lock:
                        self._last = time.monotonic()
        """)
    assert rules_of(findings) == ["JL305"]
    (f,) = findings
    assert f.line == 17 and "_last" in f.message and "Beat._lock" in f.message


def test_jl305_every_access_locked_is_clean(tmp_path):
    findings = run_lint(tmp_path, """
        import threading
        import time

        class Beat:
            def __init__(self):
                self._lock = threading.Lock()
                self._last = 0.0
                self._t = threading.Thread(target=self._run, daemon=True)

            def _run(self):
                while True:
                    with self._lock:
                        self._last = time.monotonic()

            def update(self):
                with self._lock:
                    if time.monotonic() - self._last > 1.0:
                        self._last = time.monotonic()
        """)
    assert findings == []


def test_jl305_lock_free_class_is_clean(tmp_path):
    # No locks, no threads: plain single-threaded state is out of scope.
    findings = run_lint(tmp_path, """
        class Counter:
            def __init__(self):
                self._n = 0

            def bump(self):
                self._n += 1

            def peek(self):
                return self._n
        """)
    assert findings == []


# --------------------------------------------------------------------------- #
# JL306 — thread-side truncate-write without atomic rename
# --------------------------------------------------------------------------- #


def test_jl306_daemon_truncate_write(tmp_path):
    findings = run_lint(tmp_path, """
        import json
        import threading

        class Sink:
            def __init__(self):
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()

            def _run(self):
                with open("state.json", "w") as f:
                    json.dump({}, f)
        """)
    assert rules_of(findings) == ["JL306"]
    (f,) = findings
    assert f.line == 11 and "os.replace" in f.message


def test_jl306_tmp_rename_idiom_is_clean(tmp_path):
    findings = run_lint(tmp_path, """
        import json
        import os
        import threading

        class Sink:
            def __init__(self):
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()

            def _run(self):
                tmp = "state.json.tmp"
                with open(tmp, "w") as f:
                    json.dump({}, f)
                os.replace(tmp, "state.json")
        """)
    assert findings == []


def test_jl306_append_mode_is_clean(tmp_path):
    # The JSONL sink idiom: appends are not torn by a concurrent reader.
    findings = run_lint(tmp_path, """
        import threading

        class Sink:
            def __init__(self):
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()

            def _run(self):
                with open("events.jsonl", "a") as f:
                    f.write("{}")
        """)
    assert findings == []


# --------------------------------------------------------------------------- #
# suppressions / baseline / JL000
# --------------------------------------------------------------------------- #


def test_suppression_comment(tmp_path):
    findings = run_lint(tmp_path, """
        import jax

        step = jax.jit(lambda s, b: s, donate_argnums=(0,))

        def train(state, batch):
            new_state = step(state, batch)
            loss = state.params  # jaxlint: disable=JL001 -- test rig
            return new_state, loss
        """)
    assert findings == []


def test_suppression_parsing():
    sup = parse_suppressions(
        "x = 1  # jaxlint: disable=JL001, JL101\ny = 2\n"
    )
    assert sup == {1: {"JL001", "JL101"}}
    f = Finding(path="p.py", line=1, col=0, rule="JL001", message="m")
    assert is_suppressed(f, sup)
    assert not is_suppressed(
        Finding(path="p.py", line=2, col=0, rule="JL001", message="m"), sup
    )


def test_jl000_syntax_error(tmp_path):
    findings = run_lint(tmp_path, "def broken(:\n")
    assert rules_of(findings) == ["JL000"]


def test_baseline_split_and_stale(tmp_path):
    f1 = Finding(path="a.py", line=3, col=0, rule="JL001", message="m1")
    f2 = Finding(path="b.py", line=7, col=0, rule="JL201", message="m2")
    path = tmp_path / "base.json"
    Baseline().write(str(path), [f1])
    base = Baseline.load(str(path))
    new, known, stale = base.split([f1, f2])
    assert [f.rule for f in new] == ["JL201"]
    assert [f.rule for f in known] == ["JL001"]
    assert stale == []
    # f1 fixed -> its entry goes stale
    new, known, stale = base.split([f2])
    assert [f.rule for f in new] == ["JL201"]
    assert known == [] and len(stale) == 1


def test_baseline_write_preserves_reasons(tmp_path):
    f1 = Finding(path="a.py", line=3, col=0, rule="JL001", message="m1")
    path = tmp_path / "base.json"
    Baseline().write(str(path), [f1])
    data = json.loads(path.read_text())
    data["findings"][0]["reason"] = "justified because reasons"
    path.write_text(json.dumps(data))
    Baseline.load(str(path)).write(str(path), [f1])  # rewrite keeps the reason
    data = json.loads(path.read_text())
    assert data["findings"][0]["reason"] == "justified because reasons"


# --------------------------------------------------------------------------- #
# CLI exit codes
# --------------------------------------------------------------------------- #


def test_cli_nonzero_on_fixture_dir(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent(PR3_REGRESSION))
    proc = subprocess.run(
        [sys.executable, f"{REPO}/scripts/jaxlint.py",
         "--baseline", "none", str(bad)],
        capture_output=True, text=True,
    )
    assert proc.returncode == 1
    assert "JL002" in proc.stdout


def test_cli_zero_on_repo():
    """Dogfood gate: the repo itself lints clean against its committed
    baseline — every finding is fixed or justified."""
    proc = subprocess.run(
        [sys.executable, f"{REPO}/scripts/jaxlint.py"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_list_rules():
    proc = subprocess.run(
        [sys.executable, f"{REPO}/scripts/jaxlint.py", "--list-rules"],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0
    for rule in ("JL001", "JL002", "JL101", "JL102", "JL103", "JL104",
                 "JL201", "JL301", "JL302", "JL303", "JL304", "JL305",
                 "JL306"):
        assert rule in proc.stdout


def test_cli_check_baseline_fails_on_stale_entry(tmp_path):
    """CI mode: a baseline entry whose finding was fixed must fail the run
    (suppressions may not rot), while the default mode only warns."""
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    base = tmp_path / "base.json"
    Baseline().write(
        str(base),
        [Finding(path="clean.py", line=1, col=0, rule="JL302", message="m")],
    )
    common = [sys.executable, f"{REPO}/scripts/jaxlint.py",
              "--root", str(tmp_path), "--baseline", str(base), str(clean)]
    warn = subprocess.run(common, capture_output=True, text=True)
    assert warn.returncode == 0 and "stale" in warn.stdout
    strict = subprocess.run([*common, "--check-baseline"],
                            capture_output=True, text=True)
    assert strict.returncode == 1 and "--check-baseline" in strict.stdout


# --------------------------------------------------------------------------- #
# runtime contracts
# --------------------------------------------------------------------------- #


class FakeMonitor:
    def __init__(self):
        self.programs = 0

    def total(self, group):
        return self.programs


class FakeSink:
    def __init__(self):
        self.records = []

    def log(self, rtype, **fields):
        self.records.append({"type": rtype, **fields})


def test_sentinel_budget_math():
    from analysis.runtime import RecompileBudgetExceeded, RecompileSentinel

    mon, sink = FakeMonitor(), FakeSink()
    s = RecompileSentinel(mon, group="train", per_event=1, sink=sink)
    assert s.budget == 0
    s.note_event("task_growth", task_id=0)
    s.note_event("task_growth", task_id=1)
    mon.programs = 2
    assert s.check(where="task1", task_id=1) == 2
    rec = sink.records[-1]
    assert rec["type"] == "recompile_budget"
    assert rec["budget"] == 2 and rec["programs"] == 2 and rec["ok"] is True
    # one silent re-trace over budget -> raise
    mon.programs = 3
    with pytest.raises(RecompileBudgetExceeded, match="re-traced silently"):
        s.check(where="task1", task_id=1)
    assert sink.records[-1]["ok"] is False


def test_sentinel_restore_event_and_enforce_off():
    from analysis.runtime import RecompileSentinel

    mon, sink = FakeMonitor(), FakeSink()
    s = RecompileSentinel(mon, per_event=2, sink=sink, enforce=False)
    s.note_event("restore", task_id=0)
    mon.programs = 5  # over budget (2), but enforce=False only records it
    s.check(where="resume")
    assert sink.records[-1]["ok"] is False and sink.records[-1]["budget"] == 2


def test_buffer_alias_detection():
    """The PR 3 mechanism, reproduced: on CPU, device_put of an aligned host
    array is zero-copy (the jax.Array aliases the numpy buffer), and
    jnp.copy re-homes it."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from analysis.runtime import (
        DonationAliasError,
        assert_unaliased,
        buffer_aliases,
        poison_host_tree,
    )

    # XLA's CPU zero-copy path requires 64-byte alignment; numpy's allocator
    # only guarantees 16, so carve an aligned view out of a byte buffer to
    # make the aliasing deterministic.
    nbytes = 256 * 256 * 4
    raw = np.zeros(nbytes + 64, dtype=np.uint8)
    off = (-raw.ctypes.data) % 64
    host = raw[off:off + nbytes].view(np.float32).reshape(256, 256)
    host[...] = 1.0
    aliased = jax.device_put(host)
    if not buffer_aliases(host, aliased):
        pytest.skip("this CPU backend copies on device_put")
    with pytest.raises(DonationAliasError, match="alias"):
        assert_unaliased({"w": host}, {"w": aliased}, where="test")

    rehomed = jnp.copy(aliased)
    assert not buffer_aliases(host, rehomed)
    assert_unaliased({"w": host}, {"w": rehomed}, where="test")

    # Poisoning the host tree reaches the aliased device view, not the copy.
    assert poison_host_tree({"w": host}) == 1
    assert bool(jnp.isnan(aliased).all())
    assert not bool(jnp.isnan(rehomed).any())


def test_poison_host_tree_dtypes():
    import numpy as np

    from analysis.runtime import poison_host_tree

    tree = {
        "f": np.ones(4, dtype=np.float32),
        "i": np.ones(4, dtype=np.int32),
        "b": np.ones(4, dtype=bool),  # left alone
    }
    ro = np.ones(4, dtype=np.float32)
    ro.flags.writeable = False
    tree["ro"] = ro
    assert poison_host_tree(tree) == 2
    assert np.isnan(tree["f"]).all()
    assert (tree["i"] == -(2 ** 30)).all()
    assert (tree["b"] == 1).all()
    assert (tree["ro"] == 1).all()


def test_analysis_package_imports_without_jax():
    """The CI lint stage must run in jax-free environments: importing the
    package (not analysis.runtime) may not pull in jax."""
    proc = subprocess.run(
        [sys.executable, "-c",
         "import sys; sys.modules['jax'] = None; "
         "import analysis; print(len(analysis.RULES))"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr
    assert int(proc.stdout.strip()) >= 7


# --------------------------------------------------------------------------- #
# JL401 — collective / jitted dispatch under process-divergent control flow
# --------------------------------------------------------------------------- #


def test_jl401_gated_collective_direct(tmp_path):
    findings = run_lint(tmp_path, """
        import jax
        from parallel.dist import barrier

        def save(state):
            if jax.process_index() == 0:
                barrier()
    """)
    assert [(f.rule, f.line) for f in findings] == [("JL401", 7)]
    assert "deadlock" in findings[0].message


def test_jl401_transitive_collective_through_helper(tmp_path):
    findings = run_lint(tmp_path, """
        import os
        from parallel.dist import barrier

        def sync():
            barrier()

        def save(state):
            if os.environ.get("RANK") == "0":
                sync()
    """)
    # The flagged site is the *call* under the gate, not the helper body.
    assert [(f.rule, f.line) for f in findings] == [("JL401", 10)]
    assert "transitively" in findings[0].message


def test_jl401_process_local_work_under_gate_is_clean(tmp_path):
    # The export path: collectives run unconditionally, only host-local
    # serialization is gated to process 0.  Nothing to flag.
    findings = run_lint(tmp_path, """
        import jax
        from parallel.dist import barrier, is_main_process

        def export(state, blob):
            barrier()
            if is_main_process():
                blob.append(state)
    """)
    assert "JL401" not in rules_of(findings)


# --------------------------------------------------------------------------- #
# JL402 — host write to an unsuffixed shared path without a process-0 gate
# --------------------------------------------------------------------------- #


def test_jl402_unsuffixed_shared_write(tmp_path):
    findings = run_lint(tmp_path, """
        import jax
        from parallel.dist import barrier

        def checkpoint(state):
            with open("status.json", "w") as f:
                f.write("x")
    """)
    assert [(f.rule, f.line) for f in findings] == [("JL402", 6)]
    assert "race" in findings[0].message


def test_jl402_process0_gate_is_clean(tmp_path):
    findings = run_lint(tmp_path, """
        import jax
        from parallel.dist import barrier

        def checkpoint(state):
            if jax.process_index() == 0:
                with open("status.json", "w") as f:
                    f.write("x")
    """)
    assert "JL402" not in rules_of(findings)


def test_jl402_suffixed_path_is_clean(tmp_path):
    findings = run_lint(tmp_path, """
        import jax
        from telemetry.process import process_suffixed

        def log_to(d):
            with open(process_suffixed(d, jax.process_index()), "w") as f:
                f.write("x")
    """)
    assert "JL402" not in rules_of(findings)


def test_jl402_gated_entry_function_is_clean(tmp_path):
    # A helper whose *every* call site sits under a process-0 gate is itself
    # gated: its body writes without re-checking process_index.
    findings = run_lint(tmp_path, """
        import jax
        from parallel.dist import barrier, is_main_process

        def write_manifest(path):
            with open(path, "w") as f:
                f.write("x")

        def export(state, path):
            if is_main_process():
                write_manifest(path)
    """)
    assert "JL402" not in rules_of(findings)


# --------------------------------------------------------------------------- #
# JL403 — unsorted set iteration feeding device / class ordering
# --------------------------------------------------------------------------- #


def test_jl403_set_iteration_feeds_device(tmp_path):
    findings = run_lint(tmp_path, """
        import jax
        import jax.numpy as jnp
        from parallel.dist import barrier

        step = jax.jit(lambda s, c: s)

        def replay(state, class_ids):
            for c in set(class_ids):
                state = step(state, jnp.full((1,), c))
            return state
    """)
    assert [(f.rule, f.line) for f in findings] == [("JL403", 9)]
    assert "sorted" in findings[0].message


def test_jl403_sorted_iteration_is_clean(tmp_path):
    findings = run_lint(tmp_path, """
        import jax
        import jax.numpy as jnp
        from parallel.dist import barrier

        step = jax.jit(lambda s, c: s)

        def replay(state, class_ids):
            for c in sorted(set(class_ids)):
                state = step(state, jnp.full((1,), c))
            return state
    """)
    assert "JL403" not in rules_of(findings)


def test_jl403_frozen_class_order_from_set(tmp_path):
    findings = run_lint(tmp_path, """
        import jax

        def build_order(labels):
            class_order = list(set(labels))
            return class_order
    """)
    assert [(f.rule, f.line) for f in findings] == [("JL403", 5)]


# --------------------------------------------------------------------------- #
# JL404 — host-local entropy into RNG keys / traced values
# --------------------------------------------------------------------------- #


def test_jl404_wallclock_seed(tmp_path):
    findings = run_lint(tmp_path, """
        import time
        import jax

        def make_key():
            return jax.random.PRNGKey(int(time.time()))
    """)
    assert [(f.rule, f.line) for f in findings] == [("JL404", 6)]
    assert "time.time()" in findings[0].message


def test_jl404_entropy_as_seed_kwarg(tmp_path):
    findings = run_lint(tmp_path, """
        import os
        import jax

        def shuffle(ds):
            return ds.shuffle(1024, seed=int.from_bytes(os.urandom(4), "big"))
    """)
    assert [(f.rule, f.line) for f in findings] == [("JL404", 6)]


def test_jl404_config_seed_is_clean(tmp_path):
    findings = run_lint(tmp_path, """
        import jax

        def make_key(config):
            key = jax.random.PRNGKey(config.seed)
            return jax.random.fold_in(key, config.task_id)
    """)
    assert "JL404" not in rules_of(findings)


# --------------------------------------------------------------------------- #
# JL405 — per-process-variable shapes into global jitted programs
# --------------------------------------------------------------------------- #


def test_jl405_local_len_into_jit(tmp_path):
    findings = run_lint(tmp_path, """
        import jax
        from parallel.dist import barrier

        step = jax.jit(lambda s, n: s)

        def train(state, local_batch):
            n = len(local_batch)
            return step(state, n)
    """)
    assert [(f.rule, f.line) for f in findings] == [("JL405", 9)]
    assert "process_count" in findings[0].message


def test_jl405_global_normalized_is_clean(tmp_path):
    findings = run_lint(tmp_path, """
        import jax
        from parallel.dist import barrier

        step = jax.jit(lambda s, n: s)

        def train(state, local_batch):
            global_n = len(local_batch) * jax.process_count()
            return step(state, global_n)
    """)
    assert "JL405" not in rules_of(findings)


# --------------------------------------------------------------------------- #
# fleetlint dogfood regressions — the real findings stay fixed
# --------------------------------------------------------------------------- #


def test_dogfood_telemetry_shared_writes_stay_fixed():
    """PRs must not reintroduce the unsuffixed shared-path writes fleetlint
    found in the telemetry layer (spans export, flight recorder): suffixed
    or reason-suppressed sites produce no JL402 today."""
    pkg = f"{REPO}/a_pytorch_tutorial_to_class_incremental_learning_tpu"
    findings = lint_paths(
        [f"{pkg}/telemetry/spans.py", f"{pkg}/telemetry/flight.py"],
        root=REPO,
    )
    assert [f for f in findings if f.rule == "JL402"] == []
