"""contractlint (analysis/contracts.py, JL501-506) + the ContractCheck
runtime sentinel (analysis/contractcheck.py, ``--check_contracts``).

Static half: per-rule positive/negative fixtures — each seeded cross-artifact
drift must be flagged at the expected file, and the corrected idiom must lint
clean.  Fixture snippets are strings written to tmp_path and analyzed by the
stdlib-only AST pass, never imported or executed.

Dynamic half: a sentinel fed a known registry must catch an unknown record
type, an unknown record field, an unknown metric and a label-set drift; stay
silent on vocabulary-clean traffic; and every ``contract_violation`` it emits
must itself pass the telemetry schema.

Plus the cross-pass meta-contracts this PR pins down: JL rule ids are
globally unique with non-empty summaries, ``jaxlint --list-rules`` prints the
whole catalog, the README rule table matches it mechanically, the committed
contract registry matches a fresh deterministic extraction, and the
telemetry-schema checker's negative paths reject what they claim to reject.
"""

import importlib.util
import json
import os
import subprocess
import sys
import textwrap

import pytest

from analysis import RULES
from analysis.contracts import (
    CONTRACT_RULES,
    build_registry,
    lint_contracts,
)
from analysis import contractcheck
from analysis.linter import DEFAULT_TARGETS

REPO = __file__.rsplit("/tests/", 1)[0]

# A minimal consistent artifact set; each positive test perturbs ONE file.
BASE = {
    "schema.py": """\
        NUM = (int, float)
        SCHEMA = {
            "epoch": ({"epoch": int}, {"loss": NUM}, None),
        }
        ALWAYS_REQUIRED = {"ts": NUM}
        """,
    "emit.py": """\
        def run(sink):
            sink.log("epoch", epoch=0, loss=0.1)
        """,
    "config.py": """\
        class FixtureConfig:
            live_flag: int = 1


        def build(cfg):
            return cfg.live_flag
        """,
    "injector.py": """\
        ACTIONS = {
            "engine.epoch": frozenset({"kill"}),
        }


        def run(inj):
            inj.fire("engine.epoch", epoch=1)
        """,
    "metricsreg.py": """\
        def setup(m):
            m.counter("requests_total", route="a")
        """,
    "bench.py": """\
        def report(snap, sum_counters):
            return sum_counters(snap, "requests_total")
        """,
    "README.md": """\
        # fixture

        Run with `--live-flag`. Rule JL501 guards the `epoch` record.
        """,
}


def run_contracts(tmp_path, overrides=None):
    files = dict(BASE)
    files.update(overrides or {})
    for name, text in files.items():
        (tmp_path / name).write_text(textwrap.dedent(text))
    py = sorted(n for n in files if n.endswith(".py"))
    findings, registry = lint_contracts(py, root=str(tmp_path))
    return findings, registry


def rules_of(findings):
    return sorted({f.rule for f in findings})


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# --------------------------------------------------------------------------- #
# the consistent twin
# --------------------------------------------------------------------------- #


def test_consistent_fixture_is_clean(tmp_path):
    findings, registry = run_contracts(tmp_path)
    assert findings == []
    assert set(registry["records"]) == {"epoch"}
    assert set(registry["metrics"]) == {"requests_total"}
    assert registry["fault_sites"] == ["engine.epoch"]


# --------------------------------------------------------------------------- #
# JL501 — record type vs telemetry schema (both directions)
# --------------------------------------------------------------------------- #


def test_jl501_emitted_type_unknown_to_schema(tmp_path):
    findings, _ = run_contracts(tmp_path, {
        "emit.py": """\
            def run(sink):
                sink.log("epoch", epoch=0, loss=0.1)
                sink.log("mystery_record", x=1)
            """,
    })
    assert rules_of(findings) == ["JL501"]
    (f,) = findings
    assert f.path == "emit.py" and f.line == 3
    assert "mystery_record" in f.message


def test_jl501_dict_literal_and_subscript_emits_count(tmp_path):
    findings, _ = run_contracts(tmp_path, {
        "emit.py": """\
            def run(sink, rec):
                sink.log("epoch", epoch=0, loss=0.1)
                payload = {"type": "ghost_a", "x": 1}
                rec["type"] = "ghost_b"
                return payload
            """,
    })
    assert rules_of(findings) == ["JL501"]
    assert {("emit.py", f.line) for f in findings} == {("emit.py", 3),
                                                       ("emit.py", 4)}


def test_jl501_stale_schema_entry(tmp_path):
    findings, _ = run_contracts(tmp_path, {
        "schema.py": """\
            NUM = (int, float)
            SCHEMA = {
                "epoch": ({"epoch": int}, {"loss": NUM}, None),
                "ghost_record": ({"x": int}, {}, None),
            }
            ALWAYS_REQUIRED = {"ts": NUM}
            """,
    })
    assert rules_of(findings) == ["JL501"]
    (f,) = findings
    assert f.path == "schema.py" and f.line == 4
    assert "stale" in f.message


def test_jl501_inline_suppression(tmp_path):
    findings, _ = run_contracts(tmp_path, {
        "emit.py": """\
            def run(sink):
                sink.log("epoch", epoch=0, loss=0.1)
                sink.log("mystery_record", x=1)  # jaxlint: disable=JL501
            """,
    })
    assert findings == []


def test_jl501_skipped_without_a_schema_module(tmp_path):
    files = {k: v for k, v in BASE.items() if k != "schema.py"}
    for name, text in files.items():
        (tmp_path / name).write_text(textwrap.dedent(text))
    findings, _ = lint_contracts(
        sorted(n for n in files if n.endswith(".py")), root=str(tmp_path))
    assert "JL501" not in rules_of(findings)


# --------------------------------------------------------------------------- #
# JL502 — consumer reads outside the filtered type's vocabulary
# --------------------------------------------------------------------------- #


def test_jl502_read_outside_vocabulary(tmp_path):
    findings, _ = run_contracts(tmp_path, {
        "consume.py": """\
            def tail(recs):
                epochs = [r for r in recs if r.get("type") == "epoch"]
                for e in epochs:
                    print(e["loss"])
                    print(e["bogus"])
            """,
    })
    assert rules_of(findings) == ["JL502"]
    (f,) = findings
    assert f.path == "consume.py" and f.line == 5
    assert "bogus" in f.message and "epoch" in f.message


def test_jl502_known_fields_and_always_fields_clean(tmp_path):
    findings, _ = run_contracts(tmp_path, {
        "consume.py": """\
            def tail(recs):
                epochs = [r for r in recs if r.get("type") == "epoch"]
                last = epochs[-1]
                ok = "loss" in last
                return last["epoch"], last.get("loss"), last["ts"], ok
            """,
    })
    assert findings == []


def test_jl502_union_on_rebind_passes_if_any_type_carries_field(tmp_path):
    # rec is bound to two different record streams in one scope; a field
    # carried by either candidate type must not be flagged.
    findings, _ = run_contracts(tmp_path, {
        "schema.py": """\
            NUM = (int, float)
            SCHEMA = {
                "epoch": ({"epoch": int}, {"loss": NUM}, None),
                "resume": ({"start_epoch": int}, {}, None),
            }
            ALWAYS_REQUIRED = {"ts": NUM}
            """,
        "emit.py": """\
            def run(sink):
                sink.log("epoch", epoch=0, loss=0.1)
                sink.log("resume", start_epoch=2)
            """,
        "consume.py": """\
            def tail(recs):
                out = []
                for rec in [r for r in recs if r.get("type") == "epoch"]:
                    out.append(rec.get("loss"))
                for rec in [r for r in recs if r.get("type") == "resume"]:
                    out.append(rec.get("start_epoch"))
                return out
            """,
    })
    assert findings == []


def test_jl502_if_guard_narrows_type(tmp_path):
    findings, _ = run_contracts(tmp_path, {
        "consume.py": """\
            def tail(recs):
                epochs = [r for r in recs if r.get("type") == "epoch"]
                for e in epochs:
                    if e.get("type") == "epoch":
                        print(e["nope"])
            """,
    })
    assert rules_of(findings) == ["JL502"]
    assert findings[0].line == 5


# --------------------------------------------------------------------------- #
# JL503 — config flag liveness (both directions)
# --------------------------------------------------------------------------- #


def test_jl503_dead_config_field(tmp_path):
    findings, _ = run_contracts(tmp_path, {
        "config.py": """\
            class FixtureConfig:
                dead_flag: int = 0
                live_flag: int = 1


            def build(cfg):
                return cfg.live_flag
            """,
    })
    assert rules_of(findings) == ["JL503"]
    (f,) = findings
    assert f.path == "config.py" and f.line == 2
    assert "dead_flag" in f.message and "never read" in f.message


def test_jl503_undefined_cfg_attribute_read(tmp_path):
    findings, _ = run_contracts(tmp_path, {
        "config.py": """\
            class FixtureConfig:
                live_flag: int = 1


            def build(cfg):
                return cfg.live_flag + cfg.ghost_flag
            """,
    })
    assert rules_of(findings) == ["JL503"]
    (f,) = findings
    assert f.line == 6 and "ghost_flag" in f.message


def test_jl503_argparse_dest_and_non_config_dataclass_are_defined(tmp_path):
    # add_argument dests and *Config dataclasses outside config.py both
    # legitimize cfg reads (the AugmentConfig false-positive class).
    findings, _ = run_contracts(tmp_path, {
        "other.py": """\
            class AugmentConfig:
                reprob: float = 0.0


            def cli(p):
                p.add_argument("--extra-depth", type=int)


            def use(cfg, args):
                return cfg.reprob + args.extra_depth
            """,
    })
    assert "JL503" not in rules_of(findings)


# --------------------------------------------------------------------------- #
# JL504 — fault sites vs the injector ACTIONS grammar (both directions)
# --------------------------------------------------------------------------- #


def test_jl504_fired_site_outside_grammar(tmp_path):
    findings, _ = run_contracts(tmp_path, {
        "injector.py": """\
            ACTIONS = {
                "engine.epoch": frozenset({"kill"}),
            }


            def run(inj):
                inj.fire("engine.epoch", epoch=1)
                inj.fire("engine.unknown", epoch=2)
            """,
    })
    assert rules_of(findings) == ["JL504"]
    (f,) = findings
    assert f.line == 8 and "engine.unknown" in f.message


def test_jl504_documented_site_never_fired(tmp_path):
    findings, _ = run_contracts(tmp_path, {
        "injector.py": """\
            ACTIONS = {
                "engine.epoch": frozenset({"kill"}),
                "ckpt.unfired": frozenset({"kill"}),
            }


            def run(inj):
                inj.fire("engine.epoch", epoch=1)
            """,
    })
    assert rules_of(findings) == ["JL504"]
    (f,) = findings
    assert f.path == "injector.py" and f.line == 3
    assert "never" in f.message


def test_jl504_reconcile_steps_counts_as_firing(tmp_path):
    findings, _ = run_contracts(tmp_path, {
        "injector.py": """\
            ACTIONS = {
                "engine.epoch": frozenset({"kill"}),
                "engine.step": frozenset({"kill"}),
            }


            def run(inj):
                inj.fire("engine.epoch", epoch=1)
                inj.reconcile_steps("engine.step", done=3)
            """,
    })
    assert findings == []


# --------------------------------------------------------------------------- #
# JL505 — metric name / label-set drift
# --------------------------------------------------------------------------- #


def test_jl505_consumed_metric_never_registered(tmp_path):
    findings, _ = run_contracts(tmp_path, {
        "bench.py": """\
            def report(snap, sum_counters):
                good = sum_counters(snap, "requests_total")
                bad = sum_counters(snap, "ghost_total")
                return good + bad
            """,
    })
    assert rules_of(findings) == ["JL505"]
    (f,) = findings
    assert f.path == "bench.py" and f.line == 3
    assert "ghost_total" in f.message


def test_jl505_label_set_drift_across_sites(tmp_path):
    findings, _ = run_contracts(tmp_path, {
        "metricsreg.py": """\
            def setup(m):
                m.counter("requests_total", route="a")
                m.counter("requests_total", zone="b")
            """,
    })
    assert rules_of(findings) == ["JL505"]
    (f,) = findings
    assert f.path == "metricsreg.py" and f.line == 3
    assert "label-key" in f.message


def test_jl505_dynamic_labels_and_hist_kwargs_are_clean(tmp_path):
    findings, _ = run_contracts(tmp_path, {
        "metricsreg.py": """\
            def setup(m, labels):
                m.counter("requests_total", route="a")
                m.counter("dyn_total", **labels)
                m.histogram("lat_ms", lowest=0.1, growth=1.5, buckets=40)
            """,
        "bench.py": """\
            def report(snap, sum_counters):
                a = sum_counters(snap, "requests_total")
                b = sum_counters(snap, "dyn_total")
                c = sum_counters(snap, "lat_ms")
                return a + b + c
            """,
    })
    assert findings == []


def test_jl505_kind_drift(tmp_path):
    findings, _ = run_contracts(tmp_path, {
        "metricsreg.py": """\
            def setup(m):
                m.counter("requests_total", route="a")
                m.gauge("requests_total", route="a")
            """,
    })
    assert rules_of(findings) == ["JL505"]
    assert "instrument kinds" in findings[0].message


# --------------------------------------------------------------------------- #
# JL506 — README vs reality
# --------------------------------------------------------------------------- #


def test_jl506_nonexistent_flag_rule_and_record(tmp_path):
    findings, _ = run_contracts(tmp_path, {
        "README.md": """\
            # fixture

            Run with `--live-flag` and `--no_such_flag`.
            Rules JL501 and JL999.
            The `epoch` record and the `ghost_type` record.
            """,
    })
    assert rules_of(findings) == ["JL506"]
    assert {(f.path, f.line) for f in findings} == {
        ("README.md", 3), ("README.md", 4), ("README.md", 5)}
    msgs = " ".join(f.message for f in findings)
    assert "no_such_flag" in msgs and "JL999" in msgs and "ghost_type" in msgs


def test_jl506_env_var_value_flags_are_not_doc_flags(tmp_path):
    # XLA_FLAGS=--xla_... is an env value, not a documented CLI flag.
    findings, _ = run_contracts(tmp_path, {
        "README.md": """\
            # fixture

            Run with `--live-flag`. Rule JL501 guards the `epoch` record.
            Set XLA_FLAGS=--xla_force_host_platform_device_count=8 first.
            """,
    })
    assert findings == []


# --------------------------------------------------------------------------- #
# registry: determinism + the committed artifact
# --------------------------------------------------------------------------- #


@pytest.fixture(scope="module")
def repo_scope():
    findings, registry = lint_contracts(list(DEFAULT_TARGETS), root=REPO)
    return findings, registry


def test_repo_lints_clean_and_registry_is_fresh(repo_scope):
    findings, registry = repo_scope
    baseline = json.load(
        open(os.path.join(REPO, "analysis", "contractlint_baseline.json")))
    allowed = {(e["path"], e["rule"], e["line"])
               for e in baseline.get("findings", [])}
    new = [f for f in findings if (f.path, f.rule, f.line) not in allowed]
    assert new == [], [f.render() for f in new]
    committed = json.load(
        open(os.path.join(REPO, "analysis", "contract_registry.json")))
    assert committed == registry, (
        "analysis/contract_registry.json is stale — regenerate with: "
        "python scripts/contractlint.py --write-registry")


def test_registry_build_is_deterministic(repo_scope):
    _, registry = repo_scope
    _, again = lint_contracts(list(DEFAULT_TARGETS), root=REPO)
    assert registry == again
    assert json.dumps(registry, sort_keys=True) == \
        json.dumps(again, sort_keys=True)


def test_registry_covers_the_contract_surfaces(repo_scope):
    _, registry = repo_scope
    assert "contract_violation" in registry["records"]
    assert "check_contracts" in registry["config_fields"]
    assert "check_contracts" in registry["argparse_dests"]
    assert registry["fault_sites"]  # the injector grammar is non-empty
    for name, ent in registry["metrics"].items():
        assert ent["kinds"] and ent["sites"], name


# --------------------------------------------------------------------------- #
# rule catalog: global uniqueness + README table + --list-rules
# --------------------------------------------------------------------------- #


def test_rule_ids_globally_unique_with_summaries():
    overlap = set(RULES) & set(CONTRACT_RULES)
    assert overlap == set(), f"rule id collision across passes: {overlap}"
    for rule, summary in {**RULES, **CONTRACT_RULES}.items():
        assert len(rule) == 5 and rule.startswith("JL") \
            and rule[2:].isdigit(), rule
        assert isinstance(summary, str) and summary.strip(), rule


def test_list_rules_prints_the_whole_catalog():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "jaxlint.py"),
         "--list-rules"],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0
    listed = {ln.split()[0] for ln in proc.stdout.splitlines() if ln.strip()}
    assert listed == set(RULES) | set(CONTRACT_RULES)


def test_readme_rule_table_matches_the_catalog():
    # Every | `JLxxx` | row in the README's rule table must name a live
    # rule, and every rule in the catalog must have a row — the README
    # can't drift from `jaxlint --list-rules` without failing here.
    import re
    with open(os.path.join(REPO, "README.md"), encoding="utf-8") as f:
        rows = re.findall(r"(?m)^\| `(JL\d{3})` \|", f.read())
    catalog = set(RULES) | set(CONTRACT_RULES)
    assert set(rows) == catalog, (
        f"README table vs catalog: missing rows "
        f"{sorted(catalog - set(rows))}, stale rows "
        f"{sorted(set(rows) - catalog)}")
    assert len(rows) == len(set(rows)), "duplicate README table rows"


# --------------------------------------------------------------------------- #
# contractlint CLI: exit codes + --check-registry
# --------------------------------------------------------------------------- #


def _run_cli(tmp_path, files, *extra):
    for name, text in files.items():
        (tmp_path / name).write_text(textwrap.dedent(text))
    py = sorted(n for n in files if n.endswith(".py"))
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "contractlint.py"),
         "--root", str(tmp_path), "--baseline", "none", *extra, *py],
        capture_output=True, text=True, cwd=REPO)


def test_cli_exit_codes_and_registry_staleness(tmp_path):
    proc = _run_cli(tmp_path, BASE)
    assert proc.returncode == 0, proc.stdout + proc.stderr

    reg = str(tmp_path / "registry.json")
    proc = _run_cli(tmp_path, BASE, "--registry", reg, "--write-registry")
    assert proc.returncode == 0
    # Fresh registry passes --check-registry; a perturbed one fails it.
    proc = _run_cli(tmp_path, BASE, "--registry", reg, "--check-registry")
    assert proc.returncode == 0
    stale = json.load(open(reg))
    stale["records"]["epoch"]["fields"].append("drifted")
    with open(reg, "w") as f:
        json.dump(stale, f)
    proc = _run_cli(tmp_path, BASE, "--registry", reg, "--check-registry")
    assert proc.returncode == 1
    assert "stale" in (proc.stdout + proc.stderr)

    bad = dict(BASE)
    bad["emit.py"] = """\
        def run(sink):
            sink.log("epoch", epoch=0, loss=0.1)
            sink.log("mystery_record", x=1)
        """
    proc = _run_cli(tmp_path, bad)
    assert proc.returncode == 1
    assert "JL501" in proc.stdout


# --------------------------------------------------------------------------- #
# ContractCheck sentinel (the runtime half)
# --------------------------------------------------------------------------- #

_SENTINEL_REG = {
    "version": 1,
    "records": {
        "epoch": {"fields": ["type", "ts", "epoch", "loss"],
                  "extras": None, "emitters": []},
        "blob": {"fields": ["type"], "extras": "any", "emitters": []},
        "contract_violation": {
            "fields": ["type", "ts", "kind", "name", "field", "detail",
                       "labels"],
            "extras": None, "emitters": []},
    },
    "metrics": {
        "steps_total": {"kinds": ["counter"], "label_sets": [["task"]],
                        "dynamic_labels": False, "sites": []},
        "lat_ms": {"kinds": ["histogram"], "label_sets": [[]],
                   "dynamic_labels": False, "sites": []},
        "dyn_total": {"kinds": ["counter"], "label_sets": [],
                      "dynamic_labels": True, "sites": []},
    },
}


class _RecSink:
    def __init__(self):
        self.records = []

    def log(self, record_type, **fields):
        self.records.append({"type": record_type, **fields})


class _RecRegistry:
    def __init__(self):
        self.calls = []

    def counter(self, name, **labels):
        self.calls.append(("counter", name, labels))

    def gauge(self, name, **labels):
        self.calls.append(("gauge", name, labels))

    def histogram(self, name, **kwargs):
        self.calls.append(("histogram", name, kwargs))


def _sentinel(tmp_path):
    path = tmp_path / "registry.json"
    path.write_text(json.dumps(_SENTINEL_REG))
    return contractcheck.install(registry_path=str(path))


def test_sentinel_clean_traffic_is_silent(tmp_path):
    try:
        check = _sentinel(tmp_path)
        sink = contractcheck.wrap_sink(_RecSink())
        check.bind_sink(sink)
        metrics = contractcheck.wrap_registry(_RecRegistry())
        sink.log("epoch", ts=1.0, epoch=0, loss=0.5)
        sink.log("blob", anything=object())       # extras == "any"
        metrics.counter("steps_total", task=0)
        metrics.counter("dyn_total", whatever="x")  # dynamic labels
        metrics.histogram("lat_ms", lowest=0.1, growth=1.5, buckets=40)
        assert check.violations == []
        assert [r["type"] for r in sink._inner.records] == ["epoch", "blob"]
    finally:
        contractcheck.uninstall()


def test_sentinel_catches_unknown_record_type(tmp_path):
    try:
        check = _sentinel(tmp_path)
        inner = _RecSink()
        sink = contractcheck.wrap_sink(inner)
        check.bind_sink(sink)
        sink.log("mystery_record", x=1)
        assert [v["kind"] for v in check.violations] == \
            ["unknown_record_type"]
        assert check.violations[0]["name"] == "mystery_record"
        # The violation is reported at validation time (so it precedes the
        # offending record in the stream), and the offending record still
        # reaches the sink — observe, don't drop.  Re-emitting the same
        # violation does not re-report.
        sink.log("mystery_record", x=2)
        assert [r["type"] for r in inner.records] == \
            ["contract_violation", "mystery_record", "mystery_record"]
        assert len(check.violations) == 1
    finally:
        contractcheck.uninstall()


def test_sentinel_catches_unknown_field_and_unknown_metric(tmp_path):
    try:
        check = _sentinel(tmp_path)
        inner = _RecSink()
        sink = contractcheck.wrap_sink(inner)
        check.bind_sink(sink)
        metrics = contractcheck.wrap_registry(_RecRegistry())
        sink.log("epoch", ts=1.0, epoch=0, smuggled=1)
        metrics.counter("ghost_total", task=0)
        metrics.counter("steps_total", zone="b")
        kinds = [v["kind"] for v in check.violations]
        assert kinds == ["unknown_record_field", "unknown_metric",
                         "metric_label_drift"]
        # Validation observes, never blocks: the registration went through.
        assert [c[1] for c in metrics._inner.calls] == \
            ["ghost_total", "steps_total"]
    finally:
        contractcheck.uninstall()


def test_sentinel_buffered_violations_flush_on_bind(tmp_path):
    try:
        check = _sentinel(tmp_path)
        metrics = contractcheck.wrap_registry(_RecRegistry())
        metrics.counter("ghost_total")        # before any sink exists
        assert len(check.violations) == 1
        inner = _RecSink()
        check.bind_sink(contractcheck.wrap_sink(inner))
        assert [r["type"] for r in inner.records] == ["contract_violation"]
        assert inner.records[0]["name"] == "ghost_total"
    finally:
        contractcheck.uninstall()


def test_sentinel_violation_records_pass_the_telemetry_schema(tmp_path):
    checker = _load_script("check_telemetry_schema")
    try:
        check = _sentinel(tmp_path)
        inner = _RecSink()
        sink = contractcheck.wrap_sink(inner)
        check.bind_sink(sink)
        sink.log("mystery_record", x=1)
        metrics = contractcheck.wrap_registry(_RecRegistry())
        metrics.counter("steps_total", zone="b")
        viols = [r for r in inner.records
                 if r["type"] == "contract_violation"]
        assert len(viols) == 2
        for v in viols:
            assert checker.check_record({**v, "ts": 0.0}, "test") == []
    finally:
        contractcheck.uninstall()


def test_sentinel_wrappers_are_noops_when_inactive_and_idempotent(tmp_path):
    inner = _RecSink()
    assert contractcheck.wrap_sink(inner) is inner
    assert contractcheck.wrap_registry(inner) is inner
    try:
        _sentinel(tmp_path)
        wrapped = contractcheck.wrap_sink(inner)
        assert wrapped is not inner
        assert contractcheck.wrap_sink(wrapped) is wrapped
        reg = contractcheck.wrap_registry(_RecRegistry())
        assert contractcheck.wrap_registry(reg) is reg
    finally:
        contractcheck.uninstall()


def test_sentinel_missing_registry_fails_loudly(tmp_path):
    with pytest.raises(RuntimeError, match="write-registry"):
        contractcheck.install(
            registry_path=str(tmp_path / "does_not_exist.json"))
    assert contractcheck.active() is None


# --------------------------------------------------------------------------- #
# telemetry schema checker: negative paths (scripts/check_telemetry_schema.py)
# --------------------------------------------------------------------------- #


@pytest.fixture(scope="module")
def schema_checker():
    return _load_script("check_telemetry_schema")


def test_schema_checker_accepts_a_known_record(schema_checker):
    assert schema_checker.check_record(
        {"type": "resume", "ts": 1.0, "kind": "epoch", "start_task": 1,
         "start_epoch": 2}, "test") == []


def test_schema_checker_rejects_unknown_type(schema_checker):
    errs = schema_checker.check_record(
        {"type": "mystery_record", "ts": 1.0}, "test")
    assert len(errs) == 1 and "unknown record type" in errs[0]


def test_schema_checker_rejects_missing_required_field(schema_checker):
    errs = schema_checker.check_record({"type": "resume", "ts": 1.0}, "test")
    assert errs and all("missing required" in e for e in errs)


def test_schema_checker_rejects_wrong_field_type(schema_checker):
    errs = schema_checker.check_record(
        {"type": "resume", "ts": 1.0, "kind": "epoch",
         "start_task": "one", "start_epoch": 2}, "test")
    assert len(errs) == 1
    assert "start_task" in errs[0] and "has type str" in errs[0]


def test_schema_checker_rejects_undeclared_extra_field(schema_checker):
    errs = schema_checker.check_record(
        {"type": "resume", "ts": 1.0, "kind": "epoch", "start_task": 1,
         "start_epoch": 2, "smuggled": 7}, "test")
    assert len(errs) == 1 and "undeclared field" in errs[0]


def test_schema_checker_allows_process_metadata_everywhere(schema_checker):
    assert schema_checker.check_record(
        {"type": "resume", "ts": 1.0, "kind": "epoch", "start_task": 1,
         "start_epoch": 2, "process_index": 0, "process_count": 2,
         "host_id": "h0"}, "test") == []


def test_schema_module_is_importable_standalone():
    # Satellite contract: telemetry/schema.py must import dependency-free
    # (the schema checker and contractlint both load it by path).
    spec = importlib.util.spec_from_file_location(
        "_schema_standalone",
        os.path.join(REPO, "a_pytorch_tutorial_to_class_incremental"
                           "_learning_tpu", "telemetry", "schema.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert "contract_violation" in mod.SCHEMA
    assert callable(mod.check_record)
