"""Tests for the metric layer (reference utils.py:22-118 semantics)."""

import numpy as np
import pytest

from a_pytorch_tutorial_to_class_incremental_learning_tpu.utils import (
    MetricLogger,
    SmoothedValue,
)


def test_smoothed_value_stats():
    v = SmoothedValue(window_size=3)
    for x in [1.0, 2.0, 3.0, 4.0]:
        v.update(x)
    # window holds the last 3
    assert v.median == 3.0
    assert v.avg == pytest.approx(3.0)
    assert v.max == 4.0
    assert v.value == 4.0
    # global average covers everything
    assert v.global_avg == pytest.approx(10.0 / 4)


def test_smoothed_value_weighted_update():
    v = SmoothedValue()
    v.update(80.0, n=128)  # batch-weighted accuracy, like eval acc meters
    v.update(60.0, n=64)
    assert v.global_avg == pytest.approx((80 * 128 + 60 * 64) / 192)


def test_smoothed_value_accepts_arrays():
    import jax.numpy as jnp

    v = SmoothedValue()
    v.update(jnp.asarray(2.5))
    v.update(np.float32(1.5))
    assert v.global_avg == pytest.approx(2.0)


def test_metric_logger_surface():
    ml = MetricLogger(delimiter="  ")
    ml.update(loss=1.0, acc1=50.0)
    ml.update(loss=3.0, acc1=70.0)
    assert ml.loss.global_avg == pytest.approx(2.0)
    assert ml.acc1.value == 70.0
    s = str(ml)
    assert "loss:" in s and "acc1:" in s
    with pytest.raises(AttributeError):
        ml.nonexistent_meter
    # None values are skipped (reference utils.py:83-84)
    ml.update(kd=None)
    assert "kd" not in ml.meters
    # single-process sync is a no-op
    ml.synchronize_between_processes()
    assert ml.loss.global_avg == pytest.approx(2.0)


def test_config_increments():
    from a_pytorch_tutorial_to_class_incremental_learning_tpu import CilConfig

    c = CilConfig(num_bases=50, increment=10)
    assert c.increments(100) == (50,) + (10,) * 5
    b0 = CilConfig(num_bases=0, increment=10)
    assert b0.increments(100) == (10,) * 10
    with pytest.raises(ValueError):
        CilConfig(num_bases=50, increment=7).increments(100)


def test_config_normalization_quirk():
    from a_pytorch_tutorial_to_class_incremental_learning_tpu import CilConfig
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.config import (
        CIFAR_MEAN,
        IMAGENET_MEAN,
    )

    # Default lowercase "cifar" keeps ImageNet stats (reference utils.py:231).
    assert CilConfig(data_set="cifar").normalization_stats()[0] == IMAGENET_MEAN
    assert CilConfig(data_set="CIFAR").normalization_stats()[0] == CIFAR_MEAN


def test_mesh_creation(devices8):
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.parallel import (
        make_mesh,
        batch_sharding,
    )

    mesh = make_mesh()
    assert mesh.shape["data"] == 8 and mesh.shape["model"] == 1
    mesh2 = make_mesh((4, 2))
    assert mesh2.shape["data"] == 4 and mesh2.shape["model"] == 2
    with pytest.raises(ValueError):
        make_mesh((3, 2))
    sh = batch_sharding(mesh)
    import jax
    import numpy as np

    x = jax.device_put(np.zeros((16, 4)), sh)
    assert len(x.addressable_shards) == 8
