"""Checkpoint/resume: a killed-and-resumed run reproduces the uninterrupted
run exactly (SURVEY.md §5 gap; VERDICT item 10)."""

import os

import numpy as np
import jax
import pytest

from a_pytorch_tutorial_to_class_incremental_learning_tpu.config import CilConfig
from a_pytorch_tutorial_to_class_incremental_learning_tpu.engine import CilTrainer
from a_pytorch_tutorial_to_class_incremental_learning_tpu.parallel.mesh import make_mesh
from a_pytorch_tutorial_to_class_incremental_learning_tpu.utils.checkpoint import (
    latest_task_checkpoint,
)

pytestmark = pytest.mark.heavy  # e2e/multi-process tier; excluded from -m quick


def _cfg(**kw):
    defaults = dict(
        data_set="synthetic10",
        num_bases=0,
        increment=5,
        backbone="resnet20",
        batch_size=8,
        num_epochs=2,
        eval_every_epoch=100,
        memory_size=40,
        lr=0.05,
        aa=None,
        color_jitter=0.0,
        seed=11,
    )
    defaults.update(kw)
    return CilConfig(**defaults)


@pytest.mark.parametrize("backend", ["pickle", "orbax"])
def test_kill_and_resume_reproduces(devices8, tmp_path, backend):
    import shutil

    mesh = make_mesh((8, 1))
    ckpt = str(tmp_path / "ckpts")
    ext = "ckpt" if backend == "pickle" else "orbax"

    # Uninterrupted 2-task run (also writes per-task checkpoints).
    full = CilTrainer(
        _cfg(ckpt_dir=ckpt, ckpt_backend=backend), mesh=mesh, init_dist=False
    )
    ref = full.fit()
    assert latest_task_checkpoint(ckpt).endswith(f"task_001.{ext}")

    # Simulate a crash after task 0: drop the task-1 checkpoint and resume.
    if backend == "orbax":
        shutil.rmtree(os.path.join(ckpt, "task_001.orbax"))
        os.remove(os.path.join(ckpt, "task_001.orbax.meta"))
    else:
        os.remove(os.path.join(ckpt, "task_001.ckpt"))
    resumed = CilTrainer(
        _cfg(ckpt_dir=ckpt, ckpt_backend=backend, resume=True),
        mesh=mesh,
        init_dist=False,
    )
    assert resumed.start_task == 1
    assert resumed.known == 5
    assert resumed.memory.nb_classes == 5
    assert resumed.teacher is not None
    out = resumed.fit()

    # Task-boundary resume is exact: same PRNG folds, same shuffles, same
    # memory -> bit-identical accuracy history.
    assert out["acc1s"][0] == ref["acc1s"][0]  # restored, not recomputed
    assert out["acc1s"][1] == ref["acc1s"][1]
    for a, b in zip(
        jax.tree_util.tree_leaves(full.state.params),
        jax.tree_util.tree_leaves(resumed.state.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_resume_refuses_seed_mismatch(devices8, tmp_path):
    import pytest

    mesh = make_mesh((8, 1))
    ckpt = str(tmp_path / "ckpts")
    CilTrainer(_cfg(ckpt_dir=ckpt, num_epochs=1), mesh=mesh, init_dist=False).fit()
    with pytest.raises(ValueError):
        CilTrainer(
            _cfg(ckpt_dir=ckpt, resume=True, seed=99), mesh=mesh, init_dist=False
        )


def test_resume_without_checkpoint_is_fresh(devices8, tmp_path):
    t = CilTrainer(
        _cfg(ckpt_dir=str(tmp_path / "none"), resume=True),
        mesh=make_mesh((8, 1)),
        init_dist=False,
    )
    assert t.start_task == 0 and t.known == 0


def test_epoch_checkpoint_orbax_round_trip(devices8, tmp_path):
    """Epoch checkpoints honour --ckpt_backend orbax: the crash run leaves a
    ``task_*_epoch_*.orbax`` directory + checksummed ``.meta`` sidecar, the
    resume is epoch-granular through the orbax restore path (momentum and
    teacher included), and the finished run is bit-identical to the
    fault-free twin — the same contract the pickle epoch path proves in
    tests/test_faults.py."""
    from faults.injector import FaultInjected

    mesh = make_mesh((8, 1))
    ckpt = str(tmp_path / "ckpts")
    spec = "raise@task1.epoch1"

    twin = CilTrainer(_cfg(), mesh=mesh, init_dist=False)
    ref = twin.fit()

    crashed = CilTrainer(
        _cfg(ckpt_dir=ckpt, ckpt_backend="orbax", epoch_ckpt_every=1,
             fault_spec=spec),
        mesh=mesh, init_dist=False,
    )
    with pytest.raises(FaultInjected):
        crashed.fit()
    names = os.listdir(ckpt)
    assert "task_001_epoch_001.orbax" in names
    assert "task_001_epoch_001.orbax.meta" in names
    assert "task_001_epoch_001.orbax.meta.sha256" in names

    resumed = CilTrainer(
        _cfg(ckpt_dir=ckpt, ckpt_backend="orbax", epoch_ckpt_every=1,
             fault_spec=spec, resume=True),
        mesh=mesh, init_dist=False,
    )
    assert resumed.start_task == 1
    assert resumed.start_epoch == 1
    assert resumed.resumed_from["kind"] == "epoch"
    assert resumed.resumed_from["path"].endswith("task_001_epoch_001.orbax")
    assert resumed.teacher is not None  # restored from the orbax tree
    out = resumed.fit()

    assert out["acc1s"] == ref["acc1s"]
    assert out["acc_matrix"] == ref["acc_matrix"]
    for a, b in zip(
        jax.tree_util.tree_leaves(twin.state.params),
        jax.tree_util.tree_leaves(resumed.state.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # Task 1's boundary checkpoint promoted the epoch scratch away — orbax
    # directory, .meta sidecar and checksum all gone.
    assert not any("_epoch_" in n for n in os.listdir(ckpt))


def test_bf16_selective_epoch_resume_bit_exact(devices8, tmp_path):
    """Under ``--precision bf16_selective`` the checkpoint round trip keeps
    every master copy float32 and bit-exact through an epoch-granular
    crash/resume: the resumed run's params, SGD momentum and BN statistics
    match the fault-free twin array-for-array, and nothing was narrowed to
    bf16 on the way through save/restore (the JL104 contract, proved on the
    real store rather than by lint)."""
    from faults.injector import FaultInjected

    mesh = make_mesh((8, 1))
    ckpt = str(tmp_path / "ckpts")
    kw = dict(precision="bf16_selective")
    spec = "raise@task1.epoch1"

    twin = CilTrainer(_cfg(**kw), mesh=mesh, init_dist=False)
    ref = twin.fit()

    crashed = CilTrainer(
        _cfg(ckpt_dir=ckpt, epoch_ckpt_every=1, fault_spec=spec, **kw),
        mesh=mesh,
        init_dist=False,
    )
    with pytest.raises(FaultInjected):
        crashed.fit()

    resumed = CilTrainer(
        _cfg(ckpt_dir=ckpt, epoch_ckpt_every=1, fault_spec=spec,
             resume=True, **kw),
        mesh=mesh,
        init_dist=False,
    )
    assert resumed.start_task == 1
    assert resumed.start_epoch == 1
    out = resumed.fit()

    assert out["acc1s"] == ref["acc1s"]
    for tree_name in ("params", "momentum", "batch_stats"):
        for a, b in zip(
            jax.tree_util.tree_leaves(getattr(twin.state, tree_name)),
            jax.tree_util.tree_leaves(getattr(resumed.state, tree_name)),
        ):
            a, b = np.asarray(a), np.asarray(b)
            assert a.dtype == np.float32  # master copies never narrowed
            np.testing.assert_array_equal(a, b)


def test_incomplete_orbax_checkpoint_ignored(tmp_path):
    """An orbax dir without its metadata sidecar is not a resumable
    checkpoint (crash window between the two writes), and a torn/corrupt
    sidecar is treated the same as a missing one."""
    import pickle

    d = tmp_path / "ck"
    (d / "task_003.orbax").mkdir(parents=True)
    assert latest_task_checkpoint(str(d)) is None
    (d / "task_003.orbax.meta").write_bytes(b"x")  # torn write, not a pickle
    assert latest_task_checkpoint(str(d)) is None
    (d / "task_003.orbax.meta").write_bytes(pickle.dumps({"task_id": 3}))
    assert latest_task_checkpoint(str(d)).endswith("task_003.orbax")
