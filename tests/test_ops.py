"""Pallas fused-loss kernel vs the XLA reference implementation (interpret
mode so the suite stays CPU-only)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from a_pytorch_tutorial_to_class_incremental_learning_tpu.engine.losses import (
    cross_entropy,
)
from a_pytorch_tutorial_to_class_incremental_learning_tpu.models.classifier import (
    NEG_INF,
)
from a_pytorch_tutorial_to_class_incremental_learning_tpu.ops import (
    fused_masked_cross_entropy,
)


def _masked_logits(b, width, active, seed=0):
    rng = np.random.RandomState(seed)
    logits = rng.randn(b, width).astype(np.float32) * 3
    logits[:, active:] = NEG_INF
    labels = rng.randint(0, active, b).astype(np.int64)
    return jnp.asarray(logits), jnp.asarray(labels)


@pytest.mark.parametrize("smooth", [0.0, 0.1])
@pytest.mark.parametrize("b,width,active", [(32, 100, 60), (64, 128, 128), (16, 7, 5)])
def test_fused_ce_matches_reference(smooth, b, width, active):
    logits, labels = _masked_logits(b, width, active)
    ref = cross_entropy(logits, labels, jnp.int32(active), smooth)
    got = fused_masked_cross_entropy(
        logits, labels, jnp.int32(active), smooth, True
    )
    assert np.isclose(float(got), float(ref), rtol=1e-5)


@pytest.mark.parametrize("smooth", [0.0, 0.1])
def test_fused_ce_gradients_match(smooth):
    logits, labels = _masked_logits(32, 100, 60, seed=3)
    active = jnp.int32(60)

    ref_grad = jax.grad(lambda x: cross_entropy(x, labels, active, smooth))(logits)
    got_grad = jax.grad(
        lambda x: fused_masked_cross_entropy(x, labels, active, smooth, True)
    )(logits)
    np.testing.assert_allclose(
        np.asarray(got_grad), np.asarray(ref_grad), rtol=1e-4, atol=1e-7
    )
    # Inactive columns receive exactly zero gradient in both paths.
    assert np.all(np.asarray(got_grad)[:, 60:] == 0)


def test_fused_ce_traced_num_active():
    """num_active stays a traced scalar: one jitted fn serves every task."""
    logits, labels = _masked_logits(16, 100, 50, seed=5)

    @jax.jit
    def f(x, y, na):
        return fused_masked_cross_entropy(x, y, na, 0.0, True)

    a = f(logits, labels, jnp.int32(50))
    logits2, labels2 = _masked_logits(16, 100, 30, seed=6)
    b = f(logits2, labels2, jnp.int32(30))
    ref_b = cross_entropy(logits2, labels2, jnp.int32(30), 0.0)
    assert np.isclose(float(b), float(ref_b), rtol=1e-5)
    assert a != b


def test_train_step_with_pallas_loss(devices8):
    """The engine's pallas-loss path produces the same training result as the
    XLA loss on the virtual mesh."""
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.config import (
        CilConfig,
    )
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.engine import (
        CilTrainer,
    )
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.parallel.mesh import (
        make_mesh,
    )

    base = dict(
        data_set="synthetic10", num_bases=0, increment=5, backbone="resnet20",
        batch_size=4, num_epochs=1, eval_every_epoch=100, memory_size=20,
        aa=None, color_jitter=0.0, seed=1,
    )
    losses = []
    for flag in (False, True):
        t = CilTrainer(
            CilConfig(use_pallas_loss=flag, **base),
            mesh=make_mesh((8, 1)),
            init_dist=False,
        )
        t.state = t._grow_state(t.state, 0, 0, 5)
        x = np.random.RandomState(0).randint(0, 256, (32, 32, 32, 3), np.uint8)
        y = np.random.RandomState(1).randint(0, 5, 32).astype(np.int64)
        xd, yd = t._put(x, y)
        _, m = t._steps[False](t.state, None, xd, yd, jax.random.PRNGKey(0), 0.1, 0.5)
        losses.append(float(m["loss"]))
    assert np.isclose(losses[0], losses[1], rtol=1e-5)


def test_sharded_fused_ce_matches_reference(devices8):
    """The shard_map wrapper (the multi-device TPU path) reproduces the XLA
    loss in value and gradient on a (4, 2) data×model mesh."""
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.ops import (
        sharded_fused_masked_cross_entropy,
    )
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.parallel.mesh import (
        batch_sharding,
        make_mesh,
    )

    mesh = make_mesh((4, 2))
    logits, labels = _masked_logits(16, 128, 60, seed=7)
    logits_d = jax.device_put(logits, batch_sharding(mesh))
    labels_d = jax.device_put(labels, batch_sharding(mesh))
    na = jnp.int32(60)

    def f(lg, lb):
        return sharded_fused_masked_cross_entropy(mesh, lg, lb, na, 0.1, True)

    val, grad = jax.value_and_grad(f)(logits_d, labels_d)
    ref_val, ref_grad = jax.value_and_grad(
        lambda lg: cross_entropy(lg, labels, na, 0.1)
    )(logits)
    assert np.isclose(float(val), float(ref_val), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(grad), np.asarray(ref_grad), rtol=1e-4, atol=1e-7
    )


def test_fused_ce_odd_batch_sizes():
    for b in (320, 384, 13):
        logits, labels = _masked_logits(b, 100, 60, seed=b)
        ref = cross_entropy(logits, labels, jnp.int32(60), 0.1)
        got = fused_masked_cross_entropy(logits, labels, jnp.int32(60), 0.1, True)
        assert np.isclose(float(got), float(ref), rtol=1e-5), b
