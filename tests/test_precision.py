"""Precision policy layer (ops/precision.py): preset resolution, the
--compute_dtype alias contract, config plumbing, the policy-compatible
kernel registry, and the dtype seams the presets promise (masked head
matmul accumulates f32; losses upcast at entry)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from a_pytorch_tutorial_to_class_incremental_learning_tpu.config import CilConfig
from a_pytorch_tutorial_to_class_incremental_learning_tpu.ops.precision import (
    LOGITS_DTYPE,
    PARAM_DTYPE,
    PRESETS,
    Policy,
    get_policy,
    kernel_policies,
    kernel_policy_compatible,
    policy_from_config,
    register_policy_kernel,
)


def test_presets_honour_the_fixed_points():
    assert set(PRESETS) == {"f32", "bf16_all", "bf16_selective"}
    assert PARAM_DTYPE == jnp.float32 and LOGITS_DTYPE == jnp.float32
    f32 = get_policy("f32")
    assert (f32.compute_dtype, f32.act_dtype, f32.head_dtype) == (
        jnp.float32, jnp.float32, jnp.float32)
    sel = get_policy("bf16_selective")
    assert sel.compute_dtype == jnp.bfloat16
    assert sel.act_dtype == jnp.float32  # inter-op flow stays f32
    assert sel.head_dtype == jnp.bfloat16
    legacy = get_policy("bf16_all")
    assert legacy.compute_dtype == jnp.bfloat16
    assert legacy.act_dtype == jnp.bfloat16
    assert legacy.head_dtype == jnp.float32  # head was never bf16 pre-policy


def test_compute_dtype_aliases_resolve():
    assert get_policy("float32") is PRESETS["f32"]
    assert get_policy("bfloat16") is PRESETS["bf16_all"]
    with pytest.raises(ValueError, match="unknown precision policy"):
        get_policy("fp8")


def test_policy_from_config_precedence():
    # --precision wins over the legacy alias when both are set.
    cfg = CilConfig(precision="bf16_selective", compute_dtype="float32")
    assert policy_from_config(cfg).name == "bf16_selective"
    # Legacy command lines keep working unchanged.
    assert policy_from_config(CilConfig(compute_dtype="bfloat16")).name \
        == "bf16_all"
    assert policy_from_config(CilConfig()).name == "f32"


def test_describe_is_json_friendly():
    d = get_policy("bf16_selective").describe()
    assert d == {
        "name": "bf16_selective",
        "compute_dtype": "bfloat16",
        "act_dtype": "float32",
        "head_dtype": "bfloat16",
        "param_dtype": "float32",
        "logits_dtype": "float32",
    }


def test_kernel_registry():
    # The Pallas fused loss self-registers for every preset at import.
    import a_pytorch_tutorial_to_class_incremental_learning_tpu.ops.fused_loss  # noqa: F401

    assert kernel_policies("fused_masked_cross_entropy") == frozenset(
        {"f32", "bf16_all", "bf16_selective"})
    for name in PRESETS:
        assert kernel_policy_compatible(
            "fused_masked_cross_entropy", get_policy(name))
    assert kernel_policies("no_such_kernel") == frozenset()
    assert not kernel_policy_compatible("no_such_kernel", get_policy("f32"))
    with pytest.raises(ValueError, match="unknown policy"):
        register_policy_kernel("bad", "fp8")


def test_masked_head_accumulates_f32_under_bf16_operands():
    """The head matmul under bf16_selective: operands cast to bf16, logits
    accumulated and returned f32 (preferred_element_type), masked columns
    still NEG_INF."""
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.models.classifier import (
        NEG_INF,
        masked_logits,
    )

    rng = np.random.RandomState(0)
    feats = jnp.asarray(rng.randn(4, 16).astype(np.float32))
    fc = {
        "kernel": jnp.asarray(rng.randn(16, 10).astype(np.float32) * 0.1),
        "bias": jnp.zeros((10,), jnp.float32),
    }
    ref = masked_logits(feats, fc, jnp.int32(6))
    got = masked_logits(feats, fc, jnp.int32(6), head_dtype=jnp.bfloat16)
    assert ref.dtype == jnp.float32 and got.dtype == jnp.float32
    assert np.all(np.asarray(got)[:, 6:] == NEG_INF)
    # bf16 operands round the product but the result stays close to f32.
    np.testing.assert_allclose(
        np.asarray(got)[:, :6], np.asarray(ref)[:, :6], rtol=0.05, atol=0.05)


def test_losses_upcast_bf16_logits_at_entry():
    """CE/KD accumulate in f32 even when handed bf16 logits — feeding the
    same values as bf16 vs f32 must agree to much better than bf16 epsilon
    (the LOSS_DTYPE contract at the losses' entry seam)."""
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.engine.losses import (
        cross_entropy,
        soft_target_kd,
    )
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.models.classifier import (
        NEG_INF,
    )

    rng = np.random.RandomState(1)
    logits32 = rng.randn(8, 10).astype(np.float32)
    logits32[:, 6:] = NEG_INF
    labels = jnp.asarray(rng.randint(0, 6, 8))
    lo16 = jnp.asarray(logits32).astype(jnp.bfloat16)
    # bf16 -> f32 -> bf16 is lossless for values already rounded to bf16, so
    # compare the bf16 input against its own f32 widening: any difference
    # would come from accumulating in bf16.
    wide = lo16.astype(jnp.float32)
    ce16 = cross_entropy(lo16, labels, jnp.int32(6), 0.1)
    ce32 = cross_entropy(wide, labels, jnp.int32(6), 0.1)
    assert ce16.dtype == jnp.float32
    assert np.isclose(float(ce16), float(ce32), rtol=1e-6)
    t_wide = jnp.asarray(rng.randn(8, 10).astype(np.float32))
    kd16 = soft_target_kd(lo16, t_wide, jnp.int32(6), temperature=2.0)
    kd32 = soft_target_kd(wide, t_wide, jnp.int32(6), temperature=2.0)
    assert kd16.dtype == jnp.float32
    assert np.isclose(float(kd16), float(kd32), rtol=1e-6)


def test_model_threads_policy_dtypes(devices8):
    """create_model(policy=...) lands the policy's three dtypes on the
    CilModel fields; the default stays the f32 reference."""
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.models import (
        create_model,
    )

    sel, _ = create_model(
        "resnet20", nb_classes=10, policy=get_policy("bf16_selective"))
    assert sel.dtype == jnp.bfloat16
    assert sel.act_dtype == jnp.float32
    assert sel.head_dtype == jnp.bfloat16
    ref, _ = create_model("resnet20", nb_classes=10)
    assert ref.dtype == jnp.float32
    assert ref.act_dtype is None and ref.head_dtype is None  # legacy path
