"""Engine tests: loss golden values vs torch/analytic, SGD parity, cosine
schedule, and step mechanics (SURVEY.md §4)."""

import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from a_pytorch_tutorial_to_class_incremental_learning_tpu.engine import (
    accuracy,
    cosine_lr,
    cross_entropy,
    sgd_init,
    sgd_update,
    soft_target_kd,
)
from a_pytorch_tutorial_to_class_incremental_learning_tpu.models.classifier import (
    NEG_INF,
)


def _masked(logits, active):
    width = logits.shape[-1]
    return np.where(np.arange(width) < active, logits, NEG_INF)


# --------------------------------------------------------------------------- #
# KD loss vs torch SoftTarget (reference utils.py:121-132) and analytic KL
# --------------------------------------------------------------------------- #


def test_soft_target_kd_torch_parity():
    torch = pytest.importorskip("torch")
    import torch.nn.functional as F

    rng = np.random.RandomState(0)
    known, width = 7, 12
    s_full = rng.randn(8, width).astype(np.float32) * 3
    t_full = rng.randn(8, width).astype(np.float32) * 3
    T = 2.0

    # Reference math on the sliced logits.
    s_t = torch.from_numpy(s_full[:, :known])
    t_t = torch.from_numpy(t_full[:, :known])
    ref = (
        F.kl_div(
            F.log_softmax(s_t / T, dim=1),
            F.softmax(t_t / T, dim=1),
            reduction="batchmean",
        )
        * T
        * T
    ).item()

    # Our masked version on full-width logits (teacher masked at `known`).
    ours = soft_target_kd(
        jnp.asarray(_masked(s_full, known)),
        jnp.asarray(_masked(t_full, known)),
        jnp.int32(known),
        temperature=T,
    )
    assert np.isclose(float(ours), ref, rtol=1e-5)


def test_soft_target_kd_analytic():
    # Two classes, uniform teacher; student = teacher => KL = 0.
    logits = jnp.asarray(_masked(np.zeros((4, 8), np.float32), 2))
    assert np.isclose(float(soft_target_kd(logits, logits, jnp.int32(2))), 0.0)
    # Analytic: s=(log2, 0), t=(0, 0) at T=1: KL = sum p_t (log p_t - log p_s).
    s = np.array([[np.log(2.0), 0.0]], np.float32)
    t = np.array([[0.0, 0.0]], np.float32)
    p_s = np.exp(s[0]) / np.exp(s[0]).sum()
    expected = float((0.5 * (np.log(0.5) - np.log(p_s))).sum())
    got = float(
        soft_target_kd(
            jnp.asarray(_masked(s, 2)), jnp.asarray(_masked(t, 2)),
            jnp.int32(2), temperature=1.0,
        )
    )
    assert np.isclose(got, expected, rtol=1e-5)


# --------------------------------------------------------------------------- #
# CE with label smoothing vs torch (reference template.py:219)
# --------------------------------------------------------------------------- #


@pytest.mark.parametrize("smooth", [0.0, 0.1])
def test_cross_entropy_torch_parity(smooth):
    torch = pytest.importorskip("torch")

    rng = np.random.RandomState(1)
    active, width = 6, 10
    logits = rng.randn(16, width).astype(np.float32) * 2
    labels = rng.randint(0, active, 16)
    ref = torch.nn.CrossEntropyLoss(label_smoothing=smooth)(
        torch.from_numpy(logits[:, :active]), torch.from_numpy(labels)
    ).item()
    ours = cross_entropy(
        jnp.asarray(_masked(logits, active)),
        jnp.asarray(labels),
        jnp.int32(active),
        label_smoothing=smooth,
    )
    assert np.isclose(float(ours), ref, rtol=1e-5)


def test_accuracy_percent_semantics():
    logits = np.full((4, 8), NEG_INF, np.float32)
    logits[:, :4] = [[5, 1, 0, 0], [1, 5, 0, 0], [0, 1, 5, 0], [5, 1, 2, 3]]
    labels = jnp.asarray([0, 1, 0, 2])
    a1, a5 = accuracy(jnp.asarray(logits), labels, topk=(1, 5))
    assert float(a1) == 50.0  # 2/4 correct, in percent
    assert float(a5) == 100.0  # top-5 covers all 4 active classes


# --------------------------------------------------------------------------- #
# SGD vs torch (reference template.py:246-247) and cosine schedule
# --------------------------------------------------------------------------- #


def test_sgd_torch_parity():
    torch = pytest.importorskip("torch")

    rng = np.random.RandomState(2)
    w0 = rng.randn(5, 3).astype(np.float32)
    lr, mom, wd = 0.1, 0.9, 5e-4

    tw = torch.nn.Parameter(torch.from_numpy(w0.copy()))
    opt = torch.optim.SGD([tw], lr=lr, momentum=mom, weight_decay=wd)
    params = {"w": jnp.asarray(w0)}
    buf = sgd_init(params)
    for i in range(4):
        g = rng.randn(5, 3).astype(np.float32)
        opt.zero_grad()
        tw.grad = torch.from_numpy(g.copy())
        opt.step()
        params, buf = sgd_update(params, {"w": jnp.asarray(g)}, buf, lr, mom, wd)
    np.testing.assert_allclose(
        np.asarray(params["w"]), tw.detach().numpy(), rtol=1e-5, atol=1e-6
    )


def test_cosine_lr_torch_parity():
    torch = pytest.importorskip("torch")

    base, epochs = 0.1, 10
    p = torch.nn.Parameter(torch.zeros(1))
    opt = torch.optim.SGD([p], lr=base)
    sched = torch.optim.lr_scheduler.CosineAnnealingLR(opt, T_max=epochs)
    for epoch in range(epochs):
        ref_lr = opt.param_groups[0]["lr"]
        assert np.isclose(cosine_lr(base, epoch, epochs), ref_lr, rtol=1e-6)
        sched.step()


# --------------------------------------------------------------------------- #
# RecompileSentinel (--recompile_budget): train programs trace at most once
# per (task-growth, restore) event — the ISSUE 4 acceptance bar, proved on a
# real two-task run plus a killed-and-resumed run.
# --------------------------------------------------------------------------- #


def _budget_cfg(**kw):
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.config import (
        CilConfig,
    )

    # Shapes mirror tests/test_checkpoint.py so the compiled programs hit the
    # persistent jit cache instead of re-compiling for this test alone.
    defaults = dict(
        data_set="synthetic10",
        num_bases=0,
        increment=5,
        backbone="resnet20",
        batch_size=8,
        num_epochs=2,
        eval_every_epoch=100,
        memory_size=40,
        lr=0.05,
        aa=None,
        color_jitter=0.0,
        seed=11,
        recompile_budget=True,
    )
    defaults.update(kw)
    return CilConfig(**defaults)


def _budget_records(log_path):
    import json

    out = []
    with open(log_path) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("type") == "recompile_budget":
                out.append(rec)
    return out


@pytest.mark.heavy
def test_recompile_sentinel_budget_e2e(tmp_path):
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.engine import (
        CilTrainer,
    )
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.parallel.mesh import (
        make_mesh,
    )

    ckpt = str(tmp_path / "ckpts")
    log_a = str(tmp_path / "a.jsonl")
    trainer = CilTrainer(
        _budget_cfg(ckpt_dir=ckpt, log_file=log_a),
        mesh=make_mesh((8, 1)),
        init_dist=False,
    )
    trainer.fit()  # would raise RecompileBudgetExceeded on a silent re-trace

    recs = _budget_records(log_a)
    # One check per task boundary; every verdict within budget.
    assert len(recs) == 2
    assert all(r["ok"] for r in recs)
    # Two growth events grant budget 2; the fused path compiles exactly the
    # two epoch programs (teacher absent/present) — at budget, not under it,
    # so any extra trace would have flipped ok to False.
    final = recs[-1]
    assert final["events"] == 2 and final["budget"] == 2
    assert final["programs"] == 2

    # Crash after task 0, resume: the restore must grant a budget event or
    # the resumed task's (legitimate) compile would trip the sentinel.
    # check_donation rides along: the restore path must survive its own
    # alias check + host-payload poisoning (utils/checkpoint.py).
    os.remove(os.path.join(ckpt, "task_001.ckpt"))
    log_b = str(tmp_path / "b.jsonl")
    resumed = CilTrainer(
        _budget_cfg(ckpt_dir=ckpt, log_file=log_b, resume=True,
                    check_donation=True),
        mesh=make_mesh((8, 1)),
        init_dist=False,
    )
    assert resumed.start_task == 1
    resumed.fit()

    recs = _budget_records(log_b)
    assert len(recs) == 1  # only task 1 ran
    (rec,) = recs
    assert rec["ok"]
    # restore + task-1 growth = 2 events; only the teacher-present epoch
    # program actually compiles in the resumed process.
    assert rec["events"] == 2 and rec["budget"] == 2
    assert rec["programs"] <= 2


def test_sentinel_trips_on_synthetic_leak():
    """The enforcement path itself, without a training run: a program count
    above the granted budget raises with a pointer at the jaxlint rules."""
    from analysis.runtime import RecompileBudgetExceeded, RecompileSentinel

    class Monitor:
        def total(self, group):
            return 3

    s = RecompileSentinel(Monitor(), group="train", per_event=1)
    s.note_event("task_growth", task_id=0)
    s.note_event("task_growth", task_id=1)
    with pytest.raises(RecompileBudgetExceeded, match="JL101/JL102"):
        s.check(where="task1")
