"""Engine tests: loss golden values vs torch/analytic, SGD parity, cosine
schedule, and step mechanics (SURVEY.md §4)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from a_pytorch_tutorial_to_class_incremental_learning_tpu.engine import (
    accuracy,
    cosine_lr,
    cross_entropy,
    sgd_init,
    sgd_update,
    soft_target_kd,
)
from a_pytorch_tutorial_to_class_incremental_learning_tpu.models.classifier import (
    NEG_INF,
)


def _masked(logits, active):
    width = logits.shape[-1]
    return np.where(np.arange(width) < active, logits, NEG_INF)


# --------------------------------------------------------------------------- #
# KD loss vs torch SoftTarget (reference utils.py:121-132) and analytic KL
# --------------------------------------------------------------------------- #


def test_soft_target_kd_torch_parity():
    torch = pytest.importorskip("torch")
    import torch.nn.functional as F

    rng = np.random.RandomState(0)
    known, width = 7, 12
    s_full = rng.randn(8, width).astype(np.float32) * 3
    t_full = rng.randn(8, width).astype(np.float32) * 3
    T = 2.0

    # Reference math on the sliced logits.
    s_t = torch.from_numpy(s_full[:, :known])
    t_t = torch.from_numpy(t_full[:, :known])
    ref = (
        F.kl_div(
            F.log_softmax(s_t / T, dim=1),
            F.softmax(t_t / T, dim=1),
            reduction="batchmean",
        )
        * T
        * T
    ).item()

    # Our masked version on full-width logits (teacher masked at `known`).
    ours = soft_target_kd(
        jnp.asarray(_masked(s_full, known)),
        jnp.asarray(_masked(t_full, known)),
        jnp.int32(known),
        temperature=T,
    )
    assert np.isclose(float(ours), ref, rtol=1e-5)


def test_soft_target_kd_analytic():
    # Two classes, uniform teacher; student = teacher => KL = 0.
    logits = jnp.asarray(_masked(np.zeros((4, 8), np.float32), 2))
    assert np.isclose(float(soft_target_kd(logits, logits, jnp.int32(2))), 0.0)
    # Analytic: s=(log2, 0), t=(0, 0) at T=1: KL = sum p_t (log p_t - log p_s).
    s = np.array([[np.log(2.0), 0.0]], np.float32)
    t = np.array([[0.0, 0.0]], np.float32)
    p_s = np.exp(s[0]) / np.exp(s[0]).sum()
    expected = float((0.5 * (np.log(0.5) - np.log(p_s))).sum())
    got = float(
        soft_target_kd(
            jnp.asarray(_masked(s, 2)), jnp.asarray(_masked(t, 2)),
            jnp.int32(2), temperature=1.0,
        )
    )
    assert np.isclose(got, expected, rtol=1e-5)


# --------------------------------------------------------------------------- #
# CE with label smoothing vs torch (reference template.py:219)
# --------------------------------------------------------------------------- #


@pytest.mark.parametrize("smooth", [0.0, 0.1])
def test_cross_entropy_torch_parity(smooth):
    torch = pytest.importorskip("torch")

    rng = np.random.RandomState(1)
    active, width = 6, 10
    logits = rng.randn(16, width).astype(np.float32) * 2
    labels = rng.randint(0, active, 16)
    ref = torch.nn.CrossEntropyLoss(label_smoothing=smooth)(
        torch.from_numpy(logits[:, :active]), torch.from_numpy(labels)
    ).item()
    ours = cross_entropy(
        jnp.asarray(_masked(logits, active)),
        jnp.asarray(labels),
        jnp.int32(active),
        label_smoothing=smooth,
    )
    assert np.isclose(float(ours), ref, rtol=1e-5)


def test_accuracy_percent_semantics():
    logits = np.full((4, 8), NEG_INF, np.float32)
    logits[:, :4] = [[5, 1, 0, 0], [1, 5, 0, 0], [0, 1, 5, 0], [5, 1, 2, 3]]
    labels = jnp.asarray([0, 1, 0, 2])
    a1, a5 = accuracy(jnp.asarray(logits), labels, topk=(1, 5))
    assert float(a1) == 50.0  # 2/4 correct, in percent
    assert float(a5) == 100.0  # top-5 covers all 4 active classes


# --------------------------------------------------------------------------- #
# SGD vs torch (reference template.py:246-247) and cosine schedule
# --------------------------------------------------------------------------- #


def test_sgd_torch_parity():
    torch = pytest.importorskip("torch")

    rng = np.random.RandomState(2)
    w0 = rng.randn(5, 3).astype(np.float32)
    lr, mom, wd = 0.1, 0.9, 5e-4

    tw = torch.nn.Parameter(torch.from_numpy(w0.copy()))
    opt = torch.optim.SGD([tw], lr=lr, momentum=mom, weight_decay=wd)
    params = {"w": jnp.asarray(w0)}
    buf = sgd_init(params)
    for i in range(4):
        g = rng.randn(5, 3).astype(np.float32)
        opt.zero_grad()
        tw.grad = torch.from_numpy(g.copy())
        opt.step()
        params, buf = sgd_update(params, {"w": jnp.asarray(g)}, buf, lr, mom, wd)
    np.testing.assert_allclose(
        np.asarray(params["w"]), tw.detach().numpy(), rtol=1e-5, atol=1e-6
    )


def test_cosine_lr_torch_parity():
    torch = pytest.importorskip("torch")

    base, epochs = 0.1, 10
    p = torch.nn.Parameter(torch.zeros(1))
    opt = torch.optim.SGD([p], lr=base)
    sched = torch.optim.lr_scheduler.CosineAnnealingLR(opt, T_max=epochs)
    for epoch in range(epochs):
        ref_lr = opt.param_groups[0]["lr"]
        assert np.isclose(cosine_lr(base, epoch, epochs), ref_lr, rtol=1e-6)
        sched.step()
