"""parallel/dist.py: init_distributed_mode env parsing and error paths,
the process-0 printer, and the coordination-service barrier.

``jax.distributed.initialize`` is always monkeypatched — these tests run
single-process and only verify the *host-side bootstrap logic*: which env
variables select explicit vs auto-detected initialization, when failures
raise vs degrade, and that single-process runs never touch the process
group.  The real 2-process handshake is covered by tests/test_multihost.py.
"""

import builtins
import io
import sys

import pytest

from a_pytorch_tutorial_to_class_incremental_learning_tpu.parallel import dist

_ALL_MARKERS = (
    list(dist._EXPLICIT_COORD_VARS)
    + list(dist._HOST_LIST_VARS)
    + ["MEGASCALE_COORDINATOR_ADDRESS", "SLURM_JOB_NUM_NODES",
       "JAX_NUM_PROCESSES", "NUM_PROCESSES", "JAX_PROCESS_ID", "PROCESS_ID"]
)


@pytest.fixture
def clean_dist(monkeypatch):
    """Reset dist's module state and env markers around each test.

    init_distributed_mode mutates module globals and (via
    setup_for_distributed) replaces builtins.print; without restoration a
    single test here would silence every later test's output.
    """
    for var in _ALL_MARKERS:
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setattr(dist, "_dist_initialized", False)
    monkeypatch.setattr(dist, "_printer_installed", False)
    monkeypatch.setattr(builtins, "print", builtins.print)
    calls = []

    def fake_initialize(**kwargs):
        calls.append(kwargs)

    monkeypatch.setattr(dist.jax.distributed, "initialize", fake_initialize)
    return calls


def test_single_process_is_a_noop(clean_dist):
    dist.init_distributed_mode()
    assert clean_dist == []
    assert dist._dist_initialized is False


def test_explicit_jax_env_triplet(clean_dist, monkeypatch):
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:9999")
    monkeypatch.setenv("JAX_NUM_PROCESSES", "2")
    monkeypatch.setenv("JAX_PROCESS_ID", "1")
    dist.init_distributed_mode()
    assert clean_dist == [{
        "coordinator_address": "10.0.0.1:9999",
        "num_processes": 2,
        "process_id": 1,
    }]
    assert dist._dist_initialized is True


def test_generic_env_triplet(clean_dist, monkeypatch):
    monkeypatch.setenv("COORDINATOR_ADDRESS", "10.0.0.2:1234")
    monkeypatch.setenv("NUM_PROCESSES", "4")
    monkeypatch.setenv("PROCESS_ID", "3")
    dist.init_distributed_mode()
    assert clean_dist == [{
        "coordinator_address": "10.0.0.2:1234",
        "num_processes": 4,
        "process_id": 3,
    }]


def test_jax_vars_shadow_generic_vars(clean_dist, monkeypatch):
    monkeypatch.setenv("COORDINATOR_ADDRESS", "wrong:1")
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "right:2")
    monkeypatch.setenv("JAX_NUM_PROCESSES", "2")
    monkeypatch.setenv("JAX_PROCESS_ID", "0")
    dist.init_distributed_mode()
    assert clean_dist[0]["coordinator_address"] == "right:2"


def test_coordinator_without_ids_uses_autodetection(clean_dist, monkeypatch):
    # Coordinator given but num_processes/process_id left to Cloud TPU / Slurm
    # metadata: only the address may be passed through.
    monkeypatch.setenv("COORDINATOR_ADDRESS", "10.0.0.3:5678")
    dist.init_distributed_mode()
    assert clean_dist == [{"coordinator_address": "10.0.0.3:5678"}]


def test_heuristic_markers_use_full_autodetection(clean_dist, monkeypatch):
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host1,host2")
    dist.init_distributed_mode()
    assert clean_dist == [{}]


def test_explicit_coordinator_failure_raises(clean_dist, monkeypatch):
    # The user asked for multi-host by name; degrading to N independent
    # single-process runs would silently duplicate training.
    monkeypatch.setenv("COORDINATOR_ADDRESS", "10.0.0.4:1")

    def boom(**kwargs):
        raise RuntimeError("coordination service unreachable")

    monkeypatch.setattr(dist.jax.distributed, "initialize", boom)
    with pytest.raises(RuntimeError, match="unreachable"):
        dist.init_distributed_mode()


def test_heuristic_marker_failure_degrades(clean_dist, monkeypatch):
    # Heuristic-only markers (metadata that merely looks multi-host) degrade
    # to single-process with a stderr note instead of killing the run.
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host1,host2")

    def boom(**kwargs):
        raise RuntimeError("backend already initialized")

    monkeypatch.setattr(dist.jax.distributed, "initialize", boom)
    err = io.StringIO()
    monkeypatch.setattr(sys, "stderr", err)
    dist.init_distributed_mode()
    assert "multi-host init skipped" in err.getvalue()


def test_second_call_does_not_reinitialize(clean_dist, monkeypatch):
    monkeypatch.setenv("COORDINATOR_ADDRESS", "10.0.0.5:1")
    dist.init_distributed_mode()
    dist.init_distributed_mode()
    assert len(clean_dist) == 1


def test_cpu_platform_enables_gloo_collectives(clean_dist, monkeypatch):
    # jax 0.4.x CPU clients reject cross-process computations unless a
    # collectives implementation is configured before backend creation —
    # and the flag is NOT read from the environment, so the bootstrap must
    # set it via jax.config.update.
    jax = dist.jax
    flag = "jax_cpu_collectives_implementation"
    if flag not in jax.config.values:
        pytest.skip("this jax has no CPU collectives flag")
    prior = jax.config.values[flag]
    assert "cpu" in str(jax.config.jax_platforms)  # pinned by conftest
    monkeypatch.setenv("COORDINATOR_ADDRESS", "10.0.0.6:1")
    try:
        dist.init_distributed_mode()
        assert jax.config.values[flag] == "gloo"
    finally:
        jax.config.update(flag, prior)


def test_barrier_is_noop_single_process(monkeypatch):
    # Must not touch the coordination service or issue a device collective.
    seen = []
    monkeypatch.setattr(dist.jax, "process_count", lambda: 1)
    monkeypatch.setattr(
        dist, "_barrier_seq", dist._barrier_seq, raising=True
    )
    before = dist._barrier_seq
    dist.barrier()
    assert dist._barrier_seq == before and seen == []


def test_barrier_uses_coordination_service(monkeypatch):
    monkeypatch.setattr(dist.jax, "process_count", lambda: 2)
    waited = []

    class FakeClient:
        def wait_at_barrier(self, barrier_id, timeout_in_ms, process_ids=None):
            waited.append((barrier_id, timeout_in_ms))

    from jax._src import distributed as jax_dist

    monkeypatch.setattr(jax_dist.global_state, "client", FakeClient())
    dist.barrier(timeout_s=2.0)
    dist.barrier(timeout_s=2.0)
    assert len(waited) == 2
    ids = [w[0] for w in waited]
    # Every use gets a fresh barrier id — a passed barrier cannot be re-waited.
    assert len(set(ids)) == 2 and all(i.startswith("cil_barrier_") for i in ids)
    assert waited[0][1] == 2000
