"""Test harness: force an 8-device virtual CPU mesh.

The reference has no tests at all (SURVEY.md §4); we test distributed
behaviour without a pod by faking 8 host devices, the standard JAX trick.
Environment variables must be set before jax initializes its backends, hence
the module-level assignment in conftest.
"""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

# The environment may pin JAX_PLATFORMS to an accelerator plugin; tests always
# run on the virtual 8-device CPU mesh, so force the platform via jax.config
# (must happen before any backend is initialized).
jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the suite is dominated by XLA compiles of the
# train/epoch programs; caching them makes repeat runs several times faster.
# XLA's extra AOT kernel caches are kept off — their strict machine-feature
# check has been seen to mismatch the host's own detection ("prefer-no-gather
# ... could lead to SIGILL" warnings) even on one machine.
_cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
try:
    jax.config.update("jax_persistent_cache_enable_xla_caches", "none")
except AttributeError:  # older jax without the sub-knob
    pass

import pytest  # noqa: E402


def pytest_collection_modifyitems(items):
    """Two-tier suite: everything not explicitly ``heavy`` is ``quick``, so
    ``pytest -m quick`` is the health check and ``pytest -m heavy`` the
    e2e/multi-process tier (VERDICT r2 weak #8).  Measured quick-tier
    wall-clock on this 1-core machine: 19 min with a warm
    ``tests/.jax_cache`` (uncontended), ~25+ min cold or contended — the
    tier is "quick" relative to the heavy tier's multi-hour runs, not an
    under-5-minute smoke."""
    for item in items:
        if "heavy" not in item.keywords:
            item.add_marker(pytest.mark.quick)


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs
