"""Native kernel parity: C++ herding/gather vs the numpy implementations."""

import numpy as np
import pytest

from a_pytorch_tutorial_to_class_incremental_learning_tpu.utils import native


pytestmark = pytest.mark.skipif(
    not native.native_available(), reason="libcilhost.so unavailable"
)


def _numpy_herd(features, nb):
    n = len(features)
    nb = min(nb, n)
    mu = features.mean(axis=0)
    selected = np.zeros(n, bool)
    order = np.empty(nb, np.int64)
    running = np.zeros_like(mu)
    for k in range(nb):
        cand = (running[None, :] + features) / (k + 1)
        dist = np.linalg.norm(mu[None, :] - cand, axis=1)
        dist[selected] = np.inf
        i = int(np.argmin(dist))
        order[k] = i
        selected[i] = True
        running += features[i]
    return order


def test_herding_native_matches_numpy():
    rng = np.random.RandomState(0)
    for n, d, nb in ((30, 4, 10), (200, 64, 50), (5, 2, 5)):
        feats = rng.randn(n, d).astype(np.float32)
        ref = _numpy_herd(feats.astype(np.float64), nb)
        got = native.herd_barycenter_native(feats, nb)
        np.testing.assert_array_equal(got, ref)


def test_memory_uses_native_path():
    from a_pytorch_tutorial_to_class_incremental_learning_tpu.data import (
        herd_barycenter,
    )

    rng = np.random.RandomState(1)
    feats = rng.randn(100, 16).astype(np.float32)
    np.testing.assert_array_equal(
        herd_barycenter(feats, 20), _numpy_herd(feats.astype(np.float64), 20)
    )


def test_gather_native_matches_numpy():
    rng = np.random.RandomState(2)
    src = rng.randint(0, 256, (500, 32, 32, 3)).astype(np.uint8)
    idx = rng.randint(0, 500, 4096)
    got = native.gather_u8_native(src, idx)
    np.testing.assert_array_equal(got, src[idx])
    # Out-of-range indices are rejected, not UB.
    assert native.gather_u8_native(src, np.array([500])) is None


def test_gather_rows_object_fallback():
    src = np.asarray(["a", "b", "c"], object)
    np.testing.assert_array_equal(
        native.gather_rows(src, np.array([2, 0])), src[[2, 0]]
    )
